"""Tiered (hot/cold) list-major IVF probe scan — the engine family of
:mod:`raft_tpu.neighbors.tiered` (grafttier, the billion-scale tiered
storage subsystem).

Every index so far is HBM-resident, which caps corpus size at device
memory. The tiered formulation splits the dominant plane — the packed
raw-vector tensor — in two: a **hot tier** ``hot_data[n_hot, m, d]``
stays HBM-resident and rides the exact scalar-prefetched BlockSpec
pipeline of :mod:`raft_tpu.ops.ivf_scan`, while a **cold tier**
``cold_data[n_cold, m, d]`` lives in host memory and streams through a
**double-buffered manual-DMA pipeline** (the beam_search/bq_scan
discipline: ``pltpu.make_async_copy`` from an ``ANY``-space operand
into VMEM scratch, prefetching list ``i+1``'s block while list ``i``
scores). TPU-KNN's dual-roofline methodology (PAPERS.md) is the
honest target: hot blocks should saturate HBM bandwidth, cold blocks
the host/PCIe link — and the per-step fetch plan below makes each
stream pay for exactly its own tier's bytes.

The id and norm planes (``indices``/``data_norms`` — ~2% of the bytes
at serving dims) stay fully HBM-resident: membership masking, the
shared-filter id-fold, and graftgauge's probe accounting all keep
riding the existing device path unchanged, and only the heavy vector
plane ever crosses the host link.

Per-step fetch plan (:func:`tier_fetch_plan`, computed on device from
the probed-list union): ``hot_fetch[j]`` steers the hot BlockSpec
index map — on cold steps it HOLDS the previous hot slot, so the
Pallas pipeline's unchanged-block elision skips the redundant HBM
fetch; ``cold_fetch[j]`` is the cold slot to DMA (−1 on hot and
sentinel steps); ``cold_seq[j]`` numbers the cold steps so the two
DMA buffers alternate.

Two parity-locked engines share the formulation (the ivf_scan
contract): ``pallas`` is the dual-source kernel, ``xla`` the same
math as a ``lax.scan`` selecting each block from its tier — the
portable correctness engine for CPU tier-1. Both upcast/score/merge
in exactly the order of their un-tiered ivf_scan counterparts, so a
tiered index's results are **bit-identical** to the all-HBM index per
engine (pinned in ``tests/test_tiered.py``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.core.validation import expect
from raft_tpu.distance.types import DistanceType
from raft_tpu.ops.fused_topk import (
    _COMPILER_PARAMS,
    _default_vmem_mb,
    _extract_topk,
)
from raft_tpu.ops.ivf_scan import (
    _PALLAS_MAX_K,
    _merge_smallest_id,
    unique_lists,
)

TIER_ENGINES = ("auto", "pallas", "xla")


def resolve_tier_engine(engine: str, *, hot_data=None, filter_words=None,
                        k=None, vmem_mb: int = 0) -> str:
    """Resolve a tiered ``scan_engine`` param to a concrete engine.

    ``auto`` is the dual-source Pallas kernel on TPU and the tiered
    XLA scan elsewhere. ``pallas`` degrades to ``xla`` when the
    kernel's preconditions fail: per-query (2-D) filter words (the
    id-fold trick needs one shared id plane), non-f32 storage (the
    tiered path is f32-only — the cold DMA scratch and the hot block
    must agree on layout), ``k`` past the unrolled-merge budget,
    compiled-mode layout misalignment, or a VMEM budget the hot block
    + the double-buffered cold scratch cannot fit."""
    expect(engine in TIER_ENGINES,
           f"tiered scan_engine must be one of {TIER_ENGINES}, got "
           f"{engine!r}")
    if engine == "auto":
        engine = "pallas" if jax.default_backend() == "tpu" else "xla"
    if engine != "pallas":
        return engine
    if filter_words is not None and getattr(filter_words, "ndim", 1) == 2:
        return "xla"
    if k is not None and k > _PALLAS_MAX_K:
        return "xla"
    if hot_data is not None:
        if hot_data.dtype != jnp.float32:
            return "xla"
        m_pad = -(-hot_data.shape[1] // 8) * 8
        d_pad = -(-hot_data.shape[2] // 128) * 128
        if jax.default_backend() == "tpu" and (
                m_pad != hot_data.shape[1] or d_pad != hot_data.shape[2]):
            # compiled Mosaic would force a whole-tensor jnp.pad per
            # call; interpret mode (CPU CI) keeps the pad path so any
            # test shape is coverable — same contract as ivf_scan
            return "xla"
        if vmem_mb <= 0:
            vmem_mb = _default_vmem_mb()
        fixed, per_q = _tier_vmem_plan(m_pad, d_pad,
                                       k or _PALLAS_MAX_K)
        if fixed + 8 * per_q > vmem_mb << 20:
            return "xla"
    return engine


def resolve_tier_pq_engine(engine: str) -> str:
    """Resolve a tiered-PQ ``scan_engine`` param. The tiered PQ cold
    engine is the LUT union scan with the per-step dual-tier block
    select (graftcast): list-major only — the rank-major PQ scan
    gathers per (query, rank) and has no per-list fetch step to
    steer through the slot maps, so ``rank`` is rejected rather than
    silently served from the wrong tier. ``auto`` is always the XLA
    union scan (there is no Pallas PQ engine, tiered or not)."""
    expect(engine in ("auto", "xla"),
           "tiered PQ scan_engine must be 'auto' or 'xla' — the "
           "rank-major scan has no per-list fetch step to steer "
           f"through the tier slot maps, got {engine!r}")
    return "xla"


def resolve_tier_bq_engine(engine: str) -> str:
    """Resolve a tiered-BQ ``scan_engine`` param. The tiered BQ cold
    engine is the XOR+popcount estimate-then-rerank union scan with
    every per-row plane (codes/corrections/rerank vectors) selected
    from its tier per step. ``auto`` and ``pallas`` both resolve to
    ``xla`` for now: the fused BQ kernel's conditional rerank DMA
    already rides the ANY-operand discipline, but its dual-source
    (hot BlockSpec + cold DMA) variant is the on-chip follow-on
    (ROADMAP) — degrading here keeps the engine choice honest
    instead of serving cold lists from a kernel that cannot reach
    them. ``rank`` is rejected (no per-list fetch step)."""
    expect(engine in ("auto", "pallas", "xla"),
           "tiered BQ scan_engine must be 'auto', 'pallas' or 'xla' "
           f"— got {engine!r}")
    return "xla"


def tier_slot_pair(hot_slot_map, cold_slot_map, lidc):
    """One step's (hot_slot, cold_slot) pair for clamped list id
    ``lidc`` — computed ONCE per scan step and shared by every
    plane's :func:`tier_block_select`, so a multi-plane family (BQ's
    codes + corrections + rerank vectors) cannot read two planes of
    the same list from different tiers."""
    return (jnp.take(hot_slot_map, lidc),
            jnp.take(cold_slot_map, lidc))


def tier_block_select(hot_plane, cold_plane, hs, cs):
    """THE dual-tier block fetch — the one divergence every tiered
    engine has from its all-HBM twin: step ``j``'s block comes from
    its tier via the slot pair of :func:`tier_slot_pair`. ``lax.cond``
    keeps the cold branch a real conditional (only the probed tier's
    block is read — the cold stream pays for exactly its own bytes);
    the selected values are the stored rows either way, so everything
    downstream is bit-identical to the un-tiered scan. Shared by the
    tiered flat XLA engine and the graftcast PQ/BQ cold engines
    (LUT union scan / XOR+popcount estimate)."""
    return jax.lax.cond(
        cs >= 0,
        lambda: jax.lax.dynamic_index_in_dim(
            cold_plane, jnp.maximum(cs, 0), 0, False),
        lambda: jax.lax.dynamic_index_in_dim(
            hot_plane, jnp.maximum(hs, 0), 0, False),
    )


def _tier_vmem_plan(m_pad: int, d_pad: int, k: int):
    """The tiered kernel's VMEM footprint model, shared by
    :func:`resolve_tier_engine` (the degrade decision) and
    ``_tier_scan_pallas`` (the query-tile sizing). ``fixed``: the
    double-buffered hot block + norm/id strips, PLUS the two cold DMA
    scratch buffers (the manual pipeline's landing zone), plus a
    safety margin; ``per_q``: query row + probe row + ~24 B of
    (m)-wide intermediates + the (k) running state (the ivf_scan
    arithmetic — the compute body is the same)."""
    fixed = (3 * m_pad * (d_pad * 4 + 8)
             + 2 * m_pad * d_pad * 4
             + (2 << 20))
    per_q = 4 * (d_pad + 256) + 24 * m_pad + 16 * k
    return fixed, per_q


def tier_fetch_plan(uniq: jax.Array, hot_slot_map: jax.Array,
                    cold_slot_map: jax.Array, n_lists: int):
    """Translate the probed-list union into the per-step dual-tier
    fetch plan (device-side — the slot maps are tiny resident int32
    planes). Returns ``(hot_fetch, cold_fetch, cold_seq)``, each
    ``(n_steps,)`` int32:

    - ``hot_fetch[j]``: hot slot whose block the BlockSpec index map
      streams at step j. On cold and sentinel steps it HOLDS the most
      recent hot slot (leading steps clamp to 0), so consecutive
      same-index steps let the Pallas pipeline elide the copy — a
      cold step costs no HBM block traffic.
    - ``cold_fetch[j]``: cold slot to DMA at step j, or −1 on
      hot/sentinel steps.
    - ``cold_seq[j]``: exclusive running count of cold steps before
      j — the double-buffer slot is ``cold_seq % 2``.
    """
    lidc = jnp.minimum(uniq, n_lists - 1)
    hot_raw = jnp.where(uniq < n_lists,
                        jnp.take(hot_slot_map, lidc), -1)
    cold_raw = jnp.where(uniq < n_lists,
                         jnp.take(cold_slot_map, lidc), -1)
    # carry the last hot slot forward across cold/sentinel steps
    # (f(a, b) = b if b >= 0 else a — associative, so one log-depth
    # scan instead of a sequential loop)
    carried = jax.lax.associative_scan(
        lambda a, b: jnp.where(b >= 0, b, a), hot_raw)
    hot_fetch = jnp.maximum(carried, 0)
    is_cold = (cold_raw >= 0).astype(jnp.int32)
    cold_seq = jnp.cumsum(is_cold) - is_cold
    return hot_fetch, cold_raw, cold_seq


def tiered_list_major_scan(qf, hot_data, cold_data, hot_slot_map,
                           cold_slot_map, data_norms, indices, probes,
                           filter_words=None, init_d=None, init_i=None,
                           *, k: int, metric: DistanceType,
                           engine: str = "xla",
                           interpret: bool = False):
    """Run the probe scan over a tiered index; returns the pre-epilog
    running top-k ``(best_d, best_i)`` in the ivf_scan convention
    (min-space ``norms − 2 x·y`` for L2 with +inf pads; raw inner
    products for IP with −inf pads), so the caller's metric epilog is
    shared with the un-tiered engines.

    ``hot_data``/``cold_data`` are the split vector planes;
    ``hot_slot_map``/``cold_slot_map`` the (n_lists,) int32 slot
    translation (−1 where a list lives in the other tier — every list
    is in exactly one); ``data_norms``/``indices`` the FULL resident
    planes, indexed by list id exactly like the un-tiered engines.
    Both engines break distance ties by smallest dataset id (the
    ``_extract_topk`` order) and score each block with the same
    shapes and op order as their ivf_scan counterparts, so results
    are bit-identical to the all-HBM index per engine. Probe slots
    carrying the sentinel value ``n_lists`` are masked probes and
    contribute nothing."""
    expect(engine in ("pallas", "xla"),
           f"tiered_list_major_scan engine must be pallas|xla, got "
           f"{engine!r}")
    if engine == "pallas":
        return _tier_scan_pallas(
            qf, hot_data, cold_data, hot_slot_map, cold_slot_map,
            data_norms, indices, probes, filter_words, k=k,
            metric=metric, interpret=interpret)
    return _tier_scan_xla(
        qf, hot_data, cold_data, hot_slot_map, cold_slot_map,
        data_norms, indices, probes, filter_words, init_d, init_i,
        k=k, metric=metric)


# ---------------------------------------------------------------------------
# XLA tiered engine — the portable parity reference
# ---------------------------------------------------------------------------


def _tier_scan_xla(qf, hot_data, cold_data, hot_slot_map, cold_slot_map,
                   data_norms, indices, probes, filter_words,
                   init_d=None, init_i=None, *, k: int,
                   metric: DistanceType):
    from raft_tpu.neighbors.filters import test_filter

    q = qf.shape[0]
    n_lists = indices.shape[0]
    ip_metric = metric == DistanceType.InnerProduct
    uniq = unique_lists(probes, n_lists)

    def step(carry, lid):
        best_d, best_i = carry
        lidc = jnp.minimum(lid, n_lists - 1)      # sentinel-safe index
        hs, cs = tier_slot_pair(hot_slot_map, cold_slot_map, lidc)
        # the ONE tiered divergence from ivf_scan's _scan_xla: the
        # block comes from its tier (see tier_block_select).
        rows = tier_block_select(hot_data, cold_data, hs,
                                 cs).astype(jnp.float32)       # (m, d)
        row_ids = jax.lax.dynamic_index_in_dim(indices, lidc, 0, False)
        ip = jax.lax.dot_general(
            qf, rows, (((1,), (1,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )                                                      # (q, m)
        if ip_metric:
            dist = -ip
        else:
            row_norms = jax.lax.dynamic_index_in_dim(
                data_norms, lidc, 0, False)
            dist = row_norms[None, :] - 2.0 * ip
        ids_b = jnp.broadcast_to(row_ids[None, :], dist.shape)
        probed = jnp.any(probes == lid, axis=1) & (lid < n_lists)
        ok = (ids_b >= 0) & probed[:, None]
        if filter_words is not None:
            ok = ok & test_filter(filter_words, ids_b)
        dist = jnp.where(ok, dist, jnp.inf)
        return _merge_smallest_id(best_d, best_i, dist, ids_b, k), None

    init = (
        jnp.full((q, k), jnp.inf, jnp.float32) if init_d is None
        else jnp.full_like(init_d, jnp.inf),
        jnp.full((q, k), -1, jnp.int32) if init_i is None
        else jnp.full_like(init_i, -1),
    )
    (best_d, best_i), _ = jax.lax.scan(step, init, uniq)
    if ip_metric:
        best_d = -best_d          # inf (unfilled) -> -inf, ip exact
    return best_d, best_i


# ---------------------------------------------------------------------------
# Pallas tiered engine — hot BlockSpec pipeline + cold manual-DMA pipeline
# ---------------------------------------------------------------------------


def _cold_dma(cold_ref, cbuf, sem, cslot, slot):
    """The (described, not yet started) async copy of cold block
    ``cslot`` into double-buffer ``slot``. The buffer index is
    resolved STATICALLY under two ``pl.when`` branches by the caller
    — semaphore and scratch slices stay compile-time constants."""
    return pltpu.make_async_copy(
        cold_ref.at[pl.ds(cslot, 1)], cbuf.at[pl.ds(slot, 1)],
        sem.at[slot])


def _tier_scan_kernel(u_ref, hf_ref, cf_ref, cs_ref, probes_ref, q_ref,
                      x_ref, xn_ref, ids_ref, cold_ref, outd_ref,
                      outi_ref, bestd, besti, cbuf, sem, *, k: int,
                      n_steps: int, n_lists: int, ip_metric: bool):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        bestd[:] = jnp.full_like(bestd, jnp.inf)
        besti[:] = jnp.full_like(besti, -1)

    lid = u_ref[j]                        # scalar-prefetched list id
    cslot = cf_ref[j]                     # cold slot, or -1 on hot steps
    is_cold = cslot >= 0
    slot = cs_ref[j] % 2                  # this step's double-buffer slot

    # warm-up: the first step of each query tile must fetch its own
    # cold block — there was no previous step to prefetch it
    @pl.when((j == 0) & is_cold)
    def _():
        for s in (0, 1):
            @pl.when(slot == s)
            def _(s=s):
                _cold_dma(cold_ref, cbuf, sem,
                          jnp.maximum(cslot, 0), s).start()

    # prefetch the NEXT step's cold block while this step scores —
    # the double-buffer discipline: its landing slot is the one this
    # step is NOT reading, and every started copy is waited exactly
    # once (at its own step, below)
    nxt = jnp.minimum(j + 1, n_steps - 1)
    nxt_cold = cf_ref[nxt]
    nxt_slot = cs_ref[nxt] % 2

    @pl.when((j + 1 < n_steps) & (nxt_cold >= 0))
    def _():
        for s in (0, 1):
            @pl.when(nxt_slot == s)
            def _(s=s):
                _cold_dma(cold_ref, cbuf, sem,
                          jnp.maximum(nxt_cold, 0), s).start()

    # wait for this step's cold block (started at step j-1, or just
    # above when j == 0)
    @pl.when(is_cold)
    def _():
        for s in (0, 1):
            @pl.when(slot == s)
            def _(s=s):
                _cold_dma(cold_ref, cbuf, sem,
                          jnp.maximum(cslot, 0), s).wait()

    # block source select: the hot BlockSpec block (hf held the
    # previous hot slot on cold steps, so the pipeline elided its
    # copy) or the cold DMA landing buffer. Both are f32 VMEM reads;
    # the selected values are the stored rows either way, so the
    # contraction below is bit-identical to _ivf_scan_kernel's.
    cold_blk = jnp.where(slot == 0, cbuf[0], cbuf[1])      # (m, d)
    xt = jnp.where(is_cold, cold_blk, x_ref[0])
    ip = jax.lax.dot_general(
        q_ref[:], xt, (((1,), (1,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )                                     # (q_tile, m)
    dist = -ip if ip_metric else xn_ref[:] - 2.0 * ip
    ids = ids_ref[:]                      # (1, m) — -1 marks pad/filtered
    probed = jnp.any(probes_ref[:] == lid, axis=1, keepdims=True)
    probed = jnp.logical_and(probed, lid < n_lists)
    dist = jnp.where((ids >= 0) & probed, dist, jnp.inf)

    kth = bestd[:, k - 1 : k]
    any_better = jnp.any(dist < kth)

    @pl.when(any_better)
    def _():
        cat_d = jnp.concatenate([bestd[:], dist], axis=1)
        cat_i = jnp.concatenate(
            [besti[:], jnp.broadcast_to(ids, dist.shape)], axis=1)
        new_d, new_i = _extract_topk(cat_d, cat_i, k)
        bestd[:] = new_d
        besti[:] = new_i

    @pl.when(j == n_steps - 1)
    def _():
        outd_ref[:] = -bestd[:] if ip_metric else bestd[:]
        outi_ref[:] = besti[:]


def _tier_scan_pallas(qf, hot_data, cold_data, hot_slot_map,
                      cold_slot_map, data_norms, indices, probes,
                      filter_words, *, k: int, metric: DistanceType,
                      interpret: bool, vmem_mb: int = 0):
    from raft_tpu.neighbors.filters import test_filter

    q, d = qf.shape
    n_lists = indices.shape[0]
    m = hot_data.shape[1]
    ip_metric = metric == DistanceType.InnerProduct
    if vmem_mb <= 0:
        vmem_mb = _default_vmem_mb()
    expect(hot_data.dtype == jnp.float32
           and cold_data.dtype == jnp.float32,
           "the tiered Pallas engine is f32-only — use engine='xla' "
           "for other storage dtypes")
    expect(filter_words is None
           or getattr(filter_words, "ndim", 1) == 1,
           "the tiered Pallas engine supports shared (1-D) filters "
           "only — use engine='xla' for per-query filter words")

    uniq = unique_lists(probes, n_lists)
    n_steps = uniq.shape[0]
    hot_fetch, cold_fetch, cold_seq = tier_fetch_plan(
        uniq, hot_slot_map, cold_slot_map, n_lists)

    # gathered id planes + shared-filter fold, exactly like ivf_scan
    # (the id/norm planes are fully resident, so the fold never
    # touches the cold tier)
    ids_g = jnp.take(indices, jnp.minimum(uniq, n_lists - 1), axis=0)
    if filter_words is not None:
        bits = test_filter(filter_words, ids_g)
        ids_g = jnp.where(bits & (ids_g >= 0), ids_g, -1)

    # lane/sublane alignment; no-ops on aligned serving layouts
    # (resolve_tier_engine degrades misaligned compiled runs — the
    # pad path is interpret mode's any-test-shape coverage)
    m_pad = -(-m // 8) * 8
    d_pad = -(-d // 128) * 128
    if m_pad != m or d_pad != d:
        hot_data = jnp.pad(hot_data,
                           ((0, 0), (0, m_pad - m), (0, d_pad - d)))
        cold_data = jnp.pad(cold_data,
                            ((0, 0), (0, m_pad - m), (0, d_pad - d)))
        data_norms = jnp.pad(data_norms, ((0, 0), (0, m_pad - m)),
                             constant_values=jnp.inf)
        ids_g = jnp.pad(ids_g, ((0, 0), (0, m_pad - m)),
                        constant_values=-1)
    p = probes.shape[1]
    p_pad = -(-p // 128) * 128

    fixed, per_q = _tier_vmem_plan(m_pad, d_pad, k)
    budget = (vmem_mb << 20) - fixed
    q_tile = min(max(8, (budget // per_q) // 8 * 8), -(-q // 8) * 8)
    q_pad = -(-q // q_tile) * q_tile

    qs = jnp.pad(qf.astype(jnp.float32),
                 ((0, q_pad - q), (0, d_pad - d)))
    probes_p = jnp.pad(probes.astype(jnp.int32),
                       ((0, q_pad - q), (0, p_pad - p)),
                       constant_values=-1)

    kernel = functools.partial(_tier_scan_kernel, k=k, n_steps=n_steps,
                               n_lists=n_lists, ip_metric=ip_metric)
    hot_clamp = max(hot_data.shape[0] - 1, 0)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(q_pad // q_tile, n_steps),
        in_specs=[
            pl.BlockSpec((q_tile, p_pad),
                         lambda i, j, u, hf, cf, cs: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((q_tile, d_pad),
                         lambda i, j, u, hf, cf, cs: (i, 0),
                         memory_space=pltpu.VMEM),
            # the hot tier rides the scalar-prefetched dynamic index
            # map: step j streams hot slot hf[j]; cold steps HOLD the
            # previous value, so the pipeline elides their copy
            pl.BlockSpec((1, m_pad, d_pad),
                         lambda i, j, u, hf, cf, cs: (
                             jnp.minimum(hf[j], hot_clamp), 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m_pad),
                         lambda i, j, u, hf, cf, cs: (
                             jnp.minimum(u[j], n_lists - 1), 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m_pad),
                         lambda i, j, u, hf, cf, cs: (j, 0),
                         memory_space=pltpu.VMEM),
            # the cold tier stays put (host memory on TPU): the
            # kernel DMAs one list block at a time into the
            # double-buffered VMEM scratch — the only reads the host
            # link ever serves are probed cold blocks
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=(
            pl.BlockSpec((q_tile, k),
                         lambda i, j, u, hf, cf, cs: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((q_tile, k),
                         lambda i, j, u, hf, cf, cs: (i, 0),
                         memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((q_tile, k), jnp.float32),
            pltpu.VMEM((q_tile, k), jnp.int32),
            pltpu.VMEM((2, m_pad, d_pad), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    outd, outi = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((q_pad, k), jnp.float32),
            jax.ShapeDtypeStruct((q_pad, k), jnp.int32),
        ),
        compiler_params=_COMPILER_PARAMS(
            vmem_limit_bytes=vmem_mb << 20),
        interpret=interpret,
    )(uniq, hot_fetch, cold_fetch, cold_seq, probes_p, qs, hot_data,
      data_norms, ids_g, cold_data)
    return outd[:q], outi[:q]
