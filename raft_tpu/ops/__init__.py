"""Pallas TPU kernels for the hot ops (SURVEY.md §7 stage 2).

Each kernel has an interpret-mode path so the CPU test mesh can validate
numerics; on TPU hardware they compile to Mosaic.
"""

from raft_tpu.ops.bq_scan import bq_list_major_scan, resolve_bq_engine
from raft_tpu.ops.fused_topk import fused_knn, select_k_tiles
from raft_tpu.ops.ivf_scan import (
    list_major_scan,
    resolve_scan_engine,
    unique_lists,
)

__all__ = [
    "bq_list_major_scan",
    "fused_knn",
    "resolve_bq_engine",
    "select_k_tiles",
    "list_major_scan",
    "resolve_scan_engine",
    "unique_lists",
]
