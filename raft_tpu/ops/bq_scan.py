"""Fused estimate-then-rerank BQ probe scan — the list-major engine
family of :mod:`raft_tpu.neighbors.ivf_bq` (IVF-RaBitQ, PAPERS.md
arXiv 2602.23999, in the :mod:`raft_tpu.ops.ivf_scan` formulation).

The estimate-only BQ search pays twice: a calibrated over-fetch
multiplies the candidate traffic, and the exact re-rank is a SECOND
pass over rows the estimate pass just touched. The TPU-KNN roofline
methodology (PAPERS.md) says a bandwidth-bound scan that reads its
data twice is leaving half the machine idle — so this module fuses
the two stages into ONE list-major stream:

- grid over the probed-list union (:func:`raft_tpu.ops.ivf_scan
  .unique_lists` — the scalar-prefetched block index map of Ragged
  Paged Attention steering each step's HBM→VMEM DMA);
- **estimate** the whole query tile against the block's packed sign
  words by XOR+popcount: the rotated query quantizes to
  ``_QUERY_BITS`` uniform levels per (query, list), its bit-planes
  pack into int32 lane words, and each plane scores against the code
  words as ``⟨u_j, s⟩ = popcount(c) − popcount(u_j XOR c)`` — integer
  VPU work on 1/32nd the bytes of the raw vectors;
- **prune** with the RaBitQ error bound: a row whose estimate minus
  :func:`raft_tpu.neighbors.ivf_bq.estimator_margin` cannot beat the
  running k-th *exact* distance is finished — its raw vector is never
  read;
- **re-rank** the survivors against the raw-vector plane of the SAME
  list, DMA'd into VMEM scratch *only when the block has survivors*
  (``pl.when`` + manual async copy): one exact f32 MXU GEMM, merged
  into the VMEM running top-k via the ``_extract_topk`` network.

Each probed block therefore costs one stream of codes + corrections
(+ the raw vectors only when it still holds candidates) instead of a
full estimate pass plus a full gather-refine pass. The running top-k
warms itself: the first blocks re-rank everything, later blocks prune
almost everything.

Two parity-locked engines share the formulation (the ivf_scan
contract): ``pallas`` is the fused kernel, ``xla`` the same math as a
``lax.scan`` over the union (reads every block's vectors — the
portable correctness engine for CPU tier-1 and interpret-mode
coverage). Both use identical integer estimate math and identical
f32 assembly order, so their output ids are bit-identical."""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.core.validation import expect
from raft_tpu.distance.types import DistanceType
from raft_tpu.neighbors.ivf_bq import estimator_margin
from raft_tpu.ops.fused_topk import (
    _COMPILER_PARAMS,
    _default_vmem_mb,
    _extract_topk,
)
from raft_tpu.ops.ivf_scan import (
    _PALLAS_MAX_K,
    SCAN_ENGINES,
    _merge_smallest_id,
    unique_lists,
)

# uniform quantization levels of the rotated query inside the scan
# (RaBitQ's asymmetric query treatment): 4 bits keeps the
# quantization-noise term of the margin well under the rotation term
_QUERY_BITS = 4


def auto_query_bits(bits: int) -> int:
    """Query quantization width matched to the code-ladder width.

    At ``bits < 3`` the 4-bit query grid's noise term is already well under
    the code's own quantization error; at 3+ code bits the code estimate is
    sharp enough that the query grid becomes the dominant noise source, so
    widen it to 8 bits (the widest grid the i32 cross-term accumulators
    admit without overflow headroom changes).
    """
    return 4 if bits < 3 else 8


def resolve_bq_engine(engine: str, *, data=None, filter_words=None,
                      k=None, dim_ext: int = 0, bits: int = 1,
                      n_probes: int = 0, vmem_mb: int = 0) -> str:
    """Resolve an ivf_bq ``scan_engine`` param to a concrete engine.

    ``auto`` is the fused Pallas kernel on TPU and the fused XLA scan
    elsewhere — *when the index carries the raw-vector rerank plane*
    (``data``); a codes-only index (streaming build) always runs the
    legacy rank-major estimate scan. ``pallas`` degrades to ``xla``
    when the kernel's preconditions fail: per-query (2-D) filter words
    (the id-fold trick needs one shared id plane), non-f32 vector
    storage (the exact-rerank contract), ``k`` past the
    unrolled-merge budget, compiled-mode layout misalignment, or a
    VMEM budget the resident block + vector scratch cannot fit."""
    expect(engine in SCAN_ENGINES,
           f"scan_engine must be one of {SCAN_ENGINES}, got {engine!r}")
    if engine == "auto":
        engine = "pallas" if jax.default_backend() == "tpu" else "xla"
    if engine == "rank":
        return engine
    if data is None:
        # no rerank plane — the fused engines have nothing to re-rank
        return "rank"
    if engine != "pallas":
        return engine
    if filter_words is not None and getattr(filter_words, "ndim", 1) == 2:
        return "xla"
    if k is not None and k > _PALLAS_MAX_K:
        return "xla"
    if data.dtype != jnp.float32:
        return "xla"
    m_pad = -(-data.shape[1] // 8) * 8
    d_pad = -(-data.shape[2] // 128) * 128
    de_pad = -(-max(dim_ext, 1) // 128) * 128
    if jax.default_backend() == "tpu" and (
            m_pad != data.shape[1] or d_pad != data.shape[2]
            or de_pad != dim_ext):
        # compiled Mosaic would force a whole-tensor jnp.pad per call —
        # a full HBM read+write dwarfing the scan. Interpret mode (CPU
        # CI) keeps the pad path so any test shape is coverable.
        return "xla"
    if vmem_mb <= 0:
        vmem_mb = _default_vmem_mb()
    # THE kernel's own budget arithmetic (shared helper): the
    # double-buffered code/correction blocks + the raw-vector scratch
    # + margin must leave room for at least one minimal (8-row) query
    # tile. The probe-row term uses the kernel's p_pad when the caller
    # says n_probes (256 covers the unknown case only up to that
    # width).
    p_pad = -(-max(n_probes, 1) // 128) * 128 if n_probes else 256
    fixed, per_q = _vmem_plan(
        m_pad, d_pad, de_pad, p_pad, bits * max(dim_ext, 32) // 32,
        bits, k or _PALLAS_MAX_K)
    if fixed + 8 * per_q > vmem_mb << 20:
        return "xla"
    return engine


def _vmem_plan(m_pad: int, d_pad: int, de_pad: int, p_pad: int,
               words: int, bits: int, k: int):
    """The fused kernel's VMEM footprint model — ONE implementation
    shared by :func:`resolve_bq_engine` (the degrade decision) and
    ``_bq_scan_pallas`` (the query-tile sizing), so the two can never
    drift apart. ``fixed``: double-buffered code/correction blocks +
    the raw-vector scratch + a safety margin; ``per_q``: per query
    row the kernel keeps the rotated+raw query rows, the probe row,
    ~8 (m)-wide f32/int32 intermediates (est, margin, cand,
    xor/popcount planes, exact, merge concat) and the (k) running
    state."""
    fixed = (4 * m_pad * d_pad
             + 3 * m_pad * (4 * words + 4 * (bits + 3))
             + (2 << 20))
    per_q = 4 * (de_pad + d_pad + p_pad) + 32 * m_pad + 16 * k
    return fixed, per_q


def _popcount32(v):
    """Element-wise population count of int32 lanes by the SWAR ladder
    — add/shift/and only, so it lowers on the VPU and in every XLA
    backend identically (``lax.population_count`` has no Mosaic
    lowering guarantee)."""
    v = v - ((v >> 1) & 0x55555555)
    v = (v & 0x33333333) + ((v >> 2) & 0x33333333)
    v = (v + (v >> 4)) & 0x0F0F0F0F
    # byte-sum via multiply; counts ≤ 32 keep the sign bit clear
    return (v * 0x01010101) >> 24


def _estimate_block(qt, codes_wb, rnorm_row, cfac_t, *, dim_ext: int,
                    bits: int, query_bits: int):
    """Popcount estimate of the cross term ``Σ_l a_l·⟨q̃, s_l⟩`` for
    one list block — THE shared math of both engines (one function ⇒
    identical op order ⇒ bit-identical estimates, hence identical
    prune decisions).

    ``qt`` (q, ≥dim_ext) is the rotated query side (``q̃ = Rq − Rc``
    for L2, ``Rq`` for IP; lanes past ``dim_ext`` are padding and are
    masked). ``codes_wb`` (m, bits·W) are the block's packed sign
    words, ``rnorm_row`` (1, m) and ``cfac_t`` (bits, m) the
    correction factors. Returns ``(cross (q, m) f32, delta (q, 1))``
    — ``delta`` is the query-quantization step the margin prices.

    Math: with ``q̃_i = lo + Δ·u_i + ε_i`` (uniform levels) and sign
    words ``s``: ``⟨q̃, s⟩ = Δ·⟨u, s⟩ + lo·Σs + ⟨ε, s⟩`` where
    ``⟨u, s⟩ = Σ_j 2^j·(popcount(c) − popcount(u_j XOR c))`` summed
    over lane words and ``Σs = 2·popcount(c) − D`` — exact integers;
    only the ``⟨ε, s⟩`` rounding noise survives into the margin."""
    w_cnt = dim_ext // 32
    lane = jax.lax.broadcasted_iota(jnp.int32, qt.shape, 1)
    inb = lane < dim_ext
    lo = jnp.min(jnp.where(inb, qt, jnp.inf), axis=1, keepdims=True)
    hi = jnp.max(jnp.where(inb, qt, -jnp.inf), axis=1, keepdims=True)
    levels = (1 << query_bits) - 1
    delta = jnp.maximum((hi - lo) / levels, 1e-30)
    u = jnp.round((qt - lo) / delta).astype(jnp.int32)
    u = jnp.clip(jnp.where(inb, u, 0), 0, levels)
    word = lane // 32
    shift = lane - word * 32
    # packed query bit-planes: one int32 lane word per (plane, word)
    uw = []
    for jbit in range(query_bits):
        sh = ((u >> jbit) & 1) << shift
        uw.append([jnp.sum(jnp.where(word == w, sh, 0), axis=1,
                           keepdims=True, dtype=jnp.int32)
                   for w in range(w_cnt)])
    m = codes_wb.shape[0]
    ct = jnp.transpose(codes_wb)                  # (bits·W, m)
    cross = jnp.zeros((qt.shape[0], m), jnp.float32)
    for lev in range(bits):
        pcc = jnp.zeros((1, m), jnp.int32)
        for w in range(w_cnt):
            pcc = pcc + _popcount32(
                ct[lev * w_cnt + w : lev * w_cnt + w + 1, :])
        ius = jnp.zeros((qt.shape[0], m), jnp.int32)
        for jbit in range(query_bits):
            acc = jnp.zeros((qt.shape[0], m), jnp.int32)
            for w in range(w_cnt):
                cw = ct[lev * w_cnt + w : lev * w_cnt + w + 1, :]
                acc = acc + _popcount32(
                    jnp.bitwise_xor(uw[jbit][w], cw))
            ius = ius + ((pcc - acc) << jbit)
        ssum = (2 * pcc - dim_ext).astype(jnp.float32)
        qs = delta * ius.astype(jnp.float32) + lo * ssum
        a = rnorm_row * cfac_t[lev : lev + 1, :]
        cross = cross + a * qs
    return cross, delta


def _block_estimate(qrot, crot, rnorm_row, errw_row, cfac_t, codes_wb,
                    *, dim_ext: int, bits: int, query_bits: int,
                    epsilon: float, ip_metric: bool):
    """Min-space estimate + margin for one block, shared by both
    engines. ``crot`` is the (1, D) rotated center row. Returns
    ``(est (q, m), margin (q, m))``."""
    if ip_metric:
        qt = qrot
        base_ip = jnp.sum(qrot * crot, axis=1, keepdims=True)  # ⟨q, c⟩
    else:
        qt = qrot - crot
    cross, delta = _estimate_block(qt, codes_wb, rnorm_row, cfac_t,
                                   dim_ext=dim_ext, bits=bits,
                                   query_bits=query_bits)
    lane = jax.lax.broadcasted_iota(jnp.int32, qt.shape, 1)
    qc2 = jnp.sum(jnp.where(lane < dim_ext, jnp.square(qt), 0.0),
                  axis=1, keepdims=True)
    qcn = jnp.sqrt(qc2)
    if ip_metric:
        est = -(base_ip + cross)
    else:
        rn2 = jnp.square(rnorm_row)
        est = jnp.maximum(qc2, 0.0) + rn2 - 2.0 * cross
    margin = estimator_margin(qcn, rnorm_row, errw_row, delta,
                              dim_ext, epsilon)
    return est, margin


def bq_record_geometry(words: int, bits: int):
    """Row geometry of the packed per-row BQ record plane used by the
    graph-traversal estimator (:mod:`raft_tpu.ops.beam_search`).

    A record is one dataset row's complete estimator input laid out
    contiguously so a beam gather touches ONE aligned slice per
    candidate instead of four strided planes: ``words`` int32 code
    words, then ``rnorm | cfac[bits] | errw`` as f32 bitcast to int32
    lanes. Records pad to a 4-lane multiple (``rec_pad``) and
    ``rpt = 128/gcd(rec_pad, 128)`` records tile one 128-lane-aligned
    plane row of ``pw`` lanes — every record starts on a lane boundary
    a DMA slice can address. Returns ``(rec, rec_pad, rpt, pw)``."""
    rec = words + bits + 2
    rec_pad = -(-rec // 4) * 4
    rpt = 128 // math.gcd(rec_pad, 128)
    return rec, rec_pad, rpt, rpt * rec_pad


def pack_bq_records(codes, rnorm, cfac, errw):
    """Pack per-row estimator inputs into the aligned record plane of
    :func:`bq_record_geometry` — ``(ceil(n/rpt), rpt·rec_pad)`` int32.
    Pad rows are all-zero; a zero record decodes to rnorm = 0 codes,
    which estimate-survives nothing once the candidate mask (ids ≥ 0)
    is applied, so padding never needs a side channel."""
    n, words = codes.shape
    bits = cfac.shape[1]
    _, rec_pad, rpt, _ = bq_record_geometry(words, bits)
    scal = jnp.concatenate(
        [rnorm[:, None], cfac, errw[:, None]], axis=1).astype(jnp.float32)
    row = jnp.concatenate(
        [codes.astype(jnp.int32),
         jax.lax.bitcast_convert_type(scal, jnp.int32)], axis=1)
    n_pad = -(-n // rpt) * rpt
    row = jnp.pad(row, ((0, n_pad - n), (0, rec_pad - row.shape[1])))
    return row.reshape(n_pad // rpt, rpt * rec_pad)


def unpack_bq_records(records, n: int, words: int, bits: int):
    """Exact inverse of :func:`pack_bq_records` — returns
    ``(codes (n, words) i32, rnorm (n,), cfac (n, bits), errw (n,))``.
    The XLA beam twin unpacks the SAME plane the kernel gathers from,
    so both engines estimate from identical bit patterns."""
    _, rec_pad, _, _ = bq_record_geometry(words, bits)
    rows = records.reshape(-1, rec_pad)[:n]
    codes = rows[:, :words]
    scal = jax.lax.bitcast_convert_type(
        rows[:, words:words + bits + 2], jnp.float32)
    return codes, scal[:, 0], scal[:, 1:1 + bits], scal[:, 1 + bits]


def bq_list_major_scan(qf, qrot, centers_rot, codes, rnorm, cfac, errw,
                       indices, data, data_norms, probes,
                       filter_words=None, init_d=None, init_i=None,
                       cold_planes=None, hot_slot_map=None,
                       cold_slot_map=None, *,
                       k: int, metric: DistanceType, epsilon: float,
                       engine: str = "xla", query_bits: int = _QUERY_BITS,
                       interpret: bool = False):
    """Run the fused estimate-then-rerank scan; returns the running
    top-k ``(best_d, best_i)`` with **exact** distances (full squared
    L2 with +inf pads, raw inner products with -inf pads for IP — the
    caller's metric epilog only handles the sqrt family).

    Both engines break distance ties by smallest dataset id (the
    ``_extract_topk`` order) and share one estimate/margin/prune code
    path, so their output ids are bit-identical. ``init_d``/``init_i``
    optionally provide the (q, k) running-state storage for the XLA
    engine (values are reset; the serving path donates them); the
    Pallas kernel keeps its state in VMEM scratch and ignores them.

    Probe slots carrying the sentinel value ``n_lists`` are masked
    probes (ragged rows, shard-unowned lists); both engines ignore
    them through the shared membership predicate.

    ``cold_planes`` (graftcast — the tiered BQ cold engine)
    optionally provides the cold halves of the five per-row record
    planes as ``(cold_codes, cold_rnorm, cold_cfac, cold_errw,
    cold_data)``; ``codes``/``rnorm``/``cfac``/``errw``/``data`` are
    then the HOT halves and each step selects every plane of its
    list from ONE tier via the shared
    ``(hot_slot_map, cold_slot_map)`` pair (:func:`raft_tpu.ops
    .tier_scan.tier_slot_pair` — one slot decision per step, so the
    estimate and its rerank rows can never split across tiers). XLA
    engine only: the dual-source fused kernel is the on-chip
    follow-on (``resolve_tier_bq_engine`` degrades)."""
    expect(engine in ("pallas", "xla"),
           f"bq_list_major_scan engine must be pallas|xla, got "
           f"{engine!r}")
    expect(data is not None and data_norms is not None,
           "fused BQ scan needs the raw-vector rerank plane "
           "(build with store_vectors=True)")
    if engine == "pallas":
        expect(cold_planes is None,
               "the fused BQ Pallas kernel has no dual-tier source "
               "yet — tiered BQ resolves to engine='xla' "
               "(resolve_tier_bq_engine)")
        return _bq_scan_pallas(
            qf, qrot, centers_rot, codes, rnorm, cfac, errw, indices,
            data, data_norms, probes, filter_words, k=k, metric=metric,
            epsilon=epsilon, query_bits=query_bits, interpret=interpret)
    return _bq_scan_xla(
        qf, qrot, centers_rot, codes, rnorm, cfac, errw, indices, data,
        data_norms, probes, filter_words, init_d, init_i,
        cold_planes=cold_planes, hot_slot_map=hot_slot_map,
        cold_slot_map=cold_slot_map, k=k,
        metric=metric, epsilon=epsilon, query_bits=query_bits)


# ---------------------------------------------------------------------------
# XLA engine — the portable parity reference
# ---------------------------------------------------------------------------


def _bq_scan_xla(qf, qrot, centers_rot, codes, rnorm, cfac, errw,
                 indices, data, data_norms, probes, filter_words,
                 init_d=None, init_i=None, cold_planes=None,
                 hot_slot_map=None, cold_slot_map=None, *, k: int,
                 metric: DistanceType, epsilon: float, query_bits: int):
    from raft_tpu.neighbors.filters import test_filter

    q, d = qf.shape
    # with a tiered record plane, codes.shape[0] is the HOT slot
    # count, not the list count — the resident id plane is the
    # authority (it is never tiered: ids gather per unique list)
    n_lists = indices.shape[0]
    tiered = cold_planes is not None
    if tiered:
        cold_codes, cold_rnorm, cold_cfac, cold_errw, cold_data = \
            cold_planes
    dim_ext = centers_rot.shape[1]
    bits = cfac.shape[2]
    ip_metric = metric == DistanceType.InnerProduct
    # OFF-TPU ONLY: pad the contraction dims to the SAME lane
    # multiples the Pallas kernel uses, so both engines run
    # identically-shaped f32 dots and reductions — the ulp-level
    # agreement the prune decisions (and therefore the
    # bit-parity-on-ids contract) rest on, at interpret-mode test
    # shapes. On TPU a misaligned dim means the kernel was excluded
    # by resolve_bq_engine anyway (there is nothing to bit-match),
    # and padding there would re-materialize the WHOLE rerank plane
    # per call — the exact cost the degrade rule exists to avoid.
    if jax.default_backend() != "tpu":
        d_pad = -(-d // 128) * 128
        de_pad = -(-dim_ext // 128) * 128
        if d_pad != d:
            qf = jnp.pad(qf, ((0, 0), (0, d_pad - d)))
            data = jnp.pad(data, ((0, 0), (0, 0), (0, d_pad - d)))
            if tiered:
                # the cold rerank plane must pad identically or the
                # hot/cold dots diverge from the all-HBM reference
                cold_data = jnp.pad(
                    cold_data, ((0, 0), (0, 0), (0, d_pad - d)))
        if de_pad != dim_ext:
            qrot = jnp.pad(qrot, ((0, 0), (0, de_pad - dim_ext)))
            centers_rot = jnp.pad(centers_rot,
                                  ((0, 0), (0, de_pad - dim_ext)))
    uniq = unique_lists(probes, n_lists)

    # gathered id planes, one per unique list; a shared (1-D) bitset
    # filter folds in here exactly like ivf_scan (filtered slot → id
    # -1 → padding); per-query (2-D) filters stay live and test inside
    # the step
    ids_g = jnp.take(indices, jnp.minimum(uniq, n_lists - 1), axis=0)
    filter_2d = (filter_words is not None
                 and getattr(filter_words, "ndim", 1) == 2)
    if filter_words is not None and not filter_2d:
        fbits = test_filter(filter_words, ids_g)
        ids_g = jnp.where(fbits & (ids_g >= 0), ids_g, -1)

    qn = jnp.sum(jnp.square(qf), axis=1, keepdims=True)

    def step(carry, xs):
        best_d, best_i = carry
        lid, ids_row = xs
        lidc = jnp.minimum(lid, n_lists - 1)      # sentinel-safe index
        if tiered:
            from raft_tpu.ops.tier_scan import (
                tier_block_select,
                tier_slot_pair,
            )

            # ONE slot decision per list — the estimate planes and
            # the rerank rows always come from the same tier
            hs, cs = tier_slot_pair(hot_slot_map, cold_slot_map,
                                    lidc)
            codes_b = tier_block_select(codes, cold_codes, hs, cs)
            rn = tier_block_select(rnorm, cold_rnorm, hs, cs)
            cf = tier_block_select(cfac, cold_cfac, hs, cs)
            ew = tier_block_select(errw, cold_errw, hs, cs)
        else:
            codes_b = jax.lax.dynamic_index_in_dim(codes, lidc, 0,
                                                   False)
            rn = jax.lax.dynamic_index_in_dim(rnorm, lidc, 0, False)
            cf = jax.lax.dynamic_index_in_dim(cfac, lidc, 0, False)
            ew = jax.lax.dynamic_index_in_dim(errw, lidc, 0, False)
        crot = jax.lax.dynamic_index_in_dim(centers_rot, lidc, 0, True)
        est, margin = _block_estimate(
            qrot, crot, rn[None, :], ew[None, :], jnp.transpose(cf),
            codes_b, dim_ext=dim_ext, bits=bits, query_bits=query_bits,
            epsilon=epsilon, ip_metric=ip_metric)
        ids_b = jnp.broadcast_to(ids_row[None, :], est.shape)
        probed = jnp.any(probes == lid, axis=1) & (lid < n_lists)
        ok = (ids_b >= 0) & probed[:, None]
        if filter_2d:
            ok = ok & test_filter(filter_words, ids_b)
        est = jnp.where(ok, est, jnp.inf)
        # the fused prune: only rows whose estimate (minus the error
        # bound) still beats the running k-th exact distance re-rank
        kth = best_d[:, k - 1 : k]
        cand = (est - margin) < kth
        if tiered:
            xb = tier_block_select(data, cold_data, hs, cs)
        else:
            xb = jax.lax.dynamic_index_in_dim(data, lidc, 0, False)
        xn = jax.lax.dynamic_index_in_dim(data_norms, lidc, 0, False)
        ipx = jax.lax.dot_general(
            qf, xb.astype(jnp.float32), (((1,), (1,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )                                                      # (q, m)
        if ip_metric:
            exact = -ipx
        else:
            exact = jnp.maximum(qn + xn[None, :] - 2.0 * ipx, 0.0)
        exact = jnp.where(cand, exact, jnp.inf)
        return _merge_smallest_id(best_d, best_i, exact, ids_b, k), None

    init = (
        jnp.full((q, k), jnp.inf, jnp.float32) if init_d is None
        else jnp.full_like(init_d, jnp.inf),
        jnp.full((q, k), -1, jnp.int32) if init_i is None
        else jnp.full_like(init_i, -1),
    )
    (best_d, best_i), _ = jax.lax.scan(step, init, (uniq, ids_g))
    if ip_metric:
        best_d = -best_d          # inf (unfilled) -> -inf, ip exact
    return best_d, best_i


# ---------------------------------------------------------------------------
# Pallas engine — the fused kernel
# ---------------------------------------------------------------------------


def _bq_scan_kernel(u_ref, probes_ref, qrot_ref, qf_ref, crot_ref,
                    codes_ref, rn_ref, cf_ref, ew_ref, xn_ref, ids_ref,
                    data_ref, outd_ref, outi_ref, bestd, besti, vec,
                    sem, *, k: int, n_steps: int, n_lists: int,
                    ip_metric: bool, dim_ext: int, bits: int,
                    query_bits: int, epsilon: float):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        bestd[:] = jnp.full_like(bestd, jnp.inf)
        besti[:] = jnp.full_like(besti, -1)

    lid = u_ref[j]                        # scalar-prefetched list id
    lidc = jnp.minimum(lid, n_lists - 1)
    # estimate the whole tile against the packed sign words —
    # XOR+popcount on int32 lanes, 1/32nd the bytes of the vectors
    est, margin = _block_estimate(
        qrot_ref[:], crot_ref[:], rn_ref[:], ew_ref[:],
        jnp.transpose(cf_ref[0]), codes_ref[0], dim_ext=dim_ext,
        bits=bits, query_bits=query_bits, epsilon=epsilon,
        ip_metric=ip_metric)
    ids = ids_ref[:]                      # (1, m) — -1 marks pad/filtered
    probed = jnp.any(probes_ref[:] == lid, axis=1, keepdims=True)
    probed = jnp.logical_and(probed, lid < n_lists)
    est = jnp.where((ids >= 0) & probed, est, jnp.inf)

    # the fused prune: does ANY row of this block survive the bound?
    kth = bestd[:, k - 1 : k]
    cand = (est - margin) < kth
    any_cand = jnp.any(cand)

    @pl.when(any_cand)
    def _():
        # survivors exist — stream the block's raw vectors into VMEM
        # scratch (the ONLY vector read of the whole search; a fully
        # pruned block never touches them) and re-rank exactly
        cp = pltpu.make_async_copy(data_ref.at[pl.ds(lidc, 1)], vec,
                                   sem)
        cp.start()
        cp.wait()
        qt = qf_ref[:]
        ipx = jax.lax.dot_general(
            qt, vec[0], (((1,), (1,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )                                 # (q_tile, m)
        if ip_metric:
            exact = -ipx
        else:
            qn = jnp.sum(jnp.square(qt), axis=1, keepdims=True)
            exact = jnp.maximum(qn + xn_ref[:] - 2.0 * ipx, 0.0)
        exact = jnp.where(cand, exact, jnp.inf)
        cat_d = jnp.concatenate([bestd[:], exact], axis=1)
        cat_i = jnp.concatenate(
            [besti[:], jnp.broadcast_to(ids, exact.shape)], axis=1)
        new_d, new_i = _extract_topk(cat_d, cat_i, k)
        bestd[:] = new_d
        besti[:] = new_i

    @pl.when(j == n_steps - 1)
    def _():
        outd_ref[:] = -bestd[:] if ip_metric else bestd[:]
        outi_ref[:] = besti[:]


def _bq_scan_pallas(qf, qrot, centers_rot, codes, rnorm, cfac, errw,
                    indices, data, data_norms, probes, filter_words, *,
                    k: int, metric: DistanceType, epsilon: float,
                    query_bits: int, interpret: bool, vmem_mb: int = 0):
    from raft_tpu.neighbors.filters import test_filter

    q, d = qf.shape
    n_lists, m, words = codes.shape
    dim_ext = centers_rot.shape[1]
    bits = cfac.shape[2]
    ip_metric = metric == DistanceType.InnerProduct
    if vmem_mb <= 0:
        vmem_mb = _default_vmem_mb()

    uniq = unique_lists(probes, n_lists)
    n_steps = uniq.shape[0]

    # gathered id planes + shared-filter fold, exactly like ivf_scan.
    # Per-query (2-D) filters CANNOT fold into the shared per-list
    # planes — resolve_bq_engine degrades them to xla, and a direct
    # caller bypassing it must hit this wall, not silent wrong masks
    expect(filter_words is None
           or getattr(filter_words, "ndim", 1) == 1,
           "the fused BQ Pallas engine supports shared (1-D) filters "
           "only — use engine='xla' for per-query filter words")
    ids_g = jnp.take(indices, jnp.minimum(uniq, n_lists - 1), axis=0)
    if filter_words is not None:
        fbits = test_filter(filter_words, ids_g)
        ids_g = jnp.where(fbits & (ids_g >= 0), ids_g, -1)

    # lane/sublane alignment; all no-ops on aligned serving layouts
    # (padded_extent rounds max_list_size to 8; resolve_bq_engine
    # degrades misaligned compiled runs — the pad path is interpret
    # mode's any-test-shape coverage)
    m_pad = -(-m // 8) * 8
    d_pad = -(-d // 128) * 128
    de_pad = -(-dim_ext // 128) * 128
    if m_pad != m:
        codes = jnp.pad(codes, ((0, 0), (0, m_pad - m), (0, 0)))
        rnorm = jnp.pad(rnorm, ((0, 0), (0, m_pad - m)))
        cfac = jnp.pad(cfac, ((0, 0), (0, m_pad - m), (0, 0)))
        errw = jnp.pad(errw, ((0, 0), (0, m_pad - m)))
        data_norms = jnp.pad(data_norms, ((0, 0), (0, m_pad - m)),
                             constant_values=jnp.inf)
        ids_g = jnp.pad(ids_g, ((0, 0), (0, m_pad - m)),
                        constant_values=-1)
    if m_pad != m or d_pad != d:
        data = jnp.pad(data, ((0, 0), (0, m_pad - m), (0, d_pad - d)))
    crot = centers_rot
    if de_pad != dim_ext:
        crot = jnp.pad(crot, ((0, 0), (0, de_pad - dim_ext)))
    p = probes.shape[1]
    p_pad = -(-p // 128) * 128

    # query-tile sizing from the shared VMEM footprint model (the
    # same arithmetic resolve_bq_engine admitted this shape on)
    fixed, per_q = _vmem_plan(m_pad, d_pad, de_pad, p_pad, words,
                              bits, k)
    budget = (vmem_mb << 20) - fixed
    q_tile = min(max(8, (budget // per_q) // 8 * 8), -(-q // 8) * 8)
    q_pad = -(-q // q_tile) * q_tile

    qs = jnp.pad(qf.astype(jnp.float32), ((0, q_pad - q), (0, d_pad - d)))
    qr = jnp.pad(qrot.astype(jnp.float32),
                 ((0, q_pad - q), (0, de_pad - dim_ext)))
    # pad probe rows/cols with -1: a pad query probes nothing, so its
    # running state stays empty and its rows are sliced away
    probes_p = jnp.pad(probes.astype(jnp.int32),
                       ((0, q_pad - q), (0, p_pad - p)),
                       constant_values=-1)

    kernel = functools.partial(
        _bq_scan_kernel, k=k, n_steps=n_steps, n_lists=n_lists,
        ip_metric=ip_metric, dim_ext=dim_ext, bits=bits,
        query_bits=query_bits, epsilon=epsilon)
    clamp = n_lists - 1
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(q_pad // q_tile, n_steps),
        in_specs=[
            pl.BlockSpec((q_tile, p_pad), lambda i, j, u: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((q_tile, de_pad), lambda i, j, u: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((q_tile, d_pad), lambda i, j, u: (i, 0),
                         memory_space=pltpu.VMEM),
            # the scalar-prefetched dynamic index maps: step j streams
            # list u[j]'s codes/corrections; the sentinel clamps to a
            # real list and is masked by the membership predicate
            pl.BlockSpec((1, de_pad),
                         lambda i, j, u: (jnp.minimum(u[j], clamp), 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m_pad, words),
                         lambda i, j, u: (jnp.minimum(u[j], clamp), 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m_pad),
                         lambda i, j, u: (jnp.minimum(u[j], clamp), 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m_pad, bits),
                         lambda i, j, u: (jnp.minimum(u[j], clamp), 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m_pad),
                         lambda i, j, u: (jnp.minimum(u[j], clamp), 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m_pad),
                         lambda i, j, u: (jnp.minimum(u[j], clamp), 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m_pad), lambda i, j, u: (j, 0),
                         memory_space=pltpu.VMEM),
            # the raw-vector plane stays in HBM: the kernel DMAs one
            # list block into VMEM scratch only when the prune left
            # survivors — the conditional read the one-stream
            # accounting is about
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=(
            pl.BlockSpec((q_tile, k), lambda i, j, u: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((q_tile, k), lambda i, j, u: (i, 0),
                         memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((q_tile, k), jnp.float32),
            pltpu.VMEM((q_tile, k), jnp.int32),
            pltpu.VMEM((1, m_pad, d_pad), jnp.float32),
            pltpu.SemaphoreType.DMA,
        ],
    )
    outd, outi = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((q_pad, k), jnp.float32),
            jax.ShapeDtypeStruct((q_pad, k), jnp.int32),
        ),
        compiler_params=_COMPILER_PARAMS(
            vmem_limit_bytes=vmem_mb << 20),
        interpret=interpret,
    )(uniq, probes_p, qr, qs, crot, codes, rnorm, cfac, errw,
      data_norms, ids_g, data)
    return outd[:q], outi[:q]
