"""Single-dispatch CAGRA beam search — the TPU re-design of the
reference's persistent single-CTA search kernel
(``detail/cagra/search_single_cta_kernel-inl.cuh``; plan notes
``search_plan.cuh:40-49``).

The XLA path (``neighbors/cagra._search_batch``) walks the graph with a
``lax.while_loop`` whose every iteration gathers ``w·deg`` dataset rows
from HBM — row gathers and per-iteration loop sync are exactly what TPUs
do worst. This kernel instead runs the WHOLE walk in one ``pallas_call``:

- the (quantizable) **dataset lives in VMEM** for the kernel's lifetime
  when it fits (v5e has 128 MB; 200k×128 bf16 = 51 MB) — candidate rows
  become dynamic VMEM loads, ~cycles each, no HBM latency, no XLA
  gather op. Bigger datasets (SIFT-1M and up) stay **HBM-resident**
  (``ds_mode="hbm"``): candidate rows are DMA'd in per-query batches,
  double-buffered so query ``b+1``'s row fetches fly while query ``b``
  scores — the true analog of the reference's any-size persistent
  kernel, which streams dataset rows from global memory the same way;
- the **graph stays in HBM**; only the ``w`` chosen parents' adjacency
  rows are DMA'd per iteration (w·deg·4 B per query — hundreds of bytes,
  latency hidden behind scoring);
- parent selection, id-dedup, and the top-L merge are the same
  extract-min VPU network as ``ops/fused_topk`` — no sorts anywhere;
- queries run in blocks of ``block_q`` per grid step, so scoring is a
  few small MXU contractions per iteration rather than scalar work;
- **per-row iteration budgets** arrive as a scalar-prefetched vector
  (``row_iters``): a row past its budget contributes inert no-op
  iterations, so one compiled executable serves every per-request
  ``max_iterations`` in a ragged batch bit-identically to a solo run;
- **BQ-coded traversal** (``bq_records``): gathered neighbors are first
  scored by the RaBitQ XOR+popcount estimate against a packed per-row
  record plane (:func:`raft_tpu.ops.bq_scan.bq_record_geometry`), and
  the raw dataset rows of a query's candidate batch are fetched ONLY
  when some candidate's estimate-minus-margin beats the running L-th
  exact distance (``pl.when`` conditional DMA — the bq_scan discipline
  on the neighbor-gather path). HBM traffic for the non-survivor
  majority drops from full-precision rows to code records.

Scope (the wrapper in ``neighbors/cagra`` falls back to the XLA path
otherwise): L2Expanded/L2SqrtExpanded/InnerProduct, f32/bf16/int8
dataset, ``dim % 128 == 0``, no sample filter. Any dataset size: the
VMEM budget only decides residency, not validity.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.core.validation import expect
from raft_tpu.distance.types import DistanceType
from raft_tpu.ops.bq_scan import _block_estimate, bq_record_geometry
from raft_tpu.ops.fused_topk import _COMPILER_PARAMS
from raft_tpu.neighbors._exact import dedup_candidate_mask
from raft_tpu.ops.fused_topk import _default_vmem_mb, _extract_topk

_SUPPORTED = (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
              DistanceType.InnerProduct)


def beam_search_fits(n: int, dim: int, itemsize: int,
                     vmem_mb: int = 0, extra_bytes: int = 0) -> bool:
    """Whether (n, dim) fits the VMEM-resident dataset budget (with
    ~8 MB headroom for the kernel's scratch and queries). Since the
    HBM-resident mode landed this decides *placement* (``ds_mode``
    auto), not whether the kernel applies at all. ``extra_bytes``
    charges co-resident planes (the BQ record plane) to the same
    budget."""
    if vmem_mb <= 0:
        vmem_mb = _default_vmem_mb()
    return n * dim * itemsize + extra_bytes <= (vmem_mb - 8) * 1024 * 1024


def pad_graph(graph) -> jax.Array:
    """Pad adjacency rows to the next 128 multiple (lane-aligned DMA
    unit) with -1 fill.  Call once per index when searching in query
    tiles; ``beam_search`` pads unpadded graphs itself otherwise."""
    deg = graph.shape[1]
    Gp = -(-deg // 128) * 128
    if Gp == deg:
        return graph
    return jnp.pad(graph, ((0, 0), (0, Gp - deg)), constant_values=-1)


def _beam_kernel(riters_ref, q_ref, seeds_ref, ds_ref, graph_ref, *rest,
                 L: int, w: int, k: int, C: int, deg: int, Gp: int,
                 max_iters: int, ip_metric: bool, ds_vmem: bool,
                 bq_bits: int, bq_query_bits: int, bq_epsilon: float):
    use_bq = bq_bits > 0
    pos = 0
    if use_bq:
        qrot_ref, crot_ref, rec_ref = rest[pos:pos + 3]
        pos += 3
    outd_ref, outi_ref = rest[pos:pos + 2]
    pos += 2
    cand_ref, cand_sm, dist_ref, rows_ref, gsm, sem = rest[pos:pos + 6]
    pos += 6
    if use_bq:
        bqtiles_ref, surv_ref = rest[pos:pos + 2]
        pos += 2
    dsem = rest[pos:]

    B, d = q_ref.shape
    qf = q_ref[:].astype(jnp.float32)                       # (B, d)
    qn = jnp.sum(jnp.square(qf), axis=1, keepdims=True)     # (B, 1)
    # bf16- and int8-origin rows multiply exactly in the f32
    # accumulator at DEFAULT (|int8| <= 127 is bf16-exact); f32 rows
    # need HIGHEST — the same exact-kNN choice as
    # fused_topk._knn_kernel and _exact.gathered_distances
    prec = (jax.lax.Precision.HIGHEST if ds_ref.dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)

    # per-row iteration budget: B scalar SMEM reads select into a
    # (B, 1) lane vector the loop body compares its index against
    base = pl.program_id(0) * B
    rowi = jax.lax.broadcasted_iota(jnp.int32, (B, 1), 0)
    it_vec = jnp.zeros((B, 1), jnp.int32)
    for b in range(B):
        it_vec = jnp.where(rowi == b, riters_ref[base + b], it_vec)

    if use_bq:
        words = bq_bits * d // 32
        _, rec_pad, rpt, _ = bq_record_geometry(words, bq_bits)

    def score_rows(b, rows):
        """(C, d) gathered rows -> min-form distances into dist_ref[b]
        via two small MXU contractions."""
        ip = jax.lax.dot_general(
            qf[b:b + 1], rows, (((1,), (1,)), ((), ())),
            precision=prec,
            preferred_element_type=jnp.float32)             # (1, C)
        if ip_metric:
            dist_ref[pl.ds(b, 1), :] = -ip
        else:
            rn = jax.lax.dot_general(
                jnp.ones((1, d), jnp.float32), rows * rows,
                (((1,), (1,)), ((), ())),
                precision=prec,
                preferred_element_type=jnp.float32)         # (1, C)
            dist_ref[pl.ds(b, 1), :] = jnp.maximum(
                rn - 2.0 * ip + qn[b], 0.0)

    def estimate_cand(cand, dvals):
        """BQ phase: per query, gather each candidate's packed record
        tile (dynamic VMEM loads — the plane is VMEM-resident), select
        the record's lane window, and run the shared
        :func:`raft_tpu.ops.bq_scan._block_estimate` math. A candidate
        survives iff its estimate minus the RaBitQ margin could still
        beat the query's running L-th exact distance."""
        for b in range(B):
            def gtile(c, _):
                tid = cand_sm[b, c] // rpt
                bqtiles_ref[pl.ds(c, 1), :] = rec_ref[pl.ds(tid, 1), :]
                return 0
            jax.lax.fori_loop(0, C, gtile, 0, unroll=1)
            tiles = bqtiles_ref[:]                          # (C, PW)
            offc = jnp.transpose(jnp.maximum(cand[b:b + 1], 0) % rpt)
            recs = tiles[:, 0:rec_pad]
            for o in range(1, rpt):
                recs = jnp.where(
                    offc == o, tiles[:, o * rec_pad:(o + 1) * rec_pad],
                    recs)                                   # (C, rec_pad)
            codes_wb = recs[:, :words]
            scal = jax.lax.bitcast_convert_type(
                recs[:, words:words + bq_bits + 2], jnp.float32)
            rnorm_row = jnp.transpose(scal[:, 0:1])         # (1, C)
            cfac_t = jnp.transpose(scal[:, 1:1 + bq_bits])  # (bits, C)
            errw_row = jnp.transpose(scal[:, 1 + bq_bits:2 + bq_bits])
            est, margin = _block_estimate(
                qrot_ref[b:b + 1].astype(jnp.float32), crot_ref[:],
                rnorm_row, errw_row, cfac_t, codes_wb,
                dim_ext=d, bits=bq_bits, query_bits=bq_query_bits,
                epsilon=bq_epsilon, ip_metric=ip_metric)
            kth = dvals[b:b + 1, L - 1:L]
            surv = ((est - margin) < kth) & (cand[b:b + 1] >= 0)
            surv_ref[pl.ds(b, 1), :] = surv.astype(jnp.int32)

    def score_cand(cand, dvals):
        """(B, C) candidate ids -> (B, C) min-form distances.

        VMEM-resident dataset: dynamic VMEM row loads (cycles each).
        HBM-resident dataset: per-query DMA batches, double-buffered —
        query b+1's C row fetches are in flight on the other
        buffer/semaphore while query b's rows score on the MXU.

        With BQ traversal the estimate phase runs first and a query's
        raw-row batch is gathered/DMA'd ONLY when it still holds an
        estimate-survivor — non-survivor batches cost codes, not rows."""
        # ids must be scalars for dynamic addressing: VMEM -> SMEM.
        # Invalid ids (-1) are clamped for the gather only — compiled
        # Mosaic has no OOB clamp; masking happens on the way out.
        cand_ref[:] = jnp.maximum(cand, 0)
        cp = pltpu.make_async_copy(cand_ref, cand_sm, sem)
        cp.start()
        cp.wait()
        if use_bq:
            estimate_cand(cand, dvals)

            def anyb(b):
                return jnp.any(surv_ref[pl.ds(b, 1), :] == 1)
        if ds_vmem:
            for b in range(B):
                def scoreb(b=b):
                    def gather(c, _):
                        rid = cand_sm[b, c]
                        rows_ref[pl.ds(c, 1), :] = ds_ref[pl.ds(rid, 1), :]
                        return 0
                    # Mosaic lowers fori_loop only at unroll=1 or a full
                    # unroll; partial unrolls are rejected at compile
                    # time.
                    jax.lax.fori_loop(0, C, gather, 0, unroll=1)
                    score_rows(b, rows_ref[:].astype(jnp.float32))
                if use_bq:
                    pl.when(anyb(b))(scoreb)
                else:
                    scoreb()
        else:
            dsem_ref = dsem[0]

            def fetch(b, slot):
                """Start query b's C row DMAs into buffer ``slot``."""
                def start(c, _):
                    rid = cand_sm[b, c]
                    pltpu.make_async_copy(
                        ds_ref.at[pl.ds(rid, 1), :],
                        rows_ref.at[slot, pl.ds(c, 1), :],
                        dsem_ref.at[slot]).start()
                    return 0
                jax.lax.fori_loop(0, C, start, 0, unroll=1)

            def drain(slot):
                """Retire the C row copies targeting ``slot`` with ONE
                semaphore wait: DMA waits decrement by the descriptor's
                byte count, and a (C, d) descriptor's bytes equal the
                sum of the C (1, d) transfers that signalled the sem —
                C serial scalar-core waits would sit on the hot path.
                The descriptor is built from the (C, d) landing buffer
                (src shape only feeds the byte count), not a dataset
                slice — ds_ref[0:C] would be an invalid slice whenever
                n < C (tiny dataset forced to hbm mode)."""
                pltpu.make_async_copy(
                    rows_ref.at[slot],
                    rows_ref.at[slot],
                    dsem_ref.at[slot]).wait()

            def maybe(b, fn):
                # the fetch/drain/score trio for query b shares ONE
                # predicate (surv_ref is stable inside score_cand), so
                # a skipped fetch can never strand a drain
                if use_bq:
                    pl.when(anyb(b))(fn)
                else:
                    fn()

            maybe(0, lambda: fetch(0, 0))
            for b in range(B):
                slot = b % 2
                if b + 1 < B:
                    maybe(b + 1,
                          lambda b=b: fetch(b + 1, (b + 1) % 2))

                def retire(b=b, slot=slot):
                    drain(slot)
                    score_rows(b, rows_ref[slot].astype(jnp.float32))
                maybe(b, retire)
        if use_bq:
            # skipped rows hold stale dist lanes — the survivor mask
            # (which already folds cand >= 0) is the source of truth
            return jnp.where(surv_ref[:] == 1, dist_ref[:], jnp.inf)
        return jnp.where(cand < 0, jnp.inf, dist_ref[:])

    def merge(ids, dvals, expl, cand, cd):
        """Dedup-aware top-L merge (the XLA path's _buffer_merge with
        lax.top_k replaced by the extract-min network; same shared
        dedup mask as that engine)."""
        buf_ids = jnp.where(ids >= 0, ids, -2)
        dup = dedup_candidate_mask(cand, buf_ids)
        cd = jnp.where(dup | (cand < 0), jnp.inf, cd)

        all_d = jnp.concatenate([dvals, cd], axis=1)        # (B, L+C)
        all_i = jnp.concatenate([ids, cand], axis=1)
        new_d, new_i = _extract_topk(all_d, all_i, L)
        # explored flags follow ids (buffer ids are unique post-dedup;
        # fresh candidates enter unexplored)
        keep = jnp.any(
            (new_i[:, :, None] == buf_ids[:, None, :]) & (expl == 1)[:, None, :],
            axis=2)
        return new_i, new_d, keep.astype(jnp.int32)

    # ---- seed rounds: the buffer starts as the best L of ALL seeds.
    # Seeds arrive as a multiple of the candidate width C and merge in
    # C-wide chunks, so any XLA-engine seed count (L > C, extra
    # num_random_samplings draws) rides the same scoring path.
    seeds = seeds_ref[:]                                    # (B, S)
    ids = jnp.full((B, L), -1, jnp.int32)
    dvals = jnp.full((B, L), jnp.inf)
    expl = jnp.zeros((B, L), jnp.int32)
    for chunk in range(seeds.shape[1] // C):
        cand = seeds[:, chunk * C:(chunk + 1) * C]
        ids, dvals, expl = merge(ids, dvals, expl, cand,
                                 score_cand(cand, dvals))

    def body(it, state):
        ids, dvals, expl = state
        # ---- pick w best unexplored as parents (extract-min rounds).
        # A row past its iteration budget contributes no parents: its
        # candidates are all -1, its explored flags untouched — the
        # whole iteration is a bit-exact no-op for that row.
        masked = jnp.where((expl == 1) | (ids < 0), jnp.inf, dvals)
        _, parents = _extract_topk(masked, ids, w)          # (B, w)
        pvalid = (parents >= 0) & (it < it_vec)
        # mark parents explored (ids are unique in the buffer)
        expl = jnp.where(
            jnp.any(ids[:, :, None] == jnp.where(
                pvalid, parents, -3)[:, None, :], axis=2),
            1, expl)

        # ---- fetch the parents' adjacency rows from HBM.  Mosaic only
        # allows lane-dim DMA slices at 128-aligned offsets/widths, so
        # the graph arrives padded to Gp (= deg rounded up to 128),
        # whole padded rows land at j*Gp offsets, and the compact
        # (B, C) candidate block is re-assembled with aligned-start
        # static value slices (both patterns verified on the compiler).
        cand_ref[:] = jnp.concatenate(
            [jnp.where(pvalid, parents, 0),
             jnp.zeros((B, C - w), jnp.int32)], axis=1)
        cp = pltpu.make_async_copy(cand_ref, cand_sm, sem)
        cp.start()
        cp.wait()
        dmas = []
        for b in range(B):
            for j in range(w):
                dmas.append(pltpu.make_async_copy(
                    graph_ref.at[pl.ds(cand_sm[b, j], 1), :],
                    gsm.at[pl.ds(b * w + j, 1), :],
                    sem))
                dmas[-1].start()
        for dma in dmas:
            dma.wait()
        gv = gsm[:].reshape(B, w * Gp)
        cand = jnp.concatenate(
            [gv[:, j * Gp:j * Gp + deg] for j in range(w)], axis=1)
        # lanes of an invalid parent are masked out
        lane = jax.lax.broadcasted_iota(jnp.int32, (B, C), 1) // deg
        ok = jnp.zeros((B, C), jnp.bool_)
        for j in range(w):
            ok = ok | ((lane == j) & pvalid[:, j:j + 1])
        cand = jnp.where(ok, cand, -1)

        cd = score_cand(cand, dvals)
        return merge(ids, dvals, expl, cand, cd)

    ids, dvals, _ = jax.lax.fori_loop(0, max_iters, body,
                                      (ids, dvals, expl))
    outd_ref[:] = dvals[:, :k]
    outi_ref[:] = jnp.where(jnp.isfinite(dvals[:, :k]), ids[:, :k], -1)


@functools.partial(
    jax.jit,
    static_argnames=("k", "L", "w", "max_iters", "metric", "block_q",
                     "interpret", "vmem_mb", "deg", "ds_mode",
                     "bq_bits", "bq_query_bits", "bq_epsilon"))
def beam_search(queries, dataset, graph, seeds, k: int, L: int, w: int,
                max_iters: int, metric: DistanceType, *,
                row_iters=None,
                bq_records=None, bq_qrot=None, bq_crot=None,
                bq_bits: int = 0, bq_query_bits: int = 4,
                bq_epsilon: float = 3.0,
                block_q: int = 8, interpret: bool = False,
                vmem_mb: int = 0,
                deg: int = 0,
                ds_mode: str = "auto") -> Tuple[jax.Array, jax.Array]:
    """One-dispatch graph beam search (see module docstring).

    ``seeds`` must be (q, m·w·deg) int32 for integer m ≥ 1 — the seed
    rounds reuse the candidate scoring path in w·deg-wide chunks.
    Returns min-form (q, k) distances + ids; the caller applies sqrt /
    IP negation.

    ``row_iters``: optional (q,) int32 per-row iteration budgets for
    ragged serving — row r runs ``min(row_iters[r], max_iters)`` live
    iterations and inert no-ops after, bit-identical to a solo run at
    ``max_iterations=row_iters[r]``. None means every row runs
    ``max_iters``.

    ``bq_records``/``bq_qrot``/``bq_crot`` (+ the ``bq_*`` statics)
    enable BQ-coded traversal: records is the
    :func:`raft_tpu.ops.bq_scan.pack_bq_records` plane over the WHOLE
    dataset, qrot the rotated queries (q, d), crot the rotated center
    row (1, d). The plane must be VMEM-co-resident with the kernel's
    scratch.

    ``deg``: the graph's logical degree, when ``graph`` arrives with
    its rows already padded to a 128 multiple (see ``pad_graph``) —
    callers that search in query tiles pad once instead of per tile.
    0 means the graph is unpadded and its width is the degree.

    ``ds_mode``: ``"vmem"`` pins the dataset VMEM-resident (must fit
    the budget), ``"hbm"`` streams candidate rows by double-buffered
    DMA from HBM (any size), ``"auto"`` picks by ``beam_search_fits``."""
    q, d = queries.shape
    n, gw = graph.shape
    deg = deg or gw
    expect(deg <= gw, "beam_search: deg exceeds graph width")
    C = w * deg
    expect(metric in _SUPPORTED, f"beam_search: unsupported {metric}")
    expect(d % 128 == 0, "beam_search: dim must be lane-aligned (128)")
    expect(seeds.ndim == 2 and seeds.shape[0] == q
           and seeds.shape[1] >= C and seeds.shape[1] % C == 0,
           "beam_search: seeds must be (q, m*w*deg)")
    expect(k <= L, "beam_search: k must be <= itopk L")
    if vmem_mb <= 0:
        vmem_mb = _default_vmem_mb()

    use_bq = bq_records is not None
    plane_bytes = 0
    if use_bq:
        expect(1 <= bq_bits <= 8,
               "beam_search: bq_records needs bq_bits in 1..8")
        # dim is lane-aligned, so dim_ext == d and the rotated query
        # carries exactly d lanes
        words = bq_bits * d // 32
        _, rec_pad, rpt, pw = bq_record_geometry(words, bq_bits)
        expect(tuple(bq_records.shape) == (-(-n // rpt), pw),
               "beam_search: bq_records does not match "
               f"bq_record_geometry(words={words}, bits={bq_bits}) "
               f"for n={n}")
        expect(bq_qrot is not None and tuple(bq_qrot.shape) == (q, d),
               "beam_search: bq_qrot must be (q, dim) rotated queries")
        expect(bq_crot is not None and tuple(bq_crot.shape) == (1, d),
               "beam_search: bq_crot must be the (1, dim) rotated "
               "center")
        # the plane is VMEM-resident in BOTH dataset modes (it is the
        # prune side of the conditional DMA) — it must leave the ~8 MB
        # scratch headroom; dataset placement charges it as
        # extra_bytes below
        plane_bytes = 4 * bq_records.shape[0] * pw
        expect(plane_bytes <= (vmem_mb - 8) * 1024 * 1024,
               "beam_search: BQ record plane exceeds the VMEM budget")

    B = block_q
    if row_iters is None:
        row_iters = jnp.full((q,), max_iters, jnp.int32)
    expect(row_iters.shape == (q,),
           "beam_search: row_iters must be (q,)")
    pad_q = (-q) % B
    if pad_q:
        queries = jnp.pad(queries, ((0, pad_q), (0, 0)))
        seeds = jnp.pad(seeds, ((0, pad_q), (0, 0)))
        row_iters = jnp.pad(row_iters, (0, pad_q))
        if use_bq:
            bq_qrot = jnp.pad(bq_qrot, ((0, pad_q), (0, 0)))
    qp = q + pad_q
    # bf16 halves and int8 quarters the VMEM residency (int8 is the
    # CAGRA-Q role: quantized scan + exact refine outside)
    ds = (dataset if dataset.dtype in (jnp.bfloat16, jnp.int8)
          else dataset.astype(jnp.float32))
    qs = queries.astype(jnp.float32)
    # Lane-dim DMA slices must be 128-aligned: ship the graph with its
    # rows padded to Gp and fetch whole padded rows (costs HBM
    # bandwidth ~Gp/deg per fetch; candidate scoring stays at C wide).
    Gp = -(-deg // 128) * 128
    expect(gw in (deg, Gp),
           "beam_search: graph width must be deg or deg padded to 128")
    if gw != Gp:
        graph = pad_graph(graph)

    expect(ds_mode in ("auto", "vmem", "hbm"),
           f"beam_search: ds_mode must be auto/vmem/hbm, got {ds_mode!r}")
    itemsize = jnp.dtype(ds.dtype).itemsize
    if ds_mode == "auto":
        ds_mode = ("vmem" if beam_search_fits(n, ds.shape[1], itemsize,
                                              vmem_mb, plane_bytes)
                   else "hbm")
    elif ds_mode == "vmem":
        expect(beam_search_fits(n, ds.shape[1], itemsize, vmem_mb,
                                plane_bytes),
               f"beam_search: dataset ({n}x{ds.shape[1]} {ds.dtype}) "
               "exceeds the VMEM budget; use ds_mode='hbm' or 'auto'")
    ds_vmem = ds_mode == "vmem"

    kernel = functools.partial(
        _beam_kernel, L=L, w=w, k=k, C=C, deg=deg, Gp=Gp,
        max_iters=max_iters,
        ip_metric=metric == DistanceType.InnerProduct,
        ds_vmem=ds_vmem,
        bq_bits=bq_bits if use_bq else 0,
        bq_query_bits=bq_query_bits, bq_epsilon=bq_epsilon)
    # HBM mode: candidate rows land in a (2, C, d) double buffer with a
    # per-buffer DMA semaphore; VMEM mode gathers into one (C, d) block
    if ds_vmem:
        ds_spec = pl.BlockSpec((n, ds.shape[1]), lambda i, rr: (0, 0))
        rows_scratch = pltpu.VMEM((C, d), ds.dtype)
        extra_scratch = []
    else:
        ds_spec = pl.BlockSpec(memory_space=pl.ANY)
        rows_scratch = pltpu.VMEM((2, C, d), ds.dtype)
        extra_scratch = [pltpu.SemaphoreType.DMA((2,))]
    operands = [jnp.asarray(row_iters, jnp.int32), qs, seeds, ds, graph]
    in_specs = [
        pl.BlockSpec((B, d), lambda i, rr: (i, 0)),                # queries
        pl.BlockSpec((B, seeds.shape[1]), lambda i, rr: (i, 0)),   # seeds
        ds_spec,                                                   # dataset
        pl.BlockSpec(memory_space=pl.ANY),                  # graph (HBM)
    ]
    bq_scratch = []
    if use_bq:
        operands += [bq_qrot.astype(jnp.float32),
                     bq_crot.astype(jnp.float32),
                     bq_records]
        in_specs += [
            pl.BlockSpec((B, d), lambda i, rr: (i, 0)),            # qrot
            pl.BlockSpec((1, d), lambda i, rr: (0, 0)),            # crot
            pl.BlockSpec(bq_records.shape,
                         lambda i, rr: (0, 0)),       # record plane (VMEM)
        ]
        bq_scratch = [
            pltpu.VMEM((C, pw), jnp.int32),     # gathered record tiles
            pltpu.VMEM((B, C), jnp.int32),      # estimate survivors
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(qp // B,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((B, k), lambda i, rr: (i, 0)),
            pl.BlockSpec((B, k), lambda i, rr: (i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((B, C), jnp.int32),      # cand staging
            pltpu.SMEM((B, C), jnp.int32),      # cand scalars
            pltpu.VMEM((B, C), jnp.float32),    # distances
            rows_scratch,                       # gathered rows
            pltpu.VMEM((B * w, Gp), jnp.int32),  # graph rows landing
            pltpu.SemaphoreType.DMA,
        ] + bq_scratch + extra_scratch,
    )
    outd, outi = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((qp, k), jnp.float32),
            jax.ShapeDtypeStruct((qp, k), jnp.int32),
        ],
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=vmem_mb * 1024 * 1024),
        interpret=interpret,
    )(*operands)
    return outd[:q], outi[:q]
