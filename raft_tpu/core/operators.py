"""Composable element-wise operators — analog of ``core/operators.hpp``
(``sq_op``, ``add_op``, ``key_op``…), the lambda vocabulary the reference
plugs into its kernels. In JAX these are plain callables usable with
:func:`raft_tpu.linalg.unary_op` / ``map_reduce`` / ``reduce`` and
directly inside jitted code.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = [
    "identity_op", "void_op", "sq_op", "abs_op", "sqrt_op", "nz_op",
    "add_op", "sub_op", "mul_op", "div_op", "div_checkzero_op",
    "pow_op", "min_op", "max_op", "mod_op", "equal_op", "notequal_op",
    "greater_op", "less_op", "greater_or_equal_op", "less_or_equal_op",
    "const_op", "plug_const_op", "add_const_op", "sub_const_op",
    "mul_const_op", "div_const_op", "pow_const_op",
    "key_op", "value_op", "compose_op", "map_args_op",
]


def identity_op(x, *_):
    return x


def void_op(*_):
    return None


def sq_op(x, *_):
    return x * x


def abs_op(x, *_):
    return jnp.abs(x)


def sqrt_op(x, *_):
    return jnp.sqrt(x)


def nz_op(x, *_):
    """1 where nonzero else 0 (``nz_op``)."""
    return (x != 0).astype(x.dtype)


def add_op(a, b):
    return a + b


def sub_op(a, b):
    return a - b


def mul_op(a, b):
    return a * b


def div_op(a, b):
    return a / b


def div_checkzero_op(a, b):
    """a / b, 0 where b == 0 (``div_checkzero_op``)."""
    safe = a / jnp.where(b == 0, 1, b)
    return jnp.where(b == 0, jnp.zeros_like(safe), safe)


def pow_op(a, b):
    return jnp.power(a, b)


def min_op(a, b):
    return jnp.minimum(a, b)


def max_op(a, b):
    return jnp.maximum(a, b)


def mod_op(a, b):
    return jnp.mod(a, b)


def equal_op(a, b):
    return a == b


def notequal_op(a, b):
    return a != b


def greater_op(a, b):
    return a > b


def less_op(a, b):
    return a < b


def greater_or_equal_op(a, b):
    return a >= b


def less_or_equal_op(a, b):
    return a <= b


def key_op(kvp, *_):
    """First element of a (key, value) pair (``argmin_op``/``key_op``)."""
    return kvp[0]


def value_op(kvp, *_):
    return kvp[1]


def const_op(value):
    """Ignore inputs, return ``value`` (``const_op``)."""
    return lambda *_: value


def plug_const_op(value, op, side: str = "right"):
    """Bind one operand of a binary op (``plug_const_op``)."""
    if side == "right":
        return lambda x, *_: op(x, value)
    return lambda x, *_: op(value, x)


def add_const_op(value):
    return plug_const_op(value, add_op)


def sub_const_op(value):
    return plug_const_op(value, sub_op)


def mul_const_op(value):
    return plug_const_op(value, mul_op)


def div_const_op(value):
    return plug_const_op(value, div_op)


def pow_const_op(value):
    return plug_const_op(value, pow_op)


def compose_op(*ops):
    """Apply ops innermost-last: ``compose_op(f, g)(x) == f(g(x))``
    (``compose_op``)."""

    def composed(*args):
        out = ops[-1](*args)
        for op in reversed(ops[:-1]):
            out = op(out)
        return out

    return composed


def map_args_op(op, *arg_ops):
    """Feed each argument through its own unary op before ``op``
    (``map_args_op``)."""

    def mapped(*args):
        return op(*(f(a) for f, a in zip(arg_ops, args)))

    return mapped
