"""Resources handle — the TPU-native analog of ``raft::resources``.

The reference threads a type-indexed lazy resource container through every
API (``core/resources.hpp:47``) whose CUDA specialization
(``core/device_resources.hpp:61``) carries stream, stream pool, cuBLAS /
cuSOLVER handles, comms and a workspace allocator. On TPU almost all of
that is owned by XLA: there are no user-visible streams, no BLAS handles,
and memory is managed by the runtime. What genuinely remains shared state
across algorithm calls is:

- the **device / mesh** an algorithm should target (replaces device id +
  comms clique; multi-chip sharding is expressed with ``jax.sharding.Mesh``)
- a **PRNG key stream** (replaces ``rngState_t`` seeds threaded by hand)
- **tunables**: default matmul precision, batch/tile sizes, VMEM budget
  hints for Pallas kernels
- an injected **comms** object for multi-process runs (SURVEY.md §2.6)

``Resources`` is deliberately cheap, immutable-ish, and never traced: it is
host-side configuration, passed as the first argument of every public
function exactly like the reference's ``resources const&``.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Any, Optional, Sequence

import jax
import numpy as np


def _default_device() -> jax.Device:
    return jax.devices()[0]


def apply_compilation_cache(path: str) -> None:
    """Point XLA's persistent compilation cache at ``path`` (created if
    missing) and drop the min-compile-time threshold so every serving
    executable is persisted.

    This is the process-restart half of the serving path's cold-start
    story: ``SearchExecutor.warmup`` pays tracing + XLA compile once,
    the artifacts land in ``path``, and the next process's warmup is a
    cache *load* instead of a compile. Safe to call repeatedly."""
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    try:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except AttributeError:  # renamed across jax versions; dir alone suffices
        pass
    # jax memoizes "no cache configured" at the first compile; if any
    # compile already ran (e.g. another handle's PRNG init), the new
    # dir would be silently ignored without this reset
    try:
        from jax._src import compilation_cache

        if compilation_cache._cache_initialized:  # noqa: SLF001
            compilation_cache.reset_cache()
    except Exception:  # pragma: no cover - private API moved
        pass


@dataclasses.dataclass
class Resources:
    """Shared execution context threaded through every raft_tpu call.

    Analog of ``raft::resources`` / ``raft::device_resources``
    (reference ``core/device_resources.hpp:61-237``): where the reference
    hands out streams and vendor-library handles, this hands out devices,
    meshes, PRNG keys and kernel tunables.

    Attributes:
      device: preferred device for single-chip execution. ``None`` means
        JAX default placement.
      mesh: optional ``jax.sharding.Mesh`` for multi-chip algorithms; the
        analog of the comms clique injected into the reference handle
        (``core/device_resources.hpp:214`` ``get_comms``).
      seed: base seed for the handle-owned PRNG stream.
      matmul_precision: default ``jax.lax`` precision for distance GEMMs
        ("default" | "float32" | "bfloat16" | "highest"...).
      workspace_limit_bytes: soft budget that batching heuristics use when
        deciding tile sizes (analog of the workspace memory resource,
        ``core/device_resources.hpp`` workspace accessors).
      compilation_cache_dir: when set, XLA's persistent compilation
        cache is pointed here (see :func:`apply_compilation_cache`) so
        AOT warmup done by ``SearchExecutor`` survives process
        restarts. Defaults to the ``RAFT_TPU_COMPILE_CACHE`` env var.
    """

    device: Optional[jax.Device] = None
    mesh: Optional[jax.sharding.Mesh] = None
    seed: int = 0
    matmul_precision: str = "highest"
    workspace_limit_bytes: int = 2 * 1024**3
    comms: Optional[Any] = None
    compilation_cache_dir: Optional[str] = None

    def __post_init__(self):
        self._lock = threading.Lock()
        if self.compilation_cache_dir is None:
            self.compilation_cache_dir = (
                os.environ.get("RAFT_TPU_COMPILE_CACHE") or None)
        if self.compilation_cache_dir:
            # before the PRNG-key compile below, so even the process's
            # very first executable lands in the persistent cache
            apply_compilation_cache(self.compilation_cache_dir)
        self._key = jax.random.key(self.seed)
        self._subcomms: dict[str, Any] = {}

    # -- PRNG ---------------------------------------------------------------
    def next_key(self, n: Optional[int] = None):
        """Split and return fresh PRNG key(s) from the handle-owned stream.

        Replaces the reference pattern of threading ``random::RngState``
        (``random/rng_state.hpp:38``) through algorithms by hand.
        """
        with self._lock:
            if n is None:
                self._key, out = jax.random.split(self._key)
            else:
                keys = jax.random.split(self._key, n + 1)
                self._key, out = keys[0], keys[1:]
        return out

    # -- placement ----------------------------------------------------------
    def put(self, x, sharding: Optional[jax.sharding.Sharding] = None):
        """Place an array on this handle's device (or an explicit sharding)."""
        if sharding is not None:
            return jax.device_put(x, sharding)
        if self.device is not None:
            return jax.device_put(x, self.device)
        return jax.device_put(x)

    @property
    def default_device(self) -> jax.Device:
        return self.device if self.device is not None else _default_device()

    # -- comms (multi-process / multi-chip) ----------------------------------
    def get_comms(self):
        """Return the injected comms object (analog of
        ``resource::get_comms``, ``core/device_resources.hpp:214``)."""
        if self.comms is None:
            raise RuntimeError(
                "no comms injected into Resources; construct raft_tpu.comms."
                "Comms and pass it via Resources(comms=...)"
            )
        return self.comms

    def set_subcomm(self, key: str, comm) -> None:
        """Register a sub-communicator (analog of ``resource::set_subcomm``,
        ``core/resource/sub_comms.hpp``)."""
        self._subcomms[key] = comm

    def get_subcomm(self, key: str):
        return self._subcomms[key]

    # -- sync ---------------------------------------------------------------
    def sync(self, *arrays) -> None:
        """Block until given arrays (or all pending work) are ready.

        Analog of ``device_resources::sync_stream``
        (``core/device_resources.hpp:137-201``); XLA dispatch is async the
        same way CUDA streams are.
        """
        if arrays:
            for a in arrays:
                jax.block_until_ready(a)
        else:
            # effectively a fence: a trivial transfer on the target device
            jax.block_until_ready(jax.device_put(np.zeros(()), self.default_device))


# Legacy-flavored alias, mirroring ``raft::handle_t`` == device_resources
# (reference ``core/handle.hpp``).
DeviceResources = Resources


class ResourcesManager:
    """Process-wide per-device pool of ``Resources`` handles — the analog
    of ``raft::device_resources_manager`` (``core/
    device_resources_manager.hpp:49-154``), which hands multi-threaded
    servers a shared, pre-configured handle per GPU.

    Defaults set via ``set_*`` before first use apply to every handle the
    manager creates (mirroring the reference's set-then-freeze params);
    later calls simply return the cached handle.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._handles: dict[Optional[int], Resources] = {}
        self._defaults: dict[str, Any] = {}

    def set_seed(self, seed: int) -> None:
        self._defaults["seed"] = seed

    def set_matmul_precision(self, precision: str) -> None:
        self._defaults["matmul_precision"] = precision

    def set_workspace_limit_bytes(self, n: int) -> None:
        self._defaults["workspace_limit_bytes"] = n

    def set_compilation_cache_dir(self, path: str) -> None:
        self._defaults["compilation_cache_dir"] = path

    def get_device_resources(
        self, device: "Optional[jax.Device | int]" = None
    ) -> Resources:
        """The shared handle for ``device`` (an int id, a device object, or
        None for default placement) — ``get_device_resources()``."""
        if isinstance(device, int):
            device = jax.devices()[device]
        key = None if device is None else device.id
        with self._lock:
            if key not in self._handles:
                self._handles[key] = Resources(device=device,
                                               **self._defaults)
            return self._handles[key]


resources_manager = ResourcesManager()


def get_default_resources() -> Resources:
    """Process-wide default handle: callers that do not care about
    placement share one lazily-created ``Resources``."""
    return resources_manager.get_device_resources(None)


def ensure_resources(res: Optional[Resources]) -> Resources:
    return res if res is not None else get_default_resources()


def make_local_mesh(
    axis_names: Sequence[str] = ("data",),
    shape: Optional[Sequence[int]] = None,
) -> jax.sharding.Mesh:
    """Build a mesh over all local devices.

    Convenience for tests and single-host multi-chip runs; the analog of
    raft-dask's one-process-per-GPU clique bootstrap collapsed to a single
    call (reference ``raft_dask/common/comms.py:39-250``).
    """
    devs = jax.devices()
    if shape is None:
        shape = (len(devs),) + (1,) * (len(axis_names) - 1)
    arr = np.array(devs).reshape(tuple(shape))
    return jax.sharding.Mesh(arr, tuple(axis_names))
