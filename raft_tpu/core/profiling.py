"""graftflight (PR 11) — device-truth attribution from profiler traces.

Every device-side number graftscope publishes before this module is
*modeled*: mesh phase spans carry ``collective_payload_model`` bytes
over a shared host-side dispatch window, per-shard straggler timings
come from a host readiness poll, and achieved GB/s divides modeled
bytes by host wall-clock. The TPU-KNN roofline methodology (PAPERS.md)
only means something against *measured* device time — and the
``/profile`` endpoint (PR 7) already captures traces that nothing in
the repo reads. This module closes that loop:

1. **Trace ingestion** (:func:`load_trace` / :func:`parse_chrome_trace`)
   — parse the Chrome-trace JSON a ``jax.profiler`` capture drops in
   ``profile_dir`` (``plugins/profile/<run>/*.trace.json.gz``) into
   :class:`DeviceOp` records. A device op is an ``"X"`` event whose
   args carry ``hlo_module``/``hlo_op`` (the XLA executor's own
   annotations — python host-thread events and threadpool noise carry
   neither and are ignored); its device is the trace process name
   (``/device:TPU:N`` per chip on a mesh, ``/host:CPU`` on the CPU
   backend), and its ``scope`` is the framework op path when the
   backend exports one (``tf_op``/``long_name`` — named-scope prefixes
   like the mesh bodies' ``coarse_select``/``scan``/``merge`` markers
   land there).
2. **Correlation** (:func:`correlate` / :func:`attribute`) — ops
   correlate back to :class:`~raft_tpu.core.executor.SearchExecutor`
   entries by HLO module name: each AOT compile names its module after
   the entry's cache-key digest (``jit_rt_<family>_<digest>``), so a
   trace event maps to exactly one resident executable. The result is
   MEASURED device seconds per executable, per mesh phase, and per
   shard (device), plus the invocation count observed in the window.
3. **Measured supersedes modeled** (:func:`publish`) — with an
   attribution in hand, ``serving.mesh.{coarse_select,scan,merge}``
   spans re-emit with ``modeled: False`` and device-measured windows,
   the straggler gauges recompute from per-device seconds instead of
   the post-dispatch host poll, and per-executable measured achieved
   GB/s / GFLOP/s (modeled bytes x invocations / measured device
   seconds) publish next to the wall-clock-derived numbers — see
   ``serving.metrics.derived()`` — so the two accountings can disagree
   visibly.

Everything here is host-side file parsing and registry writes — pure
stdlib, no jax import, nothing on the dispatch path. Timestamps in the
re-emitted spans are in the CAPTURE's clock domain (profiler
microseconds), a third domain next to the batcher clock and wall
clock; the spans say so via ``source: "profiler"``.
"""

from __future__ import annotations

import collections
import dataclasses
import glob
import gzip
import json
import os
from typing import Dict, Iterable, List, Optional, Tuple

from raft_tpu.core import tracing

# lifetime counters (ci/bench_compare.py snapshot floors): ingested
# captures and the totals the measured/modeled disagreement is read on
CAPTURES = "profiling.captures"
DEVICE_OPS = "profiling.device_ops"
ATTRIBUTED_SECONDS = "serving.attribution.device_seconds"
ATTRIBUTED_BYTES = "serving.attribution.modeled_bytes"
ATTRIBUTED_FLOPS = "serving.attribution.modeled_flops"

# the mesh phase markers the distributed search bodies annotate with
# jax.named_scope — ops whose scope path carries none land in
# "unattributed" (the CPU backend's chrome export drops op scopes)
PHASE_MARKERS = ("coarse_select", "scan", "merge")
UNATTRIBUTED = "unattributed"

# args keys a backend may carry the framework op path under
_SCOPE_KEYS = ("tf_op", "long_name", "op_name", "scope")


@dataclasses.dataclass(frozen=True)
class DeviceOp:
    """One measured device-op execution from a profiler capture.

    ``device`` is the trace process name (one per chip on a mesh);
    ``module`` the HLO module (= one compiled executable); ``scope``
    the framework op path when the backend exports one, else ``""``.
    Times are seconds in the capture's own clock domain."""

    device: str
    module: str
    op: str
    scope: str
    start_s: float
    dur_s: float

    @property
    def phase(self) -> str:
        """Mesh phase of this op: the first
        :data:`PHASE_MARKERS` entry appearing as a path component of
        ``scope`` (the named-scope markers the distributed search
        bodies plant), else :data:`UNATTRIBUTED`."""
        if self.scope:
            parts = self.scope.split("/")
            for marker in PHASE_MARKERS:
                if marker in parts:
                    return marker
        return UNATTRIBUTED


def trace_snapshot(profile_dir: str) -> Dict[str, float]:
    """``{path: mtime}`` of every ``*.trace.json[.gz]`` under a
    ``jax.profiler`` capture directory (the profiler nests runs as
    ``plugins/profile/<timestamp>/<host>.trace.json.gz``). A caller
    that is about to run a capture takes this snapshot and resolves
    the capture's own output with :func:`fresh_trace_file` — the
    clock-free way to identify the file that capture produced (or
    learn it produced none), instead of trusting "newest in the dir",
    which silently substitutes a PREVIOUS capture's data when the
    fresh one writes no chrome-trace sidecar. Mtimes matter: two
    captures in the same second share a timestamped run dir and the
    second OVERWRITES the first's file, so a bare path diff would
    miss it."""
    pats = (os.path.join(profile_dir, "plugins", "profile", "*",
                         "*.trace.json*"),
            os.path.join(profile_dir, "*.trace.json*"))
    out: Dict[str, float] = {}
    for pat in pats:
        for p in glob.glob(pat):
            if p.endswith((".trace.json", ".trace.json.gz")):
                try:
                    out[p] = os.path.getmtime(p)
                except OSError:   # raced a cleanup — not a capture
                    pass
    return out


def fresh_trace_file(profile_dir: str,
                     before: Dict[str, float]) -> Optional[str]:
    """The trace file a just-finished capture produced: the newest
    path that is new — or rewritten — relative to the
    :func:`trace_snapshot` taken before the capture. None when the
    capture wrote no chrome trace (the honest answer; see
    :func:`trace_snapshot` for why stale fallback is a bug)."""
    now = trace_snapshot(profile_dir)
    fresh = [p for p, m in now.items() if before.get(p) != m]
    if not fresh:
        return None
    return max(fresh, key=lambda p: now[p])


def latest_trace_file(profile_dir: str) -> Optional[str]:
    """Newest capture trace file under ``profile_dir``, or None when
    the directory holds no capture yet. For attributing a capture YOU
    just ran, prefer the :func:`trace_snapshot` /
    :func:`fresh_trace_file` pair — this entry point is for pointing
    at whatever a directory already holds."""
    found = trace_snapshot(profile_dir)
    if not found:
        return None
    return max(found, key=lambda p: found[p])


def load_trace(source) -> dict:
    """Load a Chrome-trace JSON object from ``source``: a parsed dict
    passes through; a ``.json``/``.json.gz`` file path is read; a
    directory is treated as a ``jax.profiler`` ``profile_dir`` and its
    newest capture is taken. Raises ``FileNotFoundError`` for a
    directory holding no capture."""
    if isinstance(source, dict):
        return source
    path = os.fspath(source)
    if os.path.isdir(path):
        found = latest_trace_file(path)
        if found is None:
            raise FileNotFoundError(
                f"no *.trace.json[.gz] capture under {path!r}")
        path = found
    if path.endswith(".gz"):
        with gzip.open(path, "rt") as f:
            return json.load(f)
    with open(path) as f:
        return json.load(f)


def parse_chrome_trace(data: dict) -> List[DeviceOp]:
    """Extract the device ops from one Chrome-trace JSON object.

    Process names come from the ``"M"``/``process_name`` metadata
    events; a device op is any ``"X"`` event whose args carry
    ``hlo_module`` (XLA stamps ``hlo_module``/``hlo_op`` on every op
    it executes — python host-thread events and threadpool markers
    carry neither and are skipped). Timestamps convert from the
    trace's microseconds to seconds."""
    procs: Dict[int, str] = {}
    events = data.get("traceEvents", [])
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            procs[ev.get("pid")] = ev.get("args", {}).get("name", "")
    out: List[DeviceOp] = []
    for ev in events:
        if ev.get("ph") != "X":
            continue
        args = ev.get("args") or {}
        module = args.get("hlo_module")
        if not module:
            continue
        scope = ""
        for key in _SCOPE_KEYS:
            if args.get(key):
                scope = str(args[key])
                break
        pid = ev.get("pid")
        out.append(DeviceOp(
            device=procs.get(pid, f"pid:{pid}"),
            module=str(module),
            op=str(args.get("hlo_op", ev.get("name", ""))),
            scope=scope,
            start_s=float(ev.get("ts", 0.0)) * 1e-6,
            dur_s=float(ev.get("dur", 0.0)) * 1e-6,
        ))
    return out


@dataclasses.dataclass
class ModuleAttribution:
    """Measured device truth for ONE resident executable.

    ``device_seconds`` is busy op-time summed over every device that
    ran the module (the roofline denominator); ``invocations`` the
    executions observed in the window — the MINIMUM positive
    per-(device, op) event count: a top-level op runs exactly once
    per execution, loop-body ops run once per iteration (which is why
    the maximum wildly overcounts), and conditionally-executed ops
    can only push the minimum DOWN, making the derived achieved
    GB/s conservative rather than inflated; ``phase_seconds`` buckets
    op time by the named-scope mesh phase markers; ``shard_seconds``
    by device.
    ``modeled_bytes_per_call``/``flops`` come from the entry's
    compile-time cost analysis, so measured achieved GB/s is
    ``bytes x invocations / device_seconds``."""

    digest: str
    module: str
    family: str
    device_seconds: float
    invocations: int
    phase_seconds: Dict[str, float]
    shard_seconds: Dict[str, float]
    window: Tuple[float, float]
    modeled_bytes_per_call: float = 0.0
    modeled_flops_per_call: float = 0.0
    payload_model: Optional[dict] = None

    @property
    def mesh(self) -> bool:
        """Whether this executable is a mesh (sharded) program — the
        families whose modeled phase spans the measured ones
        supersede."""
        return (self.payload_model is not None
                or self.family.startswith("dist_"))

    def measured_gbps(self) -> float:
        if self.device_seconds <= 0:
            return 0.0
        return (self.modeled_bytes_per_call * self.invocations
                / self.device_seconds / 1e9)

    def measured_gflops(self) -> float:
        if self.device_seconds <= 0:
            return 0.0
        return (self.modeled_flops_per_call * self.invocations
                / self.device_seconds / 1e9)

    def to_dict(self) -> dict:
        return {
            "digest": self.digest,
            "module": self.module,
            "family": self.family,
            "device_seconds": self.device_seconds,
            "invocations": self.invocations,
            "phase_seconds": dict(self.phase_seconds),
            "shard_seconds": dict(self.shard_seconds),
            "window": list(self.window),
            "modeled_bytes_per_call": self.modeled_bytes_per_call,
            "modeled_flops_per_call": self.modeled_flops_per_call,
            "measured_gbps": self.measured_gbps(),
            "measured_gflops": self.measured_gflops(),
            "mesh": self.mesh,
        }


@dataclasses.dataclass
class Attribution:
    """One capture's full correlation result: per-executable measured
    device truth plus the ops that matched no resident executable
    (counted, not dropped silently — a capture dominated by
    unmatched ops means the cost table and the trace disagree about
    what is resident)."""

    modules: Dict[str, ModuleAttribution]
    unmatched_modules: Dict[str, float]
    trace_file: Optional[str] = None

    def to_dict(self) -> dict:
        return {
            "modules": {d: m.to_dict() for d, m in self.modules.items()},
            "unmatched_modules": dict(self.unmatched_modules),
            "trace_file": self.trace_file,
        }


def correlate(ops: Iterable[DeviceOp], costs: dict) -> Attribution:
    """Correlate parsed device ops back to executor entries.

    ``costs`` is ``SearchExecutor.executable_costs()`` — each entry
    carries the ``hlo_module`` name its compile stamped (unique per
    executable: the module is named after the cache-key digest), plus
    the modeled per-call bytes/flops and, for mesh entries, the
    collective payload model the measured phase spans re-attach.
    Pure function of its inputs — the committed capture fixture pins
    the whole pipeline byte-exactly."""
    modmap = {}
    for digest, info in costs.items():
        name = info.get("hlo_module")
        if name:
            modmap[name] = digest
    by_module: Dict[str, List[DeviceOp]] = collections.defaultdict(list)
    unmatched: Dict[str, float] = collections.defaultdict(float)
    for op in ops:
        if op.module in modmap:
            by_module[op.module].append(op)
        else:
            unmatched[op.module] += op.dur_s
    out: Dict[str, ModuleAttribution] = {}
    for module, mops in by_module.items():
        digest = modmap[module]
        info = costs[digest]
        phase: Dict[str, float] = collections.defaultdict(float)
        shard: Dict[str, float] = collections.defaultdict(float)
        op_counts: Dict[tuple, int] = collections.defaultdict(int)
        total = 0.0
        t0 = min(op.start_s for op in mops)
        t1 = max(op.start_s + op.dur_s for op in mops)
        for op in mops:
            total += op.dur_s
            phase[op.phase] += op.dur_s
            shard[op.device] += op.dur_s
            op_counts[(op.device, op.op)] += 1
        out[digest] = ModuleAttribution(
            digest=digest, module=module,
            family=str(info.get("family", "")),
            device_seconds=total,
            # min, not max: loop-body ops repeat per iteration and
            # would overcount executions (and inflate measured GB/s)
            # by the trip count — see the class docstring
            invocations=min(op_counts.values()),
            phase_seconds=dict(phase),
            shard_seconds=dict(shard),
            window=(t0, t1),
            modeled_bytes_per_call=float(info.get("bytes_accessed", 0.0)),
            modeled_flops_per_call=float(info.get("flops", 0.0)),
            payload_model=info.get("collective_payload"),
        )
    return Attribution(modules=out, unmatched_modules=dict(unmatched))


def attribute(source, costs: dict) -> Attribution:
    """The whole ingestion pipeline: load → parse → correlate.

    ``source`` is anything :func:`load_trace` accepts (a profile dir,
    a trace file, or an already-parsed dict); ``costs`` is the
    executor's :meth:`executable_costs` table. Bumps the
    ``profiling.captures`` / ``profiling.device_ops`` lifetime
    counters — the CI snapshot floor's evidence that trace ingestion
    stayed alive."""
    data = load_trace(source)
    ops = parse_chrome_trace(data)
    attr = correlate(ops, costs)
    if isinstance(source, (str, os.PathLike)):
        path = os.fspath(source)
        attr.trace_file = (latest_trace_file(path)
                           if os.path.isdir(path) else path)
    tracing.inc_counters({CAPTURES: 1.0, DEVICE_OPS: float(len(ops))})
    return attr


def _emit_measured_mesh(att: ModuleAttribution) -> None:
    """Re-emit one mesh executable's phase + shard spans from measured
    device time — the ``modeled: False`` counterpart of the modeled
    spans ``mesh_trace`` records per dispatch.

    Phase spans lay out sequentially from the capture window's start,
    each covering its mean per-invocation measured duration (attrs
    carry the totals); the modeled wire bytes ride along from the
    entry's payload model so Perfetto shows bytes over MEASURED time.
    Shard spans and the straggler gauges
    (``serving.mesh.{shard_skew,slowest_shard}``) recompute from mean
    per-invocation per-device busy seconds — superseding the
    host-side readiness poll's numbers."""
    inv = max(att.invocations, 1)
    t = att.window[0]
    phase_bytes = {}
    if att.payload_model:
        phase_bytes = {
            "coarse_select": att.payload_model.get("coarse_bytes", 0),
            "scan": 0,
            "merge": att.payload_model.get("merge_bytes", 0),
        }
    for marker in PHASE_MARKERS + (UNATTRIBUTED,):
        secs = att.phase_seconds.get(marker, 0.0)
        if secs <= 0.0:
            continue
        mean = secs / inv
        attrs = {"modeled": False, "source": "profiler",
                 "family": att.family, "digest": att.digest,
                 "device_seconds": secs, "invocations": att.invocations}
        if marker in phase_bytes:
            attrs["wire_bytes"] = phase_bytes[marker]
        tracing.record_span(f"serving.mesh.{marker}", t, t + mean,
                            attrs=attrs)
        t += mean
    if att.shard_seconds:
        timings = [att.shard_seconds[d] / inv
                   for d in sorted(att.shard_seconds)]
        tracing.record_mesh_spans(
            att.family, att.window[0],
            att.window[0] + max(timings),
            shard_timings=timings,
            shard_attrs={"modeled": False, "source": "profiler",
                         "digest": att.digest},
            count_dispatch=False)


def publish(attr: Attribution) -> dict:
    """Publish one attribution into the live registries — the
    "measured supersedes modeled" half of graftflight.

    Per executable: ``serving.executable.<digest>.measured_*`` gauges
    (device seconds, invocations, achieved GB/s / GFLOP/s from
    modeled-bytes-over-measured-time — rendered as the labeled
    ``serving_executable_measured_*{digest=...}`` Prometheus
    families); mesh executables additionally re-emit their phase and
    shard spans with ``modeled: False`` (see
    :func:`_emit_measured_mesh`) — recomputing the straggler gauges
    from device timings. Process totals land in the
    ``serving.attribution.*`` counters ``serving.metrics.derived()``
    divides for the measured achieved-bandwidth columns. Returns
    ``{digest: measured-stats}``."""
    out = {}
    totals = {ATTRIBUTED_SECONDS: 0.0, ATTRIBUTED_BYTES: 0.0,
              ATTRIBUTED_FLOPS: 0.0}
    for digest, att in attr.modules.items():
        base = f"serving.executable.{digest}."
        stats = {
            "device_seconds": att.device_seconds,
            "invocations": att.invocations,
            "gbps": att.measured_gbps(),
            "gflops": att.measured_gflops(),
        }
        tracing.set_gauges({
            base + "measured_device_seconds": att.device_seconds,
            base + "measured_invocations": float(att.invocations),
            base + "measured_gbps": stats["gbps"],
            base + "measured_gflops": stats["gflops"],
        })
        totals[ATTRIBUTED_SECONDS] += att.device_seconds
        totals[ATTRIBUTED_BYTES] += (att.modeled_bytes_per_call
                                     * att.invocations)
        totals[ATTRIBUTED_FLOPS] += (att.modeled_flops_per_call
                                     * att.invocations)
        if att.mesh:
            _emit_measured_mesh(att)
        out[digest] = stats
    if attr.modules:
        tracing.inc_counters(totals)
    return out
