"""graftflight (PR 11) — device-truth attribution from profiler traces.

Every device-side number graftscope publishes before this module is
*modeled*: mesh phase spans carry ``collective_payload_model`` bytes
over a shared host-side dispatch window, per-shard straggler timings
come from a host readiness poll, and achieved GB/s divides modeled
bytes by host wall-clock. The TPU-KNN roofline methodology (PAPERS.md)
only means something against *measured* device time — and the
``/profile`` endpoint (PR 7) already captures traces that nothing in
the repo reads. This module closes that loop:

1. **Trace ingestion** (:func:`load_trace` / :func:`parse_chrome_trace`)
   — parse the Chrome-trace JSON a ``jax.profiler`` capture drops in
   ``profile_dir`` (``plugins/profile/<run>/*.trace.json.gz``) into
   :class:`DeviceOp` records. A device op is an ``"X"`` event whose
   args carry ``hlo_module``/``hlo_op`` (the XLA executor's own
   annotations — python host-thread events and threadpool noise carry
   neither and are ignored); its device is the trace process name
   (``/device:TPU:N`` per chip on a mesh, ``/host:CPU`` on the CPU
   backend), and its ``scope`` is the framework op path when the
   backend exports one (``tf_op``/``long_name`` — named-scope prefixes
   like the mesh bodies' ``coarse_select``/``scan``/``merge`` markers
   land there).
2. **Correlation** (:func:`correlate` / :func:`attribute`) — ops
   correlate back to :class:`~raft_tpu.core.executor.SearchExecutor`
   entries by HLO module name: each AOT compile names its module after
   the entry's cache-key digest (``jit_rt_<family>_<digest>``), so a
   trace event maps to exactly one resident executable. The result is
   MEASURED device seconds per executable, per mesh phase, and per
   shard (device), plus the invocation count observed in the window.
3. **Measured supersedes modeled** (:func:`publish`) — with an
   attribution in hand, ``serving.mesh.{coarse_select,scan,merge}``
   spans re-emit with ``modeled: False`` and device-measured windows,
   the straggler gauges recompute from per-device seconds instead of
   the post-dispatch host poll, and per-executable measured achieved
   GB/s / GFLOP/s (modeled bytes x invocations / measured device
   seconds) publish next to the wall-clock-derived numbers — see
   ``serving.metrics.derived()`` — so the two accountings can disagree
   visibly.

Everything here is host-side file parsing and registry writes — pure
stdlib, no jax import, nothing on the dispatch path. Timestamps in the
re-emitted spans are in the CAPTURE's clock domain (profiler
microseconds), a third domain next to the batcher clock and wall
clock; the spans say so via ``source: "profiler"``.

graftfleet (PR 12) grows the module into the STEADY-STATE half:

4. **Per-dispatch invocation windows** (:func:`invocation_windows`) —
   gap-clustering splits one module's capture events into the
   dispatches that produced them, so ``invocations`` becomes an exact
   per-window count instead of the MIN-per-(device, op) heuristic,
   and straggler skew / phase timing attribute PER DISPATCH
   (``serving.mesh.shard_skew_p99`` over the window skews).
5. **Rolling attribution** (:class:`RollingAttribution`) — the
   EWMA-folded state a continuous low-duty-cycle capture scheduler
   (:mod:`raft_tpu.serving.continuous`) feeds: per-executable /
   per-phase measured device seconds and achieved GB/s published as
   ``serving.attribution.rolling.*`` gauges, so ``metrics.derived()``
   carries a continuously-fresh measured number instead of the last
   incident's snapshot.
6. **xplane-pb ingestion** (:func:`parse_xplane` via
   :mod:`raft_tpu.core.xplane`) — auto-selected when a capture
   directory holds ``.xplane.pb`` but no chrome sidecar (the chrome
   path stays primary; upstream is deprecating the TPU chrome export).
"""

from __future__ import annotations

import collections
import dataclasses
import glob
import gzip
import json
import os
import threading
from typing import Dict, Iterable, List, Optional, Tuple

from raft_tpu.core import tracing, xplane

# lifetime counters (ci/bench_compare.py snapshot floors): ingested
# captures and the totals the measured/modeled disagreement is read on
CAPTURES = "profiling.captures"
DEVICE_OPS = "profiling.device_ops"
ATTRIBUTED_SECONDS = "serving.attribution.device_seconds"
ATTRIBUTED_BYTES = "serving.attribution.modeled_bytes"
ATTRIBUTED_FLOPS = "serving.attribution.modeled_flops"
# graftfleet (PR 12): rolling-attribution folds — the snapshot floor's
# evidence that the continuous-capture pipeline stayed alive
ROLLING_FOLDS = "profiling.rolling.folds"
ROLLING_PREFIX = "serving.attribution.rolling."

# the mesh phase markers the distributed search bodies annotate with
# jax.named_scope — ops whose scope path carries none land in
# "unattributed" (the CPU backend's chrome export drops op scopes)
PHASE_MARKERS = ("coarse_select", "scan", "merge")
UNATTRIBUTED = "unattributed"

# args keys a backend may carry the framework op path under
_SCOPE_KEYS = ("tf_op", "long_name", "op_name", "scope")


@dataclasses.dataclass(frozen=True)
class DeviceOp:
    """One measured device-op execution from a profiler capture.

    ``device`` is the trace process name (one per chip on a mesh);
    ``module`` the HLO module (= one compiled executable); ``scope``
    the framework op path when the backend exports one, else ``""``.
    Times are seconds in the capture's own clock domain."""

    device: str
    module: str
    op: str
    scope: str
    start_s: float
    dur_s: float

    @property
    def phase(self) -> str:
        """Mesh phase of this op: the first
        :data:`PHASE_MARKERS` entry appearing as a path component of
        ``scope`` (the named-scope markers the distributed search
        bodies plant), else :data:`UNATTRIBUTED`."""
        if self.scope:
            parts = self.scope.split("/")
            for marker in PHASE_MARKERS:
                if marker in parts:
                    return marker
        return UNATTRIBUTED


def _is_chrome(path: str) -> bool:
    return path.endswith((".trace.json", ".trace.json.gz"))


def trace_snapshot(profile_dir: str) -> Dict[str, float]:
    """``{path: mtime}`` of every ``*.trace.json[.gz]`` AND
    ``*.xplane.pb`` under a ``jax.profiler`` capture directory (the
    profiler nests runs as
    ``plugins/profile/<timestamp>/<host>.trace.json.gz``). A caller
    that is about to run a capture takes this snapshot and resolves
    the capture's own output with :func:`fresh_trace_file` — the
    clock-free way to identify the file that capture produced (or
    learn it produced none), instead of trusting "newest in the dir",
    which silently substitutes a PREVIOUS capture's data when the
    fresh one writes no chrome-trace sidecar. Mtimes matter: two
    captures in the same second share a timestamped run dir and the
    second OVERWRITES the first's file, so a bare path diff would
    miss it."""
    pats = (os.path.join(profile_dir, "plugins", "profile", "*",
                         "*.trace.json*"),
            os.path.join(profile_dir, "*.trace.json*"),
            os.path.join(profile_dir, "plugins", "profile", "*",
                         "*.xplane.pb"),
            os.path.join(profile_dir, "*.xplane.pb"))
    out: Dict[str, float] = {}
    for pat in pats:
        for p in glob.glob(pat):
            if _is_chrome(p) or p.endswith(".xplane.pb"):
                try:
                    out[p] = os.path.getmtime(p)
                except OSError:   # raced a cleanup — not a capture
                    pass
    return out


def _prefer_chrome(paths, mtimes) -> str:
    """Newest chrome-trace sidecar when any exists, else the newest
    ``.xplane.pb`` — the chrome path stays primary; the protobuf
    reader is the fallback for captures (upcoming TPU exports) that
    write no chrome sidecar at all."""
    chrome = [p for p in paths if _is_chrome(p)]
    pool = chrome or list(paths)
    return max(pool, key=lambda p: (mtimes[p], p))


def fresh_trace_file(profile_dir: str,
                     before: Dict[str, float]) -> Optional[str]:
    """The trace file a just-finished capture produced: the newest
    path that is new — or rewritten — relative to the
    :func:`trace_snapshot` taken before the capture (chrome sidecar
    preferred when the capture wrote both it and an ``.xplane.pb``).
    None when the capture wrote no trace at all (the honest answer;
    see :func:`trace_snapshot` for why stale fallback is a bug)."""
    now = trace_snapshot(profile_dir)
    fresh = [p for p, m in now.items() if before.get(p) != m]
    if not fresh:
        return None
    return _prefer_chrome(fresh, now)


def latest_trace_file(profile_dir: str) -> Optional[str]:
    """Newest capture trace file under ``profile_dir`` (chrome sidecar
    preferred; ``.xplane.pb`` when the directory holds only that), or
    None when the directory holds no capture yet. For attributing a
    capture YOU just ran, prefer the :func:`trace_snapshot` /
    :func:`fresh_trace_file` pair — this entry point is for pointing
    at whatever a directory already holds."""
    found = trace_snapshot(profile_dir)
    if not found:
        return None
    return _prefer_chrome(found, found)


def load_trace(source) -> dict:
    """Load a Chrome-trace JSON object from ``source``: a parsed dict
    passes through; a ``.json``/``.json.gz`` file path is read; a
    directory is treated as a ``jax.profiler`` ``profile_dir`` and its
    newest capture is taken. Raises ``FileNotFoundError`` for a
    directory holding no capture. Chrome traces only — use
    :func:`load_ops` for the format-dispatching entry point that also
    reads ``.xplane.pb``."""
    if isinstance(source, dict):
        return source
    path = os.fspath(source)
    if os.path.isdir(path):
        # chrome-only resolution: trace_snapshot sees .xplane.pb too
        # (PR 12), but feeding protobuf bytes to json.load would be
        # an opaque decode error — an xplane-only directory stays the
        # explicit "no chrome capture" failure it always was
        found = {p: m for p, m in trace_snapshot(path).items()
                 if _is_chrome(p)}
        if not found:
            raise FileNotFoundError(
                f"no *.trace.json[.gz] capture under {path!r} "
                "(for .xplane.pb captures use load_ops)")
        path = max(found, key=lambda p: (found[p], p))
    if path.endswith(".xplane.pb"):
        raise ValueError(
            f"{path!r} is an xplane protobuf, not a chrome trace — "
            "use load_ops/parse_xplane")
    if path.endswith(".gz"):
        with gzip.open(path, "rt") as f:
            return json.load(f)
    with open(path) as f:
        return json.load(f)


def parse_chrome_trace(data: dict) -> List[DeviceOp]:
    """Extract the device ops from one Chrome-trace JSON object.

    Process names come from the ``"M"``/``process_name`` metadata
    events; a device op is any ``"X"`` event whose args carry
    ``hlo_module`` (XLA stamps ``hlo_module``/``hlo_op`` on every op
    it executes — python host-thread events and threadpool markers
    carry neither and are skipped). Timestamps convert from the
    trace's microseconds to seconds."""
    procs: Dict[int, str] = {}
    events = data.get("traceEvents", [])
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            procs[ev.get("pid")] = ev.get("args", {}).get("name", "")
    out: List[DeviceOp] = []
    for ev in events:
        if ev.get("ph") != "X":
            continue
        args = ev.get("args") or {}
        module = args.get("hlo_module")
        if not module:
            continue
        scope = ""
        for key in _SCOPE_KEYS:
            if args.get(key):
                scope = str(args[key])
                break
        pid = ev.get("pid")
        out.append(DeviceOp(
            device=procs.get(pid, f"pid:{pid}"),
            module=str(module),
            op=str(args.get("hlo_op", ev.get("name", ""))),
            scope=scope,
            start_s=float(ev.get("ts", 0.0)) * 1e-6,
            dur_s=float(ev.get("dur", 0.0)) * 1e-6,
        ))
    return out


def parse_xplane(source) -> List[DeviceOp]:
    """Extract the device ops from one serialized XSpace
    (``.xplane.pb`` path or raw bytes) via the stdlib wire-format
    reader (:mod:`raft_tpu.core.xplane`) — the graftfleet satellite
    closing the ROADMAP xplane-ingestion follow-on. Same contract as
    :func:`parse_chrome_trace`: a device op is an event whose resolved
    stats carry ``hlo_module`` (module-less python/threadpool events
    are skipped), device = the plane name, scope = the framework op
    path stat when present, times in seconds (line ``timestamp_ns``
    base + event ``offset_ps``)."""
    if isinstance(source, (bytes, bytearray)):
        data = bytes(source)
    else:
        with open(os.fspath(source), "rb") as f:
            data = f.read()
    space = xplane.parse_xspace(data)
    out: List[DeviceOp] = []
    for plane in space["planes"]:
        device = plane["name"]
        for line in plane["lines"]:
            t0 = float(line["timestamp_ns"]) * 1e-9
            for ev in line["events"]:
                stats = xplane.resolve_stats(ev, plane["stat_metadata"])
                module = stats.get("hlo_module")
                if not module or not isinstance(module, str):
                    continue
                scope = ""
                for key in _SCOPE_KEYS:
                    v = stats.get(key)
                    if v and isinstance(v, str):
                        scope = v
                        break
                out.append(DeviceOp(
                    device=device,
                    module=module,
                    op=plane["event_metadata"].get(
                        ev["metadata_id"], str(ev["metadata_id"])),
                    scope=scope,
                    start_s=t0 + float(ev["offset_ps"]) * 1e-12,
                    dur_s=float(ev["duration_ps"]) * 1e-12,
                ))
    return out


def load_ops(source) -> Tuple[List[DeviceOp], Optional[str]]:
    """Format-dispatching ingestion front: ``(device ops, resolved
    trace file)`` from a parsed chrome dict, a ``.trace.json[.gz]``
    path, a ``.xplane.pb`` path, or a ``profile_dir`` (newest capture,
    chrome sidecar preferred — the xplane reader is auto-selected only
    when the directory holds ``.xplane.pb`` and no chrome trace)."""
    if isinstance(source, dict):
        return parse_chrome_trace(source), None
    path = os.fspath(source)
    if os.path.isdir(path):
        found = latest_trace_file(path)
        if found is None:
            raise FileNotFoundError(
                f"no *.trace.json[.gz] / *.xplane.pb capture under "
                f"{path!r}")
        path = found
    if path.endswith(".xplane.pb"):
        return parse_xplane(path), path
    return parse_chrome_trace(load_trace(path)), path


@dataclasses.dataclass
class InvocationWindow:
    """One dispatch's worth of a module's capture events (graftfleet):
    the ops between two idle gaps the gap-clustering called dispatch
    boundaries. ``shard_seconds`` is per-device busy time WITHIN the
    window, so :attr:`skew` is the straggler skew of this one dispatch
    — the per-dispatch sample the ``serving.mesh.shard_skew_p99``
    distribution is built from."""

    start_s: float
    end_s: float
    ops: int
    device_seconds: float
    phase_seconds: Dict[str, float]
    shard_seconds: Dict[str, float]

    @property
    def skew(self) -> float:
        """max − min per-device busy seconds (0.0 single-device)."""
        if len(self.shard_seconds) < 2:
            return 0.0
        vals = self.shard_seconds.values()
        return max(vals) - min(vals)

    def to_dict(self) -> dict:
        return {"start_s": self.start_s, "end_s": self.end_s,
                "ops": self.ops, "device_seconds": self.device_seconds,
                "phase_seconds": dict(self.phase_seconds),
                "shard_seconds": dict(self.shard_seconds),
                "skew": self.skew}


# auto gap-clustering knob: a gap joins the dispatch boundaries when
# it is at least this fraction of the smallest gap the op-count floor
# already forced to be a boundary — catches the dispatches the
# MIN-count heuristic undercounts (conditional top-level ops) without
# promoting intra-dispatch idle (which sits well below real dispatch
# gaps) into fake boundaries
GAP_EXTEND_RATIO = 0.5


def invocation_windows(ops: Iterable[DeviceOp], *,
                       gap_s: Optional[float] = None,
                       extend_ratio: float = GAP_EXTEND_RATIO
                       ) -> List[InvocationWindow]:
    """Split ONE module's capture events into per-dispatch invocation
    windows by gap-clustering the merged (all-device) timeline.

    Candidate boundaries are the positive idle gaps — instants where
    every device of the module went quiet before the next op started
    (overlapping devices merge: a mesh dispatch runs its shards
    concurrently, so intra-dispatch "gaps" on one device are covered
    by the other's ops). Which candidates become boundaries:

    - With an explicit ``gap_s``: every gap above it.
    - Auto (default): the op-count bounds anchor the clustering — a
      top-level unconditional op runs exactly once per dispatch, so
      the MIN positive per-(device, op) event count ``n_min`` is a
      FLOOR on invocations and the MAX count ``n_max`` (loop-body ops
      repeat per iteration) a CEILING. The largest ``n_min − 1`` gaps
      are definite boundaries; remaining gaps within
      ``extend_ratio`` of the smallest definite one also split
      (dispatches the MIN heuristic undercounted because its op was
      conditional).

    Either way at most ``n_max − 1`` boundaries are kept, so windows
    can never exceed the loop-iteration ceiling. Pure function of its
    inputs — fixture-pinned, deterministic (ties break by event
    order). An empty op list yields no windows; back-to-back
    dispatches with NO idle gap merge into one window (the caller's
    invocation count falls back to the ``n_min`` floor — see
    :func:`correlate`)."""
    mops = sorted(ops, key=lambda o: (o.start_s, o.dur_s, o.device))
    if not mops:
        return []
    counts: Dict[tuple, int] = collections.defaultdict(int)
    for op in mops:
        counts[(op.device, op.op)] += 1
    n_min = min(counts.values())
    n_max = max(counts.values())
    gaps: List[Tuple[float, int]] = []
    max_end = mops[0].start_s + mops[0].dur_s
    for i, op in enumerate(mops[1:], start=1):
        g = op.start_s - max_end
        if g > 0:
            gaps.append((g, i))
        max_end = max(max_end, op.start_s + op.dur_s)
    by_size = sorted(gaps, key=lambda gi: (-gi[0], gi[1]))
    if gap_s is not None:
        chosen = [(g, i) for g, i in by_size if g > gap_s]
    else:
        definite = by_size[:max(n_min - 1, 0)]
        chosen = list(definite)
        if definite:
            thresh = definite[-1][0] * extend_ratio
            chosen += [(g, i) for g, i in by_size[len(definite):]
                       if g >= thresh]
    chosen = chosen[:max(n_max - 1, 0)]
    cuts = sorted(i for _, i in chosen)
    windows: List[InvocationWindow] = []
    lo = 0
    for cut in cuts + [len(mops)]:
        chunk = mops[lo:cut]
        lo = cut
        if not chunk:
            continue
        phase: Dict[str, float] = collections.defaultdict(float)
        shard: Dict[str, float] = collections.defaultdict(float)
        for op in chunk:
            phase[op.phase] += op.dur_s
            shard[op.device] += op.dur_s
        windows.append(InvocationWindow(
            start_s=min(o.start_s for o in chunk),
            end_s=max(o.start_s + o.dur_s for o in chunk),
            ops=len(chunk),
            device_seconds=sum(o.dur_s for o in chunk),
            phase_seconds=dict(phase),
            shard_seconds=dict(shard),
        ))
    return windows


@dataclasses.dataclass
class ModuleAttribution:
    """Measured device truth for ONE resident executable.

    ``device_seconds`` is busy op-time summed over every device that
    ran the module (the roofline denominator); ``invocations`` the
    executions observed in the window — the exact per-dispatch window
    count from :func:`invocation_windows` gap-clustering (PR 12),
    floored by the MINIMUM positive per-(device, op) event count for
    captures whose back-to-back dispatches leave no idle gap to
    cluster on: a top-level op runs exactly once per execution,
    loop-body ops run once per iteration (which is why the maximum
    wildly overcounts), and conditionally-executed ops can only push
    the minimum DOWN, so the floor keeps the derived achieved GB/s
    conservative rather than inflated; ``phase_seconds`` buckets op
    time by the named-scope mesh phase markers; ``shard_seconds`` by
    device; ``windows`` the per-dispatch detail (per-window phase /
    shard seconds and straggler skew).
    ``modeled_bytes_per_call``/``flops`` come from the entry's
    compile-time cost analysis, so measured achieved GB/s is
    ``bytes x invocations / device_seconds``."""

    digest: str
    module: str
    family: str
    device_seconds: float
    invocations: int
    phase_seconds: Dict[str, float]
    shard_seconds: Dict[str, float]
    window: Tuple[float, float]
    modeled_bytes_per_call: float = 0.0
    modeled_flops_per_call: float = 0.0
    payload_model: Optional[dict] = None
    windows: List[InvocationWindow] = dataclasses.field(
        default_factory=list)

    def skew_samples(self) -> List[float]:
        """One straggler-skew sample per invocation window that ran on
        several devices — the per-dispatch distribution behind the
        ``serving.mesh.shard_skew_p99`` gauge."""
        return [w.skew for w in self.windows
                if len(w.shard_seconds) > 1]

    @property
    def mesh(self) -> bool:
        """Whether this executable is a mesh (sharded) program — the
        families whose modeled phase spans the measured ones
        supersede."""
        return (self.payload_model is not None
                or self.family.startswith("dist_"))

    def measured_gbps(self) -> float:
        if self.device_seconds <= 0:
            return 0.0
        return (self.modeled_bytes_per_call * self.invocations
                / self.device_seconds / 1e9)

    def measured_gflops(self) -> float:
        if self.device_seconds <= 0:
            return 0.0
        return (self.modeled_flops_per_call * self.invocations
                / self.device_seconds / 1e9)

    def to_dict(self) -> dict:
        return {
            "digest": self.digest,
            "module": self.module,
            "family": self.family,
            "device_seconds": self.device_seconds,
            "invocations": self.invocations,
            "phase_seconds": dict(self.phase_seconds),
            "shard_seconds": dict(self.shard_seconds),
            "window": list(self.window),
            "modeled_bytes_per_call": self.modeled_bytes_per_call,
            "modeled_flops_per_call": self.modeled_flops_per_call,
            "measured_gbps": self.measured_gbps(),
            "measured_gflops": self.measured_gflops(),
            "mesh": self.mesh,
            "invocation_windows": [w.to_dict() for w in self.windows],
        }


@dataclasses.dataclass
class Attribution:
    """One capture's full correlation result: per-executable measured
    device truth plus the ops that matched no resident executable
    (counted, not dropped silently — a capture dominated by
    unmatched ops means the cost table and the trace disagree about
    what is resident)."""

    modules: Dict[str, ModuleAttribution]
    unmatched_modules: Dict[str, float]
    trace_file: Optional[str] = None

    def to_dict(self) -> dict:
        return {
            "modules": {d: m.to_dict() for d, m in self.modules.items()},
            "unmatched_modules": dict(self.unmatched_modules),
            "trace_file": self.trace_file,
        }


def correlate(ops: Iterable[DeviceOp], costs: dict) -> Attribution:
    """Correlate parsed device ops back to executor entries.

    ``costs`` is ``SearchExecutor.executable_costs()`` — each entry
    carries the ``hlo_module`` name its compile stamped (unique per
    executable: the module is named after the cache-key digest), plus
    the modeled per-call bytes/flops and, for mesh entries, the
    collective payload model the measured phase spans re-attach.
    Pure function of its inputs — the committed capture fixture pins
    the whole pipeline byte-exactly."""
    modmap = {}
    for digest, info in costs.items():
        name = info.get("hlo_module")
        if name:
            modmap[name] = digest
    by_module: Dict[str, List[DeviceOp]] = collections.defaultdict(list)
    unmatched: Dict[str, float] = collections.defaultdict(float)
    for op in ops:
        if op.module in modmap:
            by_module[op.module].append(op)
        else:
            unmatched[op.module] += op.dur_s
    out: Dict[str, ModuleAttribution] = {}
    for module, mops in by_module.items():
        digest = modmap[module]
        info = costs[digest]
        phase: Dict[str, float] = collections.defaultdict(float)
        shard: Dict[str, float] = collections.defaultdict(float)
        op_counts: Dict[tuple, int] = collections.defaultdict(int)
        total = 0.0
        t0 = min(op.start_s for op in mops)
        t1 = max(op.start_s + op.dur_s for op in mops)
        for op in mops:
            total += op.dur_s
            phase[op.phase] += op.dur_s
            shard[op.device] += op.dur_s
            op_counts[(op.device, op.op)] += 1
        windows = invocation_windows(mops)
        out[digest] = ModuleAttribution(
            digest=digest, module=module,
            family=str(info.get("family", "")),
            device_seconds=total,
            # exact per-dispatch window count (PR 12 gap-clustering),
            # floored by the min positive per-(device, op) count:
            # loop-body ops repeat per iteration so the MAX overcounts,
            # and back-to-back dispatches with no idle gap merge into
            # one window so the clustering alone can UNDERcount — the
            # floor keeps the derived GB/s conservative either way
            invocations=max(len(windows), min(op_counts.values())),
            phase_seconds=dict(phase),
            shard_seconds=dict(shard),
            window=(t0, t1),
            modeled_bytes_per_call=float(info.get("bytes_accessed", 0.0)),
            modeled_flops_per_call=float(info.get("flops", 0.0)),
            payload_model=info.get("collective_payload"),
            windows=windows,
        )
    return Attribution(modules=out, unmatched_modules=dict(unmatched))


def attribute(source, costs: dict) -> Attribution:
    """The whole ingestion pipeline: load → parse → correlate.

    ``source`` is anything :func:`load_ops` accepts (a profile dir, a
    chrome-trace or ``.xplane.pb`` file, or an already-parsed chrome
    dict); ``costs`` is the executor's :meth:`executable_costs` table.
    Bumps the ``profiling.captures`` / ``profiling.device_ops``
    lifetime counters — the CI snapshot floor's evidence that trace
    ingestion stayed alive."""
    ops, trace_file = load_ops(source)
    attr = correlate(ops, costs)
    attr.trace_file = trace_file
    tracing.inc_counters({CAPTURES: 1.0, DEVICE_OPS: float(len(ops))})
    return attr


def _emit_measured_mesh(att: ModuleAttribution) -> None:
    """Re-emit one mesh executable's phase + shard spans from measured
    device time — the ``modeled: False`` counterpart of the modeled
    spans ``mesh_trace`` records per dispatch.

    Phase spans lay out sequentially from the capture window's start,
    each covering its mean per-invocation measured duration (attrs
    carry the totals); the modeled wire bytes ride along from the
    entry's payload model so Perfetto shows bytes over MEASURED time.
    Shard spans and the straggler gauges
    (``serving.mesh.{shard_skew,slowest_shard}``) recompute from mean
    per-invocation per-device busy seconds — superseding the
    host-side readiness poll's numbers."""
    inv = max(att.invocations, 1)
    t = att.window[0]
    phase_bytes = {}
    if att.payload_model:
        phase_bytes = {
            "coarse_select": att.payload_model.get("coarse_bytes", 0),
            "scan": 0,
            "merge": att.payload_model.get("merge_bytes", 0),
        }
    for marker in PHASE_MARKERS + (UNATTRIBUTED,):
        secs = att.phase_seconds.get(marker, 0.0)
        if secs <= 0.0:
            continue
        mean = secs / inv
        attrs = {"modeled": False, "source": "profiler",
                 "family": att.family, "digest": att.digest,
                 "device_seconds": secs, "invocations": att.invocations}
        if marker in phase_bytes:
            attrs["wire_bytes"] = phase_bytes[marker]
        tracing.record_span(f"serving.mesh.{marker}", t, t + mean,
                            attrs=attrs)
        t += mean
    if att.shard_seconds:
        timings = [att.shard_seconds[d] / inv
                   for d in sorted(att.shard_seconds)]
        tracing.record_mesh_spans(
            att.family, att.window[0],
            att.window[0] + max(timings),
            shard_timings=timings,
            shard_attrs={"modeled": False, "source": "profiler",
                         "digest": att.digest},
            # per-dispatch skew distribution (PR 12): one sample per
            # invocation window -> serving.mesh.shard_skew_p50/_p99
            skew_samples=att.skew_samples(),
            count_dispatch=False)


def publish(attr: Attribution) -> dict:
    """Publish one attribution into the live registries — the
    "measured supersedes modeled" half of graftflight.

    Per executable: ``serving.executable.<digest>.measured_*`` gauges
    (device seconds, invocations, achieved GB/s / GFLOP/s from
    modeled-bytes-over-measured-time — rendered as the labeled
    ``serving_executable_measured_*{digest=...}`` Prometheus
    families); mesh executables additionally re-emit their phase and
    shard spans with ``modeled: False`` (see
    :func:`_emit_measured_mesh`) — recomputing the straggler gauges
    from device timings. Process totals land in the
    ``serving.attribution.*`` counters ``serving.metrics.derived()``
    divides for the measured achieved-bandwidth columns. Returns
    ``{digest: measured-stats}``."""
    out = {}
    totals = {ATTRIBUTED_SECONDS: 0.0, ATTRIBUTED_BYTES: 0.0,
              ATTRIBUTED_FLOPS: 0.0}
    for digest, att in attr.modules.items():
        base = f"serving.executable.{digest}."
        stats = {
            "device_seconds": att.device_seconds,
            "invocations": att.invocations,
            "gbps": att.measured_gbps(),
            "gflops": att.measured_gflops(),
        }
        tracing.set_gauges({
            base + "measured_device_seconds": att.device_seconds,
            base + "measured_invocations": float(att.invocations),
            base + "measured_gbps": stats["gbps"],
            base + "measured_gflops": stats["gflops"],
        })
        totals[ATTRIBUTED_SECONDS] += att.device_seconds
        totals[ATTRIBUTED_BYTES] += (att.modeled_bytes_per_call
                                     * att.invocations)
        totals[ATTRIBUTED_FLOPS] += (att.modeled_flops_per_call
                                     * att.invocations)
        if att.mesh:
            _emit_measured_mesh(att)
        out[digest] = stats
    if attr.modules:
        tracing.inc_counters(totals)
    return out


class RollingAttribution:
    """EWMA-folded steady-state device truth (graftfleet, PR 12).

    Incident captures (graftflight) publish a point-in-time snapshot;
    the continuous low-duty-cycle scheduler
    (:class:`~raft_tpu.serving.continuous.ContinuousCapture`) instead
    folds every periodic capture window into THIS rolling state, so
    ``serving.attribution.rolling.*`` always carries a
    continuously-fresh measured number next to the wall-clock-derived
    one in ``serving.metrics.derived()`` — not the last incident's
    snapshot.

    Fold semantics (pinned by scripted tests): per capture window the
    totals (device seconds, modeled bytes/flops over all attributed
    executables), per-phase seconds, per-executable device seconds /
    bytes / flops, and the window's per-dispatch skew p99 each fold as
    ``ewma = alpha * x + (1 - alpha) * ewma`` (first fold seeds the
    state). Achieved GB/s is the RATIO of the byte and second EWMAs —
    stabler than an EWMA of ratios, and exactly the roofline
    accounting re-done on smoothed inputs. An executable ABSENT from a
    window holds its last value: a 100 ms capture simply may not have
    overlapped that program's traffic, which is no evidence it
    changed. Thread-safe; pure host-side dict work.

    Published gauges: ``serving.attribution.rolling.{windows,
    device_seconds,modeled_bytes,modeled_flops,gbps,gflops,
    shard_skew_p99}`` + ``.phase.<phase>_seconds``, and per
    executable the labeled ``serving.executable.<digest>
    .rolling_{gbps,device_seconds}`` family. The
    ``profiling.rolling.folds`` lifetime counter is the CI snapshot
    floor's evidence the pipeline stayed alive."""

    def __init__(self, alpha: float = 0.3):
        self.alpha = float(alpha)
        self._lock = threading.Lock()
        self._totals: Dict[str, float] = {}
        self._phases: Dict[str, float] = {}
        self._execs: Dict[str, Dict[str, float]] = {}
        self._skew_p99: Optional[float] = None
        self.windows = 0

    def _ewma(self, store: dict, key: str, x: float) -> float:
        prev = store.get(key)
        store[key] = (x if prev is None
                      else self.alpha * x + (1.0 - self.alpha) * prev)
        return store[key]

    def fold(self, attr: Attribution) -> Optional[dict]:
        """Fold one capture window's attribution; returns the rolling
        snapshot (None for a window that attributed nothing — an empty
        capture is not evidence of zero throughput)."""
        if not attr.modules:
            return None
        win_secs = sum(m.device_seconds for m in attr.modules.values())
        win_bytes = sum(m.modeled_bytes_per_call * m.invocations
                        for m in attr.modules.values())
        win_flops = sum(m.modeled_flops_per_call * m.invocations
                        for m in attr.modules.values())
        phases: Dict[str, float] = collections.defaultdict(float)
        skews: List[float] = []
        for m in attr.modules.values():
            for ph, s in m.phase_seconds.items():
                phases[ph] += s
            skews.extend(m.skew_samples())
        with self._lock:
            self.windows += 1
            self._ewma(self._totals, "device_seconds", win_secs)
            self._ewma(self._totals, "modeled_bytes", win_bytes)
            self._ewma(self._totals, "modeled_flops", win_flops)
            for ph, s in phases.items():
                self._ewma(self._phases, ph, s)
            if skews:
                x = tracing.sample_quantile(skews, 0.99)
                self._skew_p99 = (
                    x if self._skew_p99 is None
                    else self.alpha * x
                    + (1.0 - self.alpha) * self._skew_p99)
            for digest, m in attr.modules.items():
                ex = self._execs.setdefault(digest, {})
                self._ewma(ex, "device_seconds", m.device_seconds)
                self._ewma(ex, "modeled_bytes",
                           m.modeled_bytes_per_call * m.invocations)
                self._ewma(ex, "modeled_flops",
                           m.modeled_flops_per_call * m.invocations)
                self._ewma(ex, "invocations", float(m.invocations))
            snap = self._snapshot_locked()
        tracing.inc_counter(ROLLING_FOLDS)
        self._publish(snap)
        return snap

    @staticmethod
    def _rate(num: float, secs: float) -> float:
        return num / secs / 1e9 if secs > 0 else 0.0

    def _snapshot_locked(self) -> dict:
        t = self._totals
        out = {
            "windows": self.windows,
            "device_seconds": t.get("device_seconds", 0.0),
            "modeled_bytes": t.get("modeled_bytes", 0.0),
            "modeled_flops": t.get("modeled_flops", 0.0),
            "gbps": self._rate(t.get("modeled_bytes", 0.0),
                               t.get("device_seconds", 0.0)),
            "gflops": self._rate(t.get("modeled_flops", 0.0),
                                 t.get("device_seconds", 0.0)),
            "phase_seconds": dict(self._phases),
            "shard_skew_p99": self._skew_p99 or 0.0,
            "executables": {},
        }
        for digest, ex in self._execs.items():
            out["executables"][digest] = {
                "device_seconds": ex.get("device_seconds", 0.0),
                "invocations": ex.get("invocations", 0.0),
                "gbps": self._rate(ex.get("modeled_bytes", 0.0),
                                   ex.get("device_seconds", 0.0)),
                "gflops": self._rate(ex.get("modeled_flops", 0.0),
                                     ex.get("device_seconds", 0.0)),
            }
        return out

    def snapshot(self) -> dict:
        """The current rolling state (the gauges' source of truth)."""
        with self._lock:
            return self._snapshot_locked()

    def _publish(self, snap: dict) -> None:
        p = ROLLING_PREFIX
        vals = {
            p + "windows": float(snap["windows"]),
            p + "device_seconds": snap["device_seconds"],
            p + "modeled_bytes": snap["modeled_bytes"],
            p + "modeled_flops": snap["modeled_flops"],
            p + "gbps": snap["gbps"],
            p + "gflops": snap["gflops"],
            p + "shard_skew_p99": snap["shard_skew_p99"],
        }
        for ph, s in snap["phase_seconds"].items():
            vals[f"{p}phase.{ph}_seconds"] = s
        for digest, ex in snap["executables"].items():
            base = f"serving.executable.{digest}."
            vals[base + "rolling_gbps"] = ex["gbps"]
            vals[base + "rolling_device_seconds"] = ex["device_seconds"]
        tracing.set_gauges(vals)

    def publish(self) -> dict:
        """Re-publish the rolling gauges from the held state (scrape
        refresh after a ``metrics.reset()``) and return the snapshot."""
        snap = self.snapshot()
        if snap["windows"]:
            self._publish(snap)
        return snap
