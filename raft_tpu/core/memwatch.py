"""graftledger — the memory-truth plane (PR 13).

Every plane so far answered "what is the service *doing*" (spans,
probes, recall, device time); none answered "what does it *hold*".
The ROADMAP's tiered-storage direction needs exactly that signal:
hot/cold placement is traffic (graftgauge's probe planes) **times
bytes**, and today bytes exist only as compile-time
``memory_analysis()`` numbers per executable — the serving plane
cannot say "does this index fit?", "how close is this replica to
OOM?", or "which replica has headroom for the hot tier?" without
crashing a device to find out. This module is the byte accounting the
TPU-KNN roofline methodology (PAPERS.md) presumes and the
distributed-linalg paper's binding constraint (per-host footprint at
mesh scale) makes operational:

- **Resident-bytes model** (:func:`index_memory_model`) — a pure
  host-side model of one index's device-resident arrays: codes,
  packed sign words, correction scalars, the optional rerank plane,
  centroids — every array field of the (frozen-dataclass) index,
  byte-exact against ``arr.nbytes`` by construction
  (``prod(shape) * itemsize``; the tier-1 suite pins this per family).
  Mesh-sharded indexes model **per shard** through the arrays' own
  shardings (``sharding.shard_shape`` — host-side, no device sync).
- **Live backend truth** (:func:`device_memory_stats`) —
  ``device.memory_stats()`` (bytes_in_use / peak / limit) per local
  device, with an honest ``supported: False`` fallback on backends
  that don't expose it (CPU): the model keeps working, the live
  column reads absent rather than fake.
- **Reservation forecast** (:meth:`MemoryLedger.forecast`) — resident
  indexes + the executor's donated top-k state and probe planes +
  the max compile-time ``temp_bytes`` over its cached executables
  (any dispatch may be the one that peaks) → a per-device modeled
  peak. The divergence gauge (live in-use minus modeled resident) is
  the fragmentation/untracked-allocation signal — when it grows, the
  model is missing something real.
- **Capacity planning** (:meth:`MemoryLedger.fits`, :func:`admit`) —
  "would N more bytes fit?" answered host-side, and an **opt-in**
  typed :class:`CapacityExceeded` gate on the index build/extend
  paths (:func:`install_gate`) so admission fails in Python BEFORE a
  device OOM takes the replica down. Without an installed gate every
  build/extend admits exactly as before — the gate is a deployment
  decision, not a default.
- **Watermark sampling at dispatch**
  (:meth:`MemoryLedger.sample_dispatch`) — the executor folds a
  live high-water mark per dispatch. ``memory_stats()`` is a
  host-only backend call (no device sync, nothing traced): the
  zero-recompile and bit-identity regressions run with the ledger
  fully enabled and stay green (tested, single-chip and mesh). On
  unsupported backends the sample degrades to the heartbeat counter
  (``memory.samples`` — the CI snapshot floor) and the modeled
  watermark.

Gauges (published by :meth:`MemoryLedger.publish`, scrape-time):

- ``memory.index.<label>.resident_bytes`` (+ ``.shard_bytes`` on the
  mesh) — per watched index; rendered labeled
  (``memory_index_resident_bytes{index="..."}``)
- ``memory.device.<ordinal>.{in_use_bytes,peak_bytes,limit_bytes}``
  — live truth per device (only when supported); rendered labeled
  (``memory_device_in_use_bytes{device="0"}``)
- ``memory.resident.total_bytes`` / ``memory.reserved.
  {donated_state,probe_planes,max_temp}_bytes`` — the forecast's
  modeled terms
- ``memory.forecast.peak_bytes`` — max per-device modeled peak
- ``memory.hbm.headroom_bytes`` — live headroom (min over devices of
  limit − in_use); −1 when unknowable (no live stats, no configured
  capacity)
- ``memory.divergence_bytes`` — live in-use total minus modeled
  total (fragmentation / untracked allocations); only when live is
  supported
- ``memory.live.supported`` — 1/0
- ``memory.watermark.{in_use,forecast}_peak_bytes`` — dispatch-time
  high-water marks
- ``memory.samples`` / ``memory.gate.{admitted,refused}`` —
  lifetime counters (``memory.samples`` is the snapshot-floor
  heartbeat: watermark sampling staying wired into dispatch)

Host-sync discipline (graftlint R5 — this module is IN scope, like
``core/executor.py``): everything here is shape/dtype arithmetic and
backend introspection; nothing fetches a device array. Clock
discipline (R7 — also in scope): the ledger keeps no timestamps at
all; if one is ever needed it must come from an injected clock.
"""

from __future__ import annotations

import dataclasses
import math
import re
import threading
import weakref
from typing import Any, Dict, Optional

import jax
import numpy as np

from raft_tpu.core import tracing
from raft_tpu.core.validation import expect

SAMPLES = "memory.samples"
GATE_ADMITTED = "memory.gate.admitted"
GATE_REFUSED = "memory.gate.refused"

# gauge labels must stay ONE dot-delimited segment of the registry
# name so the exporter's labeled-family regexes can lift them into
# {index="..."} labels (same contract as graftgauge's probe labels)
_LABEL_SUB = re.compile(r"[^A-Za-z0-9_:-]").sub


class CapacityExceeded(RuntimeError):
    """Typed admission failure of the capacity gate: the planned
    allocation does not fit the device's remaining headroom. Raised
    HOST-SIDE, before any device allocation happens — the caller gets
    a catchable Python error instead of a backend OOM abort. Carries
    the numbers the refusal was computed from."""

    def __init__(self, what: str, required_bytes: int,
                 headroom_bytes: float):
        self.what = what
        self.required_bytes = int(required_bytes)
        self.headroom_bytes = float(headroom_bytes)
        super().__init__(
            f"{what}: planned allocation of {self.required_bytes} bytes "
            f"exceeds the remaining device headroom of "
            f"{int(self.headroom_bytes)} bytes (graftledger capacity "
            "gate — see raft_tpu.core.memwatch.install_gate)")


def _is_array(v: Any) -> bool:
    """Device/host arrays only — the index dataclasses also carry
    enums, bools and the mesh ``comms`` handle."""
    return hasattr(v, "shape") and hasattr(v, "dtype") \
        and not isinstance(v, (int, float, bool))


def array_bytes(a) -> int:
    """GLOBAL byte size of one array from shape × itemsize — pure
    host metadata, byte-exact against ``a.nbytes`` for the dense
    layouts every index family uses (pinned per family in tier-1)."""
    shape = tuple(a.shape)
    return int(math.prod(shape)) * int(a.dtype.itemsize)


def shard_bytes(a) -> int:
    """PER-DEVICE byte size: the array's own sharding says what one
    device actually holds (``shard_shape`` is host-side metadata —
    no placement query touches the device). Unsharded / replicated
    arrays resolve to their full size."""
    sharding = getattr(a, "sharding", None)
    if sharding is None:
        return array_bytes(a)
    try:
        shape = sharding.shard_shape(tuple(a.shape))
    except Exception:  # noqa: BLE001 — unknown sharding kinds fall back honest
        return array_bytes(a)
    return int(math.prod(shape)) * int(a.dtype.itemsize)


def per_device_bytes(a, acc: Optional[Dict[int, int]] = None
                     ) -> Dict[int, int]:
    """Fold one array's per-device residency into ``acc`` (ordinal →
    bytes): each device in the array's sharding holds one shard
    (replicated shardings hold the full array on every device). The
    forecast sums these maps across every resident array so the peak
    is per-DEVICE — the unit a device OOM is measured in."""
    acc = {} if acc is None else acc
    sb = shard_bytes(a)
    sharding = getattr(a, "sharding", None)
    devices = getattr(sharding, "device_set", None)
    if not devices:
        acc[0] = acc.get(0, 0) + array_bytes(a)
        return acc
    for d in devices:
        o = int(d.id)
        acc[o] = acc.get(o, 0) + sb
    return acc


# memory kinds that mean "this array's bytes live in HOST memory, not
# HBM" — the grafttier cold plane's placement (an array committed via
# jax.device_put(..., memory_kind="pinned_host")). Plain numpy arrays
# are host-side by construction.
_HOST_MEMORY_KINDS = ("pinned_host", "unpinned_host")


def memory_tier(a) -> str:
    """Which memory an array's bytes occupy: ``"host"`` for numpy
    arrays and device arrays committed OFF their device's default
    memory into a host kind (the grafttier cold tier), ``"device"``
    otherwise. Pure metadata — reads the array's own sharding, never
    the backend.

    A host memory KIND alone does not mean off-device: the CPU
    backend's default memory is itself ``unpinned_host`` (host and
    device are one pool there), so classification compares against
    the device's DEFAULT memory — only an array deliberately moved
    off it counts as host-tier."""
    if isinstance(a, np.ndarray):
        return "host"
    sharding = getattr(a, "sharding", None)
    kind = getattr(sharding, "memory_kind", None)
    if kind not in _HOST_MEMORY_KINDS:
        return "device"
    for d in getattr(sharding, "device_set", None) or ():
        try:
            if d.default_memory().kind == kind:
                return "device"
        except Exception:  # noqa: BLE001 — no memories API: kind decides
            break
    return "host"


def index_memory_model(index) -> dict:
    """The resident-bytes model of one index: per-component (array
    field) global and per-shard bytes, plus the totals. Works for
    every frozen-dataclass index family — single-chip and mesh-
    sharded (``shard_bytes`` reads each array's own sharding) — and
    skips optional fields that are ``None`` (a codes-only BQ index
    has no rerank plane, and models exactly that much smaller).

    Components whose bytes live in HOST memory (:func:`memory_tier` —
    the grafttier cold plane, numpy mirrors) fold into
    ``host_resident_bytes`` INSTEAD of the device totals: the device
    forecast, headroom arithmetic and divergence gauge must never
    count bytes that were deliberately moved off-HBM, while the host
    tier still shows up as its own accountable number."""
    expect(dataclasses.is_dataclass(index),
           f"index_memory_model needs an index dataclass, got "
           f"{type(index)!r}")
    components: dict = {}
    total = 0
    shard_total = 0
    host_total = 0
    per_device: Dict[int, int] = {}
    for f in dataclasses.fields(index):
        v = getattr(index, f.name, None)
        if v is None or not _is_array(v):
            continue
        b = array_bytes(v)
        tier = memory_tier(v)
        components[f.name] = {
            "bytes": b,
            "shard_bytes": shard_bytes(v),
            "shape": [int(s) for s in v.shape],
            "dtype": str(v.dtype),
            "tier": tier,
        }
        if tier == "host":
            host_total += b
            continue
        total += b
        shard_total += components[f.name]["shard_bytes"]
        per_device_bytes(v, per_device)
    return {
        "family": type(index).__name__,
        "components": components,
        "resident_bytes": total,
        "shard_resident_bytes": shard_total,
        "host_resident_bytes": host_total,
        "per_device_bytes": per_device,
    }


def packed_layout_bytes(n_lists: int, max_list_size: int,
                        row_bytes: int, *,
                        norms: bool = True,
                        indices: bool = True) -> int:
    """Planned bytes of one padded ``(n_lists, max_list_size, ...)``
    list layout BEFORE it is allocated — the number the build/extend
    capacity gate admits against. ``row_bytes`` is the per-slot
    payload (``dim * itemsize`` for flat data, ``pq_dim`` code bytes,
    packed-word + correction bytes for BQ); ``norms``/``indices`` add
    the f32 norm and int32 id planes most layouts carry."""
    slots = int(n_lists) * int(max_list_size)
    b = slots * int(row_bytes)
    if norms:
        b += slots * 4
    if indices:
        b += slots * 4
    return b


def dealt_shard_bytes(arrays, r: int) -> int:
    """Per-shard bytes of dealing these build-device tensors across
    ``r`` shards — the slot model the DISTRIBUTED build staging
    admits against (each mesh device receives ``ceil(rows / r)``
    list blocks of every dealt tensor; headroom is per-device, so
    per-shard bytes is the unit the gate must judge in). Pure shape
    arithmetic, computed BEFORE ``place_dealt`` moves anything."""
    total = 0
    for a in arrays:
        if a is None or not _is_array(a):
            continue
        rows = -(-int(a.shape[0]) // max(int(r), 1))
        rest = int(math.prod(tuple(a.shape)[1:]))
        total += rows * rest * int(a.dtype.itemsize)
    return total


def device_memory_stats(devices=None) -> dict:
    """Live backend truth: ``device.memory_stats()`` per local
    device. Returns ``{"supported": bool, "devices": {ordinal:
    {"in_use_bytes", "peak_bytes", "limit_bytes"}}}`` — a backend
    that exposes no stats (CPU) yields ``supported: False`` with an
    empty device map, never invented numbers. A host-only backend
    call: nothing is dispatched, nothing synced."""
    if devices is None:
        devices = jax.local_devices()
    out: Dict[str, Any] = {"supported": False, "devices": {}}
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 — introspection must never raise out
            stats = None
        if not stats:
            continue
        out["supported"] = True
        out["devices"][int(d.id)] = {
            "in_use_bytes": float(stats.get("bytes_in_use", 0)),
            "peak_bytes": float(stats.get("peak_bytes_in_use",
                                          stats.get("bytes_in_use", 0))),
            "limit_bytes": float(stats.get("bytes_limit", 0)),
        }
    return out


class MemoryLedger:
    """The memory-truth plane of one serving process.

    ``executor`` (optional) wires the two dispatch-path touchpoints:
    the executor calls :meth:`sample_dispatch` after every dispatch
    (host-only watermark fold), and the forecast reads the executor's
    donated-state / probe-plane / compile-time-temp reservations
    through :meth:`~raft_tpu.core.executor.SearchExecutor
    .memory_reservations`. ``capacity_bytes`` is an explicit
    per-device capacity for backends without live ``memory_stats``
    (CPU tests, or an operator pinning a budget below the physical
    limit); live limits win when present.

    Example::

        ledger = MemoryLedger(executor=ex)
        ledger.watch("sift-flat", index)
        memwatch.install_gate(ledger)        # opt-in build/extend gate
        exp = MetricsExporter(executor=ex, memory=ledger)
        # /memory.json + memory_* families now serve the byte truth

    Thread-safety: one lock guards the watch map and watermarks;
    every read path (snapshot/publish/forecast) recomputes from live
    metadata — the ledger caches nothing an extend could invalidate.
    """

    def __init__(self, executor=None, *,
                 capacity_bytes: Optional[float] = None):
        self.capacity_bytes = capacity_bytes
        self._lock = threading.Lock()
        # label -> weakref(index): a dropped index must not be held
        # resident by its own accounting (mirrors the executor's
        # probe-plane death watch)
        self._watched: "dict[str, weakref.ref]" = {}  # guarded-by: _lock
        # memory_stats support is probed once: on unsupported
        # backends the per-dispatch sample degrades to the heartbeat
        # counter instead of paying a doomed backend call per dispatch
        self._live_supported: Optional[bool] = None
        # the per-dispatch sample runs inside the executor's locked
        # dispatch core: cache the device list once so the hot path
        # never re-enumerates backends, only reads their stats
        self._devices = None
        self._wm_in_use = 0.0     # guarded-by: _lock
        self._wm_forecast = 0.0   # guarded-by: _lock
        # named byte holds (graftcast prefetch and friends): bytes a
        # background channel has claimed but serving must still see
        # as spoken for — headroom subtracts them, so an admission
        # racing a prefetch can never both win the same bytes
        self._reservations: Dict[str, int] = {}  # guarded-by: _lock
        # the last snapshot publish() produced (the flight recorder's
        # low-headroom trigger reads it instead of recomputing the
        # whole truth the same scrape just published)
        self.last_snapshot: Optional[dict] = None
        self.executor = None
        if executor is not None:
            self.attach(executor)

    # -- wiring -------------------------------------------------------------

    def attach(self, executor) -> "MemoryLedger":
        """Wire ``executor`` both ways: its dispatches sample the
        watermark, the forecast reads its reservations."""
        self.executor = executor
        if hasattr(executor, "attach_memwatch"):
            executor.attach_memwatch(self)
        return self

    def watch(self, label: str, index) -> str:
        """Register ``index`` under ``label`` (sanitized to one
        dot-free gauge segment; returned). Re-watching a label
        replaces it — the rebuild/extend pattern."""
        label = _LABEL_SUB("-", str(label)) or "index"
        with self._lock:
            self._watched[label] = weakref.ref(index)
        return label

    def unwatch(self, label: str) -> None:
        with self._lock:
            self._watched.pop(label, None)
        tracing.reset_gauges(f"memory.index.{label}.")

    def _watched_models_locked(self) -> dict:
        out = {}
        dead = []
        for label, ref in self._watched.items():
            index = ref()
            if index is None:
                dead.append(label)
                continue
            out[label] = index_memory_model(index)
        for label in dead:
            self._watched.pop(label, None)
        return out

    # -- model + forecast ---------------------------------------------------

    def resident(self) -> dict:
        """``{label: index_memory_model(index)}`` for every watched
        index still alive — pure metadata, no device touch."""
        with self._lock:
            return self._watched_models_locked()

    def live(self) -> dict:
        """:func:`device_memory_stats`, support-probed once."""
        stats = device_memory_stats()
        self._live_supported = stats["supported"]
        return stats

    def forecast(self, models: Optional[dict] = None) -> dict:
        """The reservation forecast: watched resident bytes + the
        executor's donated state / probe planes + its max
        compile-time temp, folded per device; ``peak_bytes`` is the
        worst device's modeled peak (the unit an OOM happens in).
        ``models`` lets a caller that already walked the watched
        indexes (:meth:`snapshot` does) skip a second walk."""
        if models is None:
            with self._lock:
                models = self._watched_models_locked()
        per_device: Dict[int, float] = {}
        resident_total = 0
        for model in models.values():
            resident_total += model["resident_bytes"]
            for o, b in model["per_device_bytes"].items():
                per_device[o] = per_device.get(o, 0.0) + b
        donated = probe = temp = 0.0
        if self.executor is not None and hasattr(
                self.executor, "memory_reservations"):
            res = self.executor.memory_reservations()
            donated = float(sum(res["donated_state_bytes"].values()))
            probe = float(sum(res["probe_plane_bytes"].values()))
            temp = float(res["max_temp_bytes"])
            for part in ("donated_state_bytes", "probe_plane_bytes"):
                for o, b in res[part].items():
                    per_device[o] = per_device.get(o, 0.0) + b
            # any dispatch may be the one that peaks: the max temp
            # reserves on EVERY device holding state (or device 0
            # when nothing is resident yet)
            for o in list(per_device) or [0]:
                per_device[o] = per_device.get(o, 0.0) + temp
        peak = max(per_device.values()) if per_device else 0.0
        return {
            "resident_bytes": float(resident_total),
            "donated_state_bytes": donated,
            "probe_plane_bytes": probe,
            "max_temp_bytes": temp,
            "per_device_bytes": {int(o): float(b)
                                 for o, b in per_device.items()},
            "peak_bytes": float(peak),
        }

    def _headroom_from(self, stats: dict,
                       fc: Optional[dict]) -> Optional[float]:
        """Headroom from already-computed inputs (``fc`` may be a
        thunkable None when live stats decide) — shared by the public
        :meth:`headroom_bytes` and :meth:`snapshot` so one scrape
        never re-reads the backend or re-walks the model for the same
        answer. Named holds (:meth:`reserve`) subtract LAST: reserved
        bytes are spoken for whichever source measured the room."""
        base: Optional[float] = None
        if stats["supported"] and stats["devices"]:
            rooms = [d["limit_bytes"] - d["in_use_bytes"]
                     for d in stats["devices"].values()
                     if d["limit_bytes"] > 0]
            if rooms:
                base = float(min(rooms))
        if base is None and self.capacity_bytes is not None:
            if fc is None:
                fc = self.forecast()
            base = float(self.capacity_bytes - fc["peak_bytes"])
        if base is None:
            return None
        return base - self.reserved_bytes()

    def headroom_bytes(self) -> Optional[float]:
        """Remaining per-device headroom: min over devices of
        ``limit − in_use`` from live stats; with no live support,
        ``capacity_bytes − forecast peak`` when a capacity was
        configured; ``None`` when genuinely unknowable (the gate then
        admits — refusing on ignorance would break every CPU test)."""
        return self._headroom_from(self.live(), None)

    # -- capacity planning --------------------------------------------------

    def fits(self, what, *, safety_fraction: float = 0.0) -> dict:
        """Capacity-planner verdict for ``what`` — an index (modeled
        through :func:`index_memory_model`; mesh indexes ask per
        shard), an index model dict, or a plain byte count. Returns
        ``{"fits", "required_bytes", "headroom_bytes", "unknown"}``;
        ``unknown: True`` (and ``fits: True``) when no headroom source
        exists — the honest answer, distinguishable from a measured
        yes. ``safety_fraction`` reserves that share of the headroom
        (0.1 = keep 10% free)."""
        if isinstance(what, (int, float)):
            required = int(what)
        elif isinstance(what, dict):
            required = int(what.get("shard_resident_bytes",
                                    what.get("resident_bytes", 0)))
        else:
            model = index_memory_model(what)
            required = int(model["shard_resident_bytes"])
        headroom = self.headroom_bytes()
        if headroom is None:
            return {"fits": True, "unknown": True,
                    "required_bytes": required, "headroom_bytes": None}
        usable = headroom * (1.0 - safety_fraction)
        return {"fits": required <= usable, "unknown": False,
                "required_bytes": required,
                "headroom_bytes": float(headroom)}

    def admit(self, nbytes: int, what: str) -> None:
        """Gate one planned allocation: raise :class:`CapacityExceeded`
        when ``nbytes`` exceeds the current headroom (known-headroom
        case only — see :meth:`fits`). Counts every decision
        (``memory.gate.admitted`` / ``.refused``)."""
        verdict = self.fits(nbytes)
        if not verdict["fits"]:
            tracing.inc_counter(GATE_REFUSED)
            raise CapacityExceeded(what, nbytes,
                                   verdict["headroom_bytes"])
        tracing.inc_counter(GATE_ADMITTED)

    # -- named reservations (graftcast prefetch) ----------------------------

    def reserve(self, what: str, nbytes: int) -> None:
        """Set the named hold ``what`` to ``nbytes``: the bytes a
        background channel (the tier prefetcher's staged miss cache)
        has claimed ahead of placement. Held bytes subtract from
        every subsequent :meth:`headroom_bytes` read, so a build /
        extend / sibling-prefetch admission racing this channel can
        never be granted the same bytes — a prefetch can never OOM
        what serving needs. GROWING a hold passes through the
        capacity gate (:class:`CapacityExceeded` on refusal, decision
        counted like :meth:`admit`; the prior hold is kept);
        shrinking — including to 0 — is always admissible."""
        nbytes = int(nbytes)
        expect(nbytes >= 0, "a reservation cannot hold negative bytes")
        with self._lock:
            prev = int(self._reservations.pop(what, 0))
            if nbytes <= prev:
                if nbytes > 0:
                    self._reservations[what] = nbytes
                return
        # growth: judged against headroom WITHOUT the prior hold
        # (popped above) — the gate prices the full new hold, not
        # the delta on top of bytes it already refused once
        verdict = self.fits(nbytes)
        if not verdict["fits"]:
            with self._lock:
                if prev > 0:
                    self._reservations[what] = prev
            tracing.inc_counter(GATE_REFUSED)
            raise CapacityExceeded(what, nbytes,
                                   verdict["headroom_bytes"])
        tracing.inc_counter(GATE_ADMITTED)
        with self._lock:
            self._reservations[what] = nbytes

    def release(self, what: str) -> None:
        """Drop the named hold entirely (idempotent)."""
        with self._lock:
            self._reservations.pop(what, None)

    def reserved_bytes(self) -> float:
        """Total bytes across all named holds."""
        with self._lock:
            return float(sum(self._reservations.values()))

    # -- dispatch-time watermark --------------------------------------------

    def sample_dispatch(self) -> None:
        """One watermark sample, called by the executor after each
        dispatch. Host-only: a backend ``memory_stats()`` read (never
        a device sync — nothing here enters or waits on the compiled
        program; the zero-recompile and bit-identity regressions run
        with this enabled). On unsupported backends (probed ONCE) it
        degrades to the heartbeat counter — the CI snapshot floor
        that proves sampling stayed wired into dispatch."""
        tracing.inc_counter(SAMPLES)
        if self._live_supported is False:
            return
        if self._devices is None:
            self._devices = jax.local_devices()
        stats = device_memory_stats(self._devices)
        if self._live_supported is None:
            self._live_supported = stats["supported"]
        if not stats["supported"]:
            return
        in_use = sum(d["in_use_bytes"]
                     for d in stats["devices"].values())
        with self._lock:
            self._wm_in_use = max(self._wm_in_use, in_use)

    # -- scrape surface -----------------------------------------------------

    def snapshot(self) -> dict:
        """The ``/memory.json`` body: model, live truth, forecast,
        headroom, divergence, watermarks — one structured view, all
        recomputed fresh (the ledger is stateless like the exporter:
        an extend between scrapes changes the next scrape)."""
        # each input computed exactly once per snapshot: one model
        # walk, one backend stats read, one executor-reservation read
        with self._lock:
            models = self._watched_models_locked()
        live = self.live()
        fc = self.forecast(models)
        headroom = self._headroom_from(live, fc)
        divergence = None
        if live["supported"] and live["devices"]:
            in_use = sum(d["in_use_bytes"]
                         for d in live["devices"].values())
            modeled = (fc["resident_bytes"] + fc["donated_state_bytes"]
                       + fc["probe_plane_bytes"])
            divergence = float(in_use - modeled)
        with self._lock:
            self._wm_forecast = max(self._wm_forecast, fc["peak_bytes"])
            wm_in_use, wm_forecast = self._wm_in_use, self._wm_forecast
        host_total = sum(m.get("host_resident_bytes", 0)
                         for m in models.values())
        return {
            "supported": live["supported"],
            "devices": live["devices"],
            "indexes": models,
            "resident_total_bytes": fc["resident_bytes"],
            "host_resident_total_bytes": float(host_total),
            "forecast": fc,
            "reserved_held_bytes": self.reserved_bytes(),
            "headroom_bytes": headroom,
            "divergence_bytes": divergence,
            "watermark": {"in_use_peak_bytes": wm_in_use,
                          "forecast_peak_bytes": wm_forecast},
            "capacity_bytes": self.capacity_bytes,
        }

    def publish(self) -> dict:
        """Publish the gauge surface from one :meth:`snapshot` (the
        exporter's scrape refresh calls this) and return the
        snapshot. Stale per-index gauges retire first — an unwatched
        or collected index must not linger at its old value."""
        snap = self.snapshot()
        tracing.reset_gauges("memory.index.")
        tracing.reset_gauges("memory.device.")
        vals: Dict[str, float] = {
            "memory.live.supported": 1.0 if snap["supported"] else 0.0,
            "memory.resident.total_bytes": snap["resident_total_bytes"],
            "memory.host.resident_bytes":
                snap["host_resident_total_bytes"],
            "memory.reserved.donated_state_bytes":
                snap["forecast"]["donated_state_bytes"],
            "memory.reserved.probe_planes_bytes":
                snap["forecast"]["probe_plane_bytes"],
            "memory.reserved.max_temp_bytes":
                snap["forecast"]["max_temp_bytes"],
            "memory.reserved.held_bytes": snap["reserved_held_bytes"],
            "memory.forecast.peak_bytes": snap["forecast"]["peak_bytes"],
            "memory.hbm.headroom_bytes":
                -1.0 if snap["headroom_bytes"] is None
                else float(snap["headroom_bytes"]),
            "memory.watermark.in_use_peak_bytes":
                snap["watermark"]["in_use_peak_bytes"],
            "memory.watermark.forecast_peak_bytes":
                snap["watermark"]["forecast_peak_bytes"],
        }
        if snap["divergence_bytes"] is not None:
            vals["memory.divergence_bytes"] = snap["divergence_bytes"]
        for label, model in snap["indexes"].items():
            base = f"memory.index.{label}."
            vals[base + "resident_bytes"] = float(
                model["resident_bytes"])
            vals[base + "shard_bytes"] = float(
                model["shard_resident_bytes"])
            if model.get("host_resident_bytes"):
                vals[base + "host_bytes"] = float(
                    model["host_resident_bytes"])
        for o, d in snap["devices"].items():
            base = f"memory.device.{o}."
            vals[base + "in_use_bytes"] = d["in_use_bytes"]
            vals[base + "peak_bytes"] = d["peak_bytes"]
            vals[base + "limit_bytes"] = d["limit_bytes"]
        tracing.set_gauges(vals)
        # same-scrape consumers (the flight recorder's low-headroom
        # trigger runs right after the exporter's publish) read this
        # instead of recomputing the truth that was just computed
        self.last_snapshot = snap
        return snap

    def federation_payload(self) -> dict:
        """The type-correct fleet-merge inputs (shipped inside
        ``/snapshot.json`` as the ``memory`` block): per-index
        resident bytes SUM fleet-side (each replica holds its own
        copy), headroom takes the fleet MIN (placement goes where the
        worst-off replica still fits), device truth rides per replica
        for the labeled exposition. A replica without live support
        ships ``headroom_bytes: null`` — the aggregator skips it in
        the min rather than treating ignorance as infinite room.

        Reuses the snapshot :meth:`publish` just produced when one
        exists: the exporter's scrape refresh publishes BEFORE the
        ``/snapshot.json`` body assembles, so recomputing here would
        double every model walk, backend stats read and executor-lock
        acquisition per scrape for identical data. Callers that never
        publish pay one fresh snapshot."""
        snap = (self.last_snapshot if self.last_snapshot is not None
                else self.snapshot())
        return {
            "supported": snap["supported"],
            "resident": {label: int(m["resident_bytes"])
                         for label, m in snap["indexes"].items()},
            "resident_total_bytes": int(snap["resident_total_bytes"]),
            "host_resident_total_bytes":
                int(snap["host_resident_total_bytes"]),
            "forecast_peak_bytes": snap["forecast"]["peak_bytes"],
            "headroom_bytes": snap["headroom_bytes"],
            "divergence_bytes": snap["divergence_bytes"],
            "devices": snap["devices"],
        }


# ---------------------------------------------------------------------------
# /memory_profile diffing — per-buffer divergence attribution
# ---------------------------------------------------------------------------


def parse_memory_profile(data: bytes) -> Dict[str, int]:
    """Aggregate one ``jax.profiler.device_memory_profile`` capture
    (pprof wire format, gzip or raw) into per-buffer-group byte
    totals: ``{label_key: bytes}`` where ``label_key`` renders each
    sample's pprof labels (``kind=buffer,shape=f32[...],...``) — the
    grouping the divergence gauge can point AT, instead of at the
    whole process. Pure stdlib: gzip + the protobuf wire reader
    :func:`raft_tpu.core.xplane.fields` (varints and length-delimited
    payloads only; unknown fields skipped per proto semantics).

    The summed value is the sample type whose unit string is
    ``bytes`` (pprof heap profiles carry ``(objects, bytes)`` pairs);
    captures exposing no byte-typed value fall back to the LAST value
    column, pprof's space convention."""
    import gzip

    from raft_tpu.core.xplane import _read_varint, fields

    if data[:2] == b"\x1f\x8b":
        data = gzip.decompress(data)

    strings: list = []
    sample_units: list = []
    samples: list = []
    for fnum, wtype, val in fields(data):
        if fnum == 6 and wtype == 2:          # string_table
            strings.append(val.decode("utf-8", "replace"))
        elif fnum == 1 and wtype == 2:        # sample_type: ValueType
            unit_idx = 0
            for f2, w2, v2 in fields(val):
                if f2 == 2 and w2 == 0:
                    unit_idx = v2
            sample_units.append(unit_idx)
        elif fnum == 2 and wtype == 2:        # sample
            samples.append(val)

    def string_at(i: int) -> str:
        return strings[i] if 0 <= i < len(strings) else ""

    value_idx = len(sample_units) - 1
    for i, unit in enumerate(sample_units):
        if string_at(unit) == "bytes":
            value_idx = i
            break

    out: Dict[str, int] = {}
    for raw in samples:
        values: list = []
        labels: list = []
        for fnum, wtype, val in fields(raw):
            if fnum == 2:                     # value: repeated int64
                if wtype == 0:
                    values.append(val)
                elif wtype == 2:              # packed
                    pos = 0
                    while pos < len(val):
                        v, pos = _read_varint(val, pos)
                        values.append(v)
            elif fnum == 3 and wtype == 2:    # label
                key = s = num = 0
                has_num = False
                for f2, w2, v2 in fields(val):
                    if f2 == 1 and w2 == 0:
                        key = v2
                    elif f2 == 2 and w2 == 0:
                        s = v2
                    elif f2 == 3 and w2 == 0:
                        num = v2
                        has_num = True
                kname = string_at(key)
                if not kname:
                    continue
                value = string_at(s) if s else (
                    str(num) if has_num else "")
                labels.append(f"{kname}={value}")
        if not values:
            continue
        v = values[value_idx] if value_idx < len(values) else values[-1]
        label_key = ",".join(sorted(labels)) or "(unlabeled)"
        out[label_key] = out.get(label_key, 0) + int(v)
    return out


def diff_memory_profiles(before: Dict[str, int],
                         after: Dict[str, int]) -> dict:
    """Per-buffer-group divergence between two parsed captures:
    ``deltas`` (largest |delta| first; ties by label) name which
    buffer groups grew or shrank across the window the two
    sequence-numbered captures bracket — the attribution that turns
    the process-wide divergence gauge into an answer."""
    keys = sorted(set(before) | set(after))
    deltas = []
    for key in keys:
        b = int(before.get(key, 0))
        a = int(after.get(key, 0))
        if a != b:
            deltas.append({"label": key, "from_bytes": b,
                           "to_bytes": a, "delta_bytes": a - b})
    deltas.sort(key=lambda d: (-abs(d["delta_bytes"]), d["label"]))
    return {
        "deltas": deltas,
        "total_before_bytes": int(sum(before.values())),
        "total_after_bytes": int(sum(after.values())),
        "total_delta_bytes": int(sum(after.values())
                                 - sum(before.values())),
    }


# ---------------------------------------------------------------------------
# the opt-in build/extend capacity gate
# ---------------------------------------------------------------------------

_GATE: Optional[MemoryLedger] = None  # guarded-by: _GATE_LOCK
_GATE_LOCK = threading.Lock()


def install_gate(ledger: MemoryLedger) -> None:
    """Arm the process-wide capacity gate: every index build/extend
    allocation point calls :func:`admit` with its planned bytes, and
    :class:`CapacityExceeded` is raised host-side when they don't
    fit. Opt-in by design — without this call, :func:`admit` is a
    no-op and build/extend behave exactly as before."""
    global _GATE
    with _GATE_LOCK:
        _GATE = ledger


def remove_gate() -> None:
    """Disarm the gate (tests; a deployment turning the gate off)."""
    global _GATE
    with _GATE_LOCK:
        _GATE = None


def gate() -> Optional[MemoryLedger]:
    """The armed ledger, or None."""
    with _GATE_LOCK:
        return _GATE


def admit(nbytes: int, what: str) -> None:
    """Module-level gate check the build/extend paths call: no-op
    unless a gate is installed (the opt-in), else
    :meth:`MemoryLedger.admit`."""
    g = gate()
    if g is not None:
        g.admit(int(nbytes), what)
