"""Versioned binary serialization, NumPy ``.npy``-framed.

The reference serializes every index as a stream of scalars + mdspans in
NumPy ``.npy`` encoding (``core/serialize.hpp:35-116``,
``core/detail/mdspan_numpy_serializer.hpp``). We reuse the exact same wire
idea — scalars are 0-d ``.npy`` records, arrays are ``.npy`` records — so
indexes saved here are plain concatenated npy streams, inspectable with
``numpy.lib.format``. Each index format carries a version scalar checked at
load, mirroring e.g. IVF-Flat v4 (``detail/ivf_flat_serialize.cuh:37``).
"""

from __future__ import annotations

import io
from typing import Any, BinaryIO, Union

import jax
import numpy as np
from numpy.lib import format as npy_format

Writable = Union[BinaryIO, io.BufferedIOBase]


# ml_dtypes extension dtypes (bfloat16, float8_*) have no .npy descr —
# write_array stores them as raw void records ("|V2") that lose the
# type. They ride the wire as a same-width uint view preceded by a
# unicode marker record; plain numpy dtypes are written unmarked, so
# old files read unchanged.
_EXT_MARKER = "__raft_tpu_dtype__:"
_UINT_FOR_WIDTH = {1: np.uint8, 2: np.uint16, 4: np.uint32}


def serialize_array(fh: Writable, arr) -> None:
    """Write one array as an ``.npy`` record (host transfer if needed).

    Analog of ``raft::serialize_mdspan`` (``core/serialize.hpp:35``).
    """
    np_arr = np.asarray(jax.device_get(arr) if isinstance(arr, jax.Array) else arr)
    if np_arr.dtype.kind == "V" and np_arr.dtype.names is None:
        # bfloat16 / float8 extension dtype (NOT a structured record —
        # those have .names and serialize natively)
        import ml_dtypes

        if hasattr(ml_dtypes, np_arr.dtype.name):
            npy_format.write_array(
                fh, np.asarray(_EXT_MARKER + np_arr.dtype.name),
                allow_pickle=False)
            np_arr = np_arr.view(_UINT_FOR_WIDTH[np_arr.dtype.itemsize])
    npy_format.write_array(fh, np_arr, allow_pickle=False)


def deserialize_array(fh: BinaryIO) -> np.ndarray:
    """Read one ``.npy`` record (``raft::deserialize_mdspan``)."""
    arr = npy_format.read_array(fh, allow_pickle=False)
    if (arr.dtype.kind == "U" and arr.ndim == 0
            and str(arr).startswith(_EXT_MARKER)):
        import ml_dtypes

        dtype = np.dtype(getattr(ml_dtypes, str(arr)[len(_EXT_MARKER):]))
        raw = npy_format.read_array(fh, allow_pickle=False)
        return raw.view(dtype)
    return arr


def serialize_scalar(fh: Writable, value: Any, dtype=None) -> None:
    """Write one scalar as a 0-d ``.npy`` record
    (``raft::serialize_scalar``, ``core/serialize.hpp:99``)."""
    np_val = np.asarray(value, dtype=dtype)
    if np_val.shape != ():
        raise ValueError(f"serialize_scalar expects a scalar, got shape {np_val.shape}")
    npy_format.write_array(fh, np_val, allow_pickle=False)


def deserialize_scalar(fh: BinaryIO):
    arr = npy_format.read_array(fh, allow_pickle=False)
    if arr.shape != ():
        raise ValueError(f"expected scalar record, got shape {arr.shape}")
    return arr[()]


def open_maybe_path(fh_or_path, mode: str):
    """Return (fh, owns) accepting open files, str/bytes paths, and
    os.PathLike — shared by every index save/load."""
    import os

    if isinstance(fh_or_path, (str, bytes, os.PathLike)):
        return open(fh_or_path, mode), True
    return fh_or_path, False


def check_version(found: int, expected: int, what: str) -> None:
    """Version gate used by every index loader (mirrors the serialization
    version checks, e.g. ``detail/ivf_pq_serialize.cuh:39``)."""
    if int(found) != int(expected):
        raise ValueError(
            f"{what}: serialization format version mismatch "
            f"(file v{int(found)}, loader v{int(expected)})"
        )
