"""Input validation — analog of ``RAFT_EXPECTS`` / mdspan extent checks.

The reference enforces preconditions with macros (``core/error.hpp``) and
encodes layout/extent contracts in mdspan types. Here arrays are plain
``jax.Array``/numpy, so the contracts become small check helpers used at
every public entry point (host-side, zero cost under jit tracing).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np


class RaftError(RuntimeError):
    """Analog of ``raft::exception`` (``core/error.hpp``)."""


def expect(cond: bool, msg: str) -> None:
    """``RAFT_EXPECTS(cond, msg)``."""
    if not cond:
        raise RaftError(msg)


def check_matrix(x, name: str = "x", dtype=None, cols: Optional[int] = None):
    x = jnp.asarray(x)
    expect(x.ndim == 2, f"{name} must be 2-D, got shape {x.shape}")
    if cols is not None:
        expect(x.shape[1] == cols, f"{name} must have {cols} columns, got {x.shape[1]}")
    if dtype is not None:
        x = x.astype(dtype)
    return x


def check_vector(x, name: str = "x", dtype=None, size: Optional[int] = None):
    x = jnp.asarray(x)
    expect(x.ndim == 1, f"{name} must be 1-D, got shape {x.shape}")
    if size is not None:
        expect(x.shape[0] == size, f"{name} must have length {size}, got {x.shape[0]}")
    if dtype is not None:
        x = x.astype(dtype)
    return x


def canonical_dtype(dtype) -> np.dtype:
    """Map supported input dtypes to the compute dtype used on TPU.

    The reference's vector-search dtypes are float32/float16/int8/uint8
    (``ivf_flat_types.hpp``, ``ivf_pq_types.hpp``). On TPU we compute in
    float32 (MXU accumulate) or bfloat16; int8/uint8 stay packed in storage
    and are upcast in kernels.
    """
    dt = np.dtype(dtype)
    if dt in (np.dtype(np.float64),):
        return np.dtype(np.float32)
    return dt
