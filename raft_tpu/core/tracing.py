"""Profiler ranges and serving counters — analog of the reference's
NVTX RAII ranges plus a minimal metrics registry.

Reference: ``core/nvtx.hpp:20-70`` inserts named ranges at every public
entry point. The TPU-native equivalents are ``jax.named_scope`` (annotates
the jaxpr/HLO so ranges appear in XLA profiler traces) plus
``jax.profiler.TraceAnnotation`` for host-side spans. ``range`` composes
both so one decorator/context manager covers traced and untraced code.

The counter registry is the export surface for the serving path
(``core/executor.py``): compile counts, cache hits/evictions and warmup
time land here so a frontend (or the bench harness) can scrape one
place. ``install_xla_compile_listener`` additionally taps jax's
monitoring events so *every* backend compile in the process — not just
the executor's — is visible; that is what the tier-1 recompile
regression test asserts on.
"""

from __future__ import annotations

import contextlib
import functools
import threading

import jax


@contextlib.contextmanager
def range(name: str, *fmt_args):
    """RAII-style profiling range (``common::nvtx::range``)."""
    label = name % fmt_args if fmt_args else name
    with jax.named_scope(label), jax.profiler.TraceAnnotation(label):
        yield


def annotated(name: str):
    """Decorator form, used on public API entry points."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with range(name):
                return fn(*args, **kwargs)

        return wrapper

    return deco


@contextlib.contextmanager
def capture(log_dir: str):
    """Capture an XLA profiler trace for the enclosed block — the role
    the gbench micro-benchmarks play as profiling entry points in the
    reference (SURVEY.md §5). View with TensorBoard or xprof:

        with tracing.capture("/tmp/trace"):
            index = ivf_flat.build(res, params, dataset)
    """
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def start_server(port: int = 9999):
    """Start the on-demand profiler server (``jax.profiler``) so a
    running service can be traced remotely."""
    return jax.profiler.start_server(port)


# ---------------------------------------------------------------------------
# counters — process-wide serving metrics registry
# ---------------------------------------------------------------------------

_counters: dict = {}
_counters_lock = threading.Lock()


def inc_counter(name: str, amount: float = 1.0) -> None:
    """Add ``amount`` to a named process-wide counter (creates it at 0)."""
    with _counters_lock:
        _counters[name] = _counters.get(name, 0.0) + amount


def max_counter(name: str, value: float) -> None:
    """Raise a named counter to ``value`` if it is below it (creates it
    at ``value``) — high-water-mark counters like peak bytes."""
    with _counters_lock:
        _counters[name] = max(_counters.get(name, float("-inf")), value)


def get_counter(name: str) -> float:
    """Current value of a counter (0.0 if never incremented)."""
    with _counters_lock:
        return _counters.get(name, 0.0)


def counters(prefix: str = "") -> dict:
    """Snapshot of all counters whose name starts with ``prefix``."""
    with _counters_lock:
        return {k: v for k, v in _counters.items() if k.startswith(prefix)}


def reset_counters(prefix: str = "") -> None:
    """Zero (remove) counters matching ``prefix`` — test isolation."""
    with _counters_lock:
        for k in [k for k in _counters if k.startswith(prefix)]:
            del _counters[k]


_compile_listener_installed = False

# every XLA backend compile in the process lands in these two counters
XLA_COMPILE_COUNT = "xla.backend_compile_count"
XLA_COMPILE_SECONDS = "xla.backend_compile_seconds"


def install_xla_compile_listener() -> None:
    """Count every XLA backend compile into :data:`XLA_COMPILE_COUNT` /
    :data:`XLA_COMPILE_SECONDS` via ``jax.monitoring``.

    Idempotent and process-wide. This is the ground truth the serving
    path's "steady state never compiles" guarantee is tested against:
    jax emits ``/jax/core/compile/backend_compile_duration`` exactly
    once per real (non-cached) executable build.
    """
    global _compile_listener_installed
    with _counters_lock:
        if _compile_listener_installed:
            return
        _compile_listener_installed = True

    def _on_event(name: str, secs: float, **kw) -> None:
        if name == "/jax/core/compile/backend_compile_duration":
            inc_counter(XLA_COMPILE_COUNT)
            inc_counter(XLA_COMPILE_SECONDS, secs)

    jax.monitoring.register_event_duration_secs_listener(_on_event)
