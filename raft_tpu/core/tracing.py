"""Profiler ranges and serving counters — analog of the reference's
NVTX RAII ranges plus a minimal metrics registry.

Reference: ``core/nvtx.hpp:20-70`` inserts named ranges at every public
entry point. The TPU-native equivalents are ``jax.named_scope`` (annotates
the jaxpr/HLO so ranges appear in XLA profiler traces) plus
``jax.profiler.TraceAnnotation`` for host-side spans. ``range`` composes
both so one decorator/context manager covers traced and untraced code.

The counter registry is the export surface for the serving path
(``core/executor.py``): compile counts, cache hits/evictions and warmup
time land here so a frontend (or the bench harness) can scrape one
place, and the serving frontend (``raft_tpu/serving/``) adds per-stage
latency histograms (:func:`observe` / :func:`histograms`) next to
them. ``install_xla_compile_listener`` additionally taps jax's
monitoring events so *every* backend compile in the process — not just
the executor's — is visible; that is what the tier-1 recompile
regression test asserts on.
"""

from __future__ import annotations

import builtins
import contextlib
import functools
import threading

import jax


@contextlib.contextmanager
def range(name: str, *fmt_args):
    """RAII-style profiling range (``common::nvtx::range``)."""
    label = name % fmt_args if fmt_args else name
    with jax.named_scope(label), jax.profiler.TraceAnnotation(label):
        yield


def annotated(name: str):
    """Decorator form, used on public API entry points."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with range(name):
                return fn(*args, **kwargs)

        return wrapper

    return deco


@contextlib.contextmanager
def capture(log_dir: str):
    """Capture an XLA profiler trace for the enclosed block — the role
    the gbench micro-benchmarks play as profiling entry points in the
    reference (SURVEY.md §5). View with TensorBoard or xprof:

        with tracing.capture("/tmp/trace"):
            index = ivf_flat.build(res, params, dataset)
    """
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def start_server(port: int = 9999):
    """Start the on-demand profiler server (``jax.profiler``) so a
    running service can be traced remotely."""
    return jax.profiler.start_server(port)


# ---------------------------------------------------------------------------
# counters — process-wide serving metrics registry
# ---------------------------------------------------------------------------

_counters: dict = {}
_counters_lock = threading.Lock()


def inc_counter(name: str, amount: float = 1.0) -> None:
    """Add ``amount`` to a named process-wide counter (creates it at 0)."""
    with _counters_lock:
        _counters[name] = _counters.get(name, 0.0) + amount


def max_counter(name: str, value: float) -> None:
    """Raise a named counter to ``value`` if it is below it (creates it
    at ``value``) — high-water-mark counters like peak bytes."""
    with _counters_lock:
        _counters[name] = max(_counters.get(name, float("-inf")), value)


def get_counter(name: str) -> float:
    """Current value of a counter (0.0 if never incremented)."""
    with _counters_lock:
        return _counters.get(name, 0.0)


def counters(prefix: str = "") -> dict:
    """Snapshot of all counters whose name starts with ``prefix``."""
    with _counters_lock:
        return {k: v for k, v in _counters.items() if k.startswith(prefix)}


def reset_counters(prefix: str = "") -> None:
    """Zero (remove) counters matching ``prefix`` — test isolation."""
    with _counters_lock:
        for k in [k for k in _counters if k.startswith(prefix)]:
            del _counters[k]


# ---------------------------------------------------------------------------
# histograms — per-stage latency distributions for the serving frontend
# ---------------------------------------------------------------------------

# log2-spaced bucket upper bounds from 1 µs to ~67 s: wide enough for
# queue waits and device executes alike, cheap enough (27 ints) that
# observing on the per-request hot path is a dict lookup + increment
# (builtins.range — this module's own `range` is the profiling scope)
_HIST_BOUNDS = tuple(1e-6 * (2.0 ** i) for i in builtins.range(27))

_histograms: dict = {}


class Histogram:
    """Fixed-bound latency histogram (bounds in seconds, log2-spaced).

    ``observe`` is O(log n_buckets); ``quantile`` interpolates linearly
    inside the selected bucket, which is the usual Prometheus-style
    estimate — exact enough for p50/p95/p99 serving dashboards."""

    __slots__ = ("bounds", "counts", "count", "sum")

    def __init__(self, bounds=_HIST_BOUNDS):
        self.bounds = tuple(bounds)
        self.counts = [0] * (len(self.bounds) + 1)  # +overflow bucket
        self.count = 0
        self.sum = 0.0

    def observe(self, value: float) -> None:
        lo, hi = 0, len(self.bounds)
        while lo < hi:
            mid = (lo + hi) // 2
            if value <= self.bounds[mid]:
                hi = mid
            else:
                lo = mid + 1
        self.counts[lo] += 1
        self.count += 1
        self.sum += value

    def quantile(self, q: float) -> float:
        """Estimated q-quantile (0 <= q <= 1); 0.0 when empty."""
        if self.count == 0:
            return 0.0
        target = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            if seen + c >= target and c > 0:
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = (self.bounds[i] if i < len(self.bounds)
                      else self.bounds[-1] * 2.0)
                frac = (target - seen) / c
                return lo + (hi - lo) * frac
            seen += c
        return self.bounds[-1] * 2.0

    def snapshot(self) -> dict:
        return {
            "count": self.count,
            "sum": self.sum,
            "p50": self.quantile(0.50),
            "p95": self.quantile(0.95),
            "p99": self.quantile(0.99),
        }


def observe(name: str, value: float) -> None:
    """Record ``value`` (seconds) into the named process-wide histogram
    (created on first use)."""
    with _counters_lock:
        h = _histograms.get(name)
        if h is None:
            h = _histograms[name] = Histogram()
        h.observe(value)


def get_histogram(name: str) -> Histogram:
    """The named histogram (an empty one if never observed)."""
    with _counters_lock:
        h = _histograms.get(name)
        if h is None:
            h = _histograms[name] = Histogram()
        return h


def histograms(prefix: str = "") -> dict:
    """``{name: snapshot-dict}`` for histograms matching ``prefix``."""
    with _counters_lock:
        return {k: h.snapshot() for k, h in _histograms.items()
                if k.startswith(prefix)}


def reset_histograms(prefix: str = "") -> None:
    """Drop histograms matching ``prefix`` — test isolation."""
    with _counters_lock:
        for k in [k for k in _histograms if k.startswith(prefix)]:
            del _histograms[k]


_compile_listener_installed = False

# every XLA backend compile in the process lands in these two counters
XLA_COMPILE_COUNT = "xla.backend_compile_count"
XLA_COMPILE_SECONDS = "xla.backend_compile_seconds"


def install_xla_compile_listener() -> None:
    """Count every XLA backend compile into :data:`XLA_COMPILE_COUNT` /
    :data:`XLA_COMPILE_SECONDS` via ``jax.monitoring``.

    Idempotent and process-wide. This is the ground truth the serving
    path's "steady state never compiles" guarantee is tested against:
    jax emits ``/jax/core/compile/backend_compile_duration`` exactly
    once per real (non-cached) executable build.
    """
    global _compile_listener_installed
    with _counters_lock:
        if _compile_listener_installed:
            return
        _compile_listener_installed = True

    def _on_event(name: str, secs: float, **kw) -> None:
        if name == "/jax/core/compile/backend_compile_duration":
            inc_counter(XLA_COMPILE_COUNT)
            inc_counter(XLA_COMPILE_SECONDS, secs)

    jax.monitoring.register_event_duration_secs_listener(_on_event)
