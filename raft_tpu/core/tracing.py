"""Profiler ranges — analog of the reference's NVTX RAII ranges.

Reference: ``core/nvtx.hpp:20-70`` inserts named ranges at every public
entry point. The TPU-native equivalents are ``jax.named_scope`` (annotates
the jaxpr/HLO so ranges appear in XLA profiler traces) plus
``jax.profiler.TraceAnnotation`` for host-side spans. ``range`` composes
both so one decorator/context manager covers traced and untraced code.
"""

from __future__ import annotations

import contextlib
import functools

import jax


@contextlib.contextmanager
def range(name: str, *fmt_args):
    """RAII-style profiling range (``common::nvtx::range``)."""
    label = name % fmt_args if fmt_args else name
    with jax.named_scope(label), jax.profiler.TraceAnnotation(label):
        yield


def annotated(name: str):
    """Decorator form, used on public API entry points."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with range(name):
                return fn(*args, **kwargs)

        return wrapper

    return deco


@contextlib.contextmanager
def capture(log_dir: str):
    """Capture an XLA profiler trace for the enclosed block — the role
    the gbench micro-benchmarks play as profiling entry points in the
    reference (SURVEY.md §5). View with TensorBoard or xprof:

        with tracing.capture("/tmp/trace"):
            index = ivf_flat.build(res, params, dataset)
    """
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def start_server(port: int = 9999):
    """Start the on-demand profiler server (``jax.profiler``) so a
    running service can be traced remotely."""
    return jax.profiler.start_server(port)
