"""Profiler ranges and serving counters — analog of the reference's
NVTX RAII ranges plus a minimal metrics registry.

Reference: ``core/nvtx.hpp:20-70`` inserts named ranges at every public
entry point. The TPU-native equivalents are ``jax.named_scope`` (annotates
the jaxpr/HLO so ranges appear in XLA profiler traces) plus
``jax.profiler.TraceAnnotation`` for host-side spans. ``range`` composes
both so one decorator/context manager covers traced and untraced code.

The counter registry is the export surface for the serving path
(``core/executor.py``): compile counts, cache hits/evictions and warmup
time land here so a frontend (or the bench harness) can scrape one
place, and the serving frontend (``raft_tpu/serving/``) adds per-stage
latency histograms (:func:`observe` / :func:`histograms`) next to
them. ``install_xla_compile_listener`` additionally taps jax's
monitoring events so *every* backend compile in the process — not just
the executor's — is visible; that is what the tier-1 recompile
regression test asserts on.

PR 7 (graftscope v2) extends the layer into the mesh: per-shard
timings reduce through the **straggler detector**
(:func:`straggler_stats` / :func:`record_mesh_spans`) into
``serving.mesh.{shard_skew,slowest_shard}`` gauges, and the Chrome
trace export grew a ``trace_id`` filter so per-request fetches stop
dumping the whole ring.

PR 6 (graftscope) grows this module into the full observability core:

- **Gauges** (:func:`set_gauge`) — last-value metrics next to the
  monotone counters: per-executable cost-analysis numbers, queue
  depth, arrival rate, collective payload models.
- **Request spans** (:class:`Span` / :class:`SpanRecorder`) — a
  bounded, lock-protected ring buffer of host-side stage spans keyed
  by ``trace_id``, doubling as a flight recorder for post-mortems.
  :meth:`SpanRecorder.to_chrome_trace` exports Chrome trace-event JSON
  so the serving stage spans overlay the ``jax.profiler`` device
  timeline in Perfetto.
- :class:`Histogram` grew cumulative bucket counts (the Prometheus
  exposition format needs them) and its own lock — ``get_histogram``
  hands out live instances, so unlocked ``observe`` raced concurrent
  observers before PR 6.

None of it touches the device: recording a span or bumping a counter
is a dict/deque operation under a host lock, so instrumentation adds
no host syncs and cannot perturb the zero-recompile steady state.
"""

from __future__ import annotations

import builtins
import collections
import contextlib
import dataclasses
import functools
import itertools
import threading
import time
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np


@contextlib.contextmanager
def range(name: str, *fmt_args):
    """RAII-style profiling range (``common::nvtx::range``)."""
    label = name % fmt_args if fmt_args else name
    with jax.named_scope(label), jax.profiler.TraceAnnotation(label):
        yield


def annotated(name: str):
    """Decorator form, used on public API entry points."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with range(name):
                return fn(*args, **kwargs)

        return wrapper

    return deco


@contextlib.contextmanager
def capture(log_dir: str):
    """Capture an XLA profiler trace for the enclosed block — the role
    the gbench micro-benchmarks play as profiling entry points in the
    reference (SURVEY.md §5). View with TensorBoard or xprof:

        with tracing.capture("/tmp/trace"):
            index = ivf_flat.build(res, params, dataset)
    """
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def start_server(port: int = 9999):
    """Start the on-demand profiler server (``jax.profiler``) so a
    running service can be traced remotely."""
    return jax.profiler.start_server(port)


# ---------------------------------------------------------------------------
# counters — process-wide serving metrics registry
# ---------------------------------------------------------------------------

_counters: dict = {}  # guarded-by: _counters_lock
# process-lifetime totals: everything reset_counters() has folded away.
# Session-scoped artifacts (the CI metrics snapshot) read these so
# per-test isolation resets can't blank the session's accounting.
_counters_lifetime: dict = {}  # guarded-by: _counters_lock
_counters_lock = threading.Lock()


def inc_counter(name: str, amount: float = 1.0) -> None:
    """Add ``amount`` to a named process-wide counter (creates it at 0)."""
    with _counters_lock:
        _counters[name] = _counters.get(name, 0.0) + amount


def inc_counters(amounts: Dict[str, float]) -> None:
    """Add several counters under ONE lock acquisition — the per-call
    hot-path form (the executor bumps calls + modeled flops + modeled
    bytes per dispatch; three separate locks would triple the cost)."""
    with _counters_lock:
        for name, amount in amounts.items():
            _counters[name] = _counters.get(name, 0.0) + amount


def max_counter(name: str, value: float) -> None:
    """Raise a named counter to ``value`` if it is below it (creates it
    at ``value``) — high-water-mark counters like peak bytes."""
    with _counters_lock:
        _counters[name] = max(_counters.get(name, float("-inf")), value)


def get_counter(name: str) -> float:
    """Current value of a counter (0.0 if never incremented)."""
    with _counters_lock:
        return _counters.get(name, 0.0)


def counters(prefix: str = "") -> dict:
    """Snapshot of all counters whose name starts with ``prefix``."""
    with _counters_lock:
        return {k: v for k, v in _counters.items() if k.startswith(prefix)}


def reset_counters(prefix: str = "") -> None:
    """Zero (remove) counters matching ``prefix`` — test isolation.
    The removed counts fold into the process-lifetime ledger first
    (:func:`lifetime_counters`), so a session-end artifact still sees
    accounting that a mid-session reset wiped from the live view."""
    with _counters_lock:
        for k in [k for k in _counters if k.startswith(prefix)]:
            _counters_lifetime[k] = (
                _counters_lifetime.get(k, 0.0) + _counters.pop(k))


def lifetime_counters(prefix: str = "") -> dict:
    """Process-lifetime counter totals: the live counters plus every
    count a :func:`reset_counters` call has folded away. This is the
    ledger the CI metrics snapshot floors are checked against — "was
    the modeled-throughput accounting alive at any point this
    session" — NOT a metric surface (a scrape reads :func:`counters`;
    high-water ``max_counter`` values sum across resets here, which is
    fine for an is-it-alive floor but not for reporting)."""
    with _counters_lock:
        out = {k: v for k, v in _counters_lifetime.items()
               if k.startswith(prefix)}
        for k, v in _counters.items():
            if k.startswith(prefix):
                out[k] = out.get(k, 0.0) + v
        return out


# ---------------------------------------------------------------------------
# gauges — last-value metrics (cost-analysis numbers, queue depth, rates)
# ---------------------------------------------------------------------------

_gauges: dict = {}  # guarded-by: _counters_lock


def set_gauge(name: str, value: float) -> None:
    """Set a named process-wide gauge to ``value`` (last write wins) —
    the non-monotone sibling of :func:`inc_counter`, for quantities
    that go up AND down (queue depth, arrival rate) or describe a
    current object (an executable's cost-analysis flops)."""
    with _counters_lock:
        _gauges[name] = value


def set_gauges(values: Dict[str, float]) -> None:
    """Set several gauges under one lock acquisition."""
    with _counters_lock:
        _gauges.update(values)


def get_gauge(name: str, default: float = 0.0) -> float:
    """Current value of a gauge (``default`` if never set)."""
    with _counters_lock:
        return _gauges.get(name, default)


def gauges(prefix: str = "") -> dict:
    """Snapshot of all gauges whose name starts with ``prefix``."""
    with _counters_lock:
        return {k: v for k, v in _gauges.items() if k.startswith(prefix)}


def reset_gauges(prefix: str = "") -> None:
    """Drop gauges matching ``prefix`` — test isolation, and how the
    executor retires the per-executable gauges of an evicted entry."""
    with _counters_lock:
        for k in [k for k in _gauges if k.startswith(prefix)]:
            del _gauges[k]


# ---------------------------------------------------------------------------
# histograms — per-stage latency distributions for the serving frontend
# ---------------------------------------------------------------------------

# log2-spaced bucket upper bounds from 1 µs to ~67 s: wide enough for
# queue waits and device executes alike, cheap enough (27 ints) that
# observing on the per-request hot path is a dict lookup + increment
# (builtins.range — this module's own `range` is the profiling scope)
_HIST_BOUNDS = tuple(1e-6 * (2.0 ** i) for i in builtins.range(27))

_histograms: dict = {}


class Histogram:
    """Fixed-bound latency histogram (bounds in seconds, log2-spaced).

    ``observe`` is O(log n_buckets); ``quantile`` interpolates linearly
    inside the selected bucket, which is the usual Prometheus-style
    estimate — exact enough for p50/p95/p99 serving dashboards.
    Values past the last bound land in an overflow bucket whose
    quantile estimate is pinned at ``2 * bounds[-1]``.

    Every instance carries its own lock: :func:`get_histogram` hands
    out live objects, so ``observe``/``snapshot`` must be safe against
    concurrent callers without routing through the registry lock."""

    __slots__ = ("bounds", "counts", "count", "sum", "_lock")

    def __init__(self, bounds=_HIST_BOUNDS):
        self.bounds = tuple(bounds)
        self.counts = [0] * (len(self.bounds) + 1)  # +overflow bucket; guarded-by: _lock
        self.count = 0   # guarded-by: _lock
        self.sum = 0.0   # guarded-by: _lock
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        lo, hi = 0, len(self.bounds)
        while lo < hi:
            mid = (lo + hi) // 2
            if value <= self.bounds[mid]:
                hi = mid
            else:
                lo = mid + 1
        with self._lock:
            self.counts[lo] += 1
            self.count += 1
            self.sum += value

    def _quantile_locked(self, q: float, counts, count) -> float:
        if count == 0:
            return 0.0
        target = q * count
        seen = 0
        for i, c in enumerate(counts):
            if seen + c >= target and c > 0:
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = (self.bounds[i] if i < len(self.bounds)
                      else self.bounds[-1] * 2.0)
                frac = (target - seen) / c
                return lo + (hi - lo) * frac
            seen += c
        return self.bounds[-1] * 2.0

    def quantile(self, q: float) -> float:
        """Estimated q-quantile (0 <= q <= 1); 0.0 when empty."""
        with self._lock:
            counts, count = list(self.counts), self.count
        return self._quantile_locked(q, counts, count)

    def snapshot(self) -> dict:
        """One consistent read: count/sum/quantile estimates plus the
        bucket bounds and CUMULATIVE per-bucket counts (the last entry
        is the +Inf/overflow bucket and equals ``count``) — the shape
        the Prometheus exposition format wants."""
        with self._lock:
            counts, count, total = list(self.counts), self.count, self.sum
        cumulative = list(itertools.accumulate(counts))
        return {
            "count": count,
            "sum": total,
            "p50": self._quantile_locked(0.50, counts, count),
            "p95": self._quantile_locked(0.95, counts, count),
            "p99": self._quantile_locked(0.99, counts, count),
            "bucket_bounds": list(self.bounds),
            "bucket_counts": cumulative,
        }


def observe(name: str, value: float) -> None:
    """Record ``value`` (seconds) into the named process-wide histogram
    (created on first use)."""
    with _counters_lock:
        h = _histograms.get(name)
        if h is None:
            h = _histograms[name] = Histogram()
        h.observe(value)


def get_histogram(name: str) -> Histogram:
    """The named histogram (an empty one if never observed)."""
    with _counters_lock:
        h = _histograms.get(name)
        if h is None:
            h = _histograms[name] = Histogram()
        return h


def histograms(prefix: str = "") -> dict:
    """``{name: snapshot-dict}`` for histograms matching ``prefix``."""
    with _counters_lock:
        return {k: h.snapshot() for k, h in _histograms.items()
                if k.startswith(prefix)}


def reset_histograms(prefix: str = "") -> None:
    """Drop histograms matching ``prefix`` — test isolation."""
    with _counters_lock:
        for k in [k for k in _histograms if k.startswith(prefix)]:
            del _histograms[k]


# ---------------------------------------------------------------------------
# request spans — structured host-side stage timing with trace ids
# ---------------------------------------------------------------------------

_trace_ids = itertools.count(1)


def new_trace_id() -> int:
    """Mint a process-unique trace id (monotonically increasing int).
    One is stamped on every ``SearchRequest`` at construction and
    propagated through admission → assembly → execute → split, so a
    request's whole journey is one grep in the span ring."""
    return next(_trace_ids)


@dataclasses.dataclass(frozen=True)
class Span:
    """One completed host-side span.

    ``start``/``end`` are seconds in the *recording clock's* domain —
    the serving stack records with its injectable clock, so spans from
    a manual-clock test are exact virtual timestamps, and spans from
    production overlay the profiler timeline. Zero-duration spans are
    instant markers (shed/cancel/reject reasons). ``events`` is a
    tuple of ``(ts, name, attrs)`` marks inside the span."""

    name: str
    start: float
    end: float
    trace_ids: Tuple[int, ...] = ()
    attrs: Any = dataclasses.field(default_factory=dict)
    events: tuple = ()
    tid: int = 0

    @property
    def duration(self) -> float:
        return self.end - self.start


class SpanRecorder:
    """Bounded, lock-protected span ring buffer — the flight recorder.

    The ring holds the most recent ``capacity`` spans; overwrites are
    counted in :attr:`dropped` rather than silently vanishing, so a
    post-mortem knows whether it is looking at the full story. All
    mutation is a deque append under one lock: O(1), no allocation
    beyond the span itself, safe from any thread."""

    def __init__(self, capacity: int = 8192):
        self._lock = threading.Lock()
        self._buf: "collections.deque[Span]" = collections.deque(  # guarded-by: _lock
            maxlen=max(int(capacity), 1))
        self._dropped = 0  # guarded-by: _lock

    @property
    def capacity(self) -> int:
        return self._buf.maxlen  # graftlint: disable=R8(deque reference never rebinds; maxlen is immutable)

    @property
    def dropped(self) -> int:
        """Spans overwritten by the ring since the last :meth:`clear`."""
        with self._lock:
            return self._dropped

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def record(self, name: str, start: float, end: float, *,
               trace_ids: Tuple[int, ...] = (), attrs: Optional[dict] = None,
               events: tuple = ()) -> Span:
        """Record one completed span (the serving stack's entry point —
        stages time themselves with their own clock and report here)."""
        span = Span(name=name, start=start, end=end,
                    trace_ids=tuple(trace_ids), attrs=dict(attrs or {}),
                    events=tuple(events),
                    tid=threading.get_ident())
        with self._lock:
            if len(self._buf) == self._buf.maxlen:
                self._dropped += 1
            self._buf.append(span)
        return span

    def event(self, name: str, ts: float, *,
              trace_ids: Tuple[int, ...] = (),
              attrs: Optional[dict] = None) -> Span:
        """Record an instant marker (zero-duration span) — shed,
        cancel, and reject reasons land here."""
        return self.record(name, ts, ts, trace_ids=trace_ids, attrs=attrs)

    def spans(self, trace_id: Optional[int] = None,
              name: Optional[str] = None) -> list:
        """Snapshot of recorded spans, oldest first, optionally
        filtered by ``trace_id`` membership and/or exact ``name``."""
        with self._lock:
            out = list(self._buf)
        if trace_id is not None:
            out = [s for s in out if trace_id in s.trace_ids]
        if name is not None:
            out = [s for s in out if s.name == name]
        return out

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self._dropped = 0

    # -- Chrome trace-event JSON (Perfetto / chrome://tracing) --------------

    def to_chrome_trace(self, pid: int = 0,
                        trace_id: Optional[int] = None) -> dict:
        """Export the ring as a Chrome trace-event JSON object.

        Complete spans become ``"ph": "X"`` duration events (µs
        timestamps); span events and zero-duration spans additionally
        emit ``"ph": "i"`` instant marks so reasons are visible on the
        Perfetto timeline. The precise float seconds ride along in
        ``args`` (``t0_s``/``t1_s``) because µs conversion is lossy —
        :meth:`from_chrome_trace` reads those back, making the export
        a faithful round trip. The reserved arg keys (``trace_ids`` /
        ``t0_s`` / ``t1_s`` / ``events``) win over same-named span
        attrs: a colliding attr is shadowed in the export rather than
        corrupting the rebuilt span's timing.

        ``trace_id`` restricts the export to spans carrying that id —
        the per-request fetch (``/trace.json?trace_id=``); an unknown
        id yields an empty (but valid) trace rather than an error."""
        events = []
        for s in self.spans(trace_id=trace_id):
            args = dict(s.attrs)
            args.update({
                "trace_ids": list(s.trace_ids), "t0_s": s.start,
                "t1_s": s.end,
                "events": [[ts, name, dict(attrs)]
                           for ts, name, attrs in s.events]})
            events.append({
                "name": s.name, "ph": "X", "pid": pid, "tid": s.tid,
                "ts": s.start * 1e6,
                "dur": max(s.end - s.start, 0.0) * 1e6,
                "args": args,
            })
            for ts, name, attrs in s.events:
                events.append({
                    "name": f"{s.name}.{name}", "ph": "i", "s": "t",
                    "pid": pid, "tid": s.tid, "ts": ts * 1e6,
                    "args": dict(attrs),
                })
            if s.end == s.start:
                # shed/cancel/reject markers: a dur=0 "X" slice is
                # invisible in Perfetto, the "i" mark is clickable
                events.append({
                    "name": s.name, "ph": "i", "s": "t",
                    "pid": pid, "tid": s.tid, "ts": s.start * 1e6,
                    "args": dict(s.attrs),
                })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    @staticmethod
    def from_chrome_trace(data: dict) -> list:
        """Rebuild the span list from :meth:`to_chrome_trace` output —
        the post-mortem path: load a dumped flight-recorder JSON back
        into :class:`Span` objects."""
        out = []
        for ev in data.get("traceEvents", []):
            if ev.get("ph") != "X":
                continue
            args = dict(ev.get("args", {}))
            trace_ids = tuple(args.pop("trace_ids", ()))
            start = args.pop("t0_s", ev.get("ts", 0.0) / 1e6)
            end = args.pop("t1_s",
                           (ev.get("ts", 0.0) + ev.get("dur", 0.0)) / 1e6)
            events = tuple((ts, name, dict(attrs))
                           for ts, name, attrs in args.pop("events", []))
            out.append(Span(name=ev.get("name", ""), start=start, end=end,
                            trace_ids=trace_ids, attrs=args, events=events,
                            tid=ev.get("tid", 0)))
        return out


_span_recorder = SpanRecorder()


def span_recorder() -> SpanRecorder:
    """The process-wide span ring (serving spans land here)."""
    return _span_recorder


def record_span(name: str, start: float, end: float, *,
                trace_ids: Tuple[int, ...] = (),
                attrs: Optional[dict] = None,
                events: tuple = ()) -> Span:
    """Record into the process-wide ring (see :class:`SpanRecorder`)."""
    return _span_recorder.record(name, start, end, trace_ids=trace_ids,
                                 attrs=attrs, events=events)


def span_event(name: str, ts: float, *, trace_ids: Tuple[int, ...] = (),
               attrs: Optional[dict] = None) -> Span:
    """Instant marker in the process-wide ring."""
    return _span_recorder.event(name, ts, trace_ids=trace_ids, attrs=attrs)


def reset_spans() -> None:
    """Drop every recorded span — test isolation."""
    _span_recorder.clear()


# ---------------------------------------------------------------------------
# mesh spans — per-shard attribution + the straggler detector (PR 7)
# ---------------------------------------------------------------------------

# the straggler gauges every mesh dispatch re-publishes
MESH_SHARD_SKEW = "serving.mesh.shard_skew"
MESH_SLOWEST_SHARD = "serving.mesh.slowest_shard"
MESH_SHARD_TIME_MAX = "serving.mesh.shard_time_max_s"
MESH_SHARD_TIME_MEAN = "serving.mesh.shard_time_mean_s"
# per-dispatch skew distribution (graftfleet, PR 12): when a capture's
# invocation windows yield one skew sample PER DISPATCH, the
# distribution publishes next to the last-dispatch gauge above
MESH_SHARD_SKEW_P50 = "serving.mesh.shard_skew_p50"
MESH_SHARD_SKEW_P99 = "serving.mesh.shard_skew_p99"


def sample_quantile(samples, q: float) -> float:
    """Linear-interpolated q-quantile of a small host-side sample list
    (numpy's default method, dependency-free) — 0.0 when empty. Pure
    function: the per-dispatch skew gauges are pinned exactly by the
    capture fixtures."""
    ts = sorted(float(s) for s in samples)
    if not ts:
        return 0.0
    if len(ts) == 1:
        return ts[0]
    pos = q * (len(ts) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(ts) - 1)
    return ts[lo] + (ts[hi] - ts[lo]) * (pos - lo)


def straggler_stats(timings) -> dict:
    """Reduce per-shard timings (seconds, index = shard ordinal) into
    straggler attribution: ``slowest_shard`` (argmax), ``shard_skew``
    (max − min — the wall-clock a perfectly balanced mesh would get
    back), plus max/mean. Pure function of its input, so the
    ShimExecutor-scripted tests pin the gauges exactly."""
    ts = [float(t) for t in timings]
    if not ts:
        return {"shards": 0, "shard_skew": 0.0, "slowest_shard": -1,
                "max_s": 0.0, "mean_s": 0.0}
    mx = max(ts)
    return {
        "shards": len(ts),
        "shard_skew": mx - min(ts),
        "slowest_shard": ts.index(mx),
        "max_s": mx,
        "mean_s": sum(ts) / len(ts),
    }


def poll_shard_timings(parts, t0: float, *,
                       poll_s: float = 50e-6) -> list:
    """Per-shard arrival offsets (seconds after ``t0``) from a
    NON-BLOCKING ``is_ready()`` poll over ``parts`` — a sequence of
    ``(distances, indices)`` array pairs, one per shard ordinal. The
    shared input half of the straggler detector (executor mesh_trace +
    ``ShardedIndex.search``).

    Why a poll and not a sequential block per shard: blocking in order
    makes readings cumulative — an early-ordinal straggler drags every
    later shard's reading up to its own and the skew gauge reports a
    balanced mesh in exactly the imbalance case it exists to detect.
    ``poll_s`` bounds the timing resolution; total wall time is
    unchanged (callers block on the same results right after).

    Host arrays (no ``is_ready``) are ready by definition; an
    ``is_ready`` that raises ``RuntimeError`` (a donated-state buffer
    consumed by a concurrent re-dispatch — the poll runs outside the
    executor lock) caps that shard's arrival at the consumption time
    rather than crashing the trace."""
    def _ready(a) -> bool:
        fn = getattr(a, "is_ready", None)
        if fn is None:
            return True
        try:
            return fn()
        except RuntimeError:
            return True

    timings = [0.0] * len(parts)
    # builtins.range — this module's own `range` is the profiling scope
    pending = set(builtins.range(len(parts)))
    while pending:
        for s in tuple(pending):
            d, i = parts[s]
            if _ready(d) and _ready(i):
                timings[s] = time.perf_counter() - t0
                pending.discard(s)
        if pending:
            time.sleep(poll_s)
    return timings


def record_mesh_spans(family: str, t0: float, t1: float, *,
                      trace_ids: Tuple[int, ...] = (),
                      phases: Optional[dict] = None,
                      shard_timings=None,
                      shard_attrs: Optional[dict] = None,
                      skew_samples=None,
                      count_dispatch: bool = True) -> dict:
    """Record one mesh dispatch into the flight recorder: a
    ``serving.mesh.<phase>`` span per entry of ``phases`` (attrs carry
    the modeled per-phase bytes — the phases share the dispatch window
    ``[t0, t1]`` because the compiled program is opaque host-side; the
    attribution is TPU-KNN-style modeled accounting, not a device
    profile), plus a ``serving.mesh.shard`` span per entry of
    ``shard_timings`` (seconds after ``t0`` at which that shard's
    output block became ready host-side). The straggler detector
    reduces the timings into the ``serving.mesh.*`` gauges and returns
    its stats. Everything here is host-side deque/dict work — no
    device interaction, same discipline as every other recorder.

    ``shard_attrs`` merges extra attrs onto every shard span —
    graftflight's measured re-emission marks them ``modeled: False``
    with ``source: "profiler"`` — and ``count_dispatch=False`` skips
    the ``serving.mesh.dispatches`` bump (re-attributing already
    counted dispatches from a capture is not a new dispatch).

    ``skew_samples`` (graftfleet, PR 12) carries one shard-skew sample
    PER DISPATCH — the per-invocation-window skews a capture's
    gap-clustering yields — and publishes their distribution as the
    ``serving.mesh.shard_skew_p50``/``_p99`` gauges: a capture holding
    several dispatches then attributes straggler skew per dispatch
    instead of smearing it over the whole window."""
    for phase, attrs in (phases or {}).items():
        a = dict(attrs or {})
        a["family"] = family
        record_span(f"serving.mesh.{phase}", t0, t1,
                    trace_ids=trace_ids, attrs=a)
    stats = straggler_stats(shard_timings or ())
    if shard_timings:
        for s, dt in enumerate(shard_timings):
            a = {"family": family, "shard": s}
            if shard_attrs:
                a.update(shard_attrs)
            record_span("serving.mesh.shard", t0, t0 + float(dt),
                        trace_ids=trace_ids, attrs=a)
        set_gauges({
            MESH_SHARD_SKEW: stats["shard_skew"],
            MESH_SLOWEST_SHARD: float(stats["slowest_shard"]),
            MESH_SHARD_TIME_MAX: stats["max_s"],
            MESH_SHARD_TIME_MEAN: stats["mean_s"],
        })
        if count_dispatch:
            inc_counter("serving.mesh.dispatches")
    if skew_samples:
        stats["shard_skew_p50"] = sample_quantile(skew_samples, 0.50)
        stats["shard_skew_p99"] = sample_quantile(skew_samples, 0.99)
        set_gauges({
            MESH_SHARD_SKEW_P50: stats["shard_skew_p50"],
            MESH_SHARD_SKEW_P99: stats["shard_skew_p99"],
        })
    return stats


# ---------------------------------------------------------------------------
# graftgauge — index-health, probe-frequency, and drift reducers (PR 8)
# ---------------------------------------------------------------------------
#
# Pure functions of host arrays: the serving layer fetches its inputs
# once per scrape (the executor's probe planes, an index's list_sizes)
# and reduces them here, so every gauge value is pinned exactly by a
# scripted test and nothing below ever touches the device.

# the flat (unlabeled) drift/recall gauge names graftgauge publishes
DRIFT_SCORE = "index.drift.score"
RECALL_ESTIMATE = "index.recall.estimate"


def index_health(list_sizes, max_list_size: Optional[int] = None,
                 shards: int = 0) -> dict:
    """Reduce one index's per-list populations into its health stats:
    occupancy skew (``max``/``mean``/``p99`` list size and the Gini
    coefficient of the size distribution), ``dead_lists`` (empty —
    wasted probes land there), ``overflow_lists`` (at the padded
    capacity ``max_list_size`` — the next extend() into them forces a
    full repack), and ``fill_fraction`` of the padded tensor. With
    ``shards`` > 0 the block-sharded layout's per-shard row totals
    reduce into ``shard_imbalance`` (max/mean — 1.0 is a perfectly
    balanced mesh) — the evidence the lifecycle/compaction direction
    needs to decide what to rebalance. Pure function of its inputs."""
    sizes = np.asarray(list_sizes, dtype=np.int64)
    n = int(sizes.size)
    total = int(sizes.sum())
    out = {
        "n_lists": n,
        "rows": total,
        "max_list_size": int(sizes.max()) if n else 0,
        "mean_list_size": total / n if n else 0.0,
        "p99_list_size": float(np.percentile(sizes, 99)) if n else 0.0,
        "dead_lists": int((sizes == 0).sum()),
        "overflow_lists": 0,
        "fill_fraction": 0.0,
        "gini": 0.0,
        "shard_imbalance": 1.0,
    }
    if max_list_size:
        out["overflow_lists"] = int((sizes >= max_list_size).sum())
        out["fill_fraction"] = (total / (n * max_list_size)
                                if n * max_list_size else 0.0)
    if total > 0 and n > 1:
        # Gini over list populations: 0 = perfectly even, ->1 = all
        # rows in one list (the standard inequality reduction)
        s = np.sort(sizes)
        cum = np.cumsum(s, dtype=np.float64)
        out["gini"] = float(
            (n + 1 - 2.0 * (cum.sum() / cum[-1])) / n)
    if shards > 1 and n % shards == 0:
        per_shard = sizes.reshape(shards, n // shards).sum(axis=1)
        mean = per_shard.mean()
        out["shard_imbalance"] = (float(per_shard.max() / mean)
                                  if mean > 0 else 1.0)
    return out


def probe_freq_stats(counts, top_n: int = 8) -> dict:
    """Reduce one cumulative probe-frequency plane into its traffic
    stats: lifetime ``total`` probes, ``probed_fraction`` (share of
    lists traffic ever touched — its complement is the cold set), the
    hot-set coverage fractions ``coverage_p01``/``coverage_p10``
    (share of all probes the hottest 1% / 10% of lists absorbed — the
    exact signal an HBM/host-RAM tier split keys on), and the
    ``top_n`` hottest lists as ``(list_id, count)`` pairs. Pure
    function of the fetched plane."""
    c = np.asarray(counts, dtype=np.int64)
    n = int(c.size)
    total = int(c.sum())
    if n == 0 or total == 0:
        return {"n_lists": n, "total": total, "probed_fraction": 0.0,
                "coverage_p01": 0.0, "coverage_p10": 0.0, "top": []}
    order = np.argsort(-c, kind="stable")
    sorted_c = c[order]
    cum = np.cumsum(sorted_c, dtype=np.float64)

    def coverage(frac: float) -> float:
        k = max(1, int(np.ceil(n * frac)))
        return float(cum[k - 1] / total)

    top = [(int(order[i]), int(sorted_c[i]))
           for i in builtins.range(min(top_n, n)) if sorted_c[i] > 0]
    return {
        "n_lists": n,
        "total": total,
        "probed_fraction": float((c > 0).sum() / n),
        "coverage_p01": coverage(0.01),
        "coverage_p10": coverage(0.10),
        "top": top,
    }


def js_divergence(p, q) -> float:
    """Jensen-Shannon divergence (base 2 — bounded [0, 1]) between two
    count histograms; the drift score's distance. Inputs need not be
    normalized; a zero histogram against a non-zero one scores 1.0
    (maximal drift), two zero histograms 0.0. Symmetric and finite
    even where one side has mass the other lacks — why it, and not
    KL, is the streaming drift metric."""
    pa = np.asarray(p, dtype=np.float64)
    qa = np.asarray(q, dtype=np.float64)
    ps, qs = pa.sum(), qa.sum()
    if ps == 0 and qs == 0:
        return 0.0
    if ps == 0 or qs == 0:
        return 1.0
    pa, qa = pa / ps, qa / qs
    m = 0.5 * (pa + qa)

    def kl(a, b):
        mask = a > 0
        return float(np.sum(a[mask] * np.log2(a[mask] / b[mask])))

    return 0.5 * kl(pa, m) + 0.5 * kl(qa, m)


@contextlib.contextmanager
def host_span(name: str, *, trace_ids: Tuple[int, ...] = (),
              attrs: Optional[dict] = None):
    """Context manager recording a wall-clock host span (build paths,
    scripts — places with no injectable clock). The serving stack does
    NOT use this: it records explicit clock-domain timestamps so the
    manual-clock harness stays deterministic."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        record_span(name, t0, time.perf_counter(),
                    trace_ids=trace_ids, attrs=attrs)


_compile_listener_installed = False

# every XLA backend compile in the process lands in these two counters
XLA_COMPILE_COUNT = "xla.backend_compile_count"
XLA_COMPILE_SECONDS = "xla.backend_compile_seconds"


def install_xla_compile_listener() -> None:
    """Count every XLA backend compile into :data:`XLA_COMPILE_COUNT` /
    :data:`XLA_COMPILE_SECONDS` via ``jax.monitoring``.

    Idempotent and process-wide. This is the ground truth the serving
    path's "steady state never compiles" guarantee is tested against:
    jax emits ``/jax/core/compile/backend_compile_duration`` exactly
    once per real (non-cached) executable build.
    """
    global _compile_listener_installed
    with _counters_lock:
        if _compile_listener_installed:
            return
        _compile_listener_installed = True

    def _on_event(name: str, secs: float, **kw) -> None:
        if name == "/jax/core/compile/backend_compile_duration":
            inc_counter(XLA_COMPILE_COUNT)
            inc_counter(XLA_COMPILE_SECONDS, secs)

    jax.monitoring.register_event_duration_secs_listener(_on_event)
