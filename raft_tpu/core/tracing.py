"""Profiler ranges — analog of the reference's NVTX RAII ranges.

Reference: ``core/nvtx.hpp:20-70`` inserts named ranges at every public
entry point. The TPU-native equivalents are ``jax.named_scope`` (annotates
the jaxpr/HLO so ranges appear in XLA profiler traces) plus
``jax.profiler.TraceAnnotation`` for host-side spans. ``range`` composes
both so one decorator/context manager covers traced and untraced code.
"""

from __future__ import annotations

import contextlib
import functools

import jax


@contextlib.contextmanager
def range(name: str, *fmt_args):
    """RAII-style profiling range (``common::nvtx::range``)."""
    label = name % fmt_args if fmt_args else name
    with jax.named_scope(label), jax.profiler.TraceAnnotation(label):
        yield


def annotated(name: str):
    """Decorator form, used on public API entry points."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with range(name):
                return fn(*args, **kwargs)

        return wrapper

    return deco
