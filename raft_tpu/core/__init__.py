"""Core runtime: resources handle, logging, serialization, bitset.

TPU-native analog of the reference's ``raft/core/`` layer (SURVEY.md §2.1).
The reference's mdspan/mdarray machinery collapses to plain ``jax.Array`` +
shape/dtype validation helpers; RMM/stream plumbing collapses to XLA's
async dispatch; the resources registry survives as a small Python context
holding the mesh, PRNG state and tunables shared by every algorithm.
"""

from raft_tpu.core.resources import Resources, DeviceResources
from raft_tpu.core.executor import SearchExecutor, ExecutorStats
from raft_tpu.core.logger import logger, set_level, LogLevel
from raft_tpu.core.serialize import (
    serialize_array,
    deserialize_array,
    serialize_scalar,
    deserialize_scalar,
)
from raft_tpu.core.bitset import Bitset
from raft_tpu.core.memwatch import CapacityExceeded, MemoryLedger
from raft_tpu.core import operators
from raft_tpu.core.validation import (
    expect,
    check_matrix,
    check_vector,
    canonical_dtype,
)

__all__ = [
    "Resources",
    "DeviceResources",
    "SearchExecutor",
    "ExecutorStats",
    "logger",
    "set_level",
    "LogLevel",
    "serialize_array",
    "deserialize_array",
    "serialize_scalar",
    "deserialize_scalar",
    "Bitset",
    "CapacityExceeded",
    "MemoryLedger",
    "operators",
    "expect",
    "check_matrix",
    "check_vector",
    "canonical_dtype",
]
