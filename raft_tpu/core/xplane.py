"""Minimal protobuf wire-format reader for the XSpace profiler
format (graftfleet, PR 12) — the subset :func:`raft_tpu.core.profiling
.correlate` needs.

Upstream is deprecating the TPU chrome-trace sidecar in favor of the
``.xplane.pb`` protobuf a ``jax.profiler`` capture always writes
(``plugins/profile/<run>/<host>.xplane.pb``). The chrome path stays
primary — it works today and carries the same events — but a capture
directory holding ONLY an xplane file must still attribute, so this
module decodes the XSpace containers straight off the protobuf wire
format with stdlib alone: no ``protobuf`` dependency, no generated
classes, just varints and length-delimited fields.

Decoded subset (field numbers from tensorflow/tsl's
``profiler/protobuf/xplane.proto``)::

    XSpace          planes=1
    XPlane          name=2 lines=3 event_metadata=4 stat_metadata=5
    XLine           name=2 timestamp_ns=3 events=4
    XEvent          metadata_id=1 offset_ps=2 duration_ps=3 stats=4
    XStat           metadata_id=1 double=2 uint64=3 int64=4 str=5
                    bytes=6 ref=7
    XEventMetadata  id=1 name=2 display_name=4
    XStatMetadata   id=1 name=2

Everything else on the wire (unknown fields, other stat kinds) is
skipped by wire type, which is exactly what protobuf semantics ask of
a partial reader. Stats resolve through the plane's interning tables:
a stat's NAME always comes from ``stat_metadata[metadata_id]`` and a
``ref_value`` stat's VALUE is another ``stat_metadata`` entry's name
(the profiler interns repeated strings like module names that way).

The output is plain dicts (``parse_xspace``) — conversion to
:class:`~raft_tpu.core.profiling.DeviceOp` records lives in
``profiling.parse_xplane`` so this module stays a pure decoder with
no repo imports, fixture-pinned by the committed device-free
``tests/data/graftfleet_capture.xplane.pb`` sample.
"""

from __future__ import annotations

import struct
from typing import Dict, Iterator, List, Tuple

# protobuf wire types
_VARINT, _FIXED64, _LENGTH, _FIXED32 = 0, 1, 2, 5


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    """Decode one base-128 varint at ``pos``; returns (value, end)."""
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint in xplane.pb")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint overflow in xplane.pb")


def fields(buf: bytes) -> Iterator[Tuple[int, int, object]]:
    """Iterate a message's ``(field_number, wire_type, value)``
    triples: varints yield ints, length-delimited fields yield the
    raw ``bytes`` payload, fixed32/64 yield the raw 4/8 bytes.
    Unknown fields are the CALLER's business to skip — protobuf
    forward compatibility is "ignore what you don't know", not
    "fail on it"."""
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        fnum, wtype = tag >> 3, tag & 0x7
        if wtype == _VARINT:
            val, pos = _read_varint(buf, pos)
        elif wtype == _LENGTH:
            size, pos = _read_varint(buf, pos)
            if pos + size > len(buf):
                raise ValueError("truncated length-delimited field")
            val = buf[pos:pos + size]
            pos += size
        elif wtype == _FIXED64:
            val = buf[pos:pos + 8]
            pos += 8
        elif wtype == _FIXED32:
            val = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wtype}")
        yield fnum, wtype, val


def _parse_stat(buf: bytes) -> dict:
    out = {"metadata_id": 0, "value": None}
    for fnum, wtype, val in fields(buf):
        if fnum == 1 and wtype == _VARINT:
            out["metadata_id"] = val
        elif fnum == 2 and wtype == _FIXED64:
            out["value"] = struct.unpack("<d", val)[0]
        elif fnum in (3, 4) and wtype == _VARINT:
            out["value"] = val
        elif fnum == 5 and wtype == _LENGTH:
            out["value"] = val.decode("utf-8", "replace")
        elif fnum == 6 and wtype == _LENGTH:
            out["value"] = val
        elif fnum == 7 and wtype == _VARINT:
            out["ref"] = val
    return out


def _parse_event(buf: bytes) -> dict:
    out = {"metadata_id": 0, "offset_ps": 0, "duration_ps": 0,
           "stats": []}
    for fnum, wtype, val in fields(buf):
        if fnum == 1 and wtype == _VARINT:
            out["metadata_id"] = val
        elif fnum == 2 and wtype == _VARINT:
            out["offset_ps"] = val
        elif fnum == 3 and wtype == _VARINT:
            out["duration_ps"] = val
        elif fnum == 4 and wtype == _LENGTH:
            out["stats"].append(_parse_stat(val))
    return out


def _parse_line(buf: bytes) -> dict:
    out = {"name": "", "timestamp_ns": 0, "events": []}
    for fnum, wtype, val in fields(buf):
        if fnum == 2 and wtype == _LENGTH:
            out["name"] = val.decode("utf-8", "replace")
        elif fnum == 3 and wtype == _VARINT:
            out["timestamp_ns"] = val
        elif fnum == 4 and wtype == _LENGTH:
            out["events"].append(_parse_event(val))
    return out


def _parse_named_metadata(buf: bytes) -> Tuple[int, str]:
    """XEventMetadata / XStatMetadata share the fields we need:
    ``id=1``, ``name=2``."""
    mid, name = 0, ""
    for fnum, wtype, val in fields(buf):
        if fnum == 1 and wtype == _VARINT:
            mid = val
        elif fnum == 2 and wtype == _LENGTH:
            name = val.decode("utf-8", "replace")
    return mid, name


def _parse_map_entry(buf: bytes) -> Tuple[int, bytes]:
    """A protobuf map entry is a nested message ``{key=1, value=2}``;
    XPlane's metadata maps key by int64 id."""
    key, value = 0, b""
    for fnum, wtype, val in fields(buf):
        if fnum == 1 and wtype == _VARINT:
            key = val
        elif fnum == 2 and wtype == _LENGTH:
            value = val
    return key, value


def _parse_plane(buf: bytes) -> dict:
    out = {"name": "", "lines": [],
           "event_metadata": {}, "stat_metadata": {}}
    for fnum, wtype, val in fields(buf):
        if fnum == 2 and wtype == _LENGTH:
            out["name"] = val.decode("utf-8", "replace")
        elif fnum == 3 and wtype == _LENGTH:
            out["lines"].append(_parse_line(val))
        elif fnum == 4 and wtype == _LENGTH:
            key, sub = _parse_map_entry(val)
            mid, name = _parse_named_metadata(sub)
            out["event_metadata"][mid or key] = name
        elif fnum == 5 and wtype == _LENGTH:
            key, sub = _parse_map_entry(val)
            mid, name = _parse_named_metadata(sub)
            out["stat_metadata"][mid or key] = name
    return out


def parse_xspace(data: bytes) -> dict:
    """Decode one serialized XSpace into ``{"planes": [plane-dict]}``
    (see module docstring for the per-plane shape). Pure function of
    the bytes — the committed fixture pins it."""
    planes: List[dict] = []
    for fnum, wtype, val in fields(data):
        if fnum == 1 and wtype == _LENGTH:
            planes.append(_parse_plane(val))
    return {"planes": planes}


def resolve_stats(event: dict, stat_metadata: Dict[int, str]) -> dict:
    """``{stat_name: value}`` for one event, names resolved through
    the plane's ``stat_metadata`` interning table; a ``ref`` stat's
    value is ANOTHER table entry's name (interned string)."""
    out = {}
    for stat in event["stats"]:
        name = stat_metadata.get(stat["metadata_id"])
        if not name:
            continue
        if "ref" in stat:
            out[name] = stat_metadata.get(stat["ref"], "")
        elif stat["value"] is not None:
            out[name] = stat["value"]
    return out
