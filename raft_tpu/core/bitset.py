"""Device bitset — analog of ``raft::core::bitset`` (``core/bitset.cuh:41-116``).

Backed by a ``uint32`` word array (jax.Array) so it passes through jit and
shards over meshes. Used by sample filters at search time
(``neighbors/sample_filter.cuh``) to mask index rows in/out.

Functional style: mutators return a new ``Bitset`` (XLA model), unlike the
reference's in-place device writes.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

WORD_BITS = 32


def test_words(words, idx):
    """Vectorized bit test against a raw word array — the jit-internal form
    of :meth:`Bitset.test` used by search kernels that carry ``words``
    through ``lax.scan``. Negative indices are treated as bit 0 (callers
    mask them separately)."""
    idx = jnp.asarray(idx)
    safe = jnp.clip(idx, 0)
    word = words[safe // WORD_BITS]
    return ((word >> (safe % WORD_BITS).astype(jnp.uint32)) & 1).astype(jnp.bool_)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class Bitset:
    """Fixed-length bitset over uint32 words.

    ``bits[i]`` lives at word ``i // 32``, bit ``i % 32``. ``n_bits`` is
    static (aux data) so jitted consumers specialize on length.
    """

    words: jax.Array  # uint32[ceil(n_bits/32)]
    n_bits: int

    # -- pytree plumbing -----------------------------------------------------
    def tree_flatten(self):
        return (self.words,), self.n_bits

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(words=children[0], n_bits=aux)

    # -- constructors --------------------------------------------------------
    @classmethod
    def create(cls, n_bits: int, default: bool = True) -> "Bitset":
        """All-set (default) or all-clear bitset; the reference default is
        all-set so that "no filter" passes everything."""
        n_words = (n_bits + WORD_BITS - 1) // WORD_BITS
        fill = jnp.uint32(0xFFFFFFFF) if default else jnp.uint32(0)
        return cls(jnp.full((n_words,), fill, dtype=jnp.uint32), n_bits)

    @classmethod
    def from_mask(cls, mask) -> "Bitset":
        """Pack a boolean vector into words."""
        mask = jnp.asarray(mask, dtype=jnp.bool_)
        n_bits = mask.shape[0]
        n_words = (n_bits + WORD_BITS - 1) // WORD_BITS
        padded = jnp.zeros((n_words * WORD_BITS,), jnp.bool_).at[:n_bits].set(mask)
        bits = padded.reshape(n_words, WORD_BITS).astype(jnp.uint32)
        weights = (jnp.uint32(1) << jnp.arange(WORD_BITS, dtype=jnp.uint32))
        return cls((bits * weights).sum(axis=1).astype(jnp.uint32), n_bits)

    # -- queries -------------------------------------------------------------
    def test(self, idx) -> jax.Array:
        """``bitset_view::test`` — vectorized: idx may be any int array."""
        return test_words(self.words, idx)

    def to_mask(self) -> jax.Array:
        """Unpack to bool[n_bits]."""
        shifts = jnp.arange(WORD_BITS, dtype=jnp.uint32)
        bits = (self.words[:, None] >> shifts[None, :]) & 1
        return bits.reshape(-1)[: self.n_bits].astype(jnp.bool_)

    def count(self) -> jax.Array:
        """Population count (``bitset::count``)."""
        return self.to_mask().sum(dtype=jnp.int32)

    # -- functional mutators -------------------------------------------------
    def set(self, idx, value: bool = True) -> "Bitset":
        mask = self.to_mask()
        mask = mask.at[idx].set(value)
        return Bitset.from_mask(mask)

    def flip(self) -> "Bitset":
        inverted = jnp.bitwise_not(self.words)
        # keep padding bits clear so count() stays correct
        return Bitset.from_mask(Bitset(inverted, self.n_bits).to_mask())
