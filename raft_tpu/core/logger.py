"""Logging — analog of the reference's spdlog-backed ``raft::logger``.

Reference: ``core/logger-inl.hpp:74-160`` (singleton, levels TRACE..OFF,
pattern, callback sink). Here it is a thin veneer over :mod:`logging` with
the same level vocabulary, a callback-sink hook, and trace-vector dumping
(``RAFT_LOG_TRACE_VEC``, used e.g. in ``detail/ivf_flat_search-inl.cuh:104``).
"""

from __future__ import annotations

import enum
import logging
import sys
from typing import Callable, Optional

import numpy as np


class LogLevel(enum.IntEnum):
    """Mirrors RAFT_LEVEL_* (reference ``core/logger-macros.hpp``)."""

    OFF = 0
    CRITICAL = 1
    ERROR = 2
    WARN = 3
    INFO = 4
    DEBUG = 5
    TRACE = 6


_LEVEL_TO_PY = {
    LogLevel.OFF: logging.CRITICAL + 10,
    LogLevel.CRITICAL: logging.CRITICAL,
    LogLevel.ERROR: logging.ERROR,
    LogLevel.WARN: logging.WARNING,
    LogLevel.INFO: logging.INFO,
    LogLevel.DEBUG: logging.DEBUG,
    LogLevel.TRACE: logging.DEBUG - 5,
}

logger = logging.getLogger("raft_tpu")
if not logger.handlers:
    _h = logging.StreamHandler(sys.stderr)
    _h.setFormatter(logging.Formatter("[%(levelname)s] [%(asctime)s] %(message)s"))
    logger.addHandler(_h)
    logger.setLevel(logging.WARNING)

_callback: Optional[Callable[[int, str], None]] = None


def set_level(level: LogLevel | int) -> None:
    """Set global raft_tpu log level (``logger::set_level``,
    reference ``core/logger-inl.hpp:103``)."""
    logger.setLevel(_LEVEL_TO_PY[LogLevel(level)])


def get_level() -> LogLevel:
    py = logger.getEffectiveLevel()
    best = LogLevel.OFF
    for lvl, pyl in _LEVEL_TO_PY.items():
        if pyl >= py and (best == LogLevel.OFF or pyl < _LEVEL_TO_PY[best]):
            best = lvl
    return best


def set_callback(cb: Optional[Callable[[int, str], None]]) -> None:
    """Install a callback sink (analog of the spdlog callback sink the
    reference uses to route C++ logs into Python logging)."""
    global _callback
    _callback = cb


def _emit(level: LogLevel, msg: str, *args) -> None:
    text = msg % args if args else msg
    if _callback is not None:
        _callback(int(level), text)
    logger.log(_LEVEL_TO_PY[level], "%s", text)


def trace(msg, *args):
    _emit(LogLevel.TRACE, msg, *args)


def debug(msg, *args):
    _emit(LogLevel.DEBUG, msg, *args)


def info(msg, *args):
    _emit(LogLevel.INFO, msg, *args)


def warn(msg, *args):
    _emit(LogLevel.WARN, msg, *args)


def error(msg, *args):
    _emit(LogLevel.ERROR, msg, *args)


def trace_vec(name: str, vec, limit: int = 16) -> None:
    """Dump the head of a device vector at TRACE level
    (analog of ``RAFT_LOG_TRACE_VEC``)."""
    if logger.isEnabledFor(_LEVEL_TO_PY[LogLevel.TRACE]):
        head = np.asarray(vec).reshape(-1)[:limit]
        _emit(LogLevel.TRACE, "%s = %s", name, np.array2string(head, precision=4))
