"""Shape-stable serving path: bucketed batching + AOT executable cache.

The reference ships ahead-of-time-compiled kernels (RAFT's L6 explicit
instantiation layer) so serving never compiles; under plain ``jax.jit``
this repo instead paid a full XLA trace+compile (seconds) for every new
query-batch shape — fatal for a frontend that sends varying batch
sizes. ``SearchExecutor`` is the TPU-native answer, per the TPU-KNN
peak-throughput recipe already cited in ``matrix/select_k.py``:

- **Bucketing**: query batches are padded up to power-of-two buckets,
  so every batch size in a bucket runs ONE compiled program. Search
  results are per-query-row independent in every index family, so pad
  rows cannot perturb real rows (their outputs are sliced away), and
  results are bit-identical to the direct search path.
- **AOT compilation**: each (index shapes, search params, bucket)
  specialization is compiled once via ``jit(...).lower().compile()``
  and cached; the steady-state hot path calls the compiled executable
  directly — no tracing, no dispatch-cache lookup, no recompiles.
  :meth:`warmup` builds the executables from abstract shapes before
  traffic arrives, and a persistent compilation cache directory
  (``Resources.compilation_cache_dir``) makes that warmup survive
  process restarts.
- **Donated top-k state**: the running (k-best values, ids) buffers are
  owned by the executor and donated to each call, so the scan state
  reuses one HBM allocation across calls instead of re-allocating (and
  the result write aliases the donated input). Donation is on by
  default on TPU/GPU backends; CPU ignores donation, so it is off
  there unless forced.

Counters (compile count, cache hits/misses, evictions, warmup seconds)
are exported through :mod:`raft_tpu.core.tracing` under the
``serving.`` prefix, and :func:`tracing.install_xla_compile_listener`
provides the backend-compile ground truth that the tier-1 recompile
regression test asserts on.

**Executable cost introspection (PR 6, graftscope).** AOT compilation
is the one moment the whole program is in hand, so that is where the
TPU-KNN roofline accounting moves from bench artifact to live metric:
each compiled entry captures XLA's ``cost_analysis()`` (flops, bytes
accessed) and ``memory_analysis()`` (argument/output/temp bytes → peak
HBM) once, publishes them as ``serving.executable.<digest>.*`` gauges,
and every dispatch bumps ``serving.execute.modeled_flops`` /
``.modeled_bytes`` by the entry's numbers — pure host-side dict work,
captured at compile time, so the steady state stays sync-free and
zero-recompile. Combined with the measured execute-latency histogram
(the batcher blocks on results anyway) a scrape derives live achieved
GB/s and FLOP/s. Mesh plans also publish their
``collective_payload_model`` bytes per wire dtype. :meth:`
SearchExecutor.executable_costs` is the JSON-snapshot view.

Supported index types: ``BruteForceIndex``, ``IvfFlatIndex``,
``IvfPqIndex``, ``IvfBqIndex``, ``CagraIndex``, and the mesh-sharded
``DistributedIvfFlat`` / ``DistributedIvfPq`` / ``DistributedIvfBq``
(AOT-compiled per (mesh, index shapes, params, resolved scan engine,
bucket): queries bucket exactly like the single-chip families, are
placed replicated on the mesh, and the per-shard running top-k state
is donated — steady-state multi-chip serving is zero-recompile).

**Ragged packed-batch plans (PR 9).** The bucket ladder trades pad
compute for shape stability: every batch pow2-rounds (up to ~2x pad
on the query axis) and a micro-batch must assemble whole requests.
The ragged plan family (Ragged Paged Attention, PAPERS.md) collapses
the ladder to ONE executable per (index shapes, params class): a
fixed ``(ragged_tile, dim)`` packed query tensor carries several
requests adjacently, each row's probe budget rides a per-row plane
into the engines' membership mask, and per-request ``k`` is a column
slice of the class-cap top-k (both total orders, so results stay
bit-identical per request to the bucketed path). ``n_probes``/``k``
round up to power-of-two CLASSES instead of forking executables — the
pow2 ladder moved from the batch axis (paid per dispatch, in pad
rows) to the params axis (paid once, in compiles). See
:meth:`SearchExecutor.search_ragged` / :meth:`~SearchExecutor
.ragged_key`; the serving batcher's ``BatcherConfig(ragged=True)``
admits continuously into the open packed tile and splits requests at
tile boundaries.

**One ragged family for the whole index zoo (PR 15, graftragged).**
The ragged plan DERIVES from each family's bucketed plan
(:meth:`SearchExecutor._plan_ragged`): same arrays, statics, probe
plumbing, shardings and donation split, with the serving fn swapped
for a thin wrapper that turns on the ``row_probes`` budget hook in
the SAME search body. Every IVF family — flat, PQ, BQ, single-chip
and list-sharded mesh — serves ragged through the one shared
dispatch core; the per-family bucketed plan paths shrank to the
documented non-raggable residue (see :meth:`SearchExecutor
.ragged_fallback_reason`). An opt-in small/large dual tile
(``ragged_tile_small``) cuts partial-tile pad at light load without
forking the params-class ladder: the tile is selected per dispatch
by packed-row count and never joins :meth:`~SearchExecutor
.ragged_key`.

Small print: padding/slicing a batch to/from its bucket executes tiny
device ops whose programs XLA caches per distinct batch size — the
*search* program itself never recompiles, and once a batch size has
been seen, repeats are entirely compile-free. (The ragged path has no
such per-shape micro-programs at all: packing is host-side numpy in,
one batched fetch out.)
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import hashlib
import threading
import time
import weakref
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.validation import expect


def _fused_entry_fn(queries, dataset, norms, *, k: int, metric):
    """Serving wrapper for the Pallas fused brute-force kernel."""
    from raft_tpu.ops.fused_topk import fused_knn

    return fused_knn(queries, dataset, k, metric, dataset_norms=norms)


@dataclasses.dataclass
class ExecutorStats:
    """Serving-path counters (also exported via ``tracing.counters``)."""

    compile_count: int = 0
    cache_hits: int = 0
    cache_misses: int = 0
    evictions: int = 0
    warmup_seconds: float = 0.0


@dataclasses.dataclass
class _Plan:
    """Everything needed to compile and call one bucket specialization.

    Call argument order is ``(*pre, queries, *post, [filter_words],
    [init_d, init_i])`` — matching each family's serving function
    signature."""

    key: tuple
    fn: Callable
    static: dict
    pre: tuple = ()
    post: tuple = ()
    use_filter: bool = False
    has_state: bool = True
    qdtype: Any = jnp.float32
    qdim: int = 0
    # mesh-sharded (distributed) plans: abstract avals carry the index
    # arrays' NamedShardings, padded queries and the donated state
    # buffers are placed with these shardings before the call
    sharded: bool = False
    qsharding: Any = None
    state_sharding: Any = None
    # distributed plans carry their modeled per-shard collective
    # payload as (family, thunk returning the collective_payload_model
    # dict) — evaluated and published as gauges only on a compile miss,
    # so the cache-hit hot path never builds the dict
    payload: Any = None
    # graftgauge probe-frequency accounting (IVF families, opt-in via
    # SearchExecutor(probe_accounting=True)): (pkey, n_lists,
    # counts_sharding, family, label, index) describing the donated
    # int32 counter plane this plan's dispatches thread through the
    # call — None keeps the compiled signature (and the executable
    # cache key) exactly as before
    probe: Any = None
    # ragged packed-batch plans: the compiled signature carries the
    # per-row probe-budget plane ((tile,) int32) right after the
    # packed queries — the ragged query-tile front of ops/ivf_scan
    ragged: bool = False
    # grafttier: lower with each operand's OWN sharding even off the
    # mesh — the tiered cold plane is committed to host memory, and
    # an aval that dropped its memory kind would compile an
    # executable that hauls the whole cold tier back into HBM per
    # call (exactly the copy the tier exists to avoid)
    keep_sharding: bool = False
    # 2-D query-sharded mesh plans (graftwire): the padded row count
    # when it differs from the bucket — the bucket rounded up to a
    # multiple of the query×list grid extent, so the query shards
    # split evenly AND each list shard's scatter-merge slice stays
    # whole. Dispatch pads/compiles to this instead of the bucket.
    rows: Optional[int] = None


class _Entry:
    __slots__ = ("compiled", "state", "cost", "digest", "family",
                 "payload_model")

    def __init__(self, compiled, state, cost=None, digest="",
                 family="", payload_model=None):
        self.compiled = compiled
        self.state = state
        self.cost = cost or {}
        self.digest = digest
        self.family = family
        # mesh entries keep their collective_payload_model dict so the
        # per-dispatch mesh spans can attach modeled per-phase bytes
        # without rebuilding the model on the hot path
        self.payload_model = payload_model


# readiness-poll quantum for per-shard arrival timing (mesh_trace):
# also the straggler timings' resolution — 50 µs resolves sub-ms skew
# while keeping the poll loop's host cost negligible per dispatch
_MESH_POLL_S = 50e-6


def _executable_cost(compiled) -> dict:
    """XLA's static accounting for one compiled executable: flops and
    bytes accessed from ``cost_analysis()``, the HBM footprint split
    from ``memory_analysis()``. Best-effort — backends that implement
    neither simply yield an empty dict (the gauges then read 0)."""
    cost: dict = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if ca:
            cost["flops"] = float(ca.get("flops", 0.0))
            cost["bytes_accessed"] = float(ca.get("bytes accessed", 0.0))
    except Exception:  # noqa: BLE001 — introspection must never fail a compile
        pass
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            arg = float(getattr(ma, "argument_size_in_bytes", 0))
            out = float(getattr(ma, "output_size_in_bytes", 0))
            tmp = float(getattr(ma, "temp_size_in_bytes", 0))
            alias = float(getattr(ma, "alias_size_in_bytes", 0))
            cost["argument_bytes"] = arg
            cost["output_bytes"] = out
            cost["temp_bytes"] = tmp
            # aliased (donated) outputs reuse argument storage
            cost["peak_hbm_bytes"] = arg + out + tmp - alias
    except Exception:  # noqa: BLE001 — introspection must never fail a compile
        pass
    return cost


def _cost_gauge_values(digest: str, cost: dict) -> dict:
    """The ``serving.executable.<digest>.*`` gauge values for one
    executable's cost dict (compile-time publication and scrape-time
    re-publication read from the same mapping)."""
    base = f"serving.executable.{digest}."
    return {
        base + "flops": cost.get("flops", 0.0),
        base + "bytes_accessed": cost.get("bytes_accessed", 0.0),
        base + "peak_hbm_bytes": cost.get("peak_hbm_bytes", 0.0),
    }


def _named_fn(fn: Callable, name: str) -> Callable:
    """Wrap ``fn`` under a distinct ``__name__`` so jax names the HLO
    module after it (``jit_<name>``). Every AOT entry compiles through
    a digest-derived name (graftflight, PR 11): a profiler trace's
    ``hlo_module`` arg then maps to exactly ONE resident executable —
    without this, every bucket/engine specialization of one family
    shares ``jit__search_impl_fn`` and device time cannot be
    attributed per executable. ``functools.wraps`` keeps the original
    signature visible (``__wrapped__``), so static/donate argname
    resolution is untouched; the name is a pure function of the cache
    key, so the persistent compilation cache stays stable across
    restarts."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        return fn(*args, **kwargs)

    wrapper.__name__ = name
    wrapper.__qualname__ = name
    return wrapper


def _module_name(compiled, fallback: str) -> str:
    """The compiled executable's real HLO module name (what profiler
    trace events carry in ``hlo_module``); falls back to the
    ``jit_``-prefixed wrapper name when the backend exposes no module
    introspection."""
    try:
        mods = compiled.runtime_executable().hlo_modules()
        if mods:
            return str(mods[0].name)
    except Exception:  # noqa: BLE001 — introspection must never fail a compile
        pass
    return f"jit_{fallback}"


def _sds(x) -> Optional[jax.ShapeDtypeStruct]:
    # None passes through: optional plan operands (e.g. the BQ
    # rerank plane of a codes-only index) are empty pytree args
    if x is None:
        return None
    return jax.ShapeDtypeStruct(jnp.shape(x), x.dtype)


def _sds_sharded(x) -> Optional[jax.ShapeDtypeStruct]:
    """Abstract aval carrying the array's sharding — mesh-sharded plans
    must lower with the real NamedShardings so the compiled executable
    accepts (and keeps) the mesh placement."""
    if x is None:
        return None
    return jax.ShapeDtypeStruct(jnp.shape(x), x.dtype,
                                sharding=getattr(x, "sharding", None))


def _mesh_key(comms) -> tuple:
    """Cache-key component identifying a mesh precisely (axis, names,
    shape, device ids) — ``str(mesh)`` alone would collide across
    different device sets of the same shape. Covers 2-D grids whole:
    BOTH axis names, the full device-grid shape, and the flat device
    ordering are in the tuple, so a transposed or re-axed mesh can
    never reuse another grid's executable. Everything here is already
    a hashable static (graftlint R1 watches this function — no lossy
    coercions on the key path)."""
    mesh = comms.mesh
    return ("mesh", comms.axis, tuple(mesh.axis_names),
            tuple(mesh.devices.shape),
            tuple(d.id for d in mesh.devices.flat))


def _sig(*arrays) -> tuple:
    return tuple((tuple(a.shape), str(a.dtype)) for a in arrays)


def _pow2_at_least(n: int, floor: int) -> int:
    """Smallest power-of-two multiple of ``floor`` at/above ``n`` —
    the ragged params-class rounding (a pow2 ladder on the *params*
    axis replaces the old one on the *batch* axis, so the executable
    count stays logarithmic while the query tile carries no pad)."""
    b = floor
    while b < n:
        b *= 2
    return b


def _filter_spec(fw) -> tuple:
    if fw is None:
        return ("nofilter",)
    return ("filter", fw.ndim, fw.shape[-1], str(fw.dtype))


class SearchExecutor:
    """Compile-free steady-state search across all ANN index families.

    Example::

        ex = SearchExecutor(res)
        ex.warmup(index, buckets=(64, 256), k=10)   # cold-start, AOT
        d, i = ex.search(index, queries, 10)        # never traces again

    Constructor args:
      res: shared :class:`Resources` (placement, workspace budget, and
        the persistent ``compilation_cache_dir``).
      min_bucket/max_bucket: power-of-two bucket ladder bounds. Batches
        larger than ``max_bucket`` are tiled at ``max_bucket`` with the
        ragged tail padded into the bucket (all tiles dispatched before
        any result is fetched).
      max_entries: LRU capacity of the executable cache.
      donate: donate the running top-k state buffers to each call.
        Default: enabled on backends that implement donation (not CPU).
      mesh_trace: record graftscope-v2 mesh spans around every
        mesh-sharded dispatch — the three modeled phase spans
        (coarse select / scan / merge, bytes from the entry's
        ``collective_payload_model``) plus per-shard readiness timings
        through the straggler detector
        (``serving.mesh.{shard_skew,slowest_shard}``). Costs a
        host-side readiness wait per dispatch AFTER it is enqueued
        (the batcher blocks on results anyway — but an oversized
        batch's tiles serialize, since each tile's poll completes
        before the next dispatches), compiles nothing, and adds
        nothing inside the traced program; default off so
        latency-pipelined callers (the bench riders) keep fully async
        dispatch.
      probe_accounting: graftgauge device-side probe-frequency
        accounting for the IVF families (single-chip and mesh): each
        dispatch scatter-adds its selected probe ids into a donated
        per-index int32 counter plane inside the compiled program —
        the plane threads through calls exactly like the donated top-k
        state, so steady state stays zero-recompile and search results
        stay bit-identical (the results never read the plane). The
        counters are fetched ONLY at scrape time
        (:meth:`probe_frequencies` / :meth:`publish_probe_gauges` —
        one device fetch per plane per scrape, never per dispatch).
        Default off: enabling changes the compiled signature, so it is
        part of the executable cache key.
      ragged_tile: row count of the ragged plan family's packed batch
        shape (:meth:`search_ragged`). Every ragged dispatch runs
        ``(ragged_tile, dim)`` queries — under load the serving
        batcher keeps the tile full via tile-boundary splits, so pad
        waste collapses to timer-fired partial tiles.
      ragged_tile_small: opt-in SMALL tile of the dual-tile pair
        (e.g. 64 next to a 512 large tile): a packed batch whose
        total rows fit it dispatches through the small executable,
        cutting partial-tile pad at light load. Tile selection is a
        dispatch-time row-count check — both tiles share one
        :meth:`ragged_key`, so the params-class ladder does not fork
        and steady state stays at ≤ 2 executables per (index shapes,
        params class). Default off (one tile).
    """

    def __init__(self, res: Optional[Resources] = None, *,
                 min_bucket: int = 8, max_bucket: int = 4096,
                 max_entries: int = 64, donate: Optional[bool] = None,
                 mesh_trace: bool = False,
                 probe_accounting: bool = False,
                 ragged_tile: int = 256,
                 ragged_tile_small: Optional[int] = None):
        self.res = ensure_resources(res)
        expect(0 < min_bucket <= max_bucket,
               f"need 0 < min_bucket <= max_bucket, got "
               f"({min_bucket}, {max_bucket})")
        buckets = []
        b = min_bucket
        while b < max_bucket:
            buckets.append(b)
            b *= 2
        buckets.append(max_bucket)
        self.buckets: Tuple[int, ...] = tuple(buckets)
        self.max_entries = max_entries
        if donate is None:
            donate = jax.default_backend() not in ("cpu",)
        self.donate = donate
        expect(ragged_tile > 0, "ragged_tile must be > 0")
        expect(ragged_tile_small is None
               or 0 < ragged_tile_small < ragged_tile,
               "ragged_tile_small must be in (0, ragged_tile)")
        # the ragged plan family's packed-batch shape(s): every ragged
        # dispatch runs (tile, dim) queries, so one AOT entry per
        # (index shapes, params class, tile) serves every load shape —
        # the bucket ladder collapsed to one executable, or two with
        # the opt-in dual tile (ragged_tile_small): a packed batch
        # that fits the small tile dispatches through it, cutting
        # partial-tile pad at light load WITHOUT forking the params
        # class (both tiles share one ragged_key, so admission
        # grouping and warmup are tile-oblivious)
        self.ragged_tile = ragged_tile
        self.ragged_tile_small = ragged_tile_small
        self.mesh_trace = mesh_trace
        self.probe_accounting = probe_accounting
        # graftgauge probe-frequency planes: pkey -> device counter
        # array holding the CURRENT scrape window (threaded donated
        # through dispatches, so every bucket/engine entry of one
        # index shares ONE plane; reset to zero as each scrape claims
        # its window), the scrape-side descriptors, the host-side
        # int64 lifetime totals, and the pkeys whose index a weakref
        # finalizer reported garbage-collected (drained under the
        # lock — GC callbacks only append)
        self._probe_state: dict = {}   # guarded-by: _lock
        self._probe_info: dict = {}    # guarded-by: _lock
        self._probe_totals: dict = {}  # guarded-by: _lock
        # NOT lock-guarded: GC finalizers append without the lock
        # (GIL-atomic); the list drains under _lock
        self._probe_dead: list = []
        # graftledger (PR 13): an attached MemoryLedger samples a
        # live-memory watermark after every dispatch (host-only
        # backend call — nothing enters the compiled program, so the
        # cache keys and zero-recompile contract are untouched)
        self._memwatch = None
        self.stats = ExecutorStats()
        self._cache: "collections.OrderedDict[tuple, _Entry]" = (  # guarded-by: _lock
            collections.OrderedDict())
        # digest -> {family, bucket, flops, bytes_accessed, ...}: the
        # JSON-snapshot view of the per-executable cost gauges
        self._cost_table: dict = {}  # guarded-by: _lock
        # multi-threaded frontends share one executor: the cache and
        # the donated per-entry state buffers must hand off atomically
        # (two threads donating the same state would hit jax's
        # deleted-array error). Dispatch is async, so holding the lock
        # through the executable call serializes only enqueueing.
        self._lock = threading.RLock()

    # -- bucketing ----------------------------------------------------------

    def bucket_for(self, q: int) -> int:
        """Smallest bucket >= q (the last bucket for anything larger)."""
        for b in self.buckets:
            if q <= b:
                return b
        return self.buckets[-1]

    # -- public API ---------------------------------------------------------

    def warmup(self, index, buckets=None, *, k: int, params=None,
               sample_filter=None, **kw) -> float:
        """AOT-compile the executables for ``buckets`` (default: the
        whole ladder) so first-traffic latency is a cache *call*, not a
        compile. Returns wall seconds spent (also accumulated into the
        ``serving.warmup_seconds`` counter). With a persistent
        compilation cache configured, a restarted process's warmup
        loads artifacts instead of re-compiling."""
        fw = self._resolve_filter(sample_filter)
        t0 = time.perf_counter()
        for b in (buckets if buckets is not None else self.buckets):
            expect(b in self.buckets, f"bucket {b} not in {self.buckets}")
            plan = self._plan(index, params, k, b, fw, kw)
            self._get_entry(plan, plan.rows or b, k)
        dt = time.perf_counter() - t0
        self.stats.warmup_seconds += dt
        tracing.inc_counter("serving.warmup_seconds", dt)
        return dt

    def search(self, index, queries, k: int, params=None,
               sample_filter=None, trace_ids: Tuple[int, ...] = (),
               **kw) -> Tuple[jax.Array, jax.Array]:
        """Bucketed, compile-free search. Returns (distances (q, k),
        indices (q, k) int32), bit-identical to the direct per-family
        ``search`` entry point. Extra ``kw`` are family-specific knobs
        (brute force: ``db_tile``, ``approx``). ``trace_ids`` tags the
        dispatch's flight-recorder spans (mesh plans with
        ``mesh_trace`` on) — the serving batcher passes its members'
        ids so mesh stragglers attribute back to requests."""
        expect(len(np.shape(queries)) == 2, "queries must be (q, dim)")
        q = int(np.shape(queries)[0])
        if q == 0:
            return (jnp.zeros((0, k), jnp.float32),
                    jnp.zeros((0, k), jnp.int32))
        fw = self._resolve_filter(sample_filter)
        max_b = self.buckets[-1]
        if q <= max_b:
            return self._run(index, queries, k, params, fw, kw,
                             trace_ids=trace_ids)
        # tile oversized batches at the top bucket; every tile runs the
        # same executable and all tiles dispatch before any fetch
        outs_d, outs_i = [], []
        for start in range(0, q, max_b):
            qt = queries[start:start + max_b]
            fwt = fw[start:start + max_b] if (
                fw is not None and fw.ndim == 2) else fw
            d, i = self._run(index, qt, k, params, fwt, kw,
                             trace_ids=trace_ids)
            outs_d.append(d)
            outs_i.append(i)
        return jnp.concatenate(outs_d), jnp.concatenate(outs_i)

    def coalesce_key(self, index, k: int, params=None, sample_filter=None,
                     **kw) -> tuple:
        """Hashable compatibility key for request coalescing: two
        submissions may share one bucketed call iff their keys are
        equal. This is the executor's plan key with the bucket stripped
        (any bucket serves any compatible batch) and the index's
        identity mixed in (two indexes with equal shapes must never
        coalesce). The serving batcher groups its queues by this."""
        fw = self._resolve_filter(sample_filter)
        plan = self._plan(index, params, k, self.buckets[0], fw, kw)
        # plan.key is (family, bucket, *specialization) in every family
        return (id(index), plan.key[0]) + tuple(plan.key[2:])

    def search_blocks(self, index, blocks, k: int, params=None,
                      sample_filter=None, trace_ids: Tuple[int, ...] = (),
                      **kw):
        """Batch-handle entry point for the serving frontend: run the
        per-request query blocks of ONE coalesced micro-batch as a
        single bucketed call and split the results back per block.

        ``blocks`` is a sequence of (m_j, dim) query arrays that agreed
        on :meth:`coalesce_key`; a 2-D ``sample_filter`` must be the
        row-wise concatenation matching the blocks. Returns a list of
        per-block ``(distances, indices)`` pairs, each bit-identical to
        a direct :meth:`search` of that block alone (bucketing pads
        with inert rows, so coalescing cannot perturb results).

        Every family concatenates — CAGRA's seeds became a pure
        function of query content (PR 16), which retired the last
        per-block dispatch special case."""
        expect(len(blocks) > 0, "search_blocks needs at least one block")
        sizes = [int(np.shape(b)[0]) for b in blocks]
        fw = self._resolve_filter(sample_filter)
        if len(blocks) == 1:
            cat = blocks[0]
        elif all(isinstance(b, np.ndarray) for b in blocks):
            cat = np.concatenate(blocks)
        else:
            cat = jnp.concatenate([jnp.asarray(b) for b in blocks])
        d, i = self.search(index, cat, k, params, fw,
                           trace_ids=trace_ids, **kw)
        out, start = [], 0
        for m in sizes:
            out.append((d[start:start + m], i[start:start + m]))
            start += m
        return out

    # -- ragged packed-batch plan family ------------------------------------

    def ragged_key(self, index, k: int, params=None, sample_filter=None,
                   **kw) -> Optional[tuple]:
        """Hashable packing key for the ragged continuous-batching
        path, or ``None`` when this (index, params, k) combination is
        not servable ragged — the caller then falls back to
        :meth:`coalesce_key` and the bucketed path
        (:meth:`ragged_fallback_reason` names why).

        Raggable: every IVF family — flat, PQ, BQ, single-chip AND
        list-sharded mesh — through its membership-masked list-major
        engine with exact coarse select, and CAGRA (PR 16: seeds are
        a pure function of query content; the per-row plane carries
        iteration budgets and the params class rounds
        ``max_iterations``). The documented non-raggable residue:
        CAGRA whose ``k`` class cap exceeds ``itopk_size`` (the beam
        buffer is the result surface), ``coarse_algo="approx"`` (no
        prefix property at the class cap), the rank-major engines (no
        membership mask), codes-only BQ (resolves to the rank
        estimate scan), brute force (no probe plane), ``TieredIvf``
        (the dual-tier fetch plan is placement-epoch state — see
        :meth:`ragged_fallback_reason`), and 2-D query-sharded mesh
        grids (served zero-recompile by the bucketed 2-D plans
        instead). The int8 probe wire rides ragged since its scales
        went block-independent (per-row affine over the FULL local
        coarse block — codes no longer depend on the candidate set,
        so cap-vs-solo bit-identity holds).

        Two submissions may share one packed ragged batch iff their
        keys are equal. Unlike :meth:`coalesce_key`, ``n_probes`` and
        ``k`` do NOT fork the key directly — they round up to a
        power-of-two *params class* (``n_probes`` resolves per row
        through the engines' membership mask, ``k`` through a
        caller-side column slice), so mixed-``n_probes``/``k`` traffic
        under one class cap shares ONE executable (two with the
        opt-in dual tile — the tile is selected at dispatch and is
        deliberately NOT part of this key). The degradation ladder's
        params override feeds this key like any other params (the
        batcher applies it before keying), so a degraded
        specialization that changes only ``n_probes`` keeps packing
        with live traffic. Mesh keys fold the wire knobs in through
        ``kw`` — mesh devices and params-class tuples stay hashable
        statics (graftlint R1 covers this construction)."""
        fw = self._resolve_filter(sample_filter)
        spec, _ = self._ragged_resolve(index, k, params, fw, kw)
        if spec is None:
            return None
        return (id(index), spec["family"] + "_ragged",
                str(index.metric), spec["engine"], spec["np_class"],
                spec["k_class"], _filter_spec(fw),
                tuple(sorted((n, str(v)) for n, v in kw.items())))

    def ragged_fallback_reason(self, index, k: int, params=None,
                               sample_filter=None, **kw) -> Optional[str]:
        """Why this (index, params, k) combination is NOT servable by
        the ragged plan family (``None`` when it is) — the explicit
        plan-key reason the serving batcher's bucketed fallback can be
        pinned against. The strings are stable test surface: each
        names the residue class, not the call site."""
        fw = self._resolve_filter(sample_filter)
        _, reason = self._ragged_resolve(index, k, params, fw, kw)
        return reason

    def warmup_ragged(self, index, *, k: int, params=None,
                      sample_filter=None, **kw) -> float:
        """AOT-compile the ragged executable(s) of this (index,
        params-class) — one per configured tile (a single tile by
        default, the small+large pair with ``ragged_tile_small``) —
        the whole warmup the ragged path needs, where the bucketed
        ladder compiled one executable per bucket. Raises on
        combinations :meth:`ragged_key` would refuse."""
        fw = self._resolve_filter(sample_filter)
        spec, reason = self._ragged_resolve(index, k, params, fw, kw)
        expect(spec is not None,
               "index/params combination is not servable by the ragged "
               f"plan family: {reason}")
        t0 = time.perf_counter()
        for tile in self._ragged_tiles():
            plan = self._plan_ragged(index, fw, spec, tile)
            self._get_entry(plan, tile, spec["k_class"])
        dt = time.perf_counter() - t0
        self.stats.warmup_seconds += dt
        tracing.inc_counter("serving.warmup_seconds", dt)
        return dt

    def _place_ragged_chunk(self, plan: _Plan, qt, rpt):
        """One packed tile's operands, placed for the plan: mesh
        ragged plans put the tile and its budget plane replicated in
        ONE batched transfer (exactly one placement per dispatched
        tile — the same per-dispatch transfer the bucketed mesh path
        pays); single-chip plans pass host arrays straight through
        (the compiled call owns the transfer)."""
        rpt = jnp.asarray(rpt)
        if plan.qsharding is None:
            return qt, rpt
        return jax.device_put([jnp.asarray(qt, plan.qdtype), rpt],
                              [plan.qsharding, plan.qsharding])

    def _ragged_tiles(self) -> Tuple[int, ...]:
        """The configured packed-tile ladder, small first (≤ 2 — the
        dual-tile acceptance bound is structural)."""
        if self.ragged_tile_small is not None:
            return (self.ragged_tile_small, self.ragged_tile)
        return (self.ragged_tile,)

    def _ragged_tile_for(self, total: int) -> int:
        """Dispatch-time tile selection: the small tile iff the whole
        packed batch fits it — a host-side row-count check, so the
        choice costs nothing and never forks the packing key."""
        small = self.ragged_tile_small
        if small is not None and total <= small:
            return small
        return self.ragged_tile

    def search_ragged(self, index, blocks, ks, params_list=None,
                      sample_filter=None,
                      trace_ids: Tuple[int, ...] = (), **kw):
        """Packed ragged-batch entry point: run several requests'
        query blocks — possibly with DIFFERENT per-request ``k`` and
        ``params.n_probes`` — as packed ``(tile, dim)`` calls of ONE
        compiled executable (per configured tile), and split the
        results back per block. Serves every raggable family through
        the same locked dispatch core: single-chip IVF flat/PQ/BQ and
        the list-sharded mesh families (whose packed tile and budget
        plane place replicated, with the donated per-shard top-k
        state and the list-sharded probe plane threaded exactly as
        bucketed mesh plans thread them; ``kw`` carries the mesh wire
        knobs). ``mesh_trace`` span recording is a bucketed-dispatch
        feature — ragged mesh dispatches skip it (the batcher's stage
        spans still cover the packed call).

        ``blocks`` is a sequence of (m_j, dim) query arrays; ``ks``
        and ``params_list`` give each block's ``k`` / search params (a
        scalar/single value is shared by all). Every block must agree
        on :meth:`ragged_key` — the serving batcher groups by it. A
        2-D ``sample_filter`` is the row-wise concatenation matching
        the blocks (1-D shared words pass through, exactly like
        :meth:`search_blocks`).

        Blocks pack adjacently into the tile (the tail padded with
        inert zero rows whose probe budget is 0); totals past one tile
        stream through the SAME executable in tile-sized chunks.
        Returns per-block ``(distances, indices)`` as HOST (numpy)
        arrays, each bit-identical to a direct bucketed :meth:`search`
        of that block alone (total-order coarse select +
        membership-masked probes + total-order merges make the packed
        results independent of what else shares the tile).

        The per-request split is deliberately host-side: one batched
        device fetch per packed tile replaces per-(offset, rows, k)
        device slices — whose tiny programs would otherwise compile
        per load shape, resurrecting through the back door the shape
        churn the ONE packed executable exists to kill. The serving
        batcher blocks on results immediately, so the fetch costs what
        the caller was about to pay anyway; like the bucketed path,
        every chunk is dispatched before anything else can re-donate
        its outputs."""
        expect(len(blocks) > 0, "search_ragged needs at least one block")
        n = len(blocks)
        if not isinstance(ks, (list, tuple)):
            ks = [ks] * n
        if not isinstance(params_list, (list, tuple)):
            params_list = [params_list] * n
        expect(len(ks) == n and len(params_list) == n,
               "ks/params_list must match blocks")
        fw = self._resolve_filter(sample_filter)
        # blocks repeat few distinct (params, k) pairs, and resolution
        # builds a base plan (one resolution authority — see
        # _ragged_resolve): memoize per distinct pair so a packed
        # dispatch of n blocks resolves once per pair, not n times
        memo: dict = {}
        specs = []
        for kj, pj in zip(ks, params_list):
            mk = (pj, kj)
            if mk not in memo:
                memo[mk] = self._ragged_resolve(index, kj, pj, fw, kw)
            s, reason = memo[mk]
            expect(s is not None,
                   "a block is not servable by the ragged plan "
                   f"family: {reason}")
            specs.append(s)
        classes = {(s["family"], s["engine"], s["np_class"],
                    s["k_class"]) for s in specs}
        expect(len(classes) == 1,
               "blocks must agree on the ragged params class — group "
               "submissions by SearchExecutor.ragged_key")
        spec = specs[0]
        k_class = spec["k_class"]
        sizes = [int(np.shape(b)[0]) for b in blocks]
        for b in blocks:
            expect(int(np.shape(b)[1]) == index.dim,
                   "query dim mismatch")
        total = sum(sizes)
        if total == 0:
            return [(np.zeros((0, kj), np.float32),
                     np.zeros((0, kj), np.int32)) for kj in ks]
        if fw is not None and fw.ndim == 2:
            expect(int(fw.shape[0]) == total,
                   "2-D filter rows must match the packed query rows")
        tile = self._ragged_tile_for(total)
        plan = self._plan_ragged(index, fw, spec, tile)

        # host-side packing: adjacent blocks, zero pad rows, per-row
        # probe budgets (0 on pads). numpy blocks (the serving path)
        # pack with zero device ops; device arrays fall back to one
        # concat + pad program per distinct total
        from raft_tpu.ops.ivf_scan import ragged_row_probes

        padded_total = -(-total // tile) * tile
        row_probes = ragged_row_probes(
            sizes, [s["n_probes"] for s in specs], padded_total)
        if all(isinstance(b, np.ndarray) for b in blocks):
            packed = np.zeros((padded_total, index.dim), np.float32)
            r = 0
            for b, m in zip(blocks, sizes):
                packed[r:r + m] = b
                r += m
        else:
            from raft_tpu.neighbors._batching import pad_rows

            packed = pad_rows(
                jnp.concatenate([jnp.asarray(b, jnp.float32)
                                 for b in blocks]), padded_total)
        fwp = fw
        if fw is not None and fw.ndim == 2 and padded_total > total:
            fwp = self._pad(fw, padded_total, fw.dtype)

        # pad-waste attribution: the aggregate serving.execute.rows /
        # .padded_rows counters (bumped per dispatch in the locked
        # core) additionally split per (params class, tile) here, so
        # metrics.derived()["pad_waste_by_class"] and the exporter's
        # labeled family attribute waste to the small-vs-large tile
        # choice. Class labels are pow2-bounded, tiles ≤ 2 — the
        # counter-name cardinality is structural, not client-driven.
        split = (f"p{spec['np_class']}.t{tile}")
        parts_d, parts_i, raw = [], [], []
        with self._lock:
            for start in range(0, padded_total, tile):
                q_real = min(total - start, tile)
                qt, rpt = self._place_ragged_chunk(
                    plan, packed[start:start + tile],
                    row_probes[start:start + tile])
                args = [qt, rpt]
                args.extend(plan.post)
                if plan.use_filter:
                    fwt = fwp
                    if fwp is not None and fwp.ndim == 2:
                        fwt = fwp[start:start + tile]
                    args.append(fwt)
                _, out_d, out_i, _ = self._execute_entry_locked(
                    plan, tile, k_class, args, q_real)
                tracing.inc_counters({
                    f"serving.execute.rows.{split}": q_real,
                    f"serving.execute.padded_rows.{split}": tile,
                })
                if plan.has_state:
                    # donated-state (xla) engine: the outputs ARE the
                    # state the next chunk (or the next caller)
                    # immediately re-donates, so they must be read
                    # before the lock releases — one batched fetch
                    # per tile. See the docstring for why the split
                    # is host-side by design.
                    # graftlint: disable=R5(ragged split is host-side by design: one batched fetch per packed tile replaces per-shape device-slice micro-programs; the serving caller blocks on results immediately)
                    host = jax.device_get((out_d, out_i))
                    parts_d.append(host[0][:q_real])
                    parts_i.append(host[1][:q_real])
                else:
                    # stateless (pallas) engine: nothing aliases the
                    # outputs, so only ENQUEUE under the lock — every
                    # tile dispatches before anything is fetched, and
                    # concurrent searches/scrapes are not blocked for
                    # a device execution
                    raw.append((out_d, out_i, q_real))
        for out_d, out_i, q_real in raw:
            # graftlint: disable=R5(ragged split is host-side by design: one batched fetch per packed tile replaces per-shape device-slice micro-programs; the serving caller blocks on results immediately)
            host = jax.device_get((out_d, out_i))
            parts_d.append(host[0][:q_real])
            parts_i.append(host[1][:q_real])
        if len(parts_d) == 1:
            d_all, i_all = parts_d[0], parts_i[0]
        else:
            d_all = np.concatenate(parts_d)
            i_all = np.concatenate(parts_i)
        out, row = [], 0
        for m, kj in zip(sizes, ks):
            # per-request k: a column slice of the class-cap top-k —
            # the merge is a total order, so the first k_j columns ARE
            # the solo top-k_j
            out.append((d_all[row:row + m, :kj],
                        i_all[row:row + m, :kj]))
            row += m
        return out

    # the documented non-raggable residue, as stable reason strings —
    # what ragged_fallback_reason returns and the fallback tests pin
    _RAGGED_RESIDUE = {
        "cagra_k": "cagra: the k class cap exceeds itopk_size, so the "
                   "class executable's beam buffer would differ from "
                   "the solo run's — bucketed path",
        "brute_force": "brute_force: no probe plane to budget per "
                       "row — bucketed path",
        "approx": "coarse_algo='approx' has no prefix property at "
                  "the class cap — bucketed path",
        "rank": "scan_engine resolved to the rank-major scan, which "
                "has no membership mask — bucketed path",
        "kw": "family-specific kwargs stay on the bucketed path",
        "empty": "empty index or k <= 0 — bucketed path",
        "query_axis": "query_axis grids serve through the bucketed "
                      "2-D plans (zero-recompile, scatter-merged) — "
                      "no ragged front yet",
        "dist_filter": "distributed searches have no sample_filter "
                       "support",
        "family": "index family has no ragged front — bucketed path",
    }

    def _ragged_resolve(self, index, k: int, params, fw, kw):
        """Resolve one request onto the ragged plan family:
        ``(spec, None)`` with the family tag, resolved engine and
        power-of-two class caps, or ``(None, reason)`` when the
        request must stay on the bucketed path. ONE resolver covers
        every raggable family — flat/PQ/BQ, single-chip and mesh —
        because the plan itself derives from the family's bucketed
        plan (:meth:`_plan_ragged`); only raggability and the class
        rounding live here."""
        from raft_tpu.distributed.bq import DistributedIvfBq
        from raft_tpu.distributed.ivf import (
            DistributedIvfFlat,
            DistributedIvfPq,
        )
        from raft_tpu.neighbors import ivf_bq, ivf_flat, ivf_pq
        from raft_tpu.neighbors import tiered as tiered_mod

        reasons = self._RAGGED_RESIDUE
        families = (
            # graftcast: the tiered containers joined the ragged
            # family — their plans are placement-generation-stable
            # (shape-keyed, re-snapshotted per dispatch), so epochs
            # permute placement without touching the one executable
            (tiered_mod.TieredIvf, "tiered_ivf",
             tiered_mod.TieredSearchParams, None),
            (tiered_mod.TieredIvfPq, "tiered_ivf_pq",
             ivf_pq.IvfPqSearchParams, None),
            (tiered_mod.TieredIvfBq, "tiered_ivf_bq",
             ivf_bq.IvfBqSearchParams, None),
            (DistributedIvfFlat, "dist_ivf_flat",
             ivf_flat.IvfFlatSearchParams, None),
            (DistributedIvfPq, "dist_ivf_pq",
             ivf_pq.IvfPqSearchParams, None),
            (DistributedIvfBq, "dist_ivf_bq",
             ivf_bq.IvfBqSearchParams, None),
            (ivf_flat.IvfFlatIndex, "ivf_flat",
             ivf_flat.IvfFlatSearchParams, None),
            (ivf_pq.IvfPqIndex, "ivf_pq",
             ivf_pq.IvfPqSearchParams, None),
            (ivf_bq.IvfBqIndex, "ivf_bq", ivf_bq.IvfBqSearchParams,
             None),
        )
        family = params_cls_type = None
        for typ, fam, pcls, refusal in families:
            if isinstance(index, typ):
                if refusal is not None:
                    return None, reasons[refusal]
                family, params_cls_type = fam, pcls
                break
        if family is None:
            from raft_tpu.neighbors.cagra import CagraIndex

            if isinstance(index, CagraIndex):
                return self._ragged_resolve_cagra(index, k, params, fw,
                                                  kw)
            from raft_tpu.neighbors.brute_force import BruteForceIndex

            if isinstance(index, BruteForceIndex):
                return None, reasons["brute_force"]
            return None, reasons["family"]
        mesh = family.startswith("dist_")
        if mesh:
            if kw.get("query_axis") is not None:
                return None, reasons["query_axis"]
            if not set(kw) <= {"probe_mode", "wire_dtype",
                               "probe_wire_dtype"}:
                return None, reasons["kw"]
            if fw is not None:
                return None, reasons["dist_filter"]
        elif kw:
            return None, reasons["kw"]
        params = params or params_cls_type()
        if params.coarse_algo != "exact":
            return None, reasons["approx"]
        if params.scan_engine == "rank":
            return None, reasons["rank"]
        # DistributedIvfBq carries no max_list_size property; its
        # packed-codes extent plays the same role
        extent = getattr(index, "max_list_size", None)
        if extent is None:
            extent = index.codes.shape[1]
        if extent <= 0 or k <= 0:
            return None, reasons["empty"]
        n_probes = min(params.n_probes, index.n_lists)
        np_class = min(_pow2_at_least(n_probes, 8), index.n_lists)
        k_class = _pow2_at_least(k, 8)
        # the resolved engine comes from the family's OWN bucketed
        # plan at the class caps — one resolution authority, so the
        # raggability decision and the compiled plan cannot disagree
        params_cls = dataclasses.replace(params, n_probes=np_class)
        base = self._plan(index, params_cls, k_class, self.buckets[0],
                          fw, kw)
        engine = base.static["scan_engine"]
        if engine not in (("xla",) if family.endswith("ivf_pq")
                          else ("pallas", "xla")):
            return None, reasons["rank"]
        return {"family": family, "engine": engine,
                "np_class": np_class, "k_class": k_class,
                "n_probes": n_probes, "params_cls": params_cls,
                "kw": kw}, None

    def _ragged_resolve_cagra(self, index, k: int, params, fw, kw):
        """CAGRA onto the ragged plan family (PR 16): seeds are a pure
        function of query content, so any split packs; the per-row
        budget plane carries each request's ITERATION budget (the role
        ``n_probes`` plays for the IVF families), and the params class
        rounds ``max_iterations`` up to a power of two — budget no-op
        iterations are bit-neutral in both engines, so each row equals
        its solo bucketed run. Only the class ``k`` cap must stay
        under ``itopk_size``: the beam buffer IS the result surface,
        and widening it would change the beam itself."""
        from raft_tpu.neighbors import cagra as m

        reasons = self._RAGGED_RESIDUE
        if kw:
            return None, reasons["kw"]
        params = params or m.CagraSearchParams()
        if index.graph.shape[0] == 0 or k <= 0:
            return None, reasons["empty"]
        k_class = _pow2_at_least(k, 8)
        if k_class > params.itopk_size:
            return None, reasons["cagra_k"]
        cfg = m.derive_search_config(params, index, k)
        iters_class = _pow2_at_least(cfg["max_iters"], 8)
        params_cls = dataclasses.replace(params,
                                         max_iterations=iters_class)
        base = self._plan(index, params_cls, k_class, self.buckets[0],
                          fw, kw)
        return {"family": "cagra", "engine": base.static["engine"],
                "np_class": iters_class, "k_class": k_class,
                "n_probes": cfg["max_iters"], "params_cls": params_cls,
                "kw": kw}, None

    # family tag -> (module, attr) of the packed ragged-batch twin of
    # that family's bucketed serving fn — each a thin wrapper over the
    # SAME search body with the per-row budget hook live, so the two
    # paths cannot drift. Module paths (not objects): the mapping must
    # not force the distributed imports at module load
    _RAGGED_FNS = {
        "ivf_flat": ("raft_tpu.neighbors.ivf_flat",
                     "_search_ragged_fn"),
        "ivf_pq": ("raft_tpu.neighbors.ivf_pq", "_search_ragged_fn"),
        "ivf_bq": ("raft_tpu.neighbors.ivf_bq", "_search_ragged_fn"),
        "tiered_ivf": ("raft_tpu.neighbors.tiered",
                       "_tiered_search_ragged_fn"),
        "tiered_ivf_pq": ("raft_tpu.neighbors.tiered",
                          "_tiered_pq_search_ragged_fn"),
        "tiered_ivf_bq": ("raft_tpu.neighbors.tiered",
                          "_tiered_bq_search_ragged_fn"),
        "cagra": ("raft_tpu.neighbors.cagra", "_search_ragged_fn"),
        "dist_ivf_flat": ("raft_tpu.distributed.ivf",
                          "_dist_search_ragged_fn"),
        "dist_ivf_pq": ("raft_tpu.distributed.ivf",
                        "_dist_search_ragged_pq_fn"),
        "dist_ivf_bq": ("raft_tpu.distributed.bq",
                        "_dist_search_ragged_bq_fn"),
    }

    def _ragged_fn(self, family: str) -> Callable:
        """Resolve one family's ragged serving fn (:data:`_RAGGED_FNS`
        — a missing family is a KeyError, the single point a new
        raggable family must register at)."""
        import importlib

        module, attr = self._RAGGED_FNS[family]
        return getattr(importlib.import_module(module), attr)

    def _plan_ragged(self, index, fw, spec, tile: int) -> _Plan:
        """One ragged plan builder for every raggable family — THE
        deletion this PR exists for: the plan DERIVES from the
        family's bucketed plan at the params-class caps (same arrays,
        same statics minus the pinned-exact ``coarse_algo``, same
        probe plumbing, same shardings/donation/payload model), with
        the serving fn swapped for the family's ragged twin and the
        family tag marked ``_ragged``. No per-family ragged plan code
        paths remain — a family change lands in ONE builder and both
        path families inherit it. Probe planes are shared with the
        bucketed plans (same pkey), so one cumulative histogram
        covers an index however its traffic splits across the two
        path families."""
        base = self._plan(index, spec["params_cls"], spec["k_class"],
                          tile, fw, spec["kw"])
        # coarse_algo is pinned exact; query_axis is always None here
        # (2-D grids are refused upstream) and the ragged fns don't
        # take it
        statics = {n: v for n, v in base.static.items()
                   if n not in ("coarse_algo", "query_axis")}
        key = (base.key[0] + "_ragged",) + base.key[1:]
        return dataclasses.replace(
            base, key=key, fn=self._ragged_fn(base.key[0]),
            static=statics, ragged=True)

    def ragged_executables(self, family: Optional[str] = None) -> int:
        """Resident ragged-plan executables — the acceptance surface
        of the one-executable contract (steady state: at most one per
        (index shapes, params class) per configured tile — ≤ 2 per
        family with the dual tile). ``family`` filters to one family
        tag (e.g. ``"dist_ivf_bq"``)."""
        with self._lock:
            return sum(
                1 for key in self._cache
                if key and isinstance(key[0], str)
                and key[0].endswith("_ragged")
                and (family is None or key[0] == family + "_ragged"))

    # -- internals ----------------------------------------------------------

    def _resolve_filter(self, sample_filter):
        if sample_filter is None:
            return None
        from raft_tpu.neighbors.filters import resolve_filter_words

        return resolve_filter_words(sample_filter)

    def _run(self, index, queries, k, params, fw, kw,
             trace_ids: Tuple[int, ...] = ()):
        # grafttier placement race: an epoch swap DONATES the old hot
        # plane / slot maps, and a dispatch that captured the
        # pre-swap generation but enqueued after the swap finds its
        # operands deleted (jax spells this RuntimeError or
        # INVALID_ARGUMENT ValueError depending on the path). The
        # swap serializes its enqueues with dispatch under the
        # executor lock, so each failure means a COMPLETE newer
        # generation is already in the container — rebuild and retry
        # against it. Bounded: every retry needs a fresh swap to have
        # landed in the capture→enqueue window, so under any sane
        # epoch cadence one retry is the norm; the bound guards
        # against a pathological swap storm (any other error
        # re-raises immediately). The final attempt runs WHOLLY under
        # the dispatch lock: plan capture and enqueue become atomic
        # against apply_plan (which swaps under this same RLock), so
        # a swap storm can starve at most four attempts — the fifth
        # cannot observe a donated plane. Lock order stays
        # executor._lock -> container._swap_lock, the order
        # apply_plan already established.
        for _ in range(4):
            try:
                return self._run_once(index, queries, k, params, fw,
                                      kw, trace_ids=trace_ids)
            except (RuntimeError, ValueError) as e:
                if "deleted" not in str(e).lower():
                    raise
                tracing.inc_counter(
                    "serving.execute.placement_retries")
        with self._lock:
            return self._run_once(index, queries, k, params, fw, kw,
                                  trace_ids=trace_ids)

    def _run_once(self, index, queries, k, params, fw, kw,
                  trace_ids: Tuple[int, ...] = ()):
        q = int(np.shape(queries)[0])
        bucket = self.bucket_for(q)
        plan = self._plan(index, params, k, bucket, fw, kw)
        expect(int(np.shape(queries)[1]) == plan.qdim, "query dim mismatch")

        # 2-D query-sharded plans round the padded block up to the
        # grid extent (plan.rows); every other plan pads to the bucket
        rows = plan.rows or bucket
        qp = self._pad(queries, rows, plan.qdtype)
        if plan.qsharding is not None:
            qp = jax.device_put(qp, plan.qsharding)
        args = list(plan.pre) + [qp]
        args.extend(plan.post)
        if plan.use_filter:
            fwp = fw
            if fw is not None and fw.ndim == 2:
                fwp = self._pad(fw, rows, fw.dtype)
            args.append(fwp)
        ret = None
        with self._lock:
            entry, out_d, out_i, t0 = self._execute_entry_locked(
                plan, rows, k, args, q)
            if plan.has_state and self.donate:
                # outputs alias the donated state storage: the result
                # slice (or, at full bucket, a copy — the un-padded
                # slice would BE the state arrays) must dispatch
                # before the lock releases, or a concurrent dispatch
                # of the same plan could re-donate the buffers first
                ret = ((jnp.copy(out_d), jnp.copy(out_i))
                       if q == rows
                       else (out_d[:q], out_i[:q]))
        # mesh recording AFTER the lock releases: the readiness poll
        # lasts as long as the slowest shard, and holding the executor
        # lock through it would stall OTHER threads — concurrent
        # searches and exporter scrapes (publish_cost_gauges takes the
        # same lock) — for a full device execution. The calling thread
        # itself still waits out the poll, so an oversized batch's
        # tiles DO serialize under mesh_trace (per-tile attribution is
        # the trade; see the mesh_trace docstring)
        if plan.sharded and self.mesh_trace:
            self._record_mesh_dispatch(entry, out_d, out_i, t0,
                                       trace_ids)
        if ret is not None:
            return ret
        return out_d[:q], out_i[:q]

    def _execute_entry_locked(self, plan: _Plan, rows: int, k: int,
                              args, q_real: int):
        """Shared locked dispatch core of the bucketed and ragged
        paths: entry fetch/compile, donated top-k state + graftgauge
        probe-plane threading, and the modeled-work counters. The
        caller holds ``self._lock`` (RLock) and has assembled ``args``
        up to (but not including) the donated state. Returns
        ``(entry, out_d, out_i, t0)``; with ``plan.has_state`` the
        outputs ARE the next call's donated state — the caller must
        slice or copy them before anything re-donates."""
        entry = self._get_entry_locked(plan, rows, k)
        if plan.has_state:
            args = list(args) + list(entry.state)
        kwargs = {}
        if plan.probe is not None:
            # graftgauge: thread the per-index donated counter
            # plane + the valid-row count (traced scalar — inert
            # bucket-pad rows must not pollute the histogram).
            # Created lazily on first dispatch; the lock serializes
            # the donate-and-replace handoff exactly like the
            # top-k state's.
            pkey, n_lists, csharding, family, label = plan.probe[:5]
            counts = self._probe_state.get(pkey)
            if counts is None:
                self._evict_dead_probe_planes_locked()
                counts = jnp.zeros((n_lists,), jnp.int32)
                if csharding is not None:
                    counts = jax.device_put(counts, csharding)
                self._probe_info[pkey] = {
                    "family": family, "label": label,
                    "n_lists": n_lists, "sharding": csharding}
                try:
                    # report the index's death so the plane (and
                    # its label) cannot be inherited by a new
                    # index reusing the address; the callback may
                    # fire in GC context, so it only appends —
                    # never takes the executor lock
                    weakref.finalize(plan.probe[5],
                                     self._probe_dead.append, pkey)
                except TypeError:       # non-weakref-able index
                    pass
            nv = jnp.asarray(q_real, jnp.int32)
            if plan.state_sharding is not None:
                nv = jax.device_put(nv, plan.state_sharding)
            kwargs = {"probe_counts": counts, "n_valid": nv}
        t0 = time.perf_counter()
        out = entry.compiled(*args, **kwargs)
        if plan.probe is not None:
            out_d, out_i, new_counts = out
            self._probe_state[plan.probe[0]] = new_counts
        else:
            out_d, out_i = out
        # modeled per-dispatch work, from the compile-time capture:
        # a counter bump (one host lock), never a device sync. The
        # scrape divides these by the measured execute-latency sum
        # to publish live achieved GB/s / FLOP/s. Counted AFTER the
        # dispatch so a call that raises does not inflate the
        # achieved-bandwidth numerator its failed execution never
        # contributes latency for.
        amounts = {
            "serving.execute.calls": 1.0,
            "serving.execute.rows": float(q_real),
            # dispatched row capacity incl. bucket/tile pad — the
            # pad-waste denominator the ragged-vs-bucketed A/B reads
            "serving.execute.padded_rows": float(rows),
            "serving.execute.modeled_flops":
                entry.cost.get("flops", 0.0),
            "serving.execute.modeled_bytes":
                entry.cost.get("bytes_accessed", 0.0),
        }
        if plan.probe is not None:
            # the host-side heartbeat of the device accounting —
            # what the CI snapshot floors check (lifetime ledger)
            amounts["index.probe.dispatches"] = 1.0
            amounts["index.probe.rows"] = float(q_real)
        tracing.inc_counters(amounts)
        if self._memwatch is not None:
            # graftledger watermark: a host-only memory_stats read
            # folded into the ledger's high-water mark — no device
            # sync, no traced op, degrades to a counter bump on
            # backends without live stats
            self._memwatch.sample_dispatch()
        if plan.has_state:
            # outputs alias the donated state storage; keep them as
            # the next call's state
            entry.state = (out_d, out_i)
        return entry, out_d, out_i, t0

    def _record_mesh_dispatch(self, entry, out_d, out_i, t0: float,
                              trace_ids: Tuple[int, ...]) -> None:
        """Graftscope v2 mesh span recording around one sharded
        dispatch (``mesh_trace=True``): the three modeled phase spans
        (bytes from the entry's compile-time
        ``collective_payload_model``) plus per-shard readiness timings
        — each output shard's host-visible arrival offset — reduced by
        the straggler detector into ``serving.mesh.*`` gauges. All of
        it is host-side timing + dict work AFTER the dispatch; nothing
        enters the traced program, so zero-recompile is untouched (the
        regression test runs with this enabled).

        Arrival times come from the shared non-blocking poll
        (:func:`raft_tpu.core.tracing.poll_shard_timings` — see there
        for why sequential blocking would hide early-ordinal
        stragglers, and for the donated-buffer tolerance the
        outside-the-lock poll needs)."""
        try:
            shards = [(sd.data, si.data)
                      for sd, si in zip(out_d.addressable_shards,
                                        out_i.addressable_shards)]
        except RuntimeError:
            # donated-state plans: a concurrent re-dispatch consumed
            # the output buffers before we could even enumerate the
            # shards — nothing left to time, skip this dispatch's
            # recording rather than failing the caller's search
            return
        timings = tracing.poll_shard_timings(shards, t0,
                                             poll_s=_MESH_POLL_S)
        phases = None
        if entry.payload_model is not None:
            from raft_tpu.distributed.ivf import mesh_phases

            phases = mesh_phases(entry.payload_model)
        tracing.record_mesh_spans(
            entry.family or "mesh", t0,
            t0 + (max(timings) if timings else 0.0),
            trace_ids=trace_ids, phases=phases, shard_timings=timings)

    def _pad(self, arr, rows: int, dtype):
        """Pad to ``rows`` along axis 0. numpy inputs (the serving
        frontend case) are padded host-side — zero device ops; device
        arrays pad with one tiny cached concat program."""
        if isinstance(arr, np.ndarray):
            out = np.zeros((rows,) + arr.shape[1:], dtype)
            out[: arr.shape[0]] = arr
            return out
        from raft_tpu.neighbors._batching import pad_rows

        arr = jnp.asarray(arr)
        if arr.dtype != dtype:
            arr = arr.astype(dtype)
        return pad_rows(arr, rows)

    def _get_entry(self, plan: _Plan, bucket: int, k: int) -> _Entry:
        with self._lock:
            return self._get_entry_locked(plan, bucket, k)

    def _get_entry_locked(self, plan: _Plan, bucket: int, k: int) -> _Entry:
        ent = self._cache.get(plan.key)
        if ent is not None:
            self._cache.move_to_end(plan.key)
            self.stats.cache_hits += 1
            tracing.inc_counter("serving.cache_hits")
            return ent
        self.stats.cache_misses += 1
        tracing.inc_counter("serving.cache_misses")
        # digest BEFORE compile: the HLO module is named after it
        # (jit_rt_<family>_<digest>), so a profiler trace's hlo_module
        # events correlate back to exactly this entry (graftflight)
        digest = hashlib.sha1(repr(plan.key).encode()).hexdigest()[:12]
        t0 = time.perf_counter()
        compiled = self._compile(plan, bucket, k,
                                 module=f"rt_{plan.key[0]}_{digest}")
        dt = time.perf_counter() - t0
        self.stats.compile_count += 1
        tracing.inc_counter("serving.compile_count")
        tracing.inc_counter("serving.compile_seconds", dt)
        state = None
        if plan.has_state:
            state = (jnp.zeros((bucket, k), jnp.float32),
                     jnp.zeros((bucket, k), jnp.int32))
            if plan.state_sharding is not None:
                state = tuple(jax.device_put(s, plan.state_sharding)
                              for s in state)
        # cost introspection happens HERE — compile time, once per
        # executable — so the per-dispatch accounting below is a plain
        # dict read with zero device interaction
        cost = _executable_cost(compiled)
        # the compile-time identity graftflight correlates trace events
        # on: the real module name as the profiler will spell it
        cost["hlo_module"] = _module_name(
            compiled, f"rt_{plan.key[0]}_{digest}")
        info = {"family": plan.key[0], "bucket": bucket, "k": k,
                "compile_seconds": dt, **cost}
        payload_model = None
        if plan.payload is not None:
            family, model_fn = plan.payload
            payload_model = dict(model_fn())
            info["collective_family"] = family
            info["collective_payload"] = payload_model
            from raft_tpu.distributed.ivf import publish_payload_gauges

            publish_payload_gauges(family, payload_model)
        self._cost_table[digest] = info
        tracing.set_gauges(_cost_gauge_values(digest, cost))
        ent = _Entry(compiled, state, cost=cost, digest=digest,
                     family=plan.key[0], payload_model=payload_model)
        self._cache[plan.key] = ent
        while len(self._cache) > self.max_entries:
            _, old = self._cache.popitem(last=False)
            self.stats.evictions += 1
            tracing.inc_counter("serving.evictions")
            if old.digest:
                self._cost_table.pop(old.digest, None)
                tracing.reset_gauges(f"serving.executable.{old.digest}.")
        tracing.set_gauge("serving.executor.cached_executables",
                          float(len(self._cache)))
        return ent

    def executable_costs(self) -> dict:
        """``{digest: {family, bucket, k, flops, bytes_accessed,
        peak_hbm_bytes, ...}}`` for every cached executable — the JSON
        view of the ``serving.executable.*`` gauges (one scrape shows
        which programs are resident and what each costs per call)."""
        with self._lock:
            return {d: dict(info) for d, info in self._cost_table.items()}

    def attach_memwatch(self, ledger) -> None:
        """Wire a graftledger :class:`~raft_tpu.core.memwatch
        .MemoryLedger`: every dispatch then folds a live-memory
        watermark sample (host-only — see ``_execute_entry_locked``)
        and the ledger's reservation forecast reads
        :meth:`memory_reservations`."""
        self._memwatch = ledger

    def memory_reservations(self) -> dict:
        """The executor-owned terms of graftledger's reservation
        forecast, per device ordinal: the donated running top-k state
        buffers of every cached entry, the graftgauge probe planes,
        and the max compile-time ``temp_bytes`` over the resident
        executables (any dispatch may be the one that peaks). Pure
        host-side metadata read under the executor lock — shapes,
        dtypes and the compile-time cost table; no device fetch."""
        from raft_tpu.core.memwatch import per_device_bytes

        donated: dict = {}
        planes: dict = {}
        with self._lock:
            for ent in self._cache.values():
                if ent.state is not None:
                    for arr in ent.state:
                        per_device_bytes(arr, donated)
            for arr in self._probe_state.values():
                per_device_bytes(arr, planes)
            max_temp = max(
                (float(info.get("temp_bytes", 0.0))
                 for info in self._cost_table.values()), default=0.0)
            n = len(self._cache)
        return {"donated_state_bytes": donated,
                "probe_plane_bytes": planes,
                "max_temp_bytes": max_temp,
                "executables": n}

    def publish_cost_gauges(self) -> None:
        """Re-publish every resident executable's cost gauges plus the
        cache-size gauge from the live cache. ``metrics.reset()``
        clears the whole ``serving.`` gauge namespace while the cache
        keeps its entries; an attached exporter calls this at scrape
        time so ``/metrics`` and :meth:`executable_costs` never
        disagree about which programs are resident. Mesh entries'
        ``serving.collective.*`` payload gauges re-publish too (they
        are keyed by family + wire dtypes rather than digest, so one
        gauge can represent several resident executables)."""
        with self._lock:
            table = {d: dict(info) for d, info in self._cost_table.items()}
            n = len(self._cache)
        vals = {"serving.executor.cached_executables": float(n)}
        for digest, info in table.items():
            vals.update(_cost_gauge_values(digest, info))
            if "collective_payload" in info:
                from raft_tpu.distributed.ivf import publish_payload_gauges

                publish_payload_gauges(info["collective_family"],
                                       info["collective_payload"])
        tracing.set_gauges(vals)

    def _compile(self, plan: _Plan, bucket: int, k: int,
                 module: Optional[str] = None):
        donate = ()
        if self.donate:
            if plan.has_state:
                donate += ("init_d", "init_i")
            if plan.probe is not None:
                donate += ("probe_counts",)
        fn = plan.fn if module is None else _named_fn(plan.fn, module)
        jitted = jax.jit(fn, static_argnames=tuple(plan.static),
                         donate_argnames=donate)
        sds = _sds_sharded if (plan.sharded or plan.keep_sharding) \
            else _sds
        args = [sds(a) for a in plan.pre]
        args.append(jax.ShapeDtypeStruct((bucket, plan.qdim), plan.qdtype,
                                         sharding=plan.qsharding))
        if plan.ragged:
            # per-row probe-budget plane of the packed ragged batch
            args.append(jax.ShapeDtypeStruct((bucket,), jnp.int32))
        args.extend(sds(a) for a in plan.post)
        if plan.use_filter:
            fw_spec = plan.key[-1]  # _filter_spec tuple
            if fw_spec[0] == "nofilter":
                args.append(None)
            else:
                _, ndim, width, dt = fw_spec
                shape = (bucket, width) if ndim == 2 else (width,)
                args.append(jax.ShapeDtypeStruct(shape, np.dtype(dt)))
        if plan.has_state:
            args.append(jax.ShapeDtypeStruct((bucket, k), jnp.float32,
                                             sharding=plan.state_sharding))
            args.append(jax.ShapeDtypeStruct((bucket, k), jnp.int32,
                                             sharding=plan.state_sharding))
        kwargs = {}
        if plan.probe is not None:
            # graftgauge counter plane + valid-row scalar ride as
            # KEYWORD avals: several plans skip the optional init_d /
            # init_i positionals, so a positional plane would slide
            # into the wrong parameter slot
            _, n_lists, csharding = plan.probe[:3]
            kwargs["probe_counts"] = jax.ShapeDtypeStruct(
                (n_lists,), jnp.int32, sharding=csharding)
            kwargs["n_valid"] = jax.ShapeDtypeStruct(
                (), jnp.int32, sharding=plan.state_sharding)
        return jitted.lower(*args, **kwargs, **plan.static).compile()

    # -- graftgauge probe-frequency surface ---------------------------------

    def _probe_plumbing(self, index, family: str, key: tuple,
                        sharding=None):
        """(key', probe descriptor) for one IVF-family plan: appends
        the accounting marker to the executable cache key (enabling
        accounting changes the compiled signature — it must be a
        distinct executable) and names the per-index counter plane.
        No-op (key unchanged, None) when accounting is off."""
        if not self.probe_accounting:
            return key, None
        pkey = (id(index), index.n_lists)
        digest = hashlib.sha1(
            repr((family, id(index))).encode()).hexdigest()[:6]
        # dash, not dot: the label must stay ONE dot-delimited segment
        # of the gauge name so the exporter's labeled-family regexes
        # can lift it into an {index="..."} label
        label = f"{family}-{digest}"
        # marker slots in BEFORE the trailing _filter_spec tuple —
        # _compile reads the filter spec off key[-1]
        key = key[:-1] + ("probe_accounting", key[-1])
        # the index rides along (plans are per-dispatch descriptors,
        # not cached) so first-dispatch plane creation can register
        # the death-watch weakref
        return key, (pkey, index.n_lists, sharding, family, label,
                     index)

    def _evict_dead_probe_planes_locked(self) -> None:
        """Drop planes whose index was garbage-collected. The weakref
        finalizer only APPENDS the dead pkey (list.append is atomic —
        a GC-context callback must never try to take the executor
        lock); the actual eviction happens here, under the lock, on
        the next dispatch-create or scrape. This also closes the
        id-reuse hazard: a new index reusing a dead one's address
        cannot inherit its cumulative plane."""
        while self._probe_dead:
            pkey = self._probe_dead.pop()
            self._probe_state.pop(pkey, None)
            self._probe_info.pop(pkey, None)
            self._probe_totals.pop(pkey, None)

    def probe_frequencies(self) -> dict:
        """``{label: (n_lists,) int64 numpy plane}`` of cumulative
        per-list probe counts, one entry per index that has dispatched
        with ``probe_accounting`` on. ONE device fetch per plane —
        this is the scrape-time read; nothing on the dispatch path
        ever fetches. The fetch happens under the executor lock, which
        also serializes dispatch, so it atomically CLAIMS the window
        since the last scrape: the device plane resets to zero and the
        fetched counts fold into a host-side int64 lifetime ledger
        (per-window device counts stay far from int32 overflow on any
        realistic scrape interval, while the returned totals never
        wrap) — and the claimed window bumps the monotone
        ``index.probe_freq.accounted`` counter exactly once, however
        many scrapers run concurrently."""
        out = {}
        accounted = 0
        with self._lock:
            self._evict_dead_probe_planes_locked()
            reset_keys, reset_zeros, reset_shardings = [], [], []
            for pkey, arr in self._probe_state.items():
                info = self._probe_info.get(pkey)
                if info is None:
                    continue
                window = np.asarray(jax.device_get(arr), dtype=np.int64)
                if window.any():
                    # claim the window: queue the plane for reset
                    # (placed in ONE batched device_put below)
                    reset_keys.append(pkey)
                    reset_zeros.append(
                        np.zeros(arr.shape, dtype=np.int32))
                    reset_shardings.append(info["sharding"])
                    accounted += int(window.sum())
                total = self._probe_totals.get(pkey)
                total = window if total is None else total + window
                self._probe_totals[pkey] = total
                out[info["label"]] = total.copy()
            if reset_keys:
                fresh = jax.device_put(
                    reset_zeros,
                    [s if s is not None else jax.devices()[0]
                     for s in reset_shardings])
                for pkey, plane in zip(reset_keys, fresh):
                    self._probe_state[pkey] = plane
        if accounted:
            # the mirror the CI snapshot floors check: counts that
            # really came off the device, exactly once per window
            tracing.inc_counter("index.probe_freq.accounted",
                                float(accounted))
        return out

    def publish_probe_gauges(self, top_n: int = 8,
                             planes: Optional[dict] = None) -> dict:
        """Reduce every probe plane through
        :func:`raft_tpu.core.tracing.probe_freq_stats` and publish the
        ``index.probe_freq.<label>.*`` gauges: lifetime ``total``,
        ``probed_fraction`` (share of lists traffic ever touched),
        the hot/cold coverage fractions ``coverage_p01`` /
        ``coverage_p10`` (share of probes the hottest 1% / 10% of
        lists absorb — the signal a future HBM/host-RAM tier split
        keys on), and the top-``top_n`` lists as
        ``index.probe_freq.<label>.list.<lid>`` samples (a labeled
        Prometheus family on the exporter). The monotone
        ``index.probe_freq.accounted`` mirror — the CI snapshot
        floor's ledger of counts that really came off the device — is
        bumped by :meth:`probe_frequencies` as it claims each window.
        ``planes`` lets a caller that already fetched (the exporter's
        scrape does, to share one fetch with drift detection) skip a
        second device read. Returns ``{label: stats}``."""
        if planes is None:
            planes = self.probe_frequencies()
        out = {}
        for label, counts in planes.items():
            stats = tracing.probe_freq_stats(counts, top_n=top_n)
            out[label] = stats
            base = f"index.probe_freq.{label}."
            # retire stale top-N samples before republishing — a list
            # that fell out of the top set must not linger at its old
            # value
            tracing.reset_gauges(base + "list.")
            vals = {
                base + "total": float(stats["total"]),
                base + "probed_fraction": stats["probed_fraction"],
                base + "coverage_p01": stats["coverage_p01"],
                base + "coverage_p10": stats["coverage_p10"],
            }
            for lid, c in stats["top"]:
                vals[f"{base}list.{lid}"] = float(c)
            tracing.set_gauges(vals)
        return out

    def probe_label(self, index) -> Optional[str]:
        """The gauge label of ``index``'s probe plane (None until its
        first accounted dispatch) — how graftgauge's drift detector
        pairs a watched index with its live histogram."""
        with self._lock:
            info = self._probe_info.get((id(index), index.n_lists))
        return info["label"] if info else None

    # -- per-family plans ---------------------------------------------------

    def _plan(self, index, params, k: int, bucket: int, fw, kw) -> _Plan:
        from raft_tpu.distributed.bq import DistributedIvfBq
        from raft_tpu.distributed.ivf import (
            DistributedIvfFlat,
            DistributedIvfPq,
        )
        from raft_tpu.neighbors.brute_force import BruteForceIndex
        from raft_tpu.neighbors.cagra import CagraIndex
        from raft_tpu.neighbors.ivf_bq import IvfBqIndex
        from raft_tpu.neighbors.ivf_flat import IvfFlatIndex
        from raft_tpu.neighbors.ivf_pq import IvfPqIndex
        from raft_tpu.neighbors.tiered import (
            TieredIvf,
            TieredIvfBq,
            TieredIvfPq,
        )

        if isinstance(index, BruteForceIndex):
            return self._plan_brute_force(index, k, bucket, fw, kw)
        if isinstance(index, TieredIvf):
            return self._plan_tiered(index, params, k, bucket, fw, kw)
        if isinstance(index, TieredIvfPq):
            return self._plan_tiered_pq(index, params, k, bucket, fw,
                                        kw)
        if isinstance(index, TieredIvfBq):
            return self._plan_tiered_bq(index, params, k, bucket, fw,
                                        kw)
        if isinstance(index, IvfFlatIndex):
            return self._plan_ivf_flat(index, params, k, bucket, fw, kw)
        if isinstance(index, IvfPqIndex):
            return self._plan_ivf_pq(index, params, k, bucket, fw, kw)
        if isinstance(index, IvfBqIndex):
            return self._plan_ivf_bq(index, params, k, bucket, fw, kw)
        if isinstance(index, CagraIndex):
            return self._plan_cagra(index, params, k, bucket, fw, kw)
        if isinstance(index, (DistributedIvfFlat, DistributedIvfPq,
                              DistributedIvfBq)):
            return self._plan_dist(index, params, k, bucket, fw, kw)
        raise TypeError(f"SearchExecutor does not support {type(index)!r}")

    def _dist_statics(self, index, kw) -> tuple:
        """Shared mesh-plan pieces: (comms, probe_mode, wire_dtype,
        probe_wire_dtype, query_axis) — validated. ``query_axis``
        (graftwire) names a second mesh axis to shard the padded query
        block over: 2-D list×query grids serve through the same
        bucketed AOT plans as 1-D meshes — the bucket rounds up to the
        grid extent and the cache key carries the full 2-D mesh
        identity (:func:`_mesh_key`), so steady state is
        zero-recompile. ``"auto"`` wire dtypes resolve against the
        modeled payload in :meth:`_plan_dist` (after the probe budget
        is known)."""
        from raft_tpu.comms.comms import (
            resolve_probe_wire_dtype,
            resolve_wire_dtype,
        )

        comms = index.comms
        probe_mode = kw.get("probe_mode", "global")
        wire_dtype = kw.get("wire_dtype", "f32")
        probe_wire_dtype = kw.get("probe_wire_dtype", "f32")
        query_axis = kw.get("query_axis")
        expect(probe_mode in ("global", "local"),
               f"probe_mode must be 'global' or 'local', got {probe_mode!r}")
        if wire_dtype != "auto":
            resolve_wire_dtype(wire_dtype)
        if probe_wire_dtype != "auto":
            resolve_probe_wire_dtype(probe_wire_dtype)
        if query_axis is not None:
            expect(query_axis in comms.mesh.axis_names
                   and query_axis != comms.axis,
                   f"query_axis {query_axis!r} must be another mesh axis")
        return comms, probe_mode, wire_dtype, probe_wire_dtype, query_axis

    def _plan_dist(self, index, params, k, bucket, fw, kw) -> _Plan:
        """ONE plan builder for the three list-sharded families —
        they share everything but the per-family statics/arrays, so
        the shared mesh plumbing (probe budget, mesh key, replicated
        query/state shardings, list-sharded probe plane, payload
        model) lives exactly once. The ragged plan family derives
        from this same builder (:meth:`_plan_ragged`), which is what
        retired the per-family bucketed/ragged plan-path copies."""
        from raft_tpu.distributed import bq as dist_bq
        from raft_tpu.distributed import ivf as dist_ivf
        from raft_tpu.distributed.ivf import DistributedIvfFlat, \
            DistributedIvfPq
        from raft_tpu.neighbors import ivf_bq, ivf_flat, ivf_pq

        expect(fw is None,
               "distributed searches have no sample_filter support")
        (comms, probe_mode, wire_dtype, probe_wire_dtype,
         query_axis) = self._dist_statics(index, kw)
        if isinstance(index, DistributedIvfFlat):
            from raft_tpu.ops.ivf_scan import resolve_scan_engine

            family, fn = "dist_ivf_flat", dist_ivf._dist_search_fn
            params = params or ivf_flat.IvfFlatSearchParams()
            n_probes = dist_ivf.resolve_probe_budget(
                params.n_probes, index.n_lists, comms.size, probe_mode)
            engine = resolve_scan_engine(params.scan_engine,
                                         data=index.data, k=k)
            extra, key_extra = {}, ()
            arrays = (index.centers, index.data, index.data_norms,
                      index.indices)
            # same engine/donation split as the single-chip plans: the
            # rank and XLA list-major scans thread the donated
            # per-shard (q, k) state through HBM; the Pallas kernel
            # keeps it in VMEM scratch
            has_state = engine != "pallas"
        elif isinstance(index, DistributedIvfPq):
            family, fn = "dist_ivf_pq", dist_ivf._dist_search_pq_fn
            params = params or ivf_pq.IvfPqSearchParams()
            n_probes = dist_ivf.resolve_probe_budget(
                params.n_probes, index.n_lists, comms.size, probe_mode)
            engine = ivf_pq.resolve_scan_engine(params.scan_engine)
            extra = {"codebook_kind": index.codebook_kind,
                     "score_mode": ivf_pq.resolve_score_mode(
                         params.score_mode, index.codebooks.shape[1]),
                     "lut_dtype": params.lut_dtype}
            key_extra = ()
            arrays = (index.centers, index.rotation, index.codebooks,
                      index.codes, index.indices)
            # both PQ scan engines build their carry from the donated
            # init buffers
            has_state = True
        else:
            from raft_tpu.ops.bq_scan import resolve_bq_engine

            family, fn = "dist_ivf_bq", dist_bq._dist_search_bq_fn
            params = params or ivf_bq.IvfBqSearchParams()
            n_probes = dist_ivf.resolve_probe_budget(
                params.n_probes, index.n_lists, comms.size, probe_mode)
            engine = resolve_bq_engine(
                params.scan_engine, data=index.data, filter_words=None,
                k=k, dim_ext=index.dim_ext, bits=index.bits,
                n_probes=n_probes)
            extra = {"epsilon": params.epsilon}
            key_extra = (("data", index.data is not None),)
            arrays = (index.centers, index.rotation, index.codes,
                      index.rnorm, index.cfac, index.errw,
                      index.indices, index.data, index.data_norms)
            has_state = engine != "pallas"
        rows = bucket
        if query_axis is not None:
            # the padded query block must divide the whole 2-D grid:
            # a multiple of the query-axis extent (even query shards)
            # × the list-axis extent (whole scatter-merge slices per
            # list shard) — the bucketed-block move that makes 2-D
            # grids zero-recompile like 1-D meshes
            grid = comms.mesh.shape[query_axis] * comms.size
            rows = -(-bucket // grid) * grid
        wire_dtype, probe_wire_dtype = dist_ivf.resolve_auto_wires(
            rows, k, n_probes, index.n_lists, comms.size, wire_dtype,
            probe_mode, probe_wire_dtype)
        static = {"axis": comms.axis, "mesh": comms.mesh,
                  "n_probes": n_probes, "k": k, "metric": index.metric,
                  "probe_mode": probe_mode,
                  "coarse_algo": params.coarse_algo,
                  "scan_engine": engine, "wire_dtype": wire_dtype,
                  "probe_wire_dtype": probe_wire_dtype,
                  "query_axis": query_axis, **extra}
        key = (family, rows, _mesh_key(comms),
               _sig(*(a for a in arrays if a is not None))) + key_extra \
            + (tuple(sorted((n, str(v)) for n, v in static.items())),
               _filter_spec(None))
        if query_axis is None:
            key, probe = self._probe_plumbing(
                index, family, key, sharding=comms.sharding(comms.axis))
            qsharding = comms.replicated()
        else:
            # a query-sharded dispatch would write divergent replicas
            # into the probe plane — 2-D plans skip the accounting
            probe = None
            qsharding = comms.sharding(query_axis, None)
        return _Plan(key=key, fn=fn, static=static, post=arrays,
                     qdim=index.dim, sharded=True, probe=probe,
                     has_state=has_state,
                     qsharding=qsharding,
                     state_sharding=qsharding,
                     rows=rows if query_axis is not None else None,
                     payload=(family,
                              lambda: dist_ivf.collective_payload_model(
                                  rows, k, n_probes, index.n_lists,
                                  comms.size, wire_dtype, probe_mode,
                                  probe_wire_dtype)))

    def _plan_brute_force(self, index, k, bucket, fw, kw) -> _Plan:
        from raft_tpu.neighbors import brute_force as bf

        expect(fw is None, "brute_force has no sample_filter support")
        expect(0 < k <= index.size, f"k must be in (0, {index.size}]")
        approx = bool(kw.get("approx", False))
        if not approx and bf._use_fused_kernel(index.metric, k, bucket):
            static = {"k": k, "metric": index.metric}
            key = ("bf_fused", bucket, _sig(index.dataset, index.norms),
                   tuple(sorted(static.items())), _filter_spec(None))
            return _Plan(key=key, fn=_fused_entry_fn, static=static,
                         post=(index.dataset, index.norms),
                         has_state=False, qdtype=index.dataset.dtype,
                         qdim=index.dim)
        db_tile = int(kw.get("db_tile", 32768))
        budget_cols = max(
            128, self.res.workspace_limit_bytes // (4 * bucket))
        db_tile = min(db_tile, budget_cols, max(128, index.size))
        precision = self.res.matmul_precision
        qdtype = jnp.float32
        if index.dataset.dtype == jnp.bfloat16:
            qdtype = jnp.bfloat16
            precision = "default"
        static = {"k": k, "metric": index.metric,
                  "metric_arg": index.metric_arg, "tile": db_tile,
                  "precision": precision, "approx": approx}
        key = ("bf_scan", bucket, _sig(index.dataset),
               tuple(sorted((n, str(v)) for n, v in static.items())),
               _filter_spec(None))
        return _Plan(key=key, fn=bf._knn_scan_fn, static=static,
                     post=(index.dataset,), qdtype=qdtype, qdim=index.dim)

    def _plan_ivf_flat(self, index, params, k, bucket, fw, kw) -> _Plan:
        from raft_tpu.neighbors import ivf_flat as m
        from raft_tpu.ops.ivf_scan import resolve_scan_engine

        params = params or m.IvfFlatSearchParams()
        expect(index.max_list_size > 0, "index is empty — extend() it first")
        n_probes = min(params.n_probes, index.n_lists)
        # the resolved engine is part of the static set and therefore of
        # the AOT cache key: switching engines compiles a new executable
        # instead of silently reusing the wrong one, and bucketing /
        # warmup / donation behave per engine
        engine = resolve_scan_engine(params.scan_engine, data=index.data,
                                     filter_words=fw, k=k)
        static = {"n_probes": n_probes, "k": k, "metric": index.metric,
                  "coarse_algo": params.coarse_algo, "scan_engine": engine}
        arrays = (index.centers, index.center_norms, index.data,
                  index.data_norms, index.indices)
        key = ("ivf_flat", bucket, _sig(*arrays),
               tuple(sorted((n, str(v)) for n, v in static.items())),
               _filter_spec(fw))
        key, probe = self._probe_plumbing(index, "ivf_flat", key)
        # the rank-major and XLA list-major scans thread the donated
        # (q, k) running state through HBM; the Pallas kernel keeps
        # its state in VMEM scratch, so donated buffers would go unused
        return _Plan(key=key, fn=m._search_impl_fn, static=static,
                     post=arrays, use_filter=True, qdim=index.dim,
                     has_state=engine != "pallas", probe=probe)

    def _plan_tiered(self, index, params, k, bucket, fw, kw) -> _Plan:
        from raft_tpu.neighbors import tiered as m
        from raft_tpu.ops.tier_scan import resolve_tier_engine

        params = params or m.TieredSearchParams()
        expect(index.max_list_size > 0, "tiered index is empty")
        n_probes = min(params.n_probes, index.n_lists)
        # ONE consistent placement generation for this dispatch —
        # tier_arrays() snapshots all four placement-affected arrays
        # under the container's swap lock, so a concurrent epoch can
        # never hand a plan a new hot plane against an old slot map
        hot_data, cold_data, hot_map, cold_map = index.tier_arrays()
        engine = resolve_tier_engine(params.scan_engine,
                                     hot_data=hot_data,
                                     filter_words=fw, k=k)
        static = {"n_probes": n_probes, "k": k, "metric": index.metric,
                  "coarse_algo": params.coarse_algo,
                  "scan_engine": engine}
        arrays = (index.centers, index.center_norms, hot_data,
                  cold_data, hot_map, cold_map, index.data_norms,
                  index.indices)
        # the cache key is SHAPES + statics, never array identity: a
        # placement epoch replaces hot_data/cold_data/slot maps with
        # same-shape arrays, so re-placed traffic keeps hitting this
        # exact executable — zero backend compiles across epochs (the
        # grafttier serving contract, pinned in tests)
        key = ("tiered_ivf", bucket, _sig(*arrays),
               tuple(sorted((n, str(v)) for n, v in static.items())),
               _filter_spec(fw))
        key, probe = self._probe_plumbing(index, "tiered_ivf", key)
        # keep_sharding: the cold plane's host memory kind must
        # survive into the lowered avals (see _Plan.keep_sharding)
        return _Plan(key=key, fn=m._tiered_search_fn, static=static,
                     post=arrays, use_filter=True, qdim=index.dim,
                     has_state=engine != "pallas", probe=probe,
                     keep_sharding=True)

    def _plan_tiered_pq(self, index, params, k, bucket, fw,
                        kw) -> _Plan:
        """Tiered-PQ plan (graftcast) — the ``_plan_ivf_pq`` statics
        with the codes plane split hot/cold. Same
        generation-snapshot + shape-keyed discipline as
        :meth:`_plan_tiered`: the placement arrays never enter the
        cache key, every dispatch re-snapshots one consistent
        generation, so epochs are zero-recompile."""
        from raft_tpu.neighbors import ivf_pq
        from raft_tpu.neighbors import tiered as m
        from raft_tpu.ops.tier_scan import resolve_tier_pq_engine

        params = params or ivf_pq.IvfPqSearchParams()
        expect(index.max_list_size > 0, "tiered index is empty")
        score_mode = ivf_pq.resolve_score_mode(params.score_mode,
                                               index.pq_book_size)
        engine = resolve_tier_pq_engine(params.scan_engine)
        (hot_codes,), (cold_codes,), hot_map, cold_map, _ = \
            index.tier_planes()
        static = {"n_probes": min(params.n_probes, index.n_lists),
                  "k": k, "metric": index.metric,
                  "codebook_kind": index.codebook_kind,
                  "lut_dtype": params.lut_dtype,
                  "score_mode": score_mode, "packed": index.packed,
                  "coarse_algo": params.coarse_algo,
                  "scan_engine": engine}
        arrays = (index.centers, index.rotation, index.codebooks,
                  hot_codes, cold_codes, hot_map, cold_map,
                  index.indices)
        key = ("tiered_ivf_pq", bucket, _sig(*arrays),
               tuple(sorted((n, str(v)) for n, v in static.items())),
               _filter_spec(fw))
        key, probe = self._probe_plumbing(index, "tiered_ivf_pq", key)
        return _Plan(key=key, fn=m._tiered_pq_search_fn,
                     static=static, post=arrays, use_filter=True,
                     qdim=index.dim, probe=probe, keep_sharding=True)

    def _plan_tiered_bq(self, index, params, k, bucket, fw,
                        kw) -> _Plan:
        """Tiered-BQ plan (graftcast) — the ``_plan_ivf_bq`` statics
        with the five record planes split hot/cold under one slot
        decision. Generation-snapshot + shape-keyed like the other
        tiered plans."""
        from raft_tpu.neighbors import ivf_bq
        from raft_tpu.neighbors import tiered as m
        from raft_tpu.ops.bq_scan import auto_query_bits
        from raft_tpu.ops.tier_scan import resolve_tier_bq_engine

        params = params or ivf_bq.IvfBqSearchParams()
        expect(index.max_list_size > 0, "tiered index is empty")
        engine = resolve_tier_bq_engine(params.scan_engine)
        qb = params.query_bits or auto_query_bits(index.bits)
        hots, colds, hot_map, cold_map, _ = index.tier_planes()
        static = {"n_probes": min(params.n_probes, index.n_lists),
                  "k": k, "metric": index.metric,
                  "coarse_algo": params.coarse_algo,
                  "scan_engine": engine, "epsilon": params.epsilon,
                  "query_bits": qb}
        arrays = (index.centers, index.rotation) + hots + colds + (
            hot_map, cold_map, index.indices, index.data_norms)
        key = ("tiered_ivf_bq", bucket, _sig(*arrays),
               tuple(sorted((n, str(v)) for n, v in static.items())),
               _filter_spec(fw))
        key, probe = self._probe_plumbing(index, "tiered_ivf_bq", key)
        return _Plan(key=key, fn=m._tiered_bq_search_fn,
                     static=static, post=arrays, use_filter=True,
                     qdim=index.dim, probe=probe, keep_sharding=True)

    def _plan_ivf_pq(self, index, params, k, bucket, fw, kw) -> _Plan:
        from raft_tpu.neighbors import ivf_pq as m

        params = params or m.IvfPqSearchParams()
        expect(index.max_list_size > 0, "index is empty — extend() it first")
        score_mode = m.resolve_score_mode(params.score_mode,
                                          index.pq_book_size)
        engine = m.resolve_scan_engine(params.scan_engine)
        static = {"n_probes": min(params.n_probes, index.n_lists), "k": k,
                  "metric": index.metric,
                  "codebook_kind": index.codebook_kind,
                  "lut_dtype": params.lut_dtype, "score_mode": score_mode,
                  "packed": index.packed, "coarse_algo": params.coarse_algo,
                  "scan_engine": engine}
        arrays = (index.centers, index.rotation, index.codebooks,
                  index.codes, index.indices)
        key = ("ivf_pq", bucket, _sig(*arrays),
               tuple(sorted((n, str(v)) for n, v in static.items())),
               _filter_spec(fw))
        key, probe = self._probe_plumbing(index, "ivf_pq", key)
        # both PQ scan engines build their lax.scan carry from the
        # donated init buffers — keep PR 1's donation on either path
        return _Plan(key=key, fn=m._search_impl_fn, static=static,
                     post=arrays, use_filter=True, qdim=index.dim,
                     probe=probe)

    def _plan_ivf_bq(self, index, params, k, bucket, fw, kw) -> _Plan:
        from raft_tpu.neighbors import ivf_bq as m
        from raft_tpu.ops.bq_scan import resolve_bq_engine

        params = params or m.IvfBqSearchParams()
        expect(index.max_list_size > 0, "index is empty — extend() it first")
        # the resolved engine joins the static set and therefore the
        # AOT cache key (same contract as ivf_flat): engine switch =
        # distinct executable, never a silent reuse
        n_probes = min(params.n_probes, index.n_lists)
        engine = resolve_bq_engine(
            params.scan_engine, data=index.data, filter_words=fw, k=k,
            dim_ext=index.dim_ext, bits=index.bits, n_probes=n_probes)
        from raft_tpu.ops.bq_scan import auto_query_bits

        qb = params.query_bits or auto_query_bits(index.bits)
        static = {"n_probes": n_probes, "k": k,
                  "metric": index.metric, "coarse_algo": params.coarse_algo,
                  "scan_engine": engine, "epsilon": params.epsilon,
                  "query_bits": qb}
        arrays = (index.centers, index.rotation, index.codes, index.rnorm,
                  index.cfac, index.errw, index.indices, index.data,
                  index.data_norms)
        key = ("ivf_bq", bucket, _sig(*(a for a in arrays if a is not None)),
               ("data", index.data is not None),
               tuple(sorted((n, str(v)) for n, v in static.items())),
               _filter_spec(fw))
        key, probe = self._probe_plumbing(index, "ivf_bq", key)
        # the rank and xla engines thread the donated (q, k) running
        # state through HBM; the Pallas kernel keeps it in VMEM scratch
        return _Plan(key=key, fn=m._search_impl_fn, static=static,
                     post=arrays, use_filter=True, qdim=index.dim,
                     has_state=engine != "pallas", probe=probe)

    def _plan_cagra(self, index, params, k, bucket, fw, kw) -> _Plan:
        from raft_tpu.neighbors import cagra as m
        from raft_tpu.ops.bq_scan import auto_query_bits

        params = params or m.CagraSearchParams()
        use_kernel = m._resolve_search_algo(params, index, fw)
        seed_mode = m._resolve_seed_mode(params, index)
        use_bq = m._resolve_bq_traversal(params, index, use_kernel)
        engine = "pallas" if use_kernel else "xla"
        # seeds are a pure function of query content (PR 16), so one
        # "cagra" family serves any block mix — the resolved engine and
        # plane presence join the statics/key exactly like ivf_bq's
        static = dict(m.derive_search_config(params, index, k),
                      metric=index.metric, engine=engine,
                      seed_mode=seed_mode, seed_pool=params.seed_pool,
                      bq_bits=index.bq_bits if use_bq else 0,
                      bq_query_bits=(auto_query_bits(index.bq_bits)
                                     if use_bq else 4),
                      bq_epsilon=params.bq_epsilon,
                      deg=index.graph_degree,
                      interpret=jax.default_backend() != "tpu")
        arrays = (index.dataset,
                  index.padded_graph if use_kernel else index.graph,
                  index.seed_centers, index.seed_members,
                  index.bq_rotation if use_bq else None,
                  index.bq_center_rot if use_bq else None,
                  index.bq_records if use_bq else None)
        key = ("cagra", bucket,
               _sig(*(a for a in arrays if a is not None)),
               ("planes", index.seed_centers is not None, use_bq),
               tuple(sorted((n, str(v)) for n, v in static.items())),
               _filter_spec(fw if not use_kernel else None))
        return _Plan(key=key, fn=m._serving_fn, static=static,
                     post=arrays, use_filter=not use_kernel,
                     has_state=False, qdim=index.dim)
