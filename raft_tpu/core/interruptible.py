"""Cooperative cancellation — analog of ``raft::interruptible``.

Reference: ``core/interruptible.hpp:39-123`` — a per-thread token registry
letting one thread cancel another thread's blocking stream waits. XLA has
no user streams, but long host-side driver loops (index builds batching
over a large dataset, multi-round searches) still need cancellation points.
``synchronize``/``yield_`` check the calling thread's token and raise
``InterruptedException``; ``cancel(thread_id)`` flips it from any thread.
"""

from __future__ import annotations

import threading
from typing import Optional

import jax

_tokens: dict[int, threading.Event] = {}
_lock = threading.Lock()


class InterruptedException(RuntimeError):
    """Raised at a cancellation point (``raft::interruptible::interrupted_exception``)."""


def _token(tid: Optional[int] = None) -> threading.Event:
    tid = tid if tid is not None else threading.get_ident()
    with _lock:
        if tid not in _tokens:
            _tokens[tid] = threading.Event()
        return _tokens[tid]


def cancel(thread_id: Optional[int] = None) -> None:
    """Flag a thread for cancellation (``interruptible::cancel``)."""
    _token(thread_id).set()


def yield_() -> None:
    """Cancellation point: raise if this thread was cancelled, clearing
    the flag (``interruptible::yield``)."""
    tok = _token()
    if tok.is_set():
        tok.clear()
        raise InterruptedException("raft_tpu: thread execution interrupted")


def yield_no_throw() -> bool:
    """Non-throwing check (``interruptible::yield_no_throw``)."""
    tok = _token()
    if tok.is_set():
        tok.clear()
        return True
    return False


def synchronize(*arrays) -> None:
    """Interruptible device sync (``interruptible::synchronize``,
    ``core/interruptible.hpp:83``): block on arrays then hit a
    cancellation point."""
    for a in arrays:
        jax.block_until_ready(a)
    yield_()
