"""graftroute harness — a device-free N-replica fleet in one process.

The serving harness (:mod:`raft_tpu.serving.harness`) made the
batcher's failure modes deterministic with a manual clock and shim
executors; this module lifts the same discipline to FLEET scope so
planner convergence, router failover, and rebalance-under-traffic
races are plain assertions, not races.

:class:`FleetFakeExecutor` is the per-replica engine: a pure
integer-hash distance function of (query row id, candidate id) with
the REAL scan epilog — per-list candidate generation, top-k by
(distance, id) with the smallest-id tie re-rank, +inf/−1 padding —
so a fan-out over any disjoint list partition merges back to the
solo answer bit-for-bit on the f32 wire. Distances are built as
``integer + id·2⁻¹²``: the integer part survives a bf16 wire with
order preserved (rounding is monotone and sub-1 integer gaps never
collapse), the jitter breaks ties in id order on the f32 wire and
vanishes on the bf16 wire — exercising the deterministic
smallest-id re-rank, with the measured recall floor the harness
tests pin ≥0.99 at fleet size 4.

:class:`FleetReplica` wraps one engine with liveness scripting:
``kill()`` for hard death, ``fail_results(n)`` for death DURING an
in-flight request (submit succeeds, ``result()`` raises the typed
:class:`~raft_tpu.fleet.router.ReplicaUnavailable`), plus the live
``generation`` attribute the router's steer skew check reads.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Sequence, Tuple

import numpy as np

from raft_tpu.core.validation import expect
from raft_tpu.fleet.router import ReplicaUnavailable
from raft_tpu.serving.harness import ManualClock

_HASH_A = 2654435761  # Knuth multiplicative constants — any odd
_HASH_B = 40503       # mixers do; pinned for reproducibility


class FleetFakeExecutor:
    """Deterministic per-list scan engine (host-side by contract).

    Candidate ``j`` of list ``l`` has global id ``l·list_size + j``
    and distance ``hash(qid, gid) % modulus + gid·2⁻¹²`` against
    query row id ``qid`` (the row's first component, the
    ``FakeExecutor`` row-identifying convention).
    """

    def __init__(self, n_lists: int = 32, list_size: int = 8,
                 *, modulus: int = 512, seed: int = 7):
        expect(n_lists > 0 and list_size > 0,
               "fleet engine needs non-empty lists")
        self.n_lists = int(n_lists)
        self.list_size = int(list_size)
        self.modulus = int(modulus)
        self.seed = int(seed)

    def scan_lists(self, queries, lists: Sequence[int], k: int):
        """Scan ``lists`` for every query row → ``(d, i)`` blocks of
        shape ``(rows, k)``, +inf/−1 padded, smallest-id ties."""
        q = np.asarray(queries)
        lids = np.asarray(sorted(int(l) for l in lists), np.int64)
        expect(lids.size > 0, "scan needs at least one list")
        expect(np.all((lids >= 0) & (lids < self.n_lists)),
               "list id out of range")
        qid = q[:, 0].astype(np.int64)
        gid = (lids[:, None] * self.list_size
               + np.arange(self.list_size)[None, :]).reshape(-1)
        h = (qid[:, None] * _HASH_A + gid[None, :] * _HASH_B
             + self.seed) % (2 ** 31)
        dist = (h % self.modulus).astype(np.float32) \
            + gid.astype(np.float32) * np.float32(2.0 ** -12)
        ids = np.broadcast_to(gid.astype(np.int32), dist.shape)
        rows, n = dist.shape
        d_out = np.full((rows, k), np.inf, np.float32)
        i_out = np.full((rows, k), -1, np.int32)
        take = min(k, n)
        # row-wise (distance, id) sort — the smallest-id tie re-rank
        # of the real merge epilog (np.lexsort: last key is primary)
        order = np.lexsort((ids, dist), axis=1)[:, :take]
        d_out[:, :take] = np.take_along_axis(dist, order, axis=1)
        i_out[:, :take] = np.take_along_axis(ids, order, axis=1)
        return d_out, i_out


class _FleetHandle:
    """Lazy result handle — evaluation happens at ``result()`` so a
    replica can die while the request is in flight."""

    def __init__(self, replica: "FleetReplica", queries, k, lists):
        self._replica = replica
        self._queries = queries
        self._k = k
        self._lists = lists

    def result(self):
        return self._replica._finish(self._queries, self._k,
                                     self._lists)


class FleetReplica:
    """One shared-nothing replica: full engine copy + liveness."""

    def __init__(self, name: str, executor: FleetFakeExecutor,
                 *, generation: int = 0):
        self.name = name
        self.executor = executor
        self.generation = int(generation)
        self.alive = True
        self.calls: list = []
        self._fail_results = 0

    def kill(self) -> None:
        self.alive = False

    def revive(self) -> None:
        self.alive = True
        self._fail_results = 0

    def fail_results(self, n: int = 1) -> None:
        """Script death DURING flight: the next ``n`` ``result()``
        calls raise :class:`ReplicaUnavailable` (submit succeeds)."""
        self._fail_results = int(n)

    def submit(self, queries, k: int, lists=None) -> _FleetHandle:
        self.calls.append((len(np.asarray(queries)),
                           None if lists is None else tuple(lists)))
        return _FleetHandle(self, queries, k, lists)

    def _finish(self, queries, k: int, lists):
        if self._fail_results > 0:
            self._fail_results -= 1
            raise ReplicaUnavailable(
                f"replica {self.name} died in flight")
        if not self.alive:
            raise ReplicaUnavailable(f"replica {self.name} is down")
        if lists is None:
            lists = range(self.executor.n_lists)
        return self.executor.scan_lists(queries, lists, k)


@dataclasses.dataclass
class FleetHarness:
    """Everything a fleet test needs, deterministically wired."""

    executor: FleetFakeExecutor
    replicas: Dict[str, FleetReplica]
    clock: ManualClock
    n_probes: int

    def resolve_probes(self, queries) -> Tuple[int, ...]:
        """The replica-local coarse select: probed lists are a pure
        function of the query rows' id components."""
        q = np.asarray(queries)
        lids = set()
        for qid in q[:, 0].astype(np.int64):
            for j in range(self.n_probes):
                lids.add(int((qid + 7 * j) % self.executor.n_lists))
        return tuple(sorted(lids))

    def solo(self, queries, k: int):
        """The solo-replica reference answer (bit-identity oracle):
        one engine scans every probed list."""
        return self.executor.scan_lists(
            queries, self.resolve_probes(queries), k)

    def make_queries(self, rows: int, start: int = 0) -> np.ndarray:
        q = np.zeros((rows, 4), np.float32)
        q[:, 0] = np.arange(start, start + rows, dtype=np.float32)
        return q


def make_fleet(n_replicas: int = 4, *, n_lists: int = 32,
               list_size: int = 8, n_probes: int = 4,
               modulus: int = 512, seed: int = 7) -> FleetHarness:
    """Build an N-replica fleet sharing one engine geometry (every
    replica holds the FULL index — the shared-nothing model)."""
    expect(n_replicas >= 1, "fleet needs at least one replica")
    executor = FleetFakeExecutor(n_lists, list_size,
                                 modulus=modulus, seed=seed)
    replicas = {
        f"r{i}": FleetReplica(f"r{i}", executor)
        for i in range(n_replicas)
    }
    return FleetHarness(executor=executor, replicas=replicas,
                        clock=ManualClock(), n_probes=n_probes)
