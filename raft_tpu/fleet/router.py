"""graftroute router — content-aware steering with exact fan-out.

The router sits in FRONT of each replica's batcher: it resolves a
request's probed lists (replica-local coarse select — the signal is
free, the request needed it anyway), scores coverage against the
fleet :class:`~raft_tpu.fleet.table.RoutingTable`, and either

- **steers** — some healthy replica is hot for EVERY probed list
  (and its live tiered generation matches the table's pin): the
  whole request goes there, one leg, result bit-identical to a solo
  replica because it IS a solo replica for those lists; or
- **fans out** — probed lists partition by table OWNER (disjoint —
  the long tail is owned exactly once, so no replica scans a list
  another leg also scans), and the per-leg top-k blocks merge with
  the PR 17 wire discipline: ids exact int32, distances optionally
  on a bf16 wire, ties re-ranked to the smallest id. On the f32
  wire the merge of disjoint partials is EXACT, so fan-out is also
  bit-identical to solo per engine.

Failure is typed, never silent: a replica that dies mid-request
raises :class:`ReplicaUnavailable` from its handle; the router
retries the affected lists on survivors (``fleet.route.retries``)
and only re-raises when no replica is left. Skew is handled the
same way staged prefetch hits are — a generation check: a replica
mid-rebalance (live generation ≠ table pin) is never steered to,
and ownership fan-out stays exact regardless of which tier a list
occupies.

Clock discipline (graftlint R7): the router never reads a wall
clock — table age is measured against the injected clock (batcher
convention).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Dict, Mapping, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import tracing
from raft_tpu.core.validation import expect
from raft_tpu.fleet.table import RoutingTable
from raft_tpu.serving.batcher import MonotonicClock
from raft_tpu.serving.request import ServingError

ROUTE_WIRE_DTYPES = ("f32", "bf16")

# counters
ROUTE_REQUESTS = "fleet.route.requests"
ROUTE_STEERED = "fleet.route.steered"
ROUTE_FANOUT = "fleet.route.fanout"
ROUTE_FANOUT_LEGS = "fleet.route.fanout_legs"
ROUTE_RETRIES = "fleet.route.retries"
ROUTE_UNCOVERED = "fleet.route.uncovered"
ROUTE_SKEW = "fleet.route.generation_skew"
ROUTE_TABLE_APPLIED = "fleet.route.table_applied"
ROUTE_TABLE_STALE = "fleet.route.table_stale"
# gauges
ROUTE_COVERAGE = "fleet.route.coverage_rate"
ROUTE_FANOUT_FRACTION = "fleet.route.fanout_fraction"
ROUTE_TABLE_VERSION = "fleet.route.table_version"
ROUTE_TABLE_AGE = "fleet.route.table_age_s"


class ReplicaUnavailable(ServingError):
    """A replica died (or refused) while a request was in flight on
    it — the router's typed retry-on-survivor trigger."""


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """``merge_wire_dtype`` prices the fan-out merge wire (f32 exact
    / bf16 half the distance bytes, ids always exact int32);
    ``steer`` can force always-fan-out (A/B surface)."""

    merge_wire_dtype: str = "f32"
    steer: bool = True

    def __post_init__(self):
        expect(self.merge_wire_dtype in ROUTE_WIRE_DTYPES,
               f"merge_wire_dtype must be one of {ROUTE_WIRE_DTYPES}")


@dataclasses.dataclass(frozen=True)
class RouteDecision:
    """What the router did with one request (test/debug evidence).

    ``mode``: ``steer`` | ``fanout`` | ``passthrough``; ``fallback``
    names WHY a fan-out happened (``no_table`` / ``uncovered`` /
    ``generation_skew`` / ``retry``) or None.
    """

    mode: str
    replica: Optional[str]
    lists: Tuple[int, ...]
    legs: int
    fallback: Optional[str] = None


def merge_fanout(parts, k: int, *, wire_dtype: str = "f32",
                 select_min: bool = True):
    """Merge per-leg top-k blocks — the router-side twin of the
    distributed :func:`~raft_tpu.distributed.ivf._merge_candidates`
    epilog, same deterministic smallest-id tie re-rank.

    ``parts``: per-leg ``(d (rows, ≤k), i (rows, ≤k))`` blocks with
    +inf/−1 padding. Distances cross the wire in ``wire_dtype``
    (bf16 → rounded through ``jnp.bfloat16``); ids stay exact int32.
    Returns merged ``(rows, k)`` float32/int32 arrays.
    """
    expect(wire_dtype in ROUTE_WIRE_DTYPES,
           f"wire_dtype must be one of {ROUTE_WIRE_DTYPES}")
    expect(len(parts) >= 1, "merge_fanout needs at least one leg")
    ds, ids = [], []
    for d, i in parts:
        d = jnp.asarray(d, jnp.float32)
        if wire_dtype == "bf16":
            d = d.astype(jnp.bfloat16).astype(jnp.float32)
        ds.append(d)
        ids.append(jnp.asarray(i, jnp.int32))
    cat_d = jnp.concatenate(ds, axis=1)
    cat_i = jnp.concatenate(ids, axis=1)
    sd, si = jax.lax.sort((cat_d if select_min else -cat_d, cat_i),
                          dimension=1, num_keys=2)
    sd, si = sd[:, :k], si[:, :k]
    si = jnp.where(jnp.isfinite(sd), si, -1)
    return (sd if select_min else -sd), si


def route_payload_model(q: int, k: int, legs: int,
                        wire_dtype: str = "f32") -> dict:
    """Modeled cross-replica merge payload (bytes) — the
    ``collective_payload_model`` convention applied to the router's
    fan-out: each leg ships ``(q, k)`` distances in ``wire_dtype``
    plus exact int32 ids back to the merge point."""
    expect(wire_dtype in ROUTE_WIRE_DTYPES,
           f"wire_dtype must be one of {ROUTE_WIRE_DTYPES}")
    itemsize = 2 if wire_dtype == "bf16" else 4
    per_leg = q * k * (itemsize + 4)
    return {
        "legs": int(legs),
        "per_leg_bytes": int(per_leg),
        "merge_bytes": int(per_leg * legs),
        "wire_dtype": wire_dtype,
    }


class QueryRouter:
    """Content-aware front door of an N-replica shared-nothing fleet.

    Args:
      replicas: name → replica. A replica exposes ``submit(queries,
        k, lists=...) -> handle`` (``handle.result()`` → ``(d, i)``,
        raising :class:`ReplicaUnavailable` on death) and optionally
        a live ``generation`` attribute (tiered layout epoch).
      resolve_probes: queries → probed coarse list ids (the
        replica-local coarse select, deterministic).
      health: optional callable → ``{name: bool}`` (graftfleet's
        replica health); unlisted replicas count healthy.
      clock: injected clock (``now()``), table age only.
    """

    def __init__(self, replicas: Mapping[str, object], *,
                 resolve_probes: Callable,
                 table: Optional[RoutingTable] = None,
                 config: Optional[RouterConfig] = None,
                 health: Optional[Callable] = None,
                 clock=None):
        expect(len(replicas) > 0, "router needs at least one replica")
        self._replicas = dict(replicas)
        self._resolve = resolve_probes
        self._config = config or RouterConfig()
        self._health = health
        self._clock = clock or MonotonicClock()
        self._lock = threading.Lock()
        self._table = table                      # guarded-by: _lock
        self._applied_at: Optional[float] = None  # guarded-by: _lock
        self._down: set = set()                  # guarded-by: _lock
        self._steers = {n: 0 for n in replicas}  # guarded-by: _lock
        self._requests = 0                       # guarded-by: _lock
        self._steered = 0                        # guarded-by: _lock
        self._fanned = 0                         # guarded-by: _lock

    # -- table lifecycle ------------------------------------------

    @property
    def table(self) -> Optional[RoutingTable]:
        with self._lock:
            return self._table

    def apply_table(self, table) -> bool:
        """Install a newer routing table (push or scrape delivery).

        Accepts a :class:`RoutingTable` or its ``to_json`` dict.
        Only a strictly newer version replaces the live table —
        stale pushes are refused (False, ``fleet.route.table_stale``)
        so out-of-order delivery over the federation channel is
        harmless.
        """
        if not isinstance(table, RoutingTable):
            table = RoutingTable.from_json(table)
        with self._lock:
            live = self._table
            if live is not None and table.version <= live.version:
                stale = True
            else:
                stale = False
                self._table = table
                self._applied_at = self._clock.now()
                self._down.clear()  # fresh plan, retry everyone
        tracing.inc_counter(
            ROUTE_TABLE_STALE if stale else ROUTE_TABLE_APPLIED)
        return not stale

    def snapshot(self) -> dict:
        """The ``/route.json`` payload: live table + router view."""
        with self._lock:
            table = self._table
            if table is None:
                raise LookupError("no routing table applied")
            doc = table.to_json()
            doc["router"] = {
                "requests": self._requests,
                "steered": self._steered,
                "fanout": self._fanned,
                "down": sorted(self._down),
                "steers": dict(sorted(self._steers.items())),
            }
        return doc

    # -- health ---------------------------------------------------

    def _healthy_names(self) -> list:
        healthy = {n: True for n in self._replicas}
        if self._health is not None:
            for n, ok in (self._health() or {}).items():
                if n in healthy:
                    healthy[n] = bool(ok)
        with self._lock:
            down = set(self._down)
        return sorted(n for n, ok in healthy.items()
                      if ok and n not in down)

    def _mark_down(self, name: str) -> None:
        with self._lock:
            self._down.add(name)

    # -- routing --------------------------------------------------

    def route(self, queries, k: int):
        """Answer one request: ``(d, i, decision)``.

        Bit-identity contract: for a given engine, the returned
        ``(d, i)`` equal a solo replica's answer for steered
        requests and for f32-wire fan-out; the bf16 wire trades
        distance bytes for a pinned ≥0.99 recall floor.
        """
        with self._lock:
            self._requests += 1
        tracing.inc_counter(ROUTE_REQUESTS)
        lids = tuple(int(l) for l in self._resolve(queries))
        expect(len(lids) > 0, "resolver returned no probed lists")
        if len(self._replicas) == 1:
            name = next(iter(self._replicas))
            d, i = self._replicas[name].submit(
                queries, k, lists=lids).result()
            return d, i, RouteDecision(mode="passthrough",
                                       replica=name, lists=lids,
                                       legs=1)
        table = self.table
        healthy = self._healthy_names()
        if not healthy:
            raise ReplicaUnavailable("no healthy replica in fleet")
        fallback = None
        if table is None:
            fallback = "no_table"
        elif self._config.steer:
            cover = table.covering(lids, healthy=set(healthy).__contains__)
            fresh = [n for n in cover if not self._skewed(table, n)]
            if cover and not fresh:
                tracing.inc_counter(ROUTE_SKEW)
                fallback = "generation_skew"
            elif not cover:
                tracing.inc_counter(ROUTE_UNCOVERED)
                fallback = "uncovered"
            else:
                got = self._try_steer(fresh, queries, k, lids)
                if got is not None:
                    return got
                fallback = "retry"
        else:
            fallback = "uncovered"
        return self._fan_out(queries, k, lids, table, fallback)

    def _skewed(self, table: RoutingTable, name: str) -> bool:
        pin = table.generation_of(name)
        if pin is None:
            return False
        live = getattr(self._replicas[name], "generation", None)
        return live is not None and int(live) != pin

    def _try_steer(self, cover, queries, k: int, lids):
        """One steered leg to the least-steered covering replica;
        None when the pick died mid-flight (caller fans out on the
        survivors — typed, never an error)."""
        with self._lock:
            name = min(cover, key=lambda n: (self._steers[n], n))
            self._steers[name] += 1
        try:
            d, i = self._replicas[name].submit(
                queries, k, lists=lids).result()
        except ReplicaUnavailable:
            tracing.inc_counter(ROUTE_RETRIES)
            self._mark_down(name)
            return None
        with self._lock:
            self._steered += 1
        tracing.inc_counter(ROUTE_STEERED)
        return d, i, RouteDecision(mode="steer", replica=name,
                                   lists=lids, legs=1)

    def _partition(self, lids, table: Optional[RoutingTable],
                   healthy) -> Dict[str, list]:
        """Disjoint lid → replica partition (exactness invariant:
        every probed list scanned exactly once). Owner scans when
        healthy, else the first healthy copy, else round-robin by
        lid position over the healthy fleet."""
        alive = sorted(healthy)
        legs: Dict[str, list] = {}
        for pos, lid in enumerate(sorted(lids)):
            name = None
            if table is not None:
                for cand in table.assignments[lid]:
                    if cand in healthy:
                        name = cand
                        break
            if name is None:
                name = alive[pos % len(alive)]
            legs.setdefault(name, []).append(lid)
        return legs

    def _fan_out(self, queries, k: int, lids, table, fallback):
        healthy = set(self._healthy_names())
        parts = []
        legs_run = 0
        pending = tuple(lids)
        while pending:
            if not healthy:
                raise ReplicaUnavailable(
                    "no surviving replica for lists %r" % (pending,))
            legs = self._partition(pending, table, healthy)
            pending = ()
            for name in sorted(legs):
                handle = self._replicas[name].submit(
                    queries, k, lists=tuple(legs[name]))
                try:
                    parts.append(handle.result())
                    legs_run += 1
                except ReplicaUnavailable:
                    tracing.inc_counter(ROUTE_RETRIES)
                    self._mark_down(name)
                    healthy.discard(name)
                    pending = pending + tuple(legs[name])
        with self._lock:
            self._fanned += 1
        tracing.inc_counters({ROUTE_FANOUT: 1,
                              ROUTE_FANOUT_LEGS: legs_run})
        if len(parts) == 1:
            d, i = parts[0]
        else:
            d, i = merge_fanout(
                parts, k, wire_dtype=self._config.merge_wire_dtype)
            d, i = np.asarray(d), np.asarray(i)
        return d, i, RouteDecision(mode="fanout", replica=None,
                                   lists=tuple(lids), legs=legs_run,
                                   fallback=fallback)

    # -- observability --------------------------------------------

    def payload_model(self, q: int, k: int, legs: int) -> dict:
        return route_payload_model(
            q, k, legs, self._config.merge_wire_dtype)

    def publish_gauges(self) -> None:
        """Refresh the ``fleet.route.*`` gauge family (scrape-driven,
        the TierManager/exporter convention)."""
        with self._lock:
            req = self._requests
            steered = self._steered
            fanned = self._fanned
            table = self._table
            applied = self._applied_at
            steers = dict(self._steers)
        gauges = {
            ROUTE_COVERAGE: steered / req if req else 0.0,
            ROUTE_FANOUT_FRACTION: fanned / req if req else 0.0,
            ROUTE_TABLE_VERSION:
                float(table.version) if table is not None else 0.0,
            ROUTE_TABLE_AGE:
                (self._clock.now() - applied)
                if applied is not None else 0.0,
        }
        for name, n in steers.items():
            gauges[f"fleet.route.replica.{name}.steered"] = float(n)
        tracing.set_gauges(gauges)
