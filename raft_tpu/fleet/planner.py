"""graftroute planner — fleet placement as a pure epoch function.

grafttier's :func:`~raft_tpu.serving.placement.plan_epoch` decides
WHICH lists one replica keeps hot; this module decides WHO keeps
what, fleet-wide. :func:`plan_fleet` is the same species of policy —
a pure, deterministic function, here of graftfleet's merged probe
plane × per-replica headroom — so two control planes observing the
same aggregator state emit byte-identical routing tables
(:meth:`~raft_tpu.fleet.table.RoutingTable.to_bytes` is the witness
tests pin).

Policy shape: every list gets exactly ONE owner (the long tail is
owned once — shared-nothing, no duplicate scan work on fan-out), and
lists whose measured traffic beats ``hot_share_ratio`` × the uniform
share earn replication copies (R > 1 hot replicas the router may
steer to), capped by per-replica hot capacity derived from reported
headroom. Assignment is greedy hottest-first onto the least-loaded
replica; every tie breaks deterministically (load, then slot count,
then replica name; lists order by (−count, lid)).

Rebalance rides the existing zero-recompile contract: per replica,
:func:`placement_deltas` turns a table transition into the same
(promotions, demotions) pairs :func:`raft_tpu.neighbors.tiered
.apply_plan` executes as fixed-width donated swaps — no new compiled
program, no new swap discipline. The delta also carries a staging
hint (promotions, hottest first) for the replica's
:class:`~raft_tpu.serving.prefetch.TierPrefetcher`, so a list is
staged on the replica ABOUT to become hot for it before the epoch.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, Mapping, Optional, Sequence, Tuple

import numpy as np

from raft_tpu.core import tracing
from raft_tpu.core.validation import expect
from raft_tpu.fleet.table import RoutingTable

# counters
PLAN_BUILDS = "fleet.plan.builds"
PLAN_CHANGED = "fleet.plan.changed"
# gauges
PLAN_VERSION = "fleet.plan.version"
PLAN_REPLICATED = "fleet.plan.replicated_lists"
PLAN_WINDOW_TOTAL = "fleet.plan.window_total"


@dataclasses.dataclass(frozen=True)
class FleetPlanConfig:
    """Knobs of the pure policy (all defaults deterministic).

    ``hot_share_ratio``: a list replicates once more for every
    multiple of (ratio × uniform share) its traffic reaches.
    ``max_replication``: hard cap on copies (0 → up to fleet size).
    ``list_bytes`` + ``safety_fraction``: per-replica hot capacity
    is ``floor(headroom × (1 − safety) / list_bytes)`` slots; with
    ``list_bytes == 0`` (or unreported headroom) capacity falls back
    to ``fallback_slots`` (0 → unbounded).
    ``max_swaps``: the fixed compiled swap width placement deltas
    truncate to (``plan_epoch``'s ``max_swaps`` contract).
    """

    hot_share_ratio: float = 4.0
    max_replication: int = 0
    list_bytes: int = 0
    safety_fraction: float = 0.25
    fallback_slots: int = 0
    max_swaps: int = 8


@dataclasses.dataclass(frozen=True)
class PlacementDelta:
    """One replica's ``apply_plan``-shaped rebalance step.

    ``promotions[i]`` (newly hot list) takes the slot
    ``demotions[i]`` frees — index-paired, truncated to the fixed
    swap ``width`` so the existing compiled swap program executes
    it. ``stage`` is the prefetch hint: the FULL gained set ordered
    hottest-first, fed to ``TierPrefetcher`` ahead of the epoch.
    """

    promotions: Tuple[int, ...]
    demotions: Tuple[int, ...]
    stage: Tuple[int, ...]
    width: int


def _capacity(headroom: Optional[float],
              config: FleetPlanConfig, n_lists: int) -> int:
    if config.list_bytes <= 0 or headroom is None:
        fb = int(config.fallback_slots)
        return n_lists if fb <= 0 else min(fb, n_lists)
    usable = float(headroom) * (1.0 - config.safety_fraction)
    return max(0, min(n_lists, int(usable // config.list_bytes)))


def plan_fleet(window_counts,
               replica_headroom: Mapping[str, Optional[float]],
               *, label: str = "",
               version: int = 0,
               generations: Optional[Mapping[str, int]] = None,
               config: Optional[FleetPlanConfig] = None) -> RoutingTable:
    """The pure fleet placement function.

    Args:
      window_counts: ``(n_lists,)`` merged probe-plane counts.
      replica_headroom: replica name → headroom bytes (None when the
        replica reported none — capacity falls back, see config).
      label / version / generations: carried into the table verbatim
        (the caller — :class:`FleetPlanner` — owns versioning).

    Returns a :class:`RoutingTable`; same arguments ⇒ byte-identical
    ``to_bytes()`` output.
    """
    config = config or FleetPlanConfig()
    counts = np.asarray(window_counts, np.int64)
    expect(counts.ndim == 1 and counts.size > 0,
           "window_counts must be a non-empty (n_lists,) vector")
    expect(len(replica_headroom) > 0,
           "plan_fleet needs at least one replica")
    n_lists = int(counts.size)
    names = sorted(replica_headroom)
    n_rep = len(names)
    cap = {n: _capacity(replica_headroom[n], config, n_lists)
           for n in names}
    # every list needs an owner even on a capacity-starved fleet:
    # distribute ceil(n_lists / n_rep) ownership minimums
    total = int(counts.sum())
    uniform = total / n_lists if total > 0 else 0.0
    rep_cap = n_rep if config.max_replication <= 0 \
        else min(config.max_replication, n_rep)

    def copies(c: int) -> int:
        if total <= 0 or uniform <= 0.0:
            return 1
        extra = int(float(c) / (config.hot_share_ratio * uniform))
        return max(1, min(rep_cap, 1 + extra))

    order = sorted(range(n_lists), key=lambda l: (-counts[l], l))
    load = {n: 0 for n in names}      # assigned traffic
    slots = {n: 0 for n in names}     # hot slots consumed
    assignments: list = [None] * n_lists
    cold_owned: list = []
    for lid in order:
        r = copies(int(counts[lid]))
        share = max(1, int(counts[lid])) // r if total > 0 else 1
        ranked = sorted(names,
                        key=lambda n: (load[n], slots[n], n))
        chosen = []
        for n in ranked:
            if len(chosen) == r:
                break
            if slots[n] < cap[n]:
                chosen.append(n)
        if not chosen:
            # capacity exhausted everywhere — ownership is still
            # mandatory (the owner serves the list cold); place on
            # the least-loaded replica without consuming a slot
            owner = ranked[0]
            load[owner] += share
            assignments[lid] = (owner,)
            cold_owned.append(lid)
            continue
        for n in chosen:
            load[n] += share
            slots[n] += 1
        assignments[lid] = tuple(chosen)
    gens = tuple(sorted(
        (str(n), int(g)) for n, g in (generations or {}).items()))
    return RoutingTable(version=int(version), label=label,
                        assignments=tuple(assignments),
                        counts=tuple(int(c) for c in counts),
                        generations=gens,
                        cold_owned=tuple(sorted(cold_owned)))


def placement_deltas(table: RoutingTable,
                     current_hot: Mapping[str, Sequence[int]],
                     *, max_swaps: int = 8
                     ) -> Dict[str, PlacementDelta]:
    """Per-replica rebalance steps for a table transition.

    ``current_hot`` maps replica → its CURRENT hot list ids. Gained
    lists order hottest-first (−count, lid), lost lists coldest-
    first (count, lid); pairs truncate to ``max_swaps`` — exactly
    the fixed-width contract ``apply_plan`` compiles once. Leftover
    gains stage anyway (the prefetch hint covers the full move; the
    next epoch's pairs drain it).
    """
    expect(max_swaps > 0, "max_swaps must be positive")
    counts = table.counts
    out: Dict[str, PlacementDelta] = {}
    for name in table.replicas:
        new_hot = set(table.hot_lists(name).tolist())
        cur = set(int(l) for l in current_hot.get(name, ()))
        gain = sorted(new_hot - cur,
                      key=lambda l: (-counts[l], l))
        lose = sorted(cur - new_hot,
                      key=lambda l: (counts[l], l))
        pairs = min(len(gain), len(lose), max_swaps)
        out[name] = PlacementDelta(
            promotions=tuple(gain[:pairs]),
            demotions=tuple(lose[:pairs]),
            stage=tuple(gain),
            width=max_swaps)
    return out


class FleetPlanner:
    """Versioned wrapper: aggregator signals in, routing table out.

    Reads graftfleet's typed accessors (never the ``/fleet.json``
    dict by string key), runs :func:`plan_fleet`, and bumps the
    table version ONLY when the placement actually changed — a
    steady fleet re-plans forever at one version, so pushed tables
    are idempotent and the router's stale-push refusal is cheap.
    """

    def __init__(self, aggregator, *, label: str,
                 config: Optional[FleetPlanConfig] = None):
        self._agg = aggregator
        self._label = label
        self._config = config or FleetPlanConfig()
        self._lock = threading.Lock()
        self._table: Optional[RoutingTable] = None  # guarded-by: _lock

    @property
    def table(self) -> Optional[RoutingTable]:
        with self._lock:
            return self._table

    def plan(self, *, generations: Optional[Mapping[str, int]] = None
             ) -> RoutingTable:
        """Plan from the aggregator's CURRENT merged state.

        ``generations`` optionally pins per-replica tiered-layout
        generations into the table (the router's steer skew check);
        omitted entries simply don't gate steering.
        """
        plane = self._agg.merged_probe_plane(self._label)
        headroom = {h.name: h.headroom_bytes
                    for h in self._agg.replica_headroom()}
        with self._lock:
            prev = self._table
            version = prev.version if prev is not None else 0
            cand = plan_fleet(plane.counts, headroom,
                              label=self._label, version=version,
                              generations=generations,
                              config=self._config)
            changed = prev is None or cand.to_bytes() != prev.to_bytes()
            if changed:
                cand = dataclasses.replace(cand, version=version + 1)
                self._table = cand
            table = self._table
        tracing.inc_counter(PLAN_BUILDS)
        if changed:
            tracing.inc_counter(PLAN_CHANGED)
        tracing.set_gauges({
            PLAN_VERSION: float(table.version),
            PLAN_REPLICATED: float(table.replicated_lists()),
            PLAN_WINDOW_TOTAL: float(sum(table.counts)),
        })
        for name in table.replicas:
            tracing.set_gauge(
                f"fleet.plan.replica.{name}.hot_lists",
                float(table.hot_lists(name).size))
        return table

    def deltas(self, current_hot: Mapping[str, Sequence[int]]
               ) -> Dict[str, PlacementDelta]:
        """Rebalance steps from ``current_hot`` to the live table."""
        with self._lock:
            table = self._table
        expect(table is not None, "plan() before deltas()")
        return placement_deltas(table, current_hot,
                                max_swaps=self._config.max_swaps)
