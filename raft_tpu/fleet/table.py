"""graftroute table — the serializable fleet routing table.

The table is the single artifact the planner emits and the router
consumes: for every coarse list it names the replicas hot for that
list, owner first. It is deliberately a PURE value — no clocks, no
timestamps, no RNG — so the planner's determinism claim composes:
same (merged probe plane × headroom) in, byte-identical table out
(:func:`RoutingTable.to_bytes` serializes with sorted keys and no
whitespace variance). Anything time-flavoured (table age, staleness)
lives router-side against an injected clock.

Distribution rides the existing federation channels: the serving
exporter serves the table at ``/route.json`` (scrape mode) and
accepts it on the PR 13 ``POST /push`` channel (``?route=1``) for
NAT-bound replicas — the table is small (one name tuple per list),
versioned, and diffable (:meth:`RoutingTable.diff`), so pushing a
fresh table is idempotent and stale pushes are refused by version.

Generation check: the table records, per replica, the tiered-layout
``generation`` it was planned against. The router refuses to STEER
to a replica whose live generation disagrees (mid-rebalance skew) —
it falls back to ownership fan-out, which stays exact regardless of
which tier a list currently occupies (ownership decides who scans,
not where the list's blocks live).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, Mapping, Optional, Sequence, Tuple

import numpy as np

from raft_tpu.core.validation import expect

TABLE_FORMAT = "graftroute/1"


@dataclasses.dataclass(frozen=True)
class RoutingTable:
    """Versioned fleet routing table: list → hot replicas.

    ``assignments[lid]`` is the ordered replica-name tuple hot for
    list ``lid`` — the first entry is the OWNER (scans the list on
    fan-out), later entries are traffic copies the router may steer
    to. ``counts`` is the traffic plane the plan was built from (per
    list, monotone window counts) — kept in the table so placement
    deltas can order promotions hottest-first without re-reading the
    aggregator. ``generations`` pins each replica's tiered-layout
    generation at plan time (see module docstring).
    """

    version: int
    label: str
    assignments: Tuple[Tuple[str, ...], ...]
    counts: Tuple[int, ...]
    generations: Tuple[Tuple[str, int], ...] = ()
    # lists whose owner serves them from the COLD tier (fleet hot
    # capacity exhausted): still owned exactly once — fan-out stays
    # exact — but never steer-covered and never in a hot set
    cold_owned: Tuple[int, ...] = ()

    def __post_init__(self):
        expect(self.version >= 0, "table version must be >= 0")
        expect(len(self.assignments) == len(self.counts),
               "one traffic count per assigned list")
        for lid, names in enumerate(self.assignments):
            expect(len(names) >= 1,
                   f"list {lid} must have at least an owner")

    # -- shape accessors ------------------------------------------

    @property
    def n_lists(self) -> int:
        return len(self.assignments)

    @property
    def replicas(self) -> Tuple[str, ...]:
        """Every replica named by the table, sorted."""
        seen = set()
        for names in self.assignments:
            seen.update(names)
        return tuple(sorted(seen))

    def owner(self, lid: int) -> str:
        return self.assignments[lid][0]

    def owners(self) -> Tuple[str, ...]:
        """Per-list owner names, index-aligned with list ids."""
        return tuple(names[0] for names in self.assignments)

    def hot_lists(self, replica: str) -> np.ndarray:
        """Sorted int32 list ids ``replica`` is HOT for (cold-owned
        lists are owned, not hot — they serve from the cold tier)."""
        cold = set(self.cold_owned)
        lids = [lid for lid, names in enumerate(self.assignments)
                if replica in names and lid not in cold]
        return np.asarray(lids, np.int32)

    def replicated_lists(self) -> int:
        """How many lists are hot on more than one replica."""
        return sum(1 for names in self.assignments if len(names) > 1)

    def generation_of(self, replica: str) -> Optional[int]:
        for name, gen in self.generations:
            if name == replica:
                return gen
        return None

    def covering(self, lids: Sequence[int],
                 healthy=None) -> Tuple[str, ...]:
        """Replicas hot for EVERY list in ``lids`` (sorted names).

        ``healthy`` optionally restricts candidates to replicas the
        predicate admits (the router passes fleet health here).
        """
        lids = list(lids)
        if not lids:
            return ()
        cold = set(self.cold_owned)
        cover = None
        for lid in lids:
            expect(0 <= lid < self.n_lists,
                   f"list id {lid} outside table ({self.n_lists})")
            if lid in cold:
                return ()
            names = set(self.assignments[lid])
            cover = names if cover is None else (cover & names)
            if not cover:
                return ()
        if healthy is not None:
            cover = {n for n in cover if healthy(n)}
        return tuple(sorted(cover))

    # -- serialization --------------------------------------------

    def to_json(self) -> Dict:
        return {
            "format": TABLE_FORMAT,
            "version": int(self.version),
            "label": self.label,
            "n_lists": self.n_lists,
            "assignments": [list(names) for names in self.assignments],
            "counts": [int(c) for c in self.counts],
            "generations": {n: int(g) for n, g in self.generations},
            "cold_owned": [int(l) for l in self.cold_owned],
            "replicated_lists": self.replicated_lists(),
        }

    def to_bytes(self) -> bytes:
        """Canonical byte serialization — the purity witness.

        Sorted keys, fixed separators: two tables built from the
        same inputs compare equal as BYTES, not just as values.
        """
        return json.dumps(self.to_json(), sort_keys=True,
                          separators=(",", ":")).encode("utf-8")

    @classmethod
    def from_json(cls, doc: Mapping) -> "RoutingTable":
        expect(isinstance(doc, Mapping), "routing table must be a dict")
        expect(doc.get("format") == TABLE_FORMAT,
               f"unknown routing-table format {doc.get('format')!r}")
        assignments = tuple(
            tuple(str(n) for n in names)
            for names in doc.get("assignments") or ())
        counts = tuple(int(c) for c in doc.get("counts") or ())
        gens = tuple(sorted(
            (str(n), int(g))
            for n, g in (doc.get("generations") or {}).items()))
        return cls(version=int(doc.get("version", 0)),
                   label=str(doc.get("label", "")),
                   assignments=assignments, counts=counts,
                   generations=gens,
                   cold_owned=tuple(
                       int(l) for l in doc.get("cold_owned") or ()))

    # -- diffing --------------------------------------------------

    def diff(self, old: Optional["RoutingTable"]) -> Dict:
        """Per-replica hot-set delta vs ``old`` (None → all gained).

        Returns ``{replica: {"gain": [...], "lose": [...]}}`` with
        sorted list ids — the shape the planner's placement deltas
        and the rebalance tests consume.
        """
        if old is not None:
            expect(old.n_lists == self.n_lists,
                   "diff requires same list geometry")
        out: Dict[str, Dict[str, list]] = {}
        names = set(self.replicas)
        if old is not None:
            names.update(old.replicas)
        for name in sorted(names):
            new_hot = set(self.hot_lists(name).tolist())
            old_hot = (set(old.hot_lists(name).tolist())
                       if old is not None else set())
            gain = sorted(new_hot - old_hot)
            lose = sorted(old_hot - new_hot)
            if gain or lose:
                out[name] = {"gain": gain, "lose": lose}
        return out
