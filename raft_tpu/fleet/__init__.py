"""graftroute — fleet placement planning and content-aware routing.

The layer above one replica's serving stack: graftfleet merges
probe planes, traffic, and memory truth fleet-wide (PRs 12–13);
per-replica placement is a pure epoch function executed as
zero-recompile fixed-width swaps (PRs 14/18); cross-replica merge
has an exact contract (PRs 3/17). This package closes the loop —
N identical replicas become a distributed cache hierarchy:

- :mod:`~raft_tpu.fleet.planner` — the pure fleet placement
  function (merged probe plane × headroom → per-replica hot sets
  with traffic-driven replication) plus ``apply_plan``-shaped
  rebalance deltas and prefetch staging hints;
- :mod:`~raft_tpu.fleet.table` — the versioned, diffable,
  byte-canonical routing table (served at ``/route.json``, pushed
  over the federation channel);
- :mod:`~raft_tpu.fleet.router` — coverage-steered request routing
  with exact ownership fan-out and the quantized merge wire;
- :mod:`~raft_tpu.fleet.harness` — the device-free multi-replica
  test fleet (manual clock, scripted deaths).
"""

from raft_tpu.fleet.harness import (
    FleetFakeExecutor,
    FleetHarness,
    FleetReplica,
    make_fleet,
)
from raft_tpu.fleet.planner import (
    FleetPlanConfig,
    FleetPlanner,
    PlacementDelta,
    placement_deltas,
    plan_fleet,
)
from raft_tpu.fleet.router import (
    QueryRouter,
    ReplicaUnavailable,
    RouteDecision,
    RouterConfig,
    merge_fanout,
    route_payload_model,
)
from raft_tpu.fleet.table import RoutingTable

__all__ = [
    "FleetFakeExecutor",
    "FleetHarness",
    "FleetPlanConfig",
    "FleetPlanner",
    "FleetReplica",
    "PlacementDelta",
    "QueryRouter",
    "ReplicaUnavailable",
    "RouteDecision",
    "RouterConfig",
    "RoutingTable",
    "make_fleet",
    "merge_fanout",
    "placement_deltas",
    "plan_fleet",
    "route_payload_model",
]
