"""R9 metric-inventory conformance — ends silent metric/doc drift.

The registry convention (PR 4's graftmetrics) names every counter,
gauge, and histogram with a dotted string at the registration site:
``tracing.inc_counter("serving.evictions")``, a module constant
(``CAPTURES = "profiling.captures"``), a prefix composition
(``PREFIX + "batches"``, ``f"{base}list.{lid}"``), or a dict built up
and handed to ``inc_counters``/``set_gauges`` whole. Three artifacts
restate that inventory by hand and drift silently when code moves:

- the **ARCHITECTURE.md metric tables** (the operator contract),
- the CI **``SNAPSHOT_FLOORS``** in ``ci/bench_compare.py`` (a floor
  naming a counter nothing registers is a check that can never fail
  — or never pass — again),
- the exporter's **``_HELP_PREFIXES``** table (a prefix matching no
  live family is dead HELP text).

R9 extracts every registered metric-name *pattern* (prefix
composition resolved one level deep through the program graph's
constants; unresolvable interpolations become ``*`` wildcards;
fully-dynamic names are dropped, never guessed) and cross-checks:

1. every registered pattern matches a documented pattern — an
   undocumented gauge is a finding at its registration site;
2. every ``SNAPSHOT_FLOORS`` key matches a registered counter — a
   dead floor is a finding in ``ci/bench_compare.py``;
3. every ``_HELP_PREFIXES`` prefix matches some registered metric.

Doc-side patterns come from the inventory tables' backtick spans with
brace groups expanded (``{a,b}``), placeholders (``<label>``) and
``*`` as wildcards, and the tables' ``/``-continuation shorthand
(`` `profiling.captures` / `.device_ops` ``) resolved. The rule is
quiet when the aux files are absent, so fixture projects opt in via
``Project.from_texts(..., aux=...)``.
"""

from __future__ import annotations

import ast
import functools
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from raft_tpu.analysis import astutil, proggraph
from raft_tpu.analysis.core import Finding, Project, rule

_COUNTER_FNS = {"inc_counter", "inc_counters", "max_counter"}
_GAUGE_FNS = {"set_gauge", "set_gauges"}
_HIST_FNS = {"observe", "get_histogram"}
_DICT_FNS = {"inc_counters", "set_gauges"}
_NAME_FNS = (_COUNTER_FNS | _GAUGE_FNS | _HIST_FNS) - _DICT_FNS

_MAX_PATTERNS = 16
_MAX_DEPTH = 6


def _family(leaf: str) -> str:
    if leaf in _COUNTER_FNS:
        return "counter"
    if leaf in _GAUGE_FNS:
        return "gauge"
    return "histogram"


# ---------------------------------------------------------------------------
# pattern algebra
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _compat(a: str, b: str) -> bool:
    """Could ONE concrete metric name match both patterns? ``*`` spans
    any (possibly empty) run of characters on either side."""
    memo: Dict[Tuple[int, int], bool] = {}

    def go(i: int, j: int) -> bool:
        key = (i, j)
        if key in memo:
            return memo[key]
        if i == len(a) and j == len(b):
            r = True
        elif i < len(a) and a[i] == "*":
            r = go(i + 1, j) or (j < len(b) and go(i, j + 1))
        elif j < len(b) and b[j] == "*":
            r = go(i, j + 1) or (i < len(a) and go(i + 1, j))
        elif i < len(a) and j < len(b) and a[i] == b[j]:
            r = go(i + 1, j + 1)
        else:
            r = False
        memo[key] = r
        return r

    return go(0, 0)


def _normalize(p: str) -> Optional[str]:
    """Collapse wildcard runs; drop fully-dynamic patterns (nothing
    literal left to check)."""
    p = re.sub(r"\*+", "*", p.strip())
    if not re.search(r"[A-Za-z0-9]", p.replace("*", "")):
        return None
    return p


def _product(parts: List[Set[str]]) -> Set[str]:
    out = {""}
    for p in parts:
        out = {a + b for a in out for b in p}
        if len(out) > _MAX_PATTERNS:
            out = set(sorted(out)[:_MAX_PATTERNS])
    return out


# ---------------------------------------------------------------------------
# registration extraction
# ---------------------------------------------------------------------------


def _collect_scope(body) -> Tuple[list, Dict[str, list], list, list]:
    """Calls, name assigns, subscript-store keys, and ``.update()``
    sites lexically in one scope (not descending into nested defs)."""
    calls: list = []
    assigns: Dict[str, list] = {}
    subs: list = []          # (var, key expr)
    updates: list = []       # (var, arg expr)
    stack = list(body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue
        if isinstance(n, ast.Call):
            calls.append(n)
            if (isinstance(n.func, ast.Attribute)
                    and n.func.attr == "update"
                    and isinstance(n.func.value, ast.Name) and n.args):
                updates.append((n.func.value.id, n.args[0]))
        if isinstance(n, ast.Assign) and len(n.targets) == 1:
            t = n.targets[0]
            if isinstance(t, ast.Name):
                assigns.setdefault(t.id, []).append((n.lineno, n.value))
            elif isinstance(t, ast.Subscript) and isinstance(
                    t.value, ast.Name):
                subs.append((t.value.id, t.slice))
        if isinstance(n, ast.AugAssign) and isinstance(
                n.target, ast.Subscript) and isinstance(
                    n.target.value, ast.Name):
            subs.append((n.target.value.id, n.target.slice))
        stack.extend(ast.iter_child_nodes(n))
    return calls, assigns, subs, updates


def _patterns(expr, graph, mod, assigns: Dict[str, list],
              visiting: frozenset, depth: int = 0) -> Optional[Set[str]]:
    """Resolve a metric-name expression to patterns (``*`` = dynamic
    part). None = fully dynamic, drop."""
    if expr is None or depth > _MAX_DEPTH:
        return None
    if isinstance(expr, ast.Constant):
        return {expr.value} if isinstance(expr.value, str) else None
    if isinstance(expr, ast.JoinedStr):
        parts: List[Set[str]] = []
        resolved = False
        for v in expr.values:
            if isinstance(v, ast.Constant):
                parts.append({str(v.value)})
                resolved = True
            elif isinstance(v, ast.FormattedValue):
                sub = _patterns(v.value, graph, mod, assigns, visiting,
                                depth + 1)
                if sub:
                    resolved = True
                parts.append(sub or {"*"})
            else:
                parts.append({"*"})
        return _product(parts) if resolved else None
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        left = _patterns(expr.left, graph, mod, assigns, visiting,
                         depth + 1)
        right = _patterns(expr.right, graph, mod, assigns, visiting,
                          depth + 1)
        if left is None and right is None:
            return None
        return _product([left or {"*"}, right or {"*"}])
    if isinstance(expr, ast.IfExp):
        a = _patterns(expr.body, graph, mod, assigns, visiting,
                      depth + 1) or set()
        b = _patterns(expr.orelse, graph, mod, assigns, visiting,
                      depth + 1) or set()
        return (a | b) or None
    if isinstance(expr, ast.Name):
        if expr.id in visiting:
            return None
        inner = visiting | {expr.id}
        # line-aware: a reassigned local (``base = "memory.index..."``
        # … ``base = "memory.device..."``) resolves to the NEAREST
        # preceding assignment, not the union — the union cross-products
        # every prefix with every suffix. No preceding one (loop
        # carry-around) falls back to all of them.
        cands = assigns.get(expr.id, ())
        ref = getattr(expr, "lineno", 0)
        prior = [a for a in cands if a[0] <= ref]
        if prior:
            cands = [max(prior, key=lambda a: a[0])]
        out: Set[str] = set()
        for _ln, v in cands:
            sub = _patterns(v, graph, mod, assigns, inner, depth + 1)
            if sub:
                out |= sub
        if out:
            return out
        g = mod.globals.get(expr.id)
        if g is not None and g.value is not None:
            return _patterns(g.value, graph, mod, {}, inner, depth + 1)
        sym = graph.resolve_symbol(mod, expr.id)
        return {sym} if isinstance(sym, str) else None
    if isinstance(expr, ast.Attribute):
        name = astutil.dotted(expr)
        if name is None:
            return None
        s = graph.string_constant(mod, expr)
        if s is not None:
            return {s}
        # `alias.CONST` where CONST is a composed (non-literal) global
        parts = name.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            ref = mod.imports.get(".".join(parts[:cut]))
            if (ref is not None and ref[0] == "module"
                    and len(parts) - cut == 1):
                target = graph._lookup_module(ref[1])
                if target is not None:
                    g = target.globals.get(parts[-1])
                    if g is not None and g.value is not None:
                        return _patterns(g.value, graph, target, {},
                                         visiting, depth + 1)
        return None
    return None


def _callee_dict_keys(graph, callee: proggraph.FunctionInfo
                      ) -> Set[str]:
    """One level into a dict-returning helper: every dict-display key
    and subscript-store key in its body (over-approximates, which is
    safe — these names ARE registered when the helper's result is)."""
    mod = graph.modules.get(callee.rel)
    if mod is None:
        return set()
    _calls, assigns, subs, _updates = _collect_scope(callee.node.body)
    keys: Set[str] = set()
    for node in ast.walk(callee.node):
        if isinstance(node, ast.Dict):
            for k in node.keys:
                keys |= _patterns(k, graph, mod, assigns,
                                  frozenset()) or set()
        elif isinstance(node, ast.DictComp):
            keys |= _patterns(node.key, graph, mod, assigns,
                              frozenset()) or set()
    for var, key in subs:
        keys |= _patterns(key, graph, mod, assigns, frozenset()) or set()
    return keys


def _dict_key_patterns(expr, graph, mod, assigns, subs, updates,
                       resolve_call, depth: int = 0) -> Set[str]:
    """Metric-name patterns for a dict handed to
    ``inc_counters``/``set_gauges``: a display, a comprehension, a
    local accumulator (``vals = {...}``, ``vals[k] = v``,
    ``vals.update(helper())``), or a dict-returning helper call."""
    if depth > 2 or expr is None:
        return set()
    keys: Set[str] = set()
    if isinstance(expr, ast.Dict):
        for k in expr.keys:
            keys |= _patterns(k, graph, mod, assigns,
                              frozenset()) or set()
    elif isinstance(expr, ast.DictComp):
        keys |= _patterns(expr.key, graph, mod, assigns,
                          frozenset()) or set()
    elif isinstance(expr, ast.Name):
        for _ln, v in assigns.get(expr.id, ()):
            keys |= _dict_key_patterns(v, graph, mod, assigns, subs,
                                       updates, resolve_call, depth + 1)
        for var, key in subs:
            if var == expr.id:
                keys |= _patterns(key, graph, mod, assigns,
                                  frozenset()) or set()
        for var, arg in updates:
            if var == expr.id:
                keys |= _dict_key_patterns(arg, graph, mod, assigns,
                                           subs, updates, resolve_call,
                                           depth + 1)
    elif isinstance(expr, ast.Call):
        callee = resolve_call(expr)
        if callee is not None:
            keys |= _callee_dict_keys(graph, callee)
    return keys


def registered_metrics(project: Project
                       ) -> List[Tuple[str, str, str, int]]:
    """Every metric-name pattern the library registers:
    ``(pattern, family, rel, lineno)`` — cached on the project."""
    cached = getattr(project, "_metric_inventory", None)
    if cached is not None:
        return cached
    graph = proggraph.get_graph(project)
    regs: List[Tuple[str, str, str, int]] = []
    for f in project.lib():
        if f.tree is None or f.rel not in graph.modules:
            continue
        mod = graph.modules[f.rel]
        scopes = [f.tree] + astutil.collect_functions(f.tree)
        for scope in scopes:
            body = scope.body if isinstance(scope.body, list) else []
            calls, assigns, subs, updates = _collect_scope(body)

            def resolve_call(call, _mod=mod):
                func = call.func
                if isinstance(func, ast.Name):
                    sym = graph.resolve_symbol(_mod, func.id)
                elif isinstance(func, ast.Attribute):
                    sym = graph.resolve_attr(
                        _mod, astutil.dotted(func) or "")
                else:
                    sym = None
                return sym if isinstance(
                    sym, proggraph.FunctionInfo) else None

            for call in calls:
                leaf = (astutil.call_name(call) or "").split(".")[-1]
                if leaf not in _NAME_FNS and leaf not in _DICT_FNS:
                    continue
                if not call.args:
                    continue
                fam = _family(leaf)
                if leaf in _DICT_FNS:
                    pats = _dict_key_patterns(
                        call.args[0], graph, mod, assigns, subs,
                        updates, resolve_call)
                else:
                    pats = _patterns(call.args[0], graph, mod, assigns,
                                     frozenset()) or set()
                for p in pats:
                    norm = _normalize(p)
                    if norm is not None:
                        regs.append((norm, fam, f.rel, call.lineno))
    project._metric_inventory = regs
    return regs


# ---------------------------------------------------------------------------
# documentation-side inventories
# ---------------------------------------------------------------------------

_SPAN_RE = re.compile(r"`([^`]+)`")
_PATTERN_OK_RE = re.compile(r"^[A-Za-z0-9_.*:-]+$")


def _expand_braces(s: str) -> Set[str]:
    m = re.search(r"\{([^{}]*)\}", s)
    if m is None:
        return {s}
    out: Set[str] = set()
    for alt in m.group(1).split(","):
        out |= _expand_braces(s[:m.start()] + alt.strip() + s[m.end():])
    return out


def _span_pieces(span: str, prev: Optional[str]) -> List[str]:
    """Resolve the tables' ``/``-continuation shorthand:
    `` `fleet.scrapes` / `.scrape_errors` `` and in-span
    ``coverage_p01/p10`` both complete against the previous name."""
    out: List[str] = []
    for piece in span.split("/"):
        piece = piece.strip()
        if not piece:
            continue
        if piece.startswith(".") and prev and "." in prev:
            piece = prev.rsplit(".", 1)[0] + piece
        elif piece.startswith("_") and prev and "_" in prev:
            piece = prev.rsplit("_", 1)[0] + piece
        elif out and prev:
            # bare alternative ("p10"): swap the previous name's last
            # _-or-.-separated component
            cut = max(prev.rfind("_"), prev.rfind("."))
            if cut >= 0:
                piece = prev[:cut + 1] + piece
        out.append(piece)
        prev = piece
    return out


def doc_patterns(text: str) -> Set[str]:
    """Metric patterns documented in the markdown inventory tables."""
    pats: Set[str] = set()
    prev: Optional[str] = None
    for line in text.splitlines():
        if not line.lstrip().startswith("|"):
            continue
        prev = None
        for m in _SPAN_RE.finditer(line):
            for piece in _span_pieces(m.group(1), prev):
                prev = piece
                for raw in _expand_braces(piece):
                    p = re.sub(r"<[^<>]*>", "*", raw)
                    if "." not in p or not _PATTERN_OK_RE.match(p):
                        continue
                    norm = _normalize(p)
                    if norm is not None:
                        pats.add(norm)
    return pats


def _snapshot_floors(text: str) -> List[Tuple[str, int]]:
    try:
        tree = ast.parse(text)
    except SyntaxError:
        return []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "SNAPSHOT_FLOORS"
                and isinstance(node.value, ast.Dict)):
            return [(k.value, k.lineno) for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)]
    return []


def _help_prefixes(tree) -> List[Tuple[str, int]]:
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "_HELP_PREFIXES"
                and isinstance(node.value, (ast.Tuple, ast.List))):
            out = []
            for el in node.value.elts:
                if (isinstance(el, (ast.Tuple, ast.List)) and el.elts
                        and isinstance(el.elts[0], ast.Constant)
                        and isinstance(el.elts[0].value, str)):
                    out.append((el.elts[0].value, el.lineno))
            return out
    return []


# ---------------------------------------------------------------------------
# the rule
# ---------------------------------------------------------------------------


@rule("R9", "metric-inventory", scope="program")
def check_metric_inventory(project: Project) -> Iterable[Finding]:
    """Registered metric names, the ARCHITECTURE.md inventory tables,
    ``SNAPSHOT_FLOORS``, and the exporter HELP table must agree."""
    regs = registered_metrics(project)
    out: List[Finding] = []

    arch = project.aux.get("ARCHITECTURE.md")
    if arch is not None and regs:
        docs = doc_patterns(arch)
        seen: Set[Tuple[str, str]] = set()
        for pattern, fam, rel, line in regs:
            if (pattern, fam) in seen:
                continue
            seen.add((pattern, fam))
            if not any(_compat(pattern, d) for d in docs):
                out.append(Finding(
                    "R9", rel, line,
                    f"{fam} '{pattern}' is registered here but matches "
                    "nothing in the ARCHITECTURE.md metric inventory "
                    "tables — document it or retire it"))

    bench = project.aux.get("ci/bench_compare.py")
    if bench is not None and regs:
        counters = {p for p, fam, _r, _l in regs if fam == "counter"}
        for key, line in _snapshot_floors(bench):
            if not any(_compat(key, p) for p in counters):
                out.append(Finding(
                    "R9", "ci/bench_compare.py", line,
                    f"SNAPSHOT_FLOORS names '{key}' but no code path "
                    "registers that counter — the floor can never be "
                    "exercised"))

    exporter = project.by_rel.get("raft_tpu/serving/exporter.py")
    if exporter is not None and exporter.tree is not None and regs:
        everything = {p for p, _f, _r, _l in regs}
        for prefix, line in _help_prefixes(exporter.tree):
            if not any(_compat(prefix + "*", p) for p in everything):
                out.append(Finding(
                    "R9", exporter.rel, line,
                    f"_HELP_PREFIXES entry '{prefix}' matches no "
                    "registered metric family — dead HELP text"))
    return out
