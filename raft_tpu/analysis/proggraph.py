"""Whole-program graph for graftlint v3 — the cross-module core that
R8 (lock discipline), R2v2 (interprocedural donation escape), and R9
(metric-inventory conformance) share.

One parse pass over the project resolves:

- a **repo-wide symbol table**: every module's top-level functions,
  classes, and string constants, plus its import aliases resolved to
  intra-repo modules/symbols;
- a **class field inventory**: every ``self.<field> = ...`` assignment,
  with the ``# guarded-by: <lock>`` annotation (R8's contract), the
  lock fields themselves (``threading.Lock/RLock/Condition``), and a
  one-level type guess (``self._q = AdmissionQueue(...)`` binds the
  field to that class) powering attribute-aware call resolution;
- an **intra-repo call graph**: self-calls, module-local calls,
  imported-symbol calls, ``module.func`` calls through import aliases,
  and ``self.<typed-field>.method()`` calls through the field
  inventory. Unresolvable calls stay unresolved — the analyses built
  on top are *sound about what they claim* precisely because the graph
  never guesses by bare method name.

The graph is built lazily once per :class:`~.core.Project` and cached
on it, mirroring how ``astutil`` serves the per-file rules.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from typing import Dict, List, Optional, Tuple

GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")

_LOCK_CTORS = {"Lock", "RLock", "Condition"}


def _is_lock_ctor(expr: ast.AST) -> bool:
    """``threading.Lock()`` / ``threading.RLock()`` /
    ``threading.Condition(...)`` (any import spelling), or the
    dataclass spelling ``dataclasses.field(default_factory=
    threading.Lock)``."""
    if not isinstance(expr, ast.Call):
        return False
    name = _dotted(expr.func) or ""
    if name.split(".")[-1] in _LOCK_CTORS:
        return True
    if name.split(".")[-1] == "field":
        for kw in expr.keywords:
            if kw.arg == "default_factory" and (
                    _dotted(kw.value) or "").split(".")[-1] \
                    in _LOCK_CTORS:
                return True
    return False


def _dotted(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


@dataclasses.dataclass
class FieldInfo:
    """One instance field of a class, from its ``self.X = ...`` sites."""

    name: str
    lineno: int                      # first assignment
    guarded_by: Optional[str] = None  # lock name from `# guarded-by:`
    is_lock: bool = False            # assigned a threading lock ctor
    class_name: Optional[str] = None  # `self.x = ClassName(...)` guess
    value: Optional[ast.AST] = None  # first assigned expression


@dataclasses.dataclass
class FunctionInfo:
    """A function or method, addressable repo-wide."""

    qualname: str                    # "<rel>::Class.method" / "<rel>::func"
    rel: str
    name: str
    node: ast.AST                    # FunctionDef / AsyncFunctionDef
    cls: Optional["ClassInfo"] = None

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"<fn {self.qualname}>"


@dataclasses.dataclass
class ClassInfo:
    name: str
    rel: str
    node: ast.ClassDef
    fields: Dict[str, FieldInfo] = dataclasses.field(default_factory=dict)
    methods: Dict[str, FunctionInfo] = dataclasses.field(
        default_factory=dict)
    bases: List[str] = dataclasses.field(default_factory=list)

    @property
    def qualname(self) -> str:
        return f"{self.rel}::{self.name}"


@dataclasses.dataclass
class ModuleInfo:
    rel: str
    tree: ast.AST
    classes: Dict[str, ClassInfo] = dataclasses.field(default_factory=dict)
    functions: Dict[str, FunctionInfo] = dataclasses.field(
        default_factory=dict)
    constants: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: module-level names → (guarded_by, is_lock, lineno)
    globals: Dict[str, FieldInfo] = dataclasses.field(default_factory=dict)
    #: import alias → ("module", rel) or ("symbol", rel, name)
    imports: Dict[str, tuple] = dataclasses.field(default_factory=dict)
    #: comment line → guarded-by lock name
    guard_comments: Dict[int, str] = dataclasses.field(default_factory=dict)


def _module_rel(dotted_mod: str) -> str:
    """``raft_tpu.serving.admission`` → repo-relative path candidates
    (module file or package __init__)."""
    return dotted_mod.replace(".", "/")


def _guard_comments(text: str) -> Dict[int, str]:
    out: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type != tokenize.COMMENT:
                continue
            m = GUARDED_BY_RE.search(tok.string)
            if m:
                out[tok.start[0]] = m.group(1)
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass
    return out


def _stmt_guard(stmt: ast.stmt, comments: Dict[int, str]) -> Optional[str]:
    """The guarded-by annotation covering ``stmt``: a trailing comment
    on any line the statement spans."""
    end = getattr(stmt, "end_lineno", stmt.lineno)
    for ln in range(stmt.lineno, end + 1):
        if ln in comments:
            return comments[ln]
    return None


class ProgramGraph:
    """The resolved repo: modules, classes, fields, and the call graph."""

    def __init__(self, project):
        self.project = project
        self.modules: Dict[str, ModuleInfo] = {}
        #: qualname → FunctionInfo
        self.functions: Dict[str, FunctionInfo] = {}
        #: qualname → list of (callee FunctionInfo, call node)
        self._callees: Dict[str, List[Tuple[FunctionInfo, ast.Call]]] = {}
        self._callers: Dict[str, List[Tuple[FunctionInfo, ast.Call]]] = {}
        for f in project.files:
            if f.kind != "raft_tpu" or f.tree is None:
                continue
            self.modules[f.rel] = self._index_module(f)
        self._link_imports()
        for mod in self.modules.values():
            self._build_edges(mod)

    # -- indexing -----------------------------------------------------------

    def _index_module(self, f) -> ModuleInfo:
        mod = ModuleInfo(rel=f.rel, tree=f.tree,
                         guard_comments=_guard_comments(f.text))
        for stmt in f.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info = FunctionInfo(
                    qualname=f"{f.rel}::{stmt.name}", rel=f.rel,
                    name=stmt.name, node=stmt)
                mod.functions[stmt.name] = info
                self.functions[info.qualname] = info
            elif isinstance(stmt, ast.ClassDef):
                mod.classes[stmt.name] = self._index_class(f, mod, stmt)
            elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                name = stmt.targets[0].id
                if isinstance(stmt.value, ast.Constant) and isinstance(
                        stmt.value.value, str):
                    mod.constants[name] = stmt.value.value
                mod.globals[name] = FieldInfo(
                    name=name, lineno=stmt.lineno,
                    guarded_by=_stmt_guard(stmt, mod.guard_comments),
                    is_lock=_is_lock_ctor(stmt.value), value=stmt.value)
            elif isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name):
                mod.globals[stmt.target.id] = FieldInfo(
                    name=stmt.target.id, lineno=stmt.lineno,
                    guarded_by=_stmt_guard(stmt, mod.guard_comments),
                    is_lock=_is_lock_ctor(stmt.value)
                    if stmt.value is not None else False, value=stmt.value)
            elif isinstance(stmt, (ast.Import, ast.ImportFrom)):
                self._index_import(mod, stmt)
        return mod

    def _index_import(self, mod: ModuleInfo, stmt) -> None:
        if isinstance(stmt, ast.Import):
            for a in stmt.names:
                if not a.name.startswith("raft_tpu"):
                    continue
                alias = a.asname or a.name.split(".")[0]
                if a.asname is None and "." in a.name:
                    # `import raft_tpu.core.tracing` binds `raft_tpu`;
                    # attribute chains resolve through the full path
                    mod.imports[a.name] = ("module", _module_rel(a.name))
                else:
                    mod.imports[alias] = ("module", _module_rel(a.name))
        else:
            base = stmt.module or ""
            if stmt.level:
                # relative import: anchor at this module's package
                pkg = mod.rel.rsplit("/", stmt.level)[0]
                base = pkg.replace("/", ".") + ("." + base if base else "")
            if not base.startswith("raft_tpu"):
                return
            for a in stmt.names:
                if a.name == "*":
                    continue
                alias = a.asname or a.name
                sub = _module_rel(f"{base}.{a.name}")
                mod.imports[alias] = ("maybe", _module_rel(base), a.name,
                                      sub)

    def _index_class(self, f, mod: ModuleInfo,
                     node: ast.ClassDef) -> ClassInfo:
        cls = ClassInfo(name=node.name, rel=f.rel, node=node,
                        bases=[_dotted(b) or "" for b in node.bases])
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info = FunctionInfo(
                    qualname=f"{f.rel}::{node.name}.{stmt.name}",
                    rel=f.rel, name=stmt.name, node=stmt, cls=cls)
                cls.methods[stmt.name] = info
                self.functions[info.qualname] = info
            elif isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                # dataclass-style class-body fields
                targets = stmt.targets if isinstance(stmt, ast.Assign) \
                    else [stmt.target]
                value = getattr(stmt, "value", None)
                for t in targets:
                    if isinstance(t, ast.Name) \
                            and t.id not in cls.fields:
                        cls.fields[t.id] = FieldInfo(
                            name=t.id, lineno=stmt.lineno,
                            guarded_by=_stmt_guard(
                                stmt, mod.guard_comments),
                            is_lock=_is_lock_ctor(value)
                            if value is not None else False,
                            value=value)
        # field inventory: every `self.X = ...` in any method (the
        # first assignment wins for type/lock info; a guarded-by
        # annotation anywhere sticks)
        for m in cls.methods.values():
            for stmt in ast.walk(m.node):
                if isinstance(stmt, ast.Assign):
                    targets = stmt.targets
                elif isinstance(stmt, ast.AnnAssign):
                    targets = [stmt.target]
                else:
                    continue
                value = getattr(stmt, "value", None)
                for t in targets:
                    if not (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        continue
                    fi = cls.fields.get(t.attr)
                    if fi is None:
                        fi = FieldInfo(name=t.attr, lineno=stmt.lineno)
                        cls.fields[t.attr] = fi
                        if value is not None:
                            fi.value = value
                            fi.is_lock = _is_lock_ctor(value)
                            if isinstance(value, ast.Call):
                                cn = _dotted(value.func) or ""
                                fi.class_name = cn.split(".")[-1] or None
                    guard = _stmt_guard(stmt, mod.guard_comments)
                    if guard and fi.guarded_by is None:
                        fi.guarded_by = guard
        return cls

    def _link_imports(self) -> None:
        """Second pass: 'maybe' imports become module or symbol refs
        now that every module is indexed."""
        for mod in self.modules.values():
            for alias, ref in list(mod.imports.items()):
                if ref[0] != "maybe":
                    continue
                _, base_rel, name, sub_rel = ref
                if self._lookup_module(sub_rel) is not None:
                    mod.imports[alias] = ("module", sub_rel)
                else:
                    mod.imports[alias] = ("symbol", base_rel, name)

    def _lookup_module(self, rel_noext: str) -> Optional[ModuleInfo]:
        for cand in (rel_noext + ".py", rel_noext + "/__init__.py"):
            if cand in self.modules:
                return self.modules[cand]
        return None

    # -- resolution ---------------------------------------------------------

    def resolve_symbol(self, mod: ModuleInfo, name: str):
        """A bare name in ``mod`` → FunctionInfo / ClassInfo /
        ModuleInfo / str-constant, following one import hop."""
        if name in mod.functions:
            return mod.functions[name]
        if name in mod.classes:
            return mod.classes[name]
        if name in mod.constants:
            return mod.constants[name]
        ref = mod.imports.get(name)
        if ref is None:
            return None
        if ref[0] == "module":
            return self._lookup_module(ref[1])
        target = self._lookup_module(ref[1])
        if target is None:
            return None
        tname = ref[2]
        if tname in target.functions:
            return target.functions[tname]
        if tname in target.classes:
            return target.classes[tname]
        if tname in target.constants:
            return target.constants[tname]
        return None

    def resolve_attr(self, mod: ModuleInfo, dotted_name: str):
        """``alias.attr[.attr2]`` through a module import."""
        parts = dotted_name.split(".")
        # longest import-alias prefix wins (handles `import a.b.c`)
        for cut in range(len(parts) - 1, 0, -1):
            prefix = ".".join(parts[:cut])
            ref = mod.imports.get(prefix)
            if ref is None or ref[0] != "module":
                continue
            target = self._lookup_module(ref[1])
            rest = parts[cut:]
            while target is not None and len(rest) > 1:
                nxt = target.imports.get(rest[0])
                if nxt is not None and nxt[0] == "module":
                    target = self._lookup_module(nxt[1])
                    rest = rest[1:]
                else:
                    break
            if target is None or len(rest) != 1:
                return None
            leaf = rest[0]
            if leaf in target.functions:
                return target.functions[leaf]
            if leaf in target.classes:
                return target.classes[leaf]
            if leaf in target.constants:
                return target.constants[leaf]
            return None
        return None

    def class_of_name(self, mod: ModuleInfo, name: str
                      ) -> Optional[ClassInfo]:
        sym = self.resolve_symbol(mod, name)
        return sym if isinstance(sym, ClassInfo) else None

    def resolve_call(self, fn: FunctionInfo,
                     call: ast.Call) -> Optional[FunctionInfo]:
        """Best-effort resolution of a call inside ``fn`` to an
        intra-repo function/method; None when unsure (never guesses by
        bare method name)."""
        mod = self.modules.get(fn.rel)
        if mod is None:
            return None
        func = call.func
        if isinstance(func, ast.Name):
            sym = self.resolve_symbol(mod, func.id)
            if isinstance(sym, FunctionInfo):
                return sym
            if isinstance(sym, ClassInfo):
                return sym.methods.get("__init__")
            return None
        if not isinstance(func, ast.Attribute):
            return None
        base = func.value
        # self.method(...)
        if isinstance(base, ast.Name) and base.id == "self" \
                and fn.cls is not None:
            m = fn.cls.methods.get(func.attr)
            if m is not None:
                return m
            return self._base_method(mod, fn.cls, func.attr)
        # self.field.method(...) via the typed field inventory
        if isinstance(base, ast.Attribute) and isinstance(
                base.value, ast.Name) and base.value.id == "self" \
                and fn.cls is not None:
            fi = fn.cls.fields.get(base.attr)
            if fi is not None and fi.class_name:
                target = self.class_of_name(mod, fi.class_name)
                if target is not None:
                    return target.methods.get(func.attr)
            return None
        # module_alias.func(...) / pkg.mod.func(...)
        name = _dotted(func)
        if name is not None:
            resolved = self.resolve_attr(mod, name)
            if isinstance(resolved, FunctionInfo):
                return resolved
            if isinstance(resolved, ClassInfo):
                return resolved.methods.get("__init__")
        # local_var.method(...) where local_var = ClassName(...) in
        # this function body (single-assignment, attribute-aware)
        if isinstance(base, ast.Name):
            cls = self._local_instance_class(fn, mod, base.id)
            if cls is not None:
                return cls.methods.get(func.attr)
        return None

    def _base_method(self, mod: ModuleInfo, cls: ClassInfo,
                     name: str) -> Optional[FunctionInfo]:
        for b in cls.bases:
            if not b:
                continue
            sym = self.resolve_symbol(mod, b.split(".")[-1])
            if isinstance(sym, ClassInfo) and name in sym.methods:
                return sym.methods[name]
        return None

    def _local_instance_class(self, fn: FunctionInfo, mod: ModuleInfo,
                              var: str) -> Optional[ClassInfo]:
        """Single-assignment ``var = ClassName(...)`` in ``fn``'s body;
        None when the name is rebound or not a known-class ctor."""
        assigns = [stmt for stmt in ast.walk(fn.node)
                   if isinstance(stmt, ast.Assign)
                   and len(stmt.targets) == 1
                   and isinstance(stmt.targets[0], ast.Name)
                   and stmt.targets[0].id == var]
        if len(assigns) != 1 or not isinstance(assigns[0].value, ast.Call):
            return None
        cn = (_dotted(assigns[0].value.func) or "").split(".")[-1]
        return self.class_of_name(mod, cn) if cn else None

    # -- call graph ---------------------------------------------------------

    def _build_edges(self, mod: ModuleInfo) -> None:
        fns = list(mod.functions.values())
        for cls in mod.classes.values():
            fns.extend(cls.methods.values())
        for fn in fns:
            edges: List[Tuple[FunctionInfo, ast.Call]] = []
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                callee = self.resolve_call(fn, node)
                if callee is not None:
                    edges.append((callee, node))
                    self._callers.setdefault(callee.qualname, []).append(
                        (fn, node))
            self._callees[fn.qualname] = edges

    def callees(self, fn: FunctionInfo):
        return self._callees.get(fn.qualname, [])

    def callers(self, fn: FunctionInfo):
        return self._callers.get(fn.qualname, [])

    # -- constants (R9's one-level prefix resolution) -----------------------

    def string_constant(self, mod: ModuleInfo,
                        expr: ast.AST) -> Optional[str]:
        """Resolve ``expr`` to a string constant one level deep:
        literals, module constants, and ``alias.CONST`` imports."""
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return expr.value
        if isinstance(expr, ast.Name):
            sym = self.resolve_symbol(mod, expr.id)
            return sym if isinstance(sym, str) else None
        if isinstance(expr, ast.Attribute):
            name = _dotted(expr)
            if name is not None:
                sym = self.resolve_attr(mod, name)
                return sym if isinstance(sym, str) else None
        return None


def get_graph(project) -> ProgramGraph:
    """The project's (lazily built, cached) program graph — one parse
    pass shared by every whole-program rule."""
    graph = getattr(project, "_proggraph", None)
    if graph is None:
        graph = ProgramGraph(project)
        project._proggraph = graph
    return graph
