"""CLI: ``python -m raft_tpu.analysis`` — lint the repo, exit non-zero
on any unsuppressed finding."""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

from raft_tpu.analysis import LintCache, Project, ruleset_version, run
from raft_tpu.analysis.report import (
    render_ci,
    render_rules,
    render_suppressions,
    render_text,
)


def _default_root() -> pathlib.Path:
    """The repo root: cwd when it holds the package, else the source
    checkout this installed package lives in."""
    cwd = pathlib.Path.cwd()
    if (cwd / "raft_tpu").is_dir():
        return cwd
    return pathlib.Path(__file__).resolve().parents[2]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m raft_tpu.analysis",
        description="graftlint — serving-path invariants as lint rules")
    ap.add_argument("--root", default=None,
                    help="repo root (default: auto-detect)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids (default: all)")
    ap.add_argument("--format", dest="fmt", default="text",
                    choices=("text", "json", "ci"))
    ap.add_argument("--output", default=None,
                    help="also write the JSON report to this path")
    ap.add_argument("--no-cache", action="store_true",
                    help="bypass the incremental content-hash cache "
                         "(ci/.graftlint_cache.json)")
    ap.add_argument("--lockgraph", default=None, metavar="PATH",
                    help="also dump the R8 static lock-acquisition "
                         "graph (locks, edges, cycles) as JSON")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--list-suppressions", action="store_true",
                    help="print the suppression inventory instead of "
                         "the findings (JSON with --format=json — the "
                         "same [path, rule, reason] rows the report "
                         "and the snapshot test read)")
    args = ap.parse_args(argv)

    if args.list_rules:
        sys.stdout.write(render_rules())
        return 0

    root = pathlib.Path(args.root) if args.root else _default_root()
    rules = args.rules.split(",") if args.rules else None
    project = Project.from_root(root)
    cache = None
    if not args.no_cache:
        cache = LintCache(root / "ci" / ".graftlint_cache.json",
                          ruleset_version())
    try:
        report = run(project, rules=rules, cache=cache)
    except ValueError as e:
        sys.stderr.write(f"graftlint: {e}\n")
        return 2

    if args.lockgraph:
        from raft_tpu.analysis.rules_locks import build_lock_graph

        graph = build_lock_graph(project)
        pathlib.Path(args.lockgraph).write_text(
            json.dumps(graph.to_dict(), indent=2) + "\n")
    if args.output:
        pathlib.Path(args.output).write_text(report.to_json())
    if args.list_suppressions:
        if args.fmt == "json":
            sys.stdout.write(json.dumps(
                report.suppression_inventory(), indent=2) + "\n")
        else:
            sys.stdout.write(render_suppressions(report))
        return 0
    if args.fmt == "json":
        sys.stdout.write(report.to_json())
    elif args.fmt == "ci":
        sys.stdout.write(render_ci(report))
    else:
        sys.stdout.write(render_text(report))
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
