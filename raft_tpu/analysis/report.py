"""Report rendering for graftlint — text for humans, JSON for build
artifacts, and a CI mode that prints both the findings and the full
suppression inventory (so every ``disable=`` shows up in the build log
next to its reason)."""

from __future__ import annotations

from raft_tpu.analysis.core import RULES, Report


def render_text(report: Report, verbose: bool = False) -> str:
    lines = []
    for f in report.findings:
        lines.append(f.render())
    if verbose and report.suppressed:
        lines.append("")
        lines.append(f"suppressed ({len(report.suppressed)}):")
        for f, reason in report.suppressed:
            lines.append(f"  {f.render()}  [suppressed: {reason}]")
    status = "OK" if report.ok else "FAIL"
    lines.append(
        f"graftlint: {status} — {len(report.findings)} finding(s), "
        f"{len(report.suppressed)} suppressed, "
        f"{report.n_files} files, rules {','.join(report.rules_run)}")
    return "\n".join(lines) + "\n"


def render_suppressions(report: Report) -> str:
    """The suppression inventory — one line per pragma, with reason."""
    if not report.suppressions:
        return "graftlint: no suppressions\n"
    lines = [f"graftlint: {len(report.suppressions)} suppression(s):"]
    for s in sorted(report.suppressions,
                    key=lambda s: (s.path, s.pragma_line)):
        flag = "" if s.used else "  [UNUSED]"
        lines.append(
            f"  {s.path}:{s.pragma_line}: {s.rule} — {s.reason}{flag}")
    return "\n".join(lines) + "\n"


def render_ci(report: Report) -> str:
    return render_text(report, verbose=True) + render_suppressions(report)


def render_rules() -> str:
    lines = ["graftlint rules:"]
    for rid in sorted(RULES):
        r = RULES[rid]
        lines.append(f"  {rid} {r.name}: {r.doc}")
    return "\n".join(lines) + "\n"
