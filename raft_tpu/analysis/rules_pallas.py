"""R4 Pallas-budget and R6 interpret-coverage — the kernel-discipline
rules. Both walk every ``pallas_call`` in the tree, so ops guarding and
linting share one traversal.

R4 enforces what ``resolve_scan_engine`` assumes when it promises a
kernel will compile:

- every ``pallas_call`` must set ``compiler_params`` via the
  ``_COMPILER_PARAMS`` compat alias (the pltpu.CompilerParams ↔
  TPUCompilerParams rename shim) with an explicit
  ``vmem_limit_bytes`` — an unbounded kernel is sized by Mosaic's
  default and dies on the first big shape;
- when every BlockSpec / scratch shape folds to constants, the summed
  VMEM footprint (double-buffered blocks + scratch) must fit the
  declared limit and the 128 MB physical ceiling — dynamically-sized
  kernels are expected to self-limit the way ``ivf_scan`` does, and
  are covered by the explicit-limit check instead;
- a grid dimension computed as ``a // b`` must point at a round-up
  binding (``-(-x // b) * b`` or ``pl.cdiv``) — a plain floor division
  silently drops the ragged tail of the last block.

R6 is the old ``tests/test_ops_guard.py`` walk behind the registry:
every kernel module under ``raft_tpu/ops/`` must expose a public entry
with an ``interpret`` parameter, and some test must call each entry
with ``interpret=True`` — CPU CI must always cover kernel numerics
even though Mosaic only compiles on real TPUs.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from raft_tpu.analysis import astutil
from raft_tpu.analysis.core import Finding, Project, rule

VMEM_PHYSICAL_BYTES = 128 << 20  # v4+ physical VMEM per core

_DTYPE_BYTES = {
    "float32": 4, "f32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "bf16": 2, "float16": 2, "int16": 2,
    "int8": 1, "uint8": 1, "bool_": 1, "bool": 1,
    "float64": 8, "int64": 8,
}


def _dtype_bytes(expr: Optional[ast.AST]) -> int:
    leaf = (astutil.dotted(expr) or "").split(".")[-1] if expr else ""
    return _DTYPE_BYTES.get(leaf, 4)


def _enclosing_function(tree: ast.AST, call: ast.Call):
    best = None
    for fn in astutil.collect_functions(tree):
        if fn.lineno <= call.lineno and (
                best is None or fn.lineno > best.lineno):
            # containment by line span (ast gives end_lineno on 3.8+)
            if getattr(fn, "end_lineno", 1 << 30) >= call.lineno:
                best = fn
    return best


def _is_roundup_of(binding: ast.AST, divisor: ast.AST,
                   env: Optional[astutil.Env] = None,
                   depth: int = 1) -> bool:
    """Match the repo's pad idioms against the grid divisor ``b``:
    ``-(-x // b) * b``, ``x + (-x) % b`` (via a pad variable), or
    ``pl.cdiv(x, b)``. Resolves names one level through ``env`` so a
    ``pad_q = (-q) % B; qp = q + pad_q`` chain is recognized."""
    want = ast.dump(divisor)

    def same(node):
        return ast.dump(node) == want

    for n in ast.walk(binding):
        if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Mult):
            for inner, mul in ((n.left, n.right), (n.right, n.left)):
                if not same(mul):
                    continue
                for m in ast.walk(inner):
                    if isinstance(m, ast.BinOp) \
                            and isinstance(m.op, ast.FloorDiv) \
                            and same(m.right):
                        return True
        if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Mod) \
                and same(n.right):
            return True
        if isinstance(n, ast.Call):
            nm = (astutil.call_name(n) or "").split(".")[-1]
            if nm == "cdiv" and len(n.args) == 2 and same(n.args[1]):
                return True
        if isinstance(n, ast.Name) and env is not None and depth > 0 \
                and n.id not in env.multi:
            sub = env.bindings.get(n.id)
            if sub is not None and sub is not binding \
                    and _is_roundup_of(sub, divisor, env, depth - 1):
                return True
    return False


def _pallas_calls(tree: ast.AST) -> List[ast.Call]:
    return [n for n in ast.walk(tree)
            if isinstance(n, ast.Call)
            and (astutil.call_name(n) or "").split(".")[-1]
            == "pallas_call"]


def _kw(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _collect_specs(call: ast.Call, fn) -> Tuple[List[ast.Call],
                                                List[ast.Call]]:
    """(BlockSpec calls, VMEM scratch calls) reachable from this
    pallas_call — through grid_spec=/in_specs=/out_specs= kwargs,
    following one level of local-name indirection."""
    roots: List[ast.AST] = []
    for name in ("grid_spec", "in_specs", "out_specs", "scratch_shapes"):
        v = _kw(call, name)
        if v is not None:
            roots.append(v)
    env_bindings = {}
    if fn is not None:
        for stmt in astutil.walk_in_order(fn.body):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                env_bindings[stmt.targets[0].id] = stmt.value
    resolved: List[ast.AST] = []
    for r in roots:
        if isinstance(r, ast.Name) and r.id in env_bindings:
            resolved.append(env_bindings[r.id])
        else:
            resolved.append(r)
    blockspecs, scratch = [], []
    for r in resolved:
        for n in ast.walk(r):
            if isinstance(n, ast.Call):
                leaf = (astutil.call_name(n) or "").split(".")[-1]
                if leaf == "BlockSpec":
                    blockspecs.append(n)
                elif leaf in ("VMEM", "SMEM"):
                    scratch.append(n)
    return blockspecs, scratch


@rule("R4", "pallas-budget")
def check_pallas_budget(project: Project) -> Iterable[Finding]:
    """pallas_call compiler-params discipline, static VMEM footprint
    vs the declared limit, and grid round-up evidence."""
    out: List[Finding] = []
    for f in project.lib():
        if f.tree is None:
            continue
        for call in _pallas_calls(f.tree):
            fn = _enclosing_function(f.tree, call)
            env = astutil.Env(fn) if fn is not None else None

            cp = _kw(call, "compiler_params")
            vmem_limit = None
            if cp is None:
                out.append(Finding(
                    "R4", f.rel, call.lineno,
                    "pallas_call without compiler_params — pass "
                    "_COMPILER_PARAMS(vmem_limit_bytes=...) so the "
                    "kernel states its VMEM budget"))
            else:
                cp_name = (astutil.call_name(cp) or "") if isinstance(
                    cp, ast.Call) else ""
                leaf = cp_name.split(".")[-1]
                if leaf in ("CompilerParams", "TPUCompilerParams"):
                    out.append(Finding(
                        "R4", f.rel, cp.lineno,
                        f"direct pltpu.{leaf} — use the "
                        "_COMPILER_PARAMS compat alias (the jax 0.5 "
                        "rename shim in ops.fused_topk)"))
                elif leaf != "_COMPILER_PARAMS":
                    out.append(Finding(
                        "R4", f.rel, cp.lineno,
                        "compiler_params is not built via the "
                        "_COMPILER_PARAMS compat alias"))
                if isinstance(cp, ast.Call):
                    vl = _kw(cp, "vmem_limit_bytes")
                    if vl is None:
                        out.append(Finding(
                            "R4", f.rel, cp.lineno,
                            "compiler_params without vmem_limit_bytes "
                            "— declare the budget resolve_scan_engine "
                            "checks against"))
                    else:
                        vmem_limit = astutil.const_fold(vl, env)

            # static VMEM estimate — exact when every shape folds;
            # when a dim doesn't const-fold, fall back to a symbolic
            # upper bound (min(n, CAP) is bounded by CAP even when n
            # is runtime) so bounded-dynamic kernels stay inside the
            # rule's reach instead of silently escaping it
            blockspecs, scratch = _collect_specs(call, fn)
            total = 0
            all_static = bool(blockspecs or scratch)
            bounded = False
            for bs in blockspecs:
                shape = bs.args[0] if bs.args else _kw(bs, "block_shape")
                dims = astutil.fold_shape(shape, env) if shape is not None \
                    else None
                if dims is None and shape is not None:
                    dims = astutil.shape_upper_bound(shape, env)
                    if dims is not None:
                        bounded = True
                if dims is None:
                    all_static = False
                    break
                n = 1
                for d in dims:
                    n *= max(int(d), 1)
                total += 2 * n * 4  # double-buffered, f32-conservative
            if all_static:
                for sc in scratch:
                    shape = sc.args[0] if sc.args else None
                    dims = astutil.fold_shape(shape, env)
                    if dims is None and shape is not None:
                        dims = astutil.shape_upper_bound(shape, env)
                        if dims is not None:
                            bounded = True
                    if dims is None:
                        all_static = False
                        break
                    n = 1
                    for d in dims:
                        n *= max(int(d), 1)
                    total += n * _dtype_bytes(
                        sc.args[1] if len(sc.args) > 1 else None)
            if all_static:
                budget = min(vmem_limit or VMEM_PHYSICAL_BYTES,
                             VMEM_PHYSICAL_BYTES)
                if total > budget:
                    kind = ("VMEM upper bound" if bounded
                            else "static VMEM footprint")
                    out.append(Finding(
                        "R4", f.rel, call.lineno,
                        f"{kind} ~{total >> 20} MiB "
                        "(double-buffered blocks + scratch) exceeds "
                        f"the {int(budget) >> 20} MiB budget — shrink "
                        "the BlockSpecs or raise vmem_limit_bytes"))

            # grid round-up evidence
            grid = _kw(call, "grid")
            if grid is None:
                gs = _kw(call, "grid_spec")
                if isinstance(gs, ast.Name) and env is not None:
                    gs = env.bindings.get(gs.id)
                if isinstance(gs, ast.Call):
                    grid = _kw(gs, "grid")
            if isinstance(grid, (ast.Tuple, ast.List)) and env is not None:
                for el in grid.elts:
                    expr = el
                    if isinstance(expr, ast.Name) \
                            and expr.id not in env.multi:
                        expr = env.bindings.get(expr.id, expr)
                    if isinstance(expr, ast.BinOp) and isinstance(
                            expr.op, ast.FloorDiv) and isinstance(
                            expr.left, ast.Name):
                        binding = env.bindings.get(expr.left.id)
                        if expr.left.id in env.multi or binding is None:
                            continue
                        if not _is_roundup_of(binding, expr.right, env):
                            out.append(Finding(
                                "R4", f.rel, el.lineno,
                                f"grid dimension "
                                f"'{expr.left.id} // ...' but "
                                f"'{expr.left.id}' is not padded up to "
                                "the divisor — a ragged tail would be "
                                "silently dropped; pad with "
                                "-(-x // b) * b or pl.cdiv"))
    return out


# ---------------------------------------------------------------------------
# R6 — interpret-mode coverage (the ops guard, shared traversal)
# ---------------------------------------------------------------------------


def public_kernel_entries(project: Project) -> Dict[str, List[str]]:
    """Per ops module: public module-level functions exposing an
    ``interpret`` knob — the kernel-entry convention of the package."""
    out: Dict[str, List[str]] = {}
    for f in project.lib():
        if not f.rel.startswith("raft_tpu/ops/") or f.tree is None:
            continue
        if not _pallas_calls(f.tree):
            continue
        entries = []
        for node in f.tree.body:
            if not isinstance(node, ast.FunctionDef) \
                    or node.name.startswith("_"):
                continue
            names = {a.arg for a in node.args.args
                     + node.args.kwonlyargs}
            if "interpret" in names:
                entries.append(node.name)
        out[f.rel] = entries
    return out


def interpret_covered_names(project: Project) -> Set[str]:
    """Names some test calls with a literal ``interpret=True`` — a
    docstring mention cannot satisfy the guard, only a call site."""
    covered: Set[str] = set()
    for f in project.tests():
        if f.tree is None:
            continue
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            nm = (astutil.call_name(node) or "").split(".")[-1]
            if not nm:
                continue
            for kw in node.keywords:
                if kw.arg == "interpret" \
                        and isinstance(kw.value, ast.Constant) \
                        and kw.value.value is True:
                    covered.add(nm)
    return covered


@rule("R6", "interpret-coverage", scope="program")
def check_interpret_coverage(project: Project) -> Iterable[Finding]:
    """Every pallas_call module under raft_tpu/ops/ exposes public
    entries with an ``interpret`` knob, and every entry has an
    interpret=True call site in some test."""
    out: List[Finding] = []
    covered = interpret_covered_names(project)
    for rel, entries in sorted(public_kernel_entries(project).items()):
        if not entries:
            out.append(Finding(
                "R6", rel, 1,
                "module contains pallas_call but exposes no public "
                "entry with an `interpret` parameter — CPU CI cannot "
                "cover the kernel"))
            continue
        for name in entries:
            if name not in covered:
                out.append(Finding(
                    "R6", rel, 1,
                    f"kernel entry '{name}' has no interpret=True call "
                    "in any test — add an interpret-mode parity test"))
    return out
