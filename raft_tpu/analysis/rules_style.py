"""R0 — style & hygiene (the old ``ci/check_style.py`` folded behind
the shared registry): syntax, unused imports, whitespace discipline,
no ``print`` in library code, no ``NotImplementedError`` stubs.

Pragma hygiene (malformed / unused ``graftlint: disable`` comments) is
reported under R0 as well, by the runner in :mod:`.core`.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from raft_tpu.analysis.core import Finding, Project, rule

# printing is these components' job
PRINT_EXEMPT = ("bench", "examples", "scripts", "__main__")


class _ImportTracker(ast.NodeVisitor):
    """Collect imported names and every name read anywhere."""

    def __init__(self) -> None:
        self.imported = {}
        self.used = set()

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            name = (a.asname or a.name).split(".")[0]
            self.imported[name] = node.lineno

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "__future__":
            return
        for a in node.names:
            if a.name == "*":
                continue
            self.imported[a.asname or a.name] = node.lineno

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load):
            self.used.add(node.id)


@rule("R0", "style")
def check_style(project: Project) -> Iterable[Finding]:
    """Every file parses; no unused imports (``# noqa`` and re-export
    manifests exempt); no tabs / trailing whitespace / missing EOF
    newline; no ``print()`` in library code; no NotImplementedError
    stubs in ``raft_tpu/``."""
    out: List[Finding] = []

    def err(f, line, msg):
        out.append(Finding("R0", f.rel, line, msg))

    for f in project.files:
        if f.syntax_error is not None:
            err(f, f.syntax_error.lineno or 0,
                f"does not parse: {f.syntax_error.msg}")
            continue

        noqa = {i + 1 for i, ln in enumerate(f.lines) if "# noqa" in ln}
        for i, ln in enumerate(f.lines, 1):
            if "\t" in ln:
                err(f, i, "tab character")
            if ln != ln.rstrip():
                err(f, i, "trailing whitespace")
        if f.text and not f.text.endswith("\n"):
            err(f, len(f.lines), "no newline at end of file")

        base = f.rel.rsplit("/", 1)[-1]
        if base not in ("__init__.py", "conftest.py"):
            tracker = _ImportTracker()
            tracker.visit(f.tree)
            all_strings = {
                s.value for s in ast.walk(f.tree)
                if isinstance(s, ast.Constant) and isinstance(s.value, str)
            }
            for name, line in tracker.imported.items():
                if line in noqa or name.startswith("_"):
                    continue
                if name not in tracker.used and name not in all_strings:
                    err(f, line, f"unused import '{name}'")

        in_lib = f.kind == "raft_tpu"
        exempt = (base == "__main__.py"
                  or any(p in f.rel.split("/") for p in PRINT_EXEMPT))
        if not in_lib or exempt:
            continue
        for node in ast.walk(f.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"
                    and node.lineno not in noqa):
                err(f, node.lineno,
                    "print() in library code — use the logger")
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a function whose whole body is `raise NotImplementedError`
                # is a stub; a terminal raise after dispatch is fine
                body = [s for s in node.body
                        if not (isinstance(s, ast.Expr)
                                and isinstance(s.value, ast.Constant))]
                if len(body) == 1 and isinstance(body[0], ast.Raise):
                    exc = body[0].exc
                    name = (exc.func.id if isinstance(exc, ast.Call)
                            and isinstance(exc.func, ast.Name) else
                            exc.id if isinstance(exc, ast.Name) else None)
                    if name == "NotImplementedError":
                        err(f, node.lineno, "NotImplementedError stub")
    return out
