"""R3 — collective discipline.

Every mesh program in this repo goes through the
``raft_tpu.comms.comms`` veneer: it is where the jax 0.4.x/0.5.x/0.6+
compat shims live (``shard_map`` check_vma/check_rep, ``axis_size``,
``mark_varying``), where wire-dtype policy is applied, and where the
collective-payload accounting hooks. A raw ``jax.lax`` collective (or a
direct ``jax.experimental.shard_map`` import) outside the veneer
bypasses all three — it works on the jax version it was written
against and silently breaks on the next one.

Checks:

- raw ``jax.lax`` collectives (``psum``/``pmax``/``all_gather``/
  ``ppermute``/``pvary``/…) anywhere but the veneer module, including
  the ``getattr(jax.lax, "pvary")`` feature-probe spelling;
- direct ``jax.experimental.shard_map`` imports / ``jax.shard_map``
  references outside the veneer;
- axis-name literals passed to veneer collectives that name no axis
  this module's meshes declare (a typo'd axis fails at trace time,
  but only on a code path a multi-chip test actually reaches).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from raft_tpu.analysis import astutil
from raft_tpu.analysis.core import Finding, Project, rule

VENEER_REL = "raft_tpu/comms/comms.py"

LAX_COLLECTIVES = {
    "psum", "pmax", "pmin", "pmean", "all_gather", "psum_scatter",
    "all_to_all", "ppermute", "pshuffle", "pbroadcast", "pvary",
    "pcast", "axis_index", "axis_size", "all_gather_invariant",
}

# veneer function name -> positional index of its axis argument
# (timed_dispatch is the PR 7 host-side timing shim: its axis names
# the mesh axis being timed, so a typo'd literal is the same latent
# bug an axis typo in a collective is)
VENEER_AXIS_POS = {
    "allreduce": 2, "bcast": 2, "reduce": 3, "allgather": 1,
    "allgather_wire": 1, "allgatherv": 2, "reducescatter": 2,
    "alltoall": 1, "device_send": 2, "device_recv": 2,
    "device_sendrecv": 2, "barrier": 0, "rank": 0, "size": 0,
    "mark_varying": 1, "timed_dispatch": 2,
    # graftwire quantized veneers (same positional axis slot as their
    # exact twins)
    "allreduce_quantized": 2, "reducescatter_quantized": 2,
    "allgather_quantized": 1,
}


def _comms_imports(tree: ast.AST) -> Set[str]:
    """Local names this module imported from raft_tpu.comms*."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module \
                and node.module.startswith("raft_tpu.comms"):
            for a in node.names:
                names.add(a.asname or a.name)
    return names


def _known_axes(tree: ast.AST) -> Set[str]:
    """Axis names this module's meshes / specs / signatures declare."""
    axes: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            nm = (astutil.call_name(node) or "").split(".")[-1]
            if nm in ("Mesh", "AbstractMesh", "make_mesh"):
                for kw in node.keywords:
                    if kw.arg in ("axis_names", "axis"):
                        for c in ast.walk(kw.value):
                            if isinstance(c, ast.Constant) \
                                    and isinstance(c.value, str):
                                axes.add(c.value)
            if nm in ("P", "PartitionSpec"):
                for a in node.args:
                    if isinstance(a, ast.Constant) \
                            and isinstance(a.value, str):
                        axes.add(a.value)
            if nm == "Comms" and len(node.args) >= 2 \
                    and isinstance(node.args[1], ast.Constant) \
                    and isinstance(node.args[1].value, str):
                axes.add(node.args[1].value)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # `axis: str = "data"` parameter defaults declare vocabulary
            args = node.args
            pos = args.posonlyargs + args.args
            defaults = args.defaults
            for p, d in zip(pos[len(pos) - len(defaults):], defaults):
                if "axis" in p.arg and isinstance(d, ast.Constant) \
                        and isinstance(d.value, str):
                    axes.add(d.value)
            for p, d in zip(args.kwonlyargs, args.kw_defaults):
                if d is not None and "axis" in p.arg \
                        and isinstance(d, ast.Constant) \
                        and isinstance(d.value, str):
                    axes.add(d.value)
    return axes


def _axis_arg(call: ast.Call, leaf: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == "axis":
            return kw.value
    pos = VENEER_AXIS_POS[leaf]
    if pos < len(call.args):
        return call.args[pos]
    return None


@rule("R3", "collective-discipline")
def check_collectives(project: Project) -> Iterable[Finding]:
    """Raw jax.lax collectives / shard_map imports outside the comms
    veneer; axis-name literals that no mesh in the module declares."""
    out: List[Finding] = []
    for f in project.lib():
        if f.tree is None or f.rel == VENEER_REL:
            continue

        for node in ast.walk(f.tree):
            # raw lax collectives (and the getattr feature probe)
            if isinstance(node, ast.Attribute):
                nm = astutil.dotted(node)
                if nm and nm in {f"jax.lax.{c}" for c in LAX_COLLECTIVES} \
                        | {f"lax.{c}" for c in LAX_COLLECTIVES}:
                    out.append(Finding(
                        "R3", f.rel, node.lineno,
                        f"raw {nm} outside the comms veneer — route it "
                        "through raft_tpu.comms.comms so the version "
                        "shims and payload accounting apply"))
            if isinstance(node, ast.Call):
                nm = astutil.call_name(node) or ""
                if nm == "getattr" and len(node.args) >= 2 \
                        and astutil.dotted(node.args[0]) in ("jax.lax",
                                                             "lax") \
                        and isinstance(node.args[1], ast.Constant) \
                        and node.args[1].value in LAX_COLLECTIVES:
                    out.append(Finding(
                        "R3", f.rel, node.lineno,
                        f"getattr(jax.lax, {node.args[1].value!r}) "
                        "feature probe outside the comms veneer — the "
                        "compat shim for this collective belongs in "
                        "raft_tpu.comms.comms"))
            # direct shard_map access
            if isinstance(node, ast.ImportFrom) and node.module \
                    and "shard_map" in node.module:
                out.append(Finding(
                    "R3", f.rel, node.lineno,
                    "direct jax.experimental.shard_map import — use "
                    "raft_tpu.comms.comms.shard_map (check_vma/"
                    "check_rep compat)"))
            if isinstance(node, ast.Attribute) \
                    and astutil.dotted(node) == "jax.shard_map":
                out.append(Finding(
                    "R3", f.rel, node.lineno,
                    "direct jax.shard_map reference — use "
                    "raft_tpu.comms.comms.shard_map"))

        # axis literal discipline on veneer calls
        veneer_names = _comms_imports(f.tree) & set(VENEER_AXIS_POS)
        axes = _known_axes(f.tree)
        if not axes:
            continue
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            nm = astutil.call_name(node) or ""
            leaf = nm.split(".")[-1]
            if leaf not in VENEER_AXIS_POS:
                continue
            # only calls provably bound to the comms veneer
            if not (nm.startswith("comms.") or leaf in veneer_names):
                continue
            arg = _axis_arg(node, leaf)
            if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str) and arg.value not in axes:
                out.append(Finding(
                    "R3", f.rel, node.lineno,
                    f"collective {leaf}() names axis {arg.value!r} but "
                    f"this module's meshes declare {sorted(axes)} — a "
                    "typo'd axis only fails on the multi-chip path"))
    return out
