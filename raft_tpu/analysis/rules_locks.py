"""R8 — lock discipline: the serving stack's concurrency contracts as
a machine-checked annotation convention plus a static lock-order graph.

**Guarded state.** An instance field (or module global) whose mutations
the code protects with a lock carries a ``# guarded-by: <lockname>``
trailing comment on its initialising assignment::

    def __init__(self):
        self._lock = threading.Lock()
        self._groups = {}      # guarded-by: _lock
        self._n = 0            # guarded-by: _lock

Every later read or write of ``self._groups`` must then happen with
``self._lock`` held — lexically inside ``with self._lock:``, or inside
a private helper (leading underscore) whose *every* intra-class call
site holds the lock (resolved through the program graph's self-call
edges, so the ``_flush_locked()`` idiom conforms without annotations).
``__init__``/``__del__`` are exempt (construction/teardown
happen-before publication). Public methods never inherit a caller's
lock — they are the API surface, and the analyzer cannot see external
callers.

**Lock order.** Every ``with``-acquisition while another known lock is
held — including acquisitions transitively reachable through resolved
intra-repo calls — is an edge in a static lock-acquisition graph. A
cycle in that graph is a lint failure (a latent lock-order inversion),
and acquiring a non-reentrant ``threading.Lock`` while already holding
it is a self-deadlock finding. The graph dumps as a CI artifact
(``ci/graftlint_lockgraph.json``) via ``--lockgraph``.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Set, Tuple

from raft_tpu.analysis import proggraph
from raft_tpu.analysis.core import Finding, Project, rule

EXEMPT_METHODS = {"__init__", "__del__"}


@dataclasses.dataclass
class LockDef:
    """One known lock object (class field or module global)."""

    lock_id: str           # "<rel>::Class.name" / "<rel>::name"
    name: str              # attribute / global name
    kind: str              # Lock | RLock | Condition
    rel: str
    lineno: int


@dataclasses.dataclass
class _Access:
    name: str              # guarded field / global
    lineno: int
    store: bool
    held: frozenset


@dataclasses.dataclass
class _Scan:
    """Everything one function walk produced."""

    accesses: List[_Access] = dataclasses.field(default_factory=list)
    self_calls: List[Tuple[str, int, frozenset]] = dataclasses.field(
        default_factory=list)
    calls: List[Tuple[str, int, frozenset]] = dataclasses.field(
        default_factory=list)    # resolved callee qualname
    acquires: List[Tuple[str, int, frozenset]] = dataclasses.field(
        default_factory=list)    # lock_id, line, held-before
    self_refs: Set[str] = dataclasses.field(default_factory=set)
    local_calls: List[Tuple[str, int, frozenset]] = dataclasses.field(
        default_factory=list)    # bare-name module-local calls


def _lock_kind(value: ast.AST) -> Optional[str]:
    if not isinstance(value, ast.Call):
        return None
    name = (proggraph._dotted(value.func) or "").split(".")[-1]
    if name in ("Lock", "RLock", "Condition"):
        return name
    if name == "field":  # dataclasses.field(default_factory=…Lock)
        for kw in value.keywords:
            if kw.arg == "default_factory":
                fac = (proggraph._dotted(kw.value) or "").split(".")[-1]
                if fac in ("Lock", "RLock", "Condition"):
                    return fac
    return None


class _ClassCtx:
    """Lock/guard inventory for one class (or one module's globals)."""

    def __init__(self, graph, mod, cls: Optional[proggraph.ClassInfo]):
        self.graph = graph
        self.mod = mod
        self.cls = cls
        self.locks: Dict[str, LockDef] = {}     # local name → def
        self.guards: Dict[str, str] = {}        # field/global → lock name
        fields = cls.fields if cls is not None else mod.globals
        scope = f"{mod.rel}::{cls.name}." if cls is not None \
            else f"{mod.rel}::"
        for name, fi in fields.items():
            kind = _lock_kind(fi.value) if fi.value is not None else None
            if kind is not None:
                self.locks[name] = LockDef(
                    lock_id=scope + name, name=name, kind=kind,
                    rel=mod.rel, lineno=fi.lineno)
            if fi.guarded_by is not None:
                self.guards[name] = fi.guarded_by
        # module-level locks are acquirable from methods too
        if cls is not None:
            for name, fi in mod.globals.items():
                kind = _lock_kind(fi.value) if fi.value is not None \
                    else None
                if kind is not None and name not in self.locks:
                    self.locks[name] = LockDef(
                        lock_id=f"{mod.rel}::{name}", name=name,
                        kind=kind, rel=mod.rel, lineno=fi.lineno)

    def lock_for_withitem(self, expr: ast.AST) -> Optional[LockDef]:
        # with self._lock:
        if self.cls is not None and isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self":
            return self.locks.get(expr.attr)
        # with _MODULE_LOCK:
        if isinstance(expr, ast.Name):
            ld = self.locks.get(expr.id)
            if ld is not None and "." not in ld.lock_id.split("::")[1]:
                return ld
            # class ctx: module lock by bare name
            if self.cls is not None:
                return self.locks.get(expr.id)
        return None


def _scan_function(ctx: _ClassCtx, fn: proggraph.FunctionInfo) -> _Scan:
    """Walk ``fn`` tracking the lexically-held lock set."""
    scan = _Scan()
    graph = ctx.graph
    guarded = set(ctx.guards)
    is_method = ctx.cls is not None

    # names that shadow guarded globals inside this function
    shadowed: Set[str] = set()
    if not is_method:
        declared_global: Set[str] = set()
        for n in ast.walk(fn.node):
            if isinstance(n, (ast.Global, ast.Nonlocal)):
                declared_global.update(n.names)
        params = fn.node.args
        for a in (params.posonlyargs + params.args + params.kwonlyargs
                  + ([params.vararg] if params.vararg else [])
                  + ([params.kwarg] if params.kwarg else [])):
            shadowed.add(a.arg)
        for n in ast.walk(fn.node):
            if isinstance(n, ast.Name) and isinstance(
                    n.ctx, (ast.Store, ast.Del)) \
                    and n.id not in declared_global:
                shadowed.add(n.id)
        shadowed -= declared_global

    def visit_expr(expr: ast.AST, held: frozenset) -> None:
        # an Attribute that is the func of a Call is an invocation,
        # not a value reference — exclude it from self_refs
        call_funcs = {id(c.func) for c in ast.walk(expr)
                      if isinstance(c, ast.Call)}
        for n in ast.walk(expr):
            if isinstance(n, (ast.Lambda, ast.FunctionDef,
                              ast.AsyncFunctionDef)):
                continue
            if is_method and isinstance(n, ast.Attribute) \
                    and isinstance(n.value, ast.Name) \
                    and n.value.id == "self":
                if n.attr in guarded:
                    scan.accesses.append(_Access(
                        n.attr, n.lineno,
                        isinstance(n.ctx, (ast.Store, ast.Del)), held))
                elif n.attr in (ctx.cls.methods if ctx.cls else {}) \
                        and id(n) not in call_funcs:
                    scan.self_refs.add(n.attr)
            if not is_method and isinstance(n, ast.Name) \
                    and n.id in guarded and n.id not in shadowed:
                scan.accesses.append(_Access(
                    n.id, n.lineno,
                    isinstance(n.ctx, (ast.Store, ast.Del)), held))
            if isinstance(n, ast.Call):
                callee = graph.resolve_call(fn, n)
                if callee is not None:
                    scan.calls.append((callee.qualname, n.lineno, held))
                if is_method and isinstance(n.func, ast.Attribute) \
                        and isinstance(n.func.value, ast.Name) \
                        and n.func.value.id == "self":
                    scan.self_calls.append((n.func.attr, n.lineno, held))
                elif not is_method and isinstance(n.func, ast.Name):
                    scan.local_calls.append((n.func.id, n.lineno, held))

    def walk(body, held: frozenset) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, ast.With):
                inner = set(held)
                for item in stmt.items:
                    ld = ctx.lock_for_withitem(item.context_expr)
                    if ld is not None:
                        scan.acquires.append(
                            (ld.lock_id, stmt.lineno, frozenset(inner)))
                        inner.add(ld.lock_id)
                    else:
                        visit_expr(item.context_expr, frozenset(inner))
                walk(stmt.body, frozenset(inner))
                continue
            for field in ("test", "iter", "value", "exc", "msg"):
                sub = getattr(stmt, field, None)
                if isinstance(sub, ast.AST):
                    visit_expr(sub, held)
            if isinstance(stmt, ast.Expr):
                visit_expr(stmt.value, held)
            elif isinstance(stmt, (ast.Assign, ast.AugAssign,
                                   ast.AnnAssign)):
                targets = stmt.targets if isinstance(stmt, ast.Assign) \
                    else [stmt.target]
                for t in targets:
                    visit_expr(t, held)
            elif isinstance(stmt, (ast.Return, ast.Delete)):
                for sub in getattr(stmt, "targets", []):
                    visit_expr(sub, held)
            elif isinstance(stmt, ast.For):
                visit_expr(stmt.target, held)
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if isinstance(sub, list):
                    walk(sub, held)
            for h in getattr(stmt, "handlers", []) or []:
                walk(h.body, held)
    walk(fn.node.body, frozenset())
    return scan


def _entry_held(scans: Dict[str, _Scan], names: Iterable[str],
                all_locks: Set[str], private_ok) -> Dict[str, frozenset]:
    """Fixed point: the lock set guaranteed held at each function's
    entry — the intersection over every intra-scope call site's
    effective held set. Only private (underscore) helpers that are
    exclusively called (never referenced as values) qualify; everyone
    else is an entry point with nothing guaranteed."""
    names = list(names)
    entry = {n: frozenset(all_locks) if private_ok(n) else frozenset()
             for n in names}
    for _ in range(len(names) + 1):
        changed = False
        incoming: Dict[str, List[frozenset]] = {}
        for caller, scan in scans.items():
            base = entry.get(caller, frozenset())
            for callee, _line, held in scan.self_calls \
                    + scan.local_calls:
                if callee in entry:
                    incoming.setdefault(callee, []).append(held | base)
        for n in names:
            if not private_ok(n):
                continue
            sites = incoming.get(n)
            new = frozenset.intersection(*sites) if sites \
                else frozenset()
            if new != entry[n]:
                entry[n] = new
                changed = True
        if not changed:
            break
    return entry


def _analyze_scope(graph, mod, cls, out: List[Finding],
                   lock_graph: "LockGraph") -> None:
    ctx = _ClassCtx(graph, mod, cls)
    if cls is not None:
        members = cls.methods
    else:
        members = mod.functions
    if not ctx.guards and not ctx.locks:
        return
    lock_by_name = ctx.locks
    for ld in lock_by_name.values():
        lock_graph.add_lock(ld)

    # annotation hygiene: guarded-by must name a known lock in scope
    fields = cls.fields if cls is not None else mod.globals
    for fname, lname in ctx.guards.items():
        if lname not in lock_by_name:
            where = f"{cls.name}.{fname}" if cls is not None else fname
            out.append(Finding(
                "R8", mod.rel, fields[fname].lineno,
                f"'{where}' is annotated guarded-by: {lname}, but no "
                f"lock of that name exists in scope — name a "
                "threading.Lock/RLock/Condition field or module lock"))

    scans = {name: _scan_function(ctx, fn)
             for name, fn in members.items()}

    # a method referenced as a value (callback) can be called from
    # anywhere — it never inherits a caller's lock
    escaping: Set[str] = set()
    for scan in scans.values():
        escaping |= scan.self_refs

    def private_ok(name: str) -> bool:
        return name.startswith("_") and not name.startswith("__") \
            and name not in escaping

    all_lock_ids = {ld.lock_id for ld in lock_by_name.values()}
    entry = _entry_held(scans, scans.keys(), all_lock_ids, private_ok)

    guarded_locks = {f: lock_by_name[ln].lock_id
                     for f, ln in ctx.guards.items()
                     if ln in lock_by_name}
    owner = f"{cls.name}." if cls is not None else ""
    spell = "self." if cls is not None else ""
    for name, scan in scans.items():
        if name in EXEMPT_METHODS:
            continue
        base = entry.get(name, frozenset())
        flagged: Set[str] = set()
        for acc in scan.accesses:
            need = guarded_locks.get(acc.name)
            if need is None or need in acc.held or need in base \
                    or acc.name in flagged:
                continue
            flagged.add(acc.name)
            lockname = ctx.guards[acc.name]
            verb = "write" if acc.store else "read"
            out.append(Finding(
                "R8", mod.rel, acc.lineno,
                f"{verb} of '{spell}{acc.name}' (guarded-by "
                f"{lockname}) in {owner}{name} without holding "
                f"{spell}{lockname} — wrap it in `with "
                f"{spell}{lockname}:` or reach it only from call "
                "sites that hold the lock"))
        # lock-order edges: direct acquisitions under held locks,
        # plus held-at-call-site edges resolved interprocedurally
        fn = members[name]
        for lock_id, line, held_before in scan.acquires:
            lock_graph.add_acquire(fn.qualname, lock_id, mod.rel, line,
                                   held_before | base)
        for callee, line, held in scan.calls:
            eff = held | base
            if eff:
                lock_graph.add_call(fn.qualname, callee, mod.rel, line,
                                    eff)


class LockGraph:
    """The static lock-acquisition graph: nodes = known locks, edges =
    'acquired while holding', resolved through the call graph."""

    def __init__(self):
        self.locks: Dict[str, LockDef] = {}
        #: direct acquisitions per function qualname
        self._acquires: Dict[str, List[Tuple[str, str, int, frozenset]]]\
            = {}
        #: call sites under held locks
        self._calls: List[Tuple[str, str, str, int, frozenset]] = []
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        self.self_deadlocks: List[Tuple[str, str, int]] = []

    def add_lock(self, ld: LockDef) -> None:
        self.locks.setdefault(ld.lock_id, ld)

    def add_acquire(self, fn_qual: str, lock_id: str, rel: str,
                    line: int, held: frozenset) -> None:
        self._acquires.setdefault(fn_qual, []).append(
            (lock_id, rel, line, held))
        for h in held:
            self._edge(h, lock_id, rel, line)

    def add_call(self, fn_qual: str, callee_qual: str, rel: str,
                 line: int, held: frozenset) -> None:
        self._calls.append((fn_qual, callee_qual, rel, line, held))

    def _edge(self, frm: str, to: str, rel: str, line: int) -> None:
        if frm == to:
            kind = self.locks[to].kind if to in self.locks else "Lock"
            if kind != "RLock":
                self.self_deadlocks.append((to, rel, line))
            return
        self.edges.setdefault((frm, to), (rel, line))

    def resolve(self, graph: proggraph.ProgramGraph) -> None:
        """Fold call sites in: an acquisition anywhere in the callee's
        transitive call tree happens under the caller's held set."""
        # transitive acquires per function, fixed point
        direct: Dict[str, Set[str]] = {
            q: {a[0] for a in acqs}
            for q, acqs in self._acquires.items()}
        trans: Dict[str, Set[str]] = {q: set(s)
                                      for q, s in direct.items()}
        for _ in range(64):
            changed = False
            for qual, fn in graph.functions.items():
                acc = trans.get(qual, set())
                before = len(acc)
                for callee, _call in graph.callees(fn):
                    acc |= trans.get(callee.qualname, set())
                if len(acc) != before:
                    trans[qual] = acc
                    changed = True
                elif acc and qual not in trans:
                    trans[qual] = acc
            if not changed:
                break
        for _fn_qual, callee_qual, rel, line, held in self._calls:
            for inner in trans.get(callee_qual, ()):
                for h in held:
                    self._edge(h, inner, rel, line)

    def cycles(self) -> List[List[str]]:
        """Elementary cycles via iterative DFS over the edge set —
        returns each cycle once as a lock-id list."""
        adj: Dict[str, List[str]] = {}
        for frm, to in self.edges:
            adj.setdefault(frm, []).append(to)
        seen_cycles: Set[frozenset] = set()
        out: List[List[str]] = []

        def dfs(start: str) -> None:
            stack: List[Tuple[str, List[str]]] = [(start, [start])]
            while stack:
                node, path = stack.pop()
                for nxt in adj.get(node, ()):
                    if nxt == start and len(path) > 1:
                        key = frozenset(path)
                        if key not in seen_cycles:
                            seen_cycles.add(key)
                            out.append(path + [start])
                    elif nxt not in path and nxt > start:
                        # visit only ids > start: each cycle is found
                        # from its smallest node exactly once
                        stack.append((nxt, path + [nxt]))

        for node in sorted(adj):
            dfs(node)
        return out

    def to_dict(self) -> dict:
        return {
            "locks": [dataclasses.asdict(ld) for _key, ld in
                      sorted(self.locks.items())],
            "edges": [{"from": frm, "to": to, "path": rel, "line": line}
                      for (frm, to), (rel, line) in
                      sorted(self.edges.items())],
            "cycles": self.cycles(),
            "self_deadlocks": [
                {"lock": lk, "path": rel, "line": line}
                for lk, rel, line in self.self_deadlocks],
        }


def build_lock_graph(project: Project) -> LockGraph:
    """Build (and cache on the project) the repo's lock-acquisition
    graph — the CI artifact behind ``--lockgraph``."""
    cached = getattr(project, "_lockgraph", None)
    if cached is not None:
        return cached
    graph = proggraph.get_graph(project)
    lg = LockGraph()
    findings: List[Finding] = []
    for mod in graph.modules.values():
        _analyze_scope(graph, mod, None, findings, lg)
        for cls in mod.classes.values():
            _analyze_scope(graph, mod, cls, findings, lg)
    lg.resolve(graph)
    project._lockgraph = lg
    project._lockgraph_findings = findings
    return lg


@rule("R8", "lock-discipline", scope="program")
def check_lock_discipline(project: Project) -> Iterable[Finding]:
    """Reads/writes of ``# guarded-by:`` annotated state outside the
    named lock (helper calls resolved through the program graph), plus
    lock-order-inversion cycles and self-deadlocks in the static
    lock-acquisition graph."""
    lg = build_lock_graph(project)
    out: List[Finding] = list(project._lockgraph_findings)
    for cyc in lg.cycles():
        edge = (cyc[0], cyc[1])
        rel, line = lg.edges.get(edge, ("", 0))
        pretty = " -> ".join(c.split("::")[-1] for c in cyc)
        out.append(Finding(
            "R8", rel or cyc[0].split("::")[0], line,
            f"lock-order cycle {pretty} — a thread taking these locks "
            "in different orders can deadlock; impose one global "
            "order (see ci/graftlint_lockgraph.json)"))
    for lock_id, rel, line in lg.self_deadlocks:
        out.append(Finding(
            "R8", rel, line,
            f"'{lock_id.split('::')[-1]}' (non-reentrant Lock) is "
            "acquired while already held on this path — guaranteed "
            "self-deadlock; use an RLock or split the critical "
            "section"))
    return out
