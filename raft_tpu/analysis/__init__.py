"""graftlint — a JAX/Pallas-aware static analyzer enforcing the
serving-path invariants PR 1–3 established, as CI-gated lint rules.

The performance guarantees of this repo are *invariants of how the
code is written*: AOT cache keys stay hashable statics (R1), donated
buffers are never read after donation (R2), every collective goes
through the versioned comms veneer and names a real mesh axis (R3),
every Pallas kernel states and fits its VMEM budget (R4), the serving
hot path never round-trips to the host (R5), every kernel keeps an
interpret-mode CPU reference (R6), and the serving frontend reads time
only through the injectable clock (R7). Runtime tests catch violations
one configuration at a time; graftlint machine-checks them on every
diff.

v3 adds the whole-program analyses, built on one shared parse pass
(:mod:`~raft_tpu.analysis.proggraph`): guarded state is only touched
under its annotated lock and the static lock-order graph stays
acyclic (R8), donated buffers never escape through object fields into
a read-after-donation — interprocedurally (R2 v2), and the registered
metric inventory, the ARCHITECTURE.md tables, the CI snapshot floors,
and the exporter HELP table all agree (R9).

Run::

    python -m raft_tpu.analysis               # text report, exit 1 on findings
    python -m raft_tpu.analysis --format=ci   # findings + suppression inventory
    python -m raft_tpu.analysis --format=json --output=report.json
    python -m raft_tpu.analysis --lockgraph ci/graftlint_lockgraph.json

Repo runs keep an incremental content-hash cache at
``ci/.graftlint_cache.json`` (``--no-cache`` bypasses it).

Suppress a finding only with a written reason::

    risky_line()  # graftlint: disable=R5(one-off build-path fetch)

The analyzer is stdlib-``ast`` only (no third-party deps, the same
constraint the old ``ci/check_style.py`` worked under — its checks now
live here as rule R0).
"""

from raft_tpu.analysis.core import (
    DEFAULT_DIRS,
    Finding,
    LintCache,
    Project,
    Report,
    RULES,
    Rule,
    Suppression,
    rule,
    ruleset_version,
    run,
)

# importing the rule modules registers them
from raft_tpu.analysis import rules_style  # noqa: F401
from raft_tpu.analysis import rules_trace  # noqa: F401
from raft_tpu.analysis import rules_mesh  # noqa: F401
from raft_tpu.analysis import rules_pallas  # noqa: F401
from raft_tpu.analysis import rules_hostsync  # noqa: F401
from raft_tpu.analysis import rules_clock  # noqa: F401
from raft_tpu.analysis import rules_locks  # noqa: F401
from raft_tpu.analysis import rules_metrics  # noqa: F401


def lint_texts(texts, rules=None, aux=None) -> Report:
    """Lint an in-memory {relative path: source} mapping — the fixture
    corpus entry point used by ``tests/test_analysis.py``. ``aux``
    opts a fixture into the doc-conformance checks (R9)."""
    return run(Project.from_texts(texts, aux=aux), rules=rules)


def lint_root(root, rules=None) -> Report:
    """Lint a repo checkout rooted at ``root``."""
    return run(Project.from_root(root), rules=rules)


__all__ = [
    "DEFAULT_DIRS", "Finding", "LintCache", "Project", "Report",
    "RULES", "Rule", "Suppression", "rule", "ruleset_version", "run",
    "lint_texts", "lint_root",
]
