"""Shared AST machinery for the graftlint rules.

The load-bearing abstraction is the *traced-name dataflow*: given a
function that jax traces (a ``*_fn`` serving impl, a ``shard_map``
body, a Pallas kernel), which local names hold tracers?  The repo's
signature convention makes the seed set syntactic — array operands are
**unannotated positional** parameters, compile-time statics are
keyword-only (or annotated) — and a simple forward pass propagates
tracer-ness through assignments, treating shape/dtype metadata access
as laundering (``x.shape[0]`` is a Python int, not a tracer).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# attribute accesses that yield static metadata, not a traced value
METADATA_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize",
                  "sharding", "device", "weak_type", "aval"}
# calls whose result is static metadata regardless of the arguments
METADATA_FNS = {"len", "isinstance", "type", "getattr", "hasattr",
                "str", "repr", "id", "hash", "callable",
                "np.shape", "jnp.shape", "np.ndim", "jnp.ndim",
                "np.result_type", "jnp.result_type", "np.dtype"}


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted(call.func)


def walk_in_order(body: Sequence[ast.stmt]) -> Iterable[ast.stmt]:
    """Statements in source order, recursing into compound statements
    but NOT into nested function/class definitions (separate scopes)."""
    for stmt in body:
        yield stmt
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        for field in ("body", "orelse", "finalbody"):
            yield from walk_in_order(getattr(stmt, field, []) or [])
        for h in getattr(stmt, "handlers", []) or []:
            yield from walk_in_order(h.body)


def value_names(expr: ast.AST) -> Set[str]:
    """Bare names whose *runtime value* the expression consumes.

    Names consumed only through metadata (``x.shape``, ``len(x)``),
    identity checks (``x is None``), or other laundering constructs do
    not count — conditioning on those is shape-static and jit-safe.
    """
    out: Set[str] = set()

    def visit(n: ast.AST, value: bool) -> None:
        if isinstance(n, ast.Name):
            if value and isinstance(n.ctx, ast.Load):
                out.add(n.id)
            return
        if isinstance(n, ast.Attribute):
            visit(n.value, value and n.attr not in METADATA_ATTRS)
            return
        if isinstance(n, ast.Compare):
            identity_only = all(isinstance(op, (ast.Is, ast.IsNot))
                                for op in n.ops)
            visit(n.left, value and not identity_only)
            for c in n.comparators:
                visit(c, value and not identity_only)
            return
        if isinstance(n, ast.Call):
            fname = call_name(n)
            launders = fname in METADATA_FNS
            # the callee itself: `x.astype(...)` consumes x's value
            visit(n.func, value)
            for a in n.args:
                visit(a, value and not launders)
            for kw in n.keywords:
                visit(kw.value, value and not launders)
            return
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            return  # separate scope
        for child in ast.iter_child_nodes(n):
            visit(child, value)

    visit(expr, True)
    return out


def assigned_names(target: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(target):
        if isinstance(n, ast.Name):
            out.add(n.id)
    return out


def jit_static_names(fn: ast.FunctionDef) -> Optional[Set[str]]:
    """For a jit-decorated def, the static parameter names (resolving
    static_argnums to names). None when the def is not jit-decorated."""
    for dec in fn.decorator_list:
        call = dec if isinstance(dec, ast.Call) else None
        name = dotted(call.func) if call else dotted(dec)
        if name is None:
            continue
        target = call
        if name in ("functools.partial", "partial") and call is not None:
            if not call.args:
                continue
            inner = dotted(call.args[0])
            if inner not in ("jax.jit", "jit"):
                continue
        elif name not in ("jax.jit", "jit"):
            continue
        statics: Set[str] = set()
        pos = [a.arg for a in fn.args.posonlyargs + fn.args.args]
        if target is not None:
            for kw in target.keywords:
                if kw.arg == "static_argnames":
                    for c in ast.walk(kw.value):
                        if (isinstance(c, ast.Constant)
                                and isinstance(c.value, str)):
                            statics.add(c.value)
                if kw.arg == "static_argnums":
                    for c in ast.walk(kw.value):
                        if (isinstance(c, ast.Constant)
                                and isinstance(c.value, int)
                                and c.value < len(pos)):
                            statics.add(pos[c.value])
        return statics
    return None


def seed_traced_params(fn, statics: Optional[Set[str]] = None) -> Set[str]:
    """The repo convention: unannotated positional params are traced
    arrays; keyword-only and annotated params are compile-time statics."""
    statics = statics or set()
    traced: Set[str] = set()
    args = fn.args
    pos = list(getattr(args, "posonlyargs", [])) + list(args.args)
    for p in pos:
        ann = getattr(p, "annotation", None)
        if ann is None and p.arg not in ("self", "cls", "res"):
            if p.arg not in statics:
                traced.add(p.arg)
    if args.vararg is not None and args.vararg.annotation is None:
        traced.add(args.vararg.arg)
    return traced


def traced_names(fn, statics: Optional[Set[str]] = None) -> Set[str]:
    """Seed + two forward propagation passes over the body (two passes
    give loop-carried names a chance to converge)."""
    traced = seed_traced_params(fn, statics)
    body = fn.body if isinstance(fn.body, list) else [ast.Return(fn.body)]
    for _ in range(2):
        for stmt in walk_in_order(body):
            if isinstance(stmt, ast.Assign):
                hot = bool(value_names(stmt.value) & traced)
                for t in stmt.targets:
                    names = assigned_names(t)
                    if hot:
                        traced |= names
                    elif isinstance(t, ast.Name):
                        traced.discard(t.id)
            elif isinstance(stmt, ast.AugAssign):
                if value_names(stmt.value) & traced:
                    traced |= assigned_names(stmt.target)
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                if value_names(stmt.value) & traced:
                    traced |= assigned_names(stmt.target)
            elif isinstance(stmt, ast.For):
                if value_names(stmt.iter) & traced:
                    traced |= assigned_names(stmt.target)
    return traced


def collect_functions(tree: ast.AST) -> List[ast.FunctionDef]:
    return [n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


def traced_bodies(tree: ast.AST) -> List[Tuple[ast.AST, Set[str], str]]:
    """Functions jax traces, with their traced-name sets:

    - ``*_fn`` serving impls (repo naming convention)
    - jit-decorated defs (statics read off the decorator)
    - bodies passed to ``shard_map`` / ``comms.run`` / ``pallas_call``
      (by local name, nested def, lambda, or ``functools.partial``)

    Returns (node, traced names, origin tag).
    """
    fns = collect_functions(tree)
    by_name: Dict[str, ast.FunctionDef] = {}
    for f in fns:
        by_name.setdefault(f.name, f)

    out: List[Tuple[ast.AST, Set[str], str]] = []
    seen: Set[int] = set()

    def add(fn, statics, origin):
        if id(fn) in seen:
            return
        seen.add(id(fn))
        if isinstance(fn, ast.Lambda):
            traced = {a.arg for a in fn.args.args + fn.args.posonlyargs}
            out.append((fn, traced, origin))
        else:
            out.append((fn, traced_names(fn, statics), origin))

    for f in fns:
        statics = jit_static_names(f)
        if statics is not None:
            add(f, statics, "jit")
        elif f.name.endswith("_fn"):
            add(f, None, "fn-convention")

    def resolve_body_arg(arg):
        if isinstance(arg, (ast.Lambda,)):
            return arg
        if isinstance(arg, ast.Name):
            return by_name.get(arg.id)
        if isinstance(arg, ast.Call):
            nm = call_name(arg)
            if nm in ("functools.partial", "partial") and arg.args:
                return resolve_body_arg(arg.args[0])
        return None

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        nm = call_name(node) or ""
        leaf = nm.split(".")[-1]
        if leaf in ("shard_map", "pallas_call") and node.args:
            body = resolve_body_arg(node.args[0])
            if body is not None:
                add(body, None, leaf)
        if leaf == "run" and nm.endswith(".run") and node.args:
            # Comms.run(fn, *args, in_specs=..., out_specs=...)
            if any(kw.arg == "in_specs" for kw in node.keywords):
                body = resolve_body_arg(node.args[0])
                if body is not None:
                    add(body, None, "comms.run")
    return out


# ---------------------------------------------------------------------------
# constant folding (R4's static VMEM estimate)
# ---------------------------------------------------------------------------


class Env:
    """Lazy single-assignment constant environment for one function."""

    def __init__(self, fn: ast.AST):
        self.bindings: Dict[str, ast.AST] = {}
        self.multi: Set[str] = set()
        body = fn.body if isinstance(fn.body, list) else []
        for stmt in walk_in_order(body):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                name = stmt.targets[0].id
                if name in self.bindings:
                    self.multi.add(name)
                self.bindings[name] = stmt.value
            elif isinstance(stmt, (ast.AugAssign, ast.For)):
                for n in assigned_names(getattr(stmt, "target", stmt)):
                    self.multi.add(n)
        self._memo: Dict[str, Optional[float]] = {}
        self._stack: Set[str] = set()

    def lookup(self, name: str) -> Optional[float]:
        if name in self.multi or name not in self.bindings:
            return None
        if name in self._memo:
            return self._memo[name]
        if name in self._stack:
            return None
        self._stack.add(name)
        try:
            val = const_fold(self.bindings[name], self)
        finally:
            self._stack.discard(name)
        self._memo[name] = val
        return val


def const_fold(expr: ast.AST, env: Optional[Env] = None):
    """Best-effort numeric fold; None when any input is dynamic."""
    if isinstance(expr, ast.Constant) and isinstance(
            expr.value, (int, float)) and not isinstance(expr.value, bool):
        return expr.value
    if isinstance(expr, ast.Name):
        return env.lookup(expr.id) if env else None
    if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.USub):
        v = const_fold(expr.operand, env)
        return None if v is None else -v
    if isinstance(expr, ast.BinOp):
        left = const_fold(expr.left, env)
        right = const_fold(expr.right, env)
        if left is None or right is None:
            return None
        try:
            if isinstance(expr.op, ast.Add):
                return left + right
            if isinstance(expr.op, ast.Sub):
                return left - right
            if isinstance(expr.op, ast.Mult):
                return left * right
            if isinstance(expr.op, ast.FloorDiv):
                return left // right
            if isinstance(expr.op, ast.Mod):
                return left % right
            if isinstance(expr.op, ast.LShift):
                return int(left) << int(right)
            if isinstance(expr.op, ast.RShift):
                return int(left) >> int(right)
            if isinstance(expr.op, ast.Pow):
                return left ** right
        except (ZeroDivisionError, TypeError, ValueError, OverflowError):
            return None
        return None
    if isinstance(expr, ast.Call):
        nm = call_name(expr)
        if nm in ("min", "max") and expr.args and not expr.keywords:
            vals = [const_fold(a, env) for a in expr.args]
            if any(v is None for v in vals):
                return None
            return min(vals) if nm == "min" else max(vals)
        if nm == "int" and len(expr.args) == 1:
            v = const_fold(expr.args[0], env)
            return None if v is None else int(v)
    return None


def fold_shape(shape_expr: ast.AST, env: Optional[Env]) -> Optional[List[int]]:
    """Fold a literal shape tuple to ints; None if any dim is dynamic."""
    if not isinstance(shape_expr, (ast.Tuple, ast.List)):
        return None
    dims: List[int] = []
    for el in shape_expr.elts:
        v = const_fold(el, env)
        if v is None:
            return None
        dims.append(int(v))
    return dims


def upper_bound(expr: ast.AST, env: Optional[Env] = None,
                _seen: Optional[Set[str]] = None):
    """Best-effort numeric *upper bound* for shape arithmetic.

    Where ``const_fold`` gives up the moment any input is dynamic,
    this keeps going through the bounding constructs shape code
    actually uses: ``min(n, CAP)`` is bounded by CAP even when ``n``
    is a runtime value, ``a % b`` by ``b - 1``, ``a // c`` by
    ``bound(a) // c``.  Assumes nonnegative operands — true for the
    dimension arithmetic this serves — so products/sums of bounds are
    bounds.  None when no finite bound can be established.
    """
    v = const_fold(expr, env)
    if v is not None:
        return v
    _seen = _seen or set()
    if isinstance(expr, ast.Name):
        if env is None or expr.id in env.multi \
                or expr.id not in env.bindings or expr.id in _seen:
            return None
        return upper_bound(env.bindings[expr.id], env, _seen | {expr.id})
    if isinstance(expr, ast.Call):
        nm = call_name(expr)
        if nm == "min" and expr.args and not expr.keywords:
            # min is bounded by ANY bounded arm
            known = [b for b in (upper_bound(a, env, _seen)
                                 for a in expr.args) if b is not None]
            return min(known) if known else None
        if nm == "max" and expr.args and not expr.keywords:
            # max needs every arm bounded
            bounds = [upper_bound(a, env, _seen) for a in expr.args]
            if any(b is None for b in bounds):
                return None
            return max(bounds)
        if nm == "int" and len(expr.args) == 1:
            b = upper_bound(expr.args[0], env, _seen)
            return None if b is None else int(b)
        return None
    if isinstance(expr, ast.BinOp):
        if isinstance(expr.op, ast.Mod):
            b = const_fold(expr.right, env)
            return b - 1 if b is not None and b > 0 else None
        left = upper_bound(expr.left, env, _seen)
        if left is None:
            return None
        if isinstance(expr.op, (ast.Add, ast.Mult)):
            right = upper_bound(expr.right, env, _seen)
            if right is None:
                return None
            return left + right if isinstance(expr.op, ast.Add) \
                else left * right
        if isinstance(expr.op, (ast.FloorDiv, ast.Sub)):
            # only a *constant* right keeps the bound direction sound
            right = const_fold(expr.right, env)
            if right is None:
                return None
            if isinstance(expr.op, ast.Sub):
                return left - right
            return left // right if right > 0 else None
    return None


def shape_upper_bound(shape_expr: ast.AST,
                      env: Optional[Env]) -> Optional[List[int]]:
    """Per-dim upper bounds for a literal shape tuple; None when any
    dim admits no finite bound."""
    if not isinstance(shape_expr, (ast.Tuple, ast.List)):
        return None
    dims: List[int] = []
    for el in shape_expr.elts:
        v = upper_bound(el, env)
        if v is None:
            return None
        dims.append(int(v))
    return dims
