"""R5 — host-sync lint for the serving hot path.

TPU-KNN's peak-throughput recipe (and PR 1's executor design) dies by
a thousand silent host round-trips: one ``.item()`` in a scan loop
serializes every dispatch; an ``np.asarray`` on a device array fetches
the whole buffer; a ``device_put`` inside a Python loop issues one
transfer per iteration where one batched call would do.

Scope — the hot modules named by the serving stack:
``core/executor.py``, ``core/memwatch.py`` (PR 13 — graftledger's
watermark sample runs per dispatch), ``raft_tpu/ops/*``,
``raft_tpu/distributed/*`` (except ``checkpoint.py``, which is the
host-IO module by design), ``raft_tpu/neighbors/*``, and the request
frontend ``raft_tpu/serving/*`` (PR 5 — the batcher sits on the
per-request hot path: one stray ``.item()`` or per-iteration
``device_put`` in a dispatch loop taxes every request in the
process). Within them:

- ``.item()`` anywhere (it is never right on the hot path);
- ``np.asarray`` / ``np.array`` / ``jax.device_get``, and
  ``float()``/``int()`` of traced values, inside jit-traced serving
  bodies (``*_fn`` impls, ``shard_map``/Pallas bodies) and
  ``search*`` entry points — host fetches the steady state must not
  pay (build/save/load paths are host-side by contract and exempt);
- ``jax.device_put`` inside a ``for``/``while`` loop — transfers
  belong in one batched call per step, not one per iteration.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from raft_tpu.analysis import astutil
from raft_tpu.analysis.core import Finding, Project, rule

HOT_PREFIXES = ("raft_tpu/ops/", "raft_tpu/distributed/",
                "raft_tpu/neighbors/", "raft_tpu/serving/",
                "raft_tpu/fleet/")
# core/memwatch.py joined in PR 13: its watermark sample runs on the
# executor's dispatch path, so a stray .item()/device_get there taxes
# every search in the process (the module itself is shape/dtype
# arithmetic + backend introspection by contract)
HOT_FILES = ("raft_tpu/core/executor.py", "raft_tpu/core/memwatch.py")
EXEMPT = ("raft_tpu/distributed/checkpoint.py",)

_FETCH_CALLS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
                "jax.device_get", "device_get"}


def _is_hot(rel: str) -> bool:
    if rel in EXEMPT:
        return False
    return rel in HOT_FILES or rel.startswith(HOT_PREFIXES)


def _serving_scopes(tree: ast.AST):
    """jit-traced bodies plus host-side ``search*`` orchestration."""
    scopes = list(astutil.traced_bodies(tree))
    seen = {id(fn) for fn, _, _ in scopes}
    for fn in astutil.collect_functions(tree):
        if id(fn) not in seen and (fn.name == "search"
                                   or fn.name.startswith("search_")
                                   or fn.name.startswith("_search")):
            scopes.append((fn, astutil.traced_names(fn), "search-entry"))
    return scopes


@rule("R5", "host-sync")
def check_host_sync(project: Project) -> Iterable[Finding]:
    """Host round-trips (.item, np.asarray/device_get, float/int of
    traced values, per-iteration device_put) in the serving hot
    modules."""
    out: List[Finding] = []
    for f in project.lib():
        if f.tree is None or not _is_hot(f.rel):
            continue

        # .item() anywhere in a hot module
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item" and not node.args:
                out.append(Finding(
                    "R5", f.rel, node.lineno,
                    ".item() in a hot module — a blocking host sync "
                    "per call; keep the value on device or fetch it "
                    "once, batched"))

        # device_put inside python loops
        for loop in ast.walk(f.tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for node in ast.walk(loop):
                if node is loop:
                    continue
                if isinstance(node, ast.Call) and (
                        astutil.call_name(node) or "").endswith(
                        "device_put"):
                    out.append(Finding(
                        "R5", f.rel, node.lineno,
                        "device_put inside a python loop — one "
                        "transfer per iteration; batch the placements "
                        "into a single device_put call"))

        # host fetches inside serving scopes
        for fn, traced, origin in _serving_scopes(f.tree):
            body = fn.body if isinstance(fn.body, list) else []
            reported: Set[int] = set()
            for stmt in astutil.walk_in_order(body):
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call) \
                            or node.lineno in reported:
                        continue
                    nm = astutil.call_name(node) or ""
                    if nm in _FETCH_CALLS:
                        reported.add(node.lineno)
                        out.append(Finding(
                            "R5", f.rel, node.lineno,
                            f"{nm}() inside {origin} "
                            f"'{getattr(fn, 'name', '<lambda>')}' — "
                            "fetches device data to host on the "
                            "serving path"))
                    leaf = nm.split(".")[-1]
                    if leaf in ("float", "int") and node.args:
                        hot = astutil.value_names(node.args[0]) & traced
                        if hot:
                            reported.add(node.lineno)
                            out.append(Finding(
                                "R5", f.rel, node.lineno,
                                f"{leaf}() of traced value(s) "
                                f"{sorted(hot)} inside {origin} "
                                f"'{getattr(fn, 'name', '<lambda>')}'"
                                " — forces a device sync (and fails "
                                "under jit); keep it as an array"))
    return out
