"""graftlint core model — source files, suppression pragmas, the rule
registry, and the runner.

The analyzer itself is self-contained (stdlib ``ast`` only — the same
no-third-party-deps constraint as the old ``ci/check_style.py``); it
never *executes* the code it checks, it only parses it. Note the CLI
(``python -m raft_tpu.analysis``) still pays the ``raft_tpu`` package
import (which pulls in jax) — the analysis modules merely add nothing
on top.

Suppressions are written next to the finding they silence::

    x = risky()  # graftlint: disable=R5(build-path host fetch, one-off)

or on their own line, covering the next statement::

    # graftlint: disable=R3(pvary compat shim lives here by design)
    out = jax.lax.ppermute(x, axis, perm)

The rule id must match and the parenthesized reason is mandatory —
a pragma without a reason, and a pragma that silences nothing, are
themselves findings (rule R0), so the suppression inventory can only
grow deliberately and is snapshot-tested.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import io
import json
import pathlib
import re
import tokenize
from typing import Callable, Dict, Iterable, List, Optional, Sequence

PRAGMA_RE = re.compile(r"#\s*graftlint:\s*disable=(.*?)\s*$")
PRAGMA_ID_RE = re.compile(r"\s*([A-Z][A-Z0-9]*)\s*")


def parse_pragma_items(payload: str):
    """Parse ``R1(reason), R5(reason with (parens))`` — returns
    ([(rule, reason-or-None)], trailing-garbage-flag). Reasons may
    contain balanced parentheses."""
    items = []
    pos, bad = 0, False
    while pos < len(payload):
        m = PRAGMA_ID_RE.match(payload, pos)
        if not m:
            bad = bad or bool(payload[pos:].strip(", \t"))
            break
        rule_id = m.group(1)
        pos = m.end()
        reason = None
        if pos < len(payload) and payload[pos] == "(":
            depth, start = 1, pos + 1
            pos += 1
            while pos < len(payload) and depth:
                if payload[pos] == "(":
                    depth += 1
                elif payload[pos] == ")":
                    depth -= 1
                pos += 1
            if depth:
                bad = True
                break
            reason = payload[start:pos - 1]
        items.append((rule_id, reason))
        rest = payload[pos:].lstrip()
        if rest.startswith(","):
            pos = len(payload) - len(rest) + 1
        elif rest:
            bad = True
            break
        else:
            break
    return items, bad

#: directories scanned by default, relative to the repo root — the same
#: set the old ci/check_style.py walked.
DEFAULT_DIRS = ("raft_tpu", "tests", "examples", "scripts")

#: non-scanned files the whole-program rules read as evidence: R9
#: cross-checks the registered metric names against ARCHITECTURE.md's
#: inventory tables and the ``SNAPSHOT_FLOORS`` dict in the bench gate
AUX_FILES = ("ARCHITECTURE.md", "ci/bench_compare.py")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str  # repo-relative posix path
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


@dataclasses.dataclass
class Suppression:
    """One ``# graftlint: disable=RULE(reason)`` pragma."""

    rule: str
    path: str
    line: int          # code line the pragma covers
    pragma_line: int   # line the comment physically sits on
    reason: str
    used: bool = False


class SourceFile:
    """A parsed source file plus its suppression pragmas."""

    def __init__(self, rel: str, text: str):
        self.rel = rel.replace("\\", "/")
        parts = self.rel.split("/")
        self.kind = parts[0] if parts[0] in DEFAULT_DIRS else "other"
        self.text = text
        self.lines = text.splitlines()
        self.syntax_error: Optional[SyntaxError] = None
        try:
            self.tree: Optional[ast.AST] = ast.parse(text)
        except SyntaxError as e:
            self.tree = None
            self.syntax_error = e
        self.suppressions: List[Suppression] = []
        self.bad_pragmas: List[tuple] = []  # (line, why)
        self._parse_pragmas()

    # -- pragmas ------------------------------------------------------------

    def _stmt_start(self, line: int) -> int:
        """First line of the innermost statement spanning ``line`` —
        findings anchor to a node's first line, so a pragma trailing a
        *continuation* line of a multi-line statement must map back to
        the statement start to suppress anything."""
        if self.tree is None:
            return line
        best = line
        best_span = None
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.stmt):
                continue
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= line <= end:
                span = end - node.lineno
                if best_span is None or span <= best_span:
                    best, best_span = node.lineno, span
        return best

    def _covered_line(self, pragma_line: int, own_line: bool) -> int:
        """A trailing pragma covers its statement; a comment-only
        pragma covers the statement starting at (or spanning) the next
        non-blank, non-comment line."""
        if own_line:
            return self._stmt_start(pragma_line)
        for j in range(pragma_line, len(self.lines)):
            nxt = self.lines[j].strip()
            if nxt and not nxt.startswith("#"):
                return self._stmt_start(j + 1)
        return pragma_line

    def _comment_tokens(self):
        """Real COMMENT tokens only — a pragma quoted inside a
        docstring (e.g. this module's own examples) is not a pragma."""
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.text).readline):
                if tok.type == tokenize.COMMENT:
                    yield tok.start[0], tok.start[1], tok.string
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return

    def _parse_pragmas(self) -> None:
        for i, col, comment in self._comment_tokens():
            m = PRAGMA_RE.search(comment)
            if not m:
                continue
            own_line = bool(self.lines[i - 1][:col].strip())
            covered = self._covered_line(i, own_line)
            items, bad = parse_pragma_items(m.group(1))
            for rule_id, reason in items:
                if reason is None or not reason.strip():
                    self.bad_pragmas.append(
                        (i, f"suppression of {rule_id} carries no reason "
                            "— write disable="
                            f"{rule_id}(why this is safe)"))
                    continue
                self.suppressions.append(Suppression(
                    rule=rule_id, path=self.rel, line=covered,
                    pragma_line=i, reason=reason.strip()))
            if bad or not items:
                self.bad_pragmas.append(
                    (i, "malformed graftlint pragma — expected "
                        "disable=RULE(reason)"))

    def suppression_for(self, rule: str, line: int) -> Optional[Suppression]:
        # findings anchor to a node's own line, which for a multi-line
        # statement may be a continuation line — normalize both sides
        # to the statement start so a trailing pragma anywhere in the
        # statement suppresses any finding inside it
        stmt = self._stmt_start(line)
        for s in self.suppressions:
            if s.rule == rule and s.line in (line, stmt):
                return s
        return None


class Project:
    """The set of files one analysis run sees.

    ``aux`` carries the non-Python evidence whole-program rules
    cross-check against (ARCHITECTURE.md's metric tables, the bench
    gate's ``SNAPSHOT_FLOORS``) as ``{repo-relative path: text}`` —
    absent entries simply disable the corresponding check, so fixture
    projects opt in per test.
    """

    def __init__(self, files: Sequence[SourceFile],
                 root: Optional[pathlib.Path] = None,
                 aux: Optional[Dict[str, str]] = None):
        self.files = list(files)
        self.root = root
        self.by_rel = {f.rel: f for f in self.files}
        self.aux = dict(aux or {})

    @classmethod
    def from_root(cls, root, dirs: Sequence[str] = DEFAULT_DIRS
                  ) -> "Project":
        root = pathlib.Path(root).resolve()
        files = []
        for d in dirs:
            base = root / d
            if not base.exists():
                continue
            for path in sorted(base.rglob("*.py")):
                if "__pycache__" in path.parts:
                    continue
                rel = path.relative_to(root).as_posix()
                files.append(SourceFile(rel, path.read_text()))
        aux = {}
        for rel in AUX_FILES:
            p = root / rel
            if p.exists():
                aux[rel] = p.read_text()
        return cls(files, root, aux)

    @classmethod
    def from_texts(cls, texts: Dict[str, str],
                   aux: Optional[Dict[str, str]] = None) -> "Project":
        """Synthetic project for the fixture corpus: path -> source."""
        return cls([SourceFile(rel, text)
                    for rel, text in sorted(texts.items())], aux=aux)

    def lib(self) -> List[SourceFile]:
        return [f for f in self.files if f.kind == "raft_tpu"]

    def tests(self) -> List[SourceFile]:
        return [f for f in self.files if f.kind == "tests"]


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    name: str
    doc: str
    check: Callable[[Project], Iterable[Finding]]
    #: "file" — findings for a file depend only on that file's text, so
    #: the incremental cache can key them per (file sha, rule-set
    #: version); "program" — findings depend on the whole tree (cross
    #: -module graph, test↔lib coverage, doc cross-checks), cached per
    #: project digest instead
    scope: str = "file"


RULES: Dict[str, Rule] = {}


def rule(rule_id: str, name: str, scope: str = "file"):
    """Register a checker under a rule id. The checker's docstring is
    the rule's documentation (surfaced by ``--list-rules``)."""
    assert scope in ("file", "program"), scope

    def deco(fn):
        doc = " ".join((fn.__doc__ or "").split())
        RULES[rule_id] = Rule(rule_id, name, doc, fn, scope)
        return fn

    return deco


# ---------------------------------------------------------------------------
# incremental cache
# ---------------------------------------------------------------------------


def _sha1(text: str) -> str:
    return hashlib.sha1(text.encode("utf-8", "replace")).hexdigest()


def ruleset_version(package_dir: Optional[pathlib.Path] = None) -> str:
    """Content hash of the analysis package itself — any edit to a rule
    or this runner invalidates every cache entry, so a stale cache can
    never mask a new rule's findings."""
    base = package_dir or pathlib.Path(__file__).resolve().parent
    h = hashlib.sha1()
    for p in sorted(base.glob("*.py")):
        h.update(p.name.encode())
        h.update(p.read_bytes())
    return h.hexdigest()


class LintCache:
    """Content-hash finding cache (``ci/.graftlint_cache.json``).

    File-scope rules key per ``(file sha, rule-set version)``;
    whole-program rules key on the project digest (every file sha +
    every aux text). Raw findings are cached *pre-suppression* — the
    pragma fold is cheap and always runs fresh, so editing only a
    pragma still flips a finding's suppressed state on a full cache
    hit.
    """

    def __init__(self, path, version: str):
        self.path = pathlib.Path(path)
        self.version = version
        self.hits = 0
        self.misses = 0
        self._dirty = False
        data: dict = {}
        try:
            data = json.loads(self.path.read_text())
        except (OSError, ValueError):
            data = {}
        if data.get("version") != version:
            data = {}
        self._files: dict = data.get("files", {})
        self._program: dict = data.get("program", {})

    @staticmethod
    def _load(items) -> List[Finding]:
        return [Finding(**d) for d in items]

    def get_file(self, rule_id: str, rel: str,
                 sha: str) -> Optional[List[Finding]]:
        entry = self._files.get(rel)
        if entry is None or entry.get("sha") != sha:
            return None
        found = entry.get("rules", {}).get(rule_id)
        return None if found is None else self._load(found)

    def put_file(self, rule_id: str, rel: str, sha: str,
                 findings: List[Finding]) -> None:
        entry = self._files.setdefault(rel, {"sha": sha, "rules": {}})
        if entry.get("sha") != sha:
            self._files[rel] = entry = {"sha": sha, "rules": {}}
        entry["rules"][rule_id] = [dataclasses.asdict(f)
                                   for f in findings]
        self._dirty = True

    def get_program(self, rule_id: str,
                    digest: str) -> Optional[List[Finding]]:
        entry = self._program.get(rule_id)
        if entry is None or entry.get("digest") != digest:
            return None
        return self._load(entry.get("findings", []))

    def put_program(self, rule_id: str, digest: str,
                    findings: List[Finding]) -> None:
        self._program[rule_id] = {
            "digest": digest,
            "findings": [dataclasses.asdict(f) for f in findings]}
        self._dirty = True

    def prune(self, live_rels: Iterable[str]) -> None:
        """Drop entries for files no longer in the project."""
        live = set(live_rels)
        for rel in list(self._files):
            if rel not in live:
                del self._files[rel]
                self._dirty = True

    def save(self) -> None:
        if not self._dirty:
            return
        payload = {"version": self.version, "files": self._files,
                   "program": self._program}
        try:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            self.path.write_text(json.dumps(payload) + "\n")
        except OSError:
            pass  # a read-only checkout still lints, just uncached


def project_digest(project: Project) -> str:
    """One hash over every file and aux text — the whole-program cache
    key component."""
    h = hashlib.sha1()
    for f in sorted(project.files, key=lambda f: f.rel):
        h.update(f.rel.encode())
        h.update(_sha1(f.text).encode())
    for rel in sorted(project.aux):
        h.update(rel.encode())
        h.update(_sha1(project.aux[rel]).encode())
    return h.hexdigest()


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Report:
    findings: List[Finding]                 # unsuppressed — gate on these
    suppressed: List[tuple]                 # (Finding, reason)
    suppressions: List[Suppression]         # full inventory
    rules_run: List[str]
    n_files: int
    cache_hits: int = 0
    cache_misses: int = 0
    cache_enabled: bool = False

    @property
    def ok(self) -> bool:
        return not self.findings

    def suppression_inventory(self) -> List[List[str]]:
        """The canonical ``[path, rule, reason]`` inventory, sorted —
        the ONE shape the snapshot test, ``--list-suppressions``, and
        the ``ci/graftlint_report.json`` artifact all read."""
        return sorted([s.path, s.rule, s.reason]
                      for s in self.suppressions)

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "rules_run": self.rules_run,
            "n_files": self.n_files,
            "cache": {"enabled": self.cache_enabled,
                      "hits": self.cache_hits,
                      "misses": self.cache_misses},
            "findings": [dataclasses.asdict(f) for f in self.findings],
            "suppressed": [
                dict(dataclasses.asdict(f), reason=reason)
                for f, reason in self.suppressed
            ],
            "suppressions": [dataclasses.asdict(s)
                             for s in self.suppressions],
            "suppression_inventory": self.suppression_inventory(),
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2) + "\n"


def _run_rules(project: Project, selected: Sequence[str],
               cache: Optional[LintCache]) -> List[Finding]:
    """Raw (pre-suppression) findings, served from the cache where the
    content hashes allow."""
    raw: List[Finding] = []
    if cache is None:
        for rid in selected:
            raw.extend(RULES[rid].check(project))
        return raw

    digest = project_digest(project)
    shas = {f.rel: _sha1(f.text) for f in project.files}
    cache.prune(shas)
    for rid in selected:
        r = RULES[rid]
        if r.scope == "program":
            cached = cache.get_program(rid, digest)
            if cached is not None:
                cache.hits += 1
                raw.extend(cached)
            else:
                cache.misses += 1
                found = list(r.check(project))
                cache.put_program(rid, digest, found)
                raw.extend(found)
            continue
        # file scope: serve per-file hits, re-lint only the misses as
        # a sub-project (sound because a file-scope rule's findings
        # for a file depend only on that file's text)
        missing: List[SourceFile] = []
        for f in project.files:
            cached = cache.get_file(rid, f.rel, shas[f.rel])
            if cached is not None:
                cache.hits += 1
                raw.extend(cached)
            else:
                cache.misses += 1
                missing.append(f)
        if not missing:
            continue
        sub = Project(missing, project.root, project.aux)
        fresh = list(r.check(sub))
        by_rel: Dict[str, List[Finding]] = {f.rel: [] for f in missing}
        for fd in fresh:
            by_rel.setdefault(fd.path, []).append(fd)
        for f in missing:
            cache.put_file(rid, f.rel, shas[f.rel],
                           by_rel.get(f.rel, []))
        raw.extend(fresh)
    return raw


def run(project: Project, rules: Optional[Sequence[str]] = None,
        cache: Optional[LintCache] = None) -> Report:
    """Run ``rules`` (default: all registered) over ``project`` and
    fold in suppression pragmas + pragma hygiene. With ``cache``,
    unchanged (file sha, rule-set version) work is served from the
    content-hash cache and the hit/miss counts land in the report."""
    selected = list(rules) if rules is not None else sorted(RULES)
    unknown = [r for r in selected if r not in RULES]
    if unknown:
        raise ValueError(f"unknown rule(s) {unknown}; have {sorted(RULES)}")

    raw = _run_rules(project, selected, cache)

    findings: List[Finding] = []
    suppressed: List[tuple] = []
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.rule)):
        sf = project.by_rel.get(f.path)
        sup = sf.suppression_for(f.rule, f.line) if sf else None
        if sup is not None:
            sup.used = True
            suppressed.append((f, sup.reason))
        else:
            findings.append(f)

    # pragma hygiene rides rule R0 (it is style discipline); an unused
    # pragma only counts against rules that actually ran this pass
    inventory: List[Suppression] = []
    for sf in project.files:
        if "R0" in selected:
            for line, why in sf.bad_pragmas:
                findings.append(Finding("R0", sf.rel, line, why))
        for s in sf.suppressions:
            inventory.append(s)
            if "R0" not in selected:
                continue
            if s.rule not in RULES:
                findings.append(Finding(
                    "R0", sf.rel, s.pragma_line,
                    f"suppression names unknown rule {s.rule!r} "
                    f"(registered: {', '.join(sorted(RULES))}) — a "
                    "typo'd id silences nothing"))
            elif not s.used and s.rule in selected:
                findings.append(Finding(
                    "R0", sf.rel, s.pragma_line,
                    f"unused suppression of {s.rule} — the rule no "
                    "longer fires here; delete the pragma"))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    if cache is not None:
        cache.save()
    return Report(findings=findings, suppressed=suppressed,
                  suppressions=inventory, rules_run=selected,
                  n_files=len(project.files),
                  cache_hits=cache.hits if cache else 0,
                  cache_misses=cache.misses if cache else 0,
                  cache_enabled=cache is not None)
