"""R1 recompile-hazard and R2 donation-safety — the trace-discipline
rules defending PR 1's zero-recompile serving contract.

R1 has two teeth:

- **tracer control flow**: a Python ``if``/``while``/``for`` on a
  traced value inside a jit-traced body (``*_fn`` serving impls,
  ``shard_map``/``comms.run`` bodies, Pallas kernels, jit-decorated
  defs) either crashes at trace time (ConcretizationTypeError) or —
  worse — silently retraces per value when the operand is weakly
  concrete. Shape/metadata conditions (``x.ndim == 2``,
  ``fw is None``) are static and exempt.
- **cache-key discipline**: the executor's AOT cache keys (``_Plan``'s
  ``key=`` tuples and any ``key = (...)`` feeding them) must stay
  hashable statics — a bare list/set/dict display (not folded through
  ``tuple()``/``frozenset()``), or a ``float()``/``int()``/``.item()``
  of runtime data, makes the key unhashable or data-dependent and
  turns every search into a cache miss + recompile. The serving
  frontend's coalescing keys (``coalesce_key = (...)`` /
  ``compat_key = (...)`` / the ragged path's ``ragged_key`` /
  ``packing_key`` tuples and the ``compat_key=`` field of
  ``SearchRequest``) carry the same contract — an unhashable key there
  breaks request grouping, a data-dependent one silently splits every
  micro-batch (and on the ragged path would fork the ONE packed
  executable per load shape, resurrecting the bucket ladder). The
  mesh ragged plan keys (graftragged) extend the same discipline to
  RETURN position: ``ragged_key``/``coalesce_key``/``packing_key``
  functions build their tuples in the return expression, and a mesh
  key folding in device ids or wire-knob kwargs must keep them
  hashable statics (``tuple()``-wrapped, never a bare list display or
  a ``float()`` of runtime data).

R2 follows donated buffers: an argument donated to a jitted call
(``donate_argnums``/``donate_argnames`` at the ``jax.jit`` site, or
the repo's ``donate=True`` convention on ``extend``-style entry
points) is dead storage after the call — reading it again raises
jax's deleted-array error on backends that honor donation and
silently "works" on CPU, which is exactly the kind of
configuration-dependent regression this rule exists to catch.

**v2 (interprocedural escape).** Donation is tracked as *dotted
paths*, not bare names, and propagates across function boundaries
through the program graph:

- a donated buffer reached through an object field (``entry.state``)
  kills that path — and any alias it escaped into earlier
  (``self._plane = x`` before ``x`` is donated makes ``self._plane``
  dead too);
- a function that donates (a field of) one of its parameters without
  rebinding it before returning earns a *donation summary*; every
  resolved intra-repo call site applies the summary to its argument,
  so a read-after-donation two calls away from the ``jax.jit`` site
  is a finding in the caller;
- the blessed ``state = step(state)`` threading — rebinding the path
  on (or after) the donating call line — conforms at every level, as
  does the executor's documented donated-plane lifecycle (rebind
  before return kills the summary).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Set, Tuple

from raft_tpu.analysis import astutil, proggraph
from raft_tpu.analysis.core import Finding, Project, rule

_KEY_WRAPPERS = ("tuple", "frozenset")
_BANNED_DISPLAYS = (ast.List, ast.Set, ast.Dict, ast.ListComp,
                    ast.SetComp, ast.DictComp)


def _check_key_expr(f, expr: ast.AST, out: List[Finding]) -> None:
    """Flag unhashable displays and data-dependent scalars in a cache
    key expression."""

    def visit(n: ast.AST, wrapped: bool) -> None:
        if isinstance(n, _BANNED_DISPLAYS) and not wrapped:
            out.append(Finding(
                "R1", f.rel, n.lineno,
                f"unhashable {type(n).__name__.lower()} in an executor "
                "cache key — wrap it in tuple()/frozenset() so the AOT "
                "cache can hash it"))
            return
        if isinstance(n, ast.Call):
            nm = astutil.call_name(n) or ""
            leaf = nm.split(".")[-1]
            if leaf in ("float", "int") and n.args and not isinstance(
                    n.args[0], ast.Constant):
                out.append(Finding(
                    "R1", f.rel, n.lineno,
                    f"{leaf}() of runtime data in an executor cache key "
                    "— keys must be built from hashable statics, not "
                    "values pulled off arrays"))
            if leaf == "item":
                out.append(Finding(
                    "R1", f.rel, n.lineno,
                    ".item() in an executor cache key — a host sync per "
                    "lookup and a data-dependent key"))
            wrapped = wrapped or leaf in _KEY_WRAPPERS
        for child in ast.iter_child_nodes(n):
            visit(child, wrapped)

    visit(expr, False)


@rule("R1", "recompile-hazard")
def check_recompile(project: Project) -> Iterable[Finding]:
    """Python control flow on traced values inside jit-traced bodies,
    and unhashable / data-dependent executor cache keys."""
    out: List[Finding] = []
    for f in project.lib():
        if f.tree is None:
            continue
        for fn, traced, origin in astutil.traced_bodies(f.tree):
            body = fn.body if isinstance(fn.body, list) else []
            for stmt in astutil.walk_in_order(body):
                if isinstance(stmt, (ast.If, ast.While)):
                    hot = astutil.value_names(stmt.test) & traced
                    if hot:
                        kind = ("if" if isinstance(stmt, ast.If)
                                else "while")
                        out.append(Finding(
                            "R1", f.rel, stmt.lineno,
                            f"python `{kind}` on traced value(s) "
                            f"{sorted(hot)} inside {origin} body "
                            f"'{getattr(fn, 'name', '<lambda>')}' — "
                            "use lax.cond/jnp.where, or hoist the "
                            "decision to a static"))
                elif isinstance(stmt, ast.For):
                    hot = astutil.value_names(stmt.iter) & traced
                    if hot:
                        out.append(Finding(
                            "R1", f.rel, stmt.lineno,
                            f"python `for` over traced value(s) "
                            f"{sorted(hot)} inside {origin} body "
                            f"'{getattr(fn, 'name', '<lambda>')}' — "
                            "use lax.scan/fori_loop"))

        # cache-key discipline: `_Plan(key=...)` + the serving layer's
        # `SearchRequest(compat_key=...)`, the named key tuples that
        # feed either, and — since the mesh ragged plan family keys on
        # (mesh devices, params-class tuples, wire-knob kw) — every
        # RETURN of a key-returning function (`ragged_key` /
        # `coalesce_key` / `packing_key` / `mesh_key` spellings): a
        # list of device ids or a float() of runtime data in a mesh
        # ragged key is exactly as cache-fatal as in a `_Plan(key=)`
        # expression, and those keys are built in return position
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Call):
                nm = astutil.call_name(node) or ""
                if nm.split(".")[-1] in ("_Plan", "SearchRequest"):
                    for kw in node.keywords:
                        if kw.arg in ("key", "compat_key"):
                            _check_key_expr(f, kw.value, out)
            elif isinstance(node, ast.Assign):
                if (len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and node.targets[0].id in (
                            "key", "cache_key", "coalesce_key",
                            "compat_key", "ragged_key", "packing_key",
                            "mesh_ragged_key", "mesh_key")
                        and isinstance(node.value, ast.Tuple)):
                    _check_key_expr(f, node.value, out)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                # lstrip covers private spellings like _mesh_key — the
                # 2-D mesh identity tuple feeds every dist plan key, so
                # a lossy coercion there is cache-fatal mesh-wide
                if node.name.lstrip("_") in (
                        "ragged_key", "coalesce_key", "packing_key",
                        "mesh_ragged_key", "mesh_key"):
                    for stmt in ast.walk(node):
                        if (isinstance(stmt, ast.Return)
                                and isinstance(stmt.value, (
                                    ast.Tuple, ast.BinOp)
                                    + _BANNED_DISPLAYS)):
                            _check_key_expr(f, stmt.value, out)
    return out


# ---------------------------------------------------------------------------
# R2 — donation safety
# ---------------------------------------------------------------------------


def _positional_names(fn) -> list:
    return [a.arg for a in fn.args.posonlyargs + fn.args.args]


def _donated_argnums(call: ast.Call, resolve_fn=None) -> Optional[Set[int]]:
    """For a ``jax.jit(f, donate_argnums=...)`` /
    ``jax.jit(f, donate_argnames=...)`` call, the donated positional
    indices (None when the call is not a donating jit).
    ``donate_argnames`` needs the wrapped function's signature —
    ``resolve_fn`` maps its first argument to a local def when one is
    in scope."""
    nm = astutil.call_name(call) or ""
    if nm.split(".")[-1] != "jit":
        return None
    nums: Set[int] = set()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            nums |= {c.value for c in ast.walk(kw.value)
                     if isinstance(c, ast.Constant)
                     and isinstance(c.value, int)}
        if kw.arg == "donate_argnames" and resolve_fn is not None:
            names = {c.value for c in ast.walk(kw.value)
                     if isinstance(c, ast.Constant)
                     and isinstance(c.value, str)}
            fn = resolve_fn(call.args[0]) if call.args else None
            if fn is not None:
                pos = _positional_names(fn)
                nums |= {i for i, p in enumerate(pos) if p in names}
    return nums or None


def _decorator_donated_argnums(fn) -> Optional[Set[int]]:
    """Donated positional indices for the ``@partial(jax.jit,
    donate_argnums=...)`` / ``@jax.jit(donate_argnames=...)`` decorator
    forms — the shape 5 of the repo's 7 donation sites use."""
    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        nm = astutil.dotted(dec.func) or ""
        target = dec
        if nm.split(".")[-1] == "partial" and dec.args:
            inner = astutil.dotted(dec.args[0]) or ""
            if inner.split(".")[-1] != "jit":
                continue
        elif nm.split(".")[-1] != "jit":
            continue
        nums: Set[int] = set()
        pos = _positional_names(fn)
        for kw in target.keywords:
            if kw.arg == "donate_argnums":
                nums |= {c.value for c in ast.walk(kw.value)
                         if isinstance(c, ast.Constant)
                         and isinstance(c.value, int)}
            if kw.arg == "donate_argnames":
                names = {c.value for c in ast.walk(kw.value)
                         if isinstance(c, ast.Constant)
                         and isinstance(c.value, str)}
                nums |= {i for i, p in enumerate(pos) if p in names}
        if nums:
            return nums
    return None


def _prefixes(path: str) -> List[str]:
    """``entry.state`` → ``["entry", "entry.state"]`` — a store to any
    of them rebinds (part of) the donated region."""
    parts = path.split(".")
    return [".".join(parts[:i]) for i in range(1, len(parts) + 1)]


def _path_index(scope) -> Tuple[List[Tuple[int, str]],
                                Dict[str, List[int]]]:
    """Dotted-path loads and stores in ``scope`` (``del`` counts as a
    store — explicitly dropping a donated ref is the safe ending)."""
    loads: List[Tuple[int, str]] = []
    stores: Dict[str, List[int]] = {}
    for n in ast.walk(scope):
        if not isinstance(n, (ast.Name, ast.Attribute)):
            continue
        p = astutil.dotted(n)
        if p is None:
            continue
        if isinstance(n.ctx, ast.Load):
            loads.append((n.lineno, p))
        else:
            stores.setdefault(p, []).append(n.lineno)
    return loads, stores


def _scan_reads_after(f, call_stmt_line: int, call_end_line: int,
                      donated: Set[str], loads, stores,
                      out: List[Finding], how: str,
                      seen: Set[tuple]) -> None:
    """Flag loads of donated paths (or anything under them) after the
    donating call, up to the first rebind of the path or a prefix of
    it (a rebind on the call line itself is the blessed
    ``state = step(state)`` threading idiom). Loads count as "after"
    only past the call's last line — a multi-line call's own argument
    expressions are the donation, not a read-after."""
    for path in sorted(donated):
        rebinds = [ln for pre in _prefixes(path)
                   for ln in stores.get(pre, ())
                   if ln >= call_stmt_line]
        horizon = min(rebinds) if rebinds else float("inf")
        for ln, p in sorted(loads):
            if p != path and not p.startswith(path + "."):
                continue
            if not (call_end_line < ln < horizon):
                continue
            key = (f.rel, ln, path)
            if key not in seen:
                seen.add(key)
                out.append(Finding(
                    "R2", f.rel, ln,
                    f"'{path}' is read after being donated "
                    f"({how} at line {call_stmt_line}) — donated "
                    "buffers are deleted on donating backends; thread "
                    "the result instead"))
            break  # one finding per donated path per site is enough


def _escaped_aliases(scope, call_stmt_line: int,
                     donated: Set[str]) -> Set[str]:
    """Paths the donated buffer escaped into BEFORE the donating call:
    ``self._plane = x`` then ``donate(x)`` leaves ``self._plane``
    dangling too (one aliasing hop)."""
    extra: Set[str] = set()
    for stmt in ast.walk(scope):
        if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and stmt.lineno < call_stmt_line):
            continue
        target = astutil.dotted(stmt.targets[0])
        source = astutil.dotted(stmt.value)
        if not target or not source:
            continue
        for p in donated:
            if p == source or p.startswith(source + "."):
                extra.add(target + p[len(source):])
    return extra


def _module_donating(f, resolve_fn, all_fns) -> Dict[str, Set[int]]:
    """Donating callables visible from any scope of ``f``: module-level
    ``g = jax.jit(f, donate_*)`` bindings and decorator-form
    ``@partial(jax.jit, donate_*)`` defs (keyed by bare name)."""
    donating: Dict[str, Set[int]] = {}
    for stmt in astutil.walk_in_order(f.tree.body):
        if (isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and isinstance(stmt.value, ast.Call)):
            nums = _donated_argnums(stmt.value, resolve_fn)
            if nums:
                donating[stmt.targets[0].id] = nums
    for fn in all_fns:
        nums = _decorator_donated_argnums(fn)
        if nums:
            donating[fn.name] = nums
    return donating


def _local_bindings(body, resolve_fn) -> Dict[str, Set[int]]:
    """Names bound to a donating ``jax.jit(...)`` inside this scope."""
    donating: Dict[str, Set[int]] = {}
    for stmt in astutil.walk_in_order(body):
        if (isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and isinstance(stmt.value, ast.Call)):
            nums = _donated_argnums(stmt.value, resolve_fn)
            if nums:
                donating[stmt.targets[0].id] = nums
    return donating


def _direct_sites(scope, donating
                  ) -> List[Tuple[int, int, Set[str], str]]:
    """(line, end line, donated paths, how) for every jit-donation /
    ``donate=True`` call lexically in ``scope``."""
    body = getattr(scope, "body", [])
    if not isinstance(body, list):
        return []
    sites: List[Tuple[int, int, Set[str], str]] = []
    visited: Set[int] = set()
    for stmt in astutil.walk_in_order(body):
        for call in ast.walk(stmt):
            if not isinstance(call, ast.Call) or id(call) in visited:
                continue
            visited.add(id(call))
            nm = astutil.call_name(call) or ""
            donated: Set[str] = set()
            how = ""
            if isinstance(call.func, ast.Name) \
                    and call.func.id in donating:
                for i in donating[call.func.id]:
                    if i < len(call.args):
                        p = astutil.dotted(call.args[i])
                        if p:
                            donated.add(p)
                how = f"donate_argnums of '{call.func.id}'"
            elif any(kw.arg == "donate"
                     and isinstance(kw.value, ast.Constant)
                     and kw.value.value is True
                     for kw in call.keywords):
                # entry-point convention: fn(res, index, ...,
                # donate=True) donates the INDEX-owned buffers
                # (second positional or index= keyword) — later
                # args (new rows, ids) stay caller-owned
                donated = {p for p in (astutil.dotted(a)
                                       for a in call.args[1:2]) if p}
                donated |= {p for p in (astutil.dotted(kw.value)
                                        for kw in call.keywords
                                        if kw.arg == "index") if p}
                how = f"donate=True call to '{nm}'"
            if donated:
                sites.append((call.lineno,
                              call.end_lineno or call.lineno,
                              donated, how))
    return sites


# -- interprocedural summaries ----------------------------------------------


@dataclasses.dataclass
class _FnFacts:
    """Per-function facts feeding the donation-summary fixpoint."""

    info: proggraph.FunctionInfo
    params: List[str]
    direct: List[Tuple[int, int, Set[str], str]]
    loads: List[Tuple[int, str]]
    stores: Dict[str, List[int]]


def _is_static(fn_node) -> bool:
    return any((astutil.dotted(d) or "").split(".")[-1] == "staticmethod"
               for d in fn_node.decorator_list)


def _arg_for_param(call: ast.Call, callee: proggraph.FunctionInfo,
                   idx: int) -> Optional[ast.AST]:
    """The caller expression bound to the callee's positional param
    ``idx`` — methods bind the receiver to param 0 (``self``), so
    ``entry.claim()`` maps a ``(0, '.state')`` summary to
    ``entry.state`` in the caller."""
    pos = _positional_names(callee.node)
    shift = 0
    if callee.cls is not None and not _is_static(callee.node):
        if isinstance(call.func, ast.Attribute):
            if idx == 0:
                return call.func.value
        elif idx == 0:
            return None  # ClassName(...): the receiver is the new object
        shift = 1
    j = idx - shift
    if 0 <= j < len(call.args) \
            and not isinstance(call.args[j], ast.Starred):
        return call.args[j]
    if idx < len(pos):
        for kw in call.keywords:
            if kw.arg == pos[idx]:
                return kw.value
    return None


def _summary_paths(call: ast.Call, callee: proggraph.FunctionInfo,
                   summary) -> Set[str]:
    """Apply a callee's donation summary at one call site → the donated
    dotted paths in the caller's scope."""
    paths: Set[str] = set()
    for idx, suffix in summary:
        arg = _arg_for_param(call, callee, idx)
        p = astutil.dotted(arg) if arg is not None else None
        if p:
            paths.add(p + suffix)
    return paths


def _rebound(stores: Dict[str, List[int]], path: str,
             line: int) -> bool:
    return any(ln >= line for pre in _prefixes(path)
               for ln in stores.get(pre, ()))


def _collect_facts(graph, project) -> Dict[str, _FnFacts]:
    facts: Dict[str, _FnFacts] = {}
    for rel, mod in graph.modules.items():
        f = project.by_rel.get(rel)
        if f is None or f.tree is None:
            continue
        all_fns = astutil.collect_functions(f.tree)
        by_name: Dict[str, ast.AST] = {}
        for fn in all_fns:
            by_name.setdefault(fn.name, fn)

        def resolve_fn(arg, _by=by_name):
            return _by.get(arg.id) if isinstance(arg, ast.Name) else None

        module_donating = _module_donating(f, resolve_fn, all_fns)
        infos = list(mod.functions.values())
        for cls in mod.classes.values():
            infos.extend(cls.methods.values())
        for fi in infos:
            donating = dict(module_donating)
            donating.update(_local_bindings(fi.node.body, resolve_fn))
            loads, stores = _path_index(fi.node)
            facts[fi.qualname] = _FnFacts(
                info=fi, params=_positional_names(fi.node),
                direct=_direct_sites(fi.node, donating),
                loads=loads, stores=stores)
    return facts


def _summaries(graph, facts: Dict[str, _FnFacts]
               ) -> Dict[str, Set[Tuple[int, str]]]:
    """Fixpoint: ``summary[qualname] = {(param_index, attr_suffix)}``
    — paths of a parameter the function donates (directly, or through
    a summarized callee) and does NOT rebind before returning. A
    jit-decorated donating def seeds its declared argnums."""
    summ: Dict[str, Set[Tuple[int, str]]] = {}
    for qn, fi in graph.functions.items():
        nums = _decorator_donated_argnums(fi.node)
        if nums:
            summ[qn] = {(i, "") for i in nums}
    for _ in range(12):  # diameter cap; repo call chains are shallow
        changed = False
        for qn, fx in facts.items():
            new = set(summ.get(qn, set()))
            sites = list(fx.direct)
            for callee, call in graph.callees(fx.info):
                s = summ.get(callee.qualname)
                if s:
                    paths = _summary_paths(call, callee, s)
                    if paths:
                        sites.append((call.lineno,
                                      call.end_lineno or call.lineno,
                                      paths, ""))
            for line, _end, paths, _how in sites:
                for p in paths:
                    root = p.split(".", 1)[0]
                    if root not in fx.params:
                        continue
                    if _rebound(fx.stores, p, line):
                        continue
                    new.add((fx.params.index(root), p[len(root):]))
            if new != summ.get(qn, set()):
                summ[qn] = new
                changed = True
        if not changed:
            break
    return summ


@rule("R2", "donation-safety", scope="program")
def check_donation(project: Project) -> Iterable[Finding]:
    """Buffers donated to a jitted call (donate_argnums at the jax.jit
    site, or the ``donate=True`` entry-point convention) must not be
    read after the call site — tracked as dotted paths, through field
    escapes, and across function boundaries via donation summaries."""
    out: List[Finding] = []
    seen: Set[tuple] = set()
    graph = proggraph.get_graph(project)
    facts = _collect_facts(graph, project)
    summ = _summaries(graph, facts)
    by_node = {id(fx.info.node): fx for fx in facts.values()}

    for f in project.lib():
        if f.tree is None:
            continue
        all_fns = astutil.collect_functions(f.tree)
        by_name: Dict[str, ast.AST] = {}
        for fn in all_fns:
            by_name.setdefault(fn.name, fn)

        def resolve_fn(arg, _by=by_name):
            return _by.get(arg.id) if isinstance(arg, ast.Name) else None

        module_donating = _module_donating(f, resolve_fn, all_fns)
        for scope in [f.tree] + all_fns:
            body = getattr(scope, "body", [])
            if not isinstance(body, list):
                continue
            donating = dict(module_donating)
            donating.update(_local_bindings(body, resolve_fn))
            sites = _direct_sites(scope, donating)
            fx = by_node.get(id(scope))
            if fx is not None:
                # interprocedural: calls into functions whose summary
                # says they donate (a field of) this argument
                for callee, call in graph.callees(fx.info):
                    s = summ.get(callee.qualname)
                    if not s:
                        continue
                    paths = _summary_paths(call, callee, s)
                    if paths:
                        sites.append((
                            call.lineno,
                            call.end_lineno or call.lineno, paths,
                            f"donation escaping through "
                            f"'{callee.name}'"))
            if not sites:
                continue
            loads, stores = _path_index(scope)
            for line, end, paths, how in sites:
                paths = set(paths) | _escaped_aliases(scope, line, paths)
                _scan_reads_after(f, line, end, paths, loads, stores,
                                  out, how, seen)
    return out
