"""R1 recompile-hazard and R2 donation-safety — the trace-discipline
rules defending PR 1's zero-recompile serving contract.

R1 has two teeth:

- **tracer control flow**: a Python ``if``/``while``/``for`` on a
  traced value inside a jit-traced body (``*_fn`` serving impls,
  ``shard_map``/``comms.run`` bodies, Pallas kernels, jit-decorated
  defs) either crashes at trace time (ConcretizationTypeError) or —
  worse — silently retraces per value when the operand is weakly
  concrete. Shape/metadata conditions (``x.ndim == 2``,
  ``fw is None``) are static and exempt.
- **cache-key discipline**: the executor's AOT cache keys (``_Plan``'s
  ``key=`` tuples and any ``key = (...)`` feeding them) must stay
  hashable statics — a bare list/set/dict display (not folded through
  ``tuple()``/``frozenset()``), or a ``float()``/``int()``/``.item()``
  of runtime data, makes the key unhashable or data-dependent and
  turns every search into a cache miss + recompile. The serving
  frontend's coalescing keys (``coalesce_key = (...)`` /
  ``compat_key = (...)`` / the ragged path's ``ragged_key`` /
  ``packing_key`` tuples and the ``compat_key=`` field of
  ``SearchRequest``) carry the same contract — an unhashable key there
  breaks request grouping, a data-dependent one silently splits every
  micro-batch (and on the ragged path would fork the ONE packed
  executable per load shape, resurrecting the bucket ladder). The
  mesh ragged plan keys (graftragged) extend the same discipline to
  RETURN position: ``ragged_key``/``coalesce_key``/``packing_key``
  functions build their tuples in the return expression, and a mesh
  key folding in device ids or wire-knob kwargs must keep them
  hashable statics (``tuple()``-wrapped, never a bare list display or
  a ``float()`` of runtime data).

R2 follows donated buffers: an argument donated to a jitted call
(``donate_argnums``/``donate_argnames`` at the ``jax.jit`` site, or
the repo's ``donate=True`` convention on ``extend``-style entry
points) is dead storage after the call — reading it again raises
jax's deleted-array error on backends that honor donation and
silently "works" on CPU, which is exactly the kind of
configuration-dependent regression this rule exists to catch.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from raft_tpu.analysis import astutil
from raft_tpu.analysis.core import Finding, Project, rule

_KEY_WRAPPERS = ("tuple", "frozenset")
_BANNED_DISPLAYS = (ast.List, ast.Set, ast.Dict, ast.ListComp,
                    ast.SetComp, ast.DictComp)


def _check_key_expr(f, expr: ast.AST, out: List[Finding]) -> None:
    """Flag unhashable displays and data-dependent scalars in a cache
    key expression."""

    def visit(n: ast.AST, wrapped: bool) -> None:
        if isinstance(n, _BANNED_DISPLAYS) and not wrapped:
            out.append(Finding(
                "R1", f.rel, n.lineno,
                f"unhashable {type(n).__name__.lower()} in an executor "
                "cache key — wrap it in tuple()/frozenset() so the AOT "
                "cache can hash it"))
            return
        if isinstance(n, ast.Call):
            nm = astutil.call_name(n) or ""
            leaf = nm.split(".")[-1]
            if leaf in ("float", "int") and n.args and not isinstance(
                    n.args[0], ast.Constant):
                out.append(Finding(
                    "R1", f.rel, n.lineno,
                    f"{leaf}() of runtime data in an executor cache key "
                    "— keys must be built from hashable statics, not "
                    "values pulled off arrays"))
            if leaf == "item":
                out.append(Finding(
                    "R1", f.rel, n.lineno,
                    ".item() in an executor cache key — a host sync per "
                    "lookup and a data-dependent key"))
            wrapped = wrapped or leaf in _KEY_WRAPPERS
        for child in ast.iter_child_nodes(n):
            visit(child, wrapped)

    visit(expr, False)


@rule("R1", "recompile-hazard")
def check_recompile(project: Project) -> Iterable[Finding]:
    """Python control flow on traced values inside jit-traced bodies,
    and unhashable / data-dependent executor cache keys."""
    out: List[Finding] = []
    for f in project.lib():
        if f.tree is None:
            continue
        for fn, traced, origin in astutil.traced_bodies(f.tree):
            body = fn.body if isinstance(fn.body, list) else []
            for stmt in astutil.walk_in_order(body):
                if isinstance(stmt, (ast.If, ast.While)):
                    hot = astutil.value_names(stmt.test) & traced
                    if hot:
                        kind = ("if" if isinstance(stmt, ast.If)
                                else "while")
                        out.append(Finding(
                            "R1", f.rel, stmt.lineno,
                            f"python `{kind}` on traced value(s) "
                            f"{sorted(hot)} inside {origin} body "
                            f"'{getattr(fn, 'name', '<lambda>')}' — "
                            "use lax.cond/jnp.where, or hoist the "
                            "decision to a static"))
                elif isinstance(stmt, ast.For):
                    hot = astutil.value_names(stmt.iter) & traced
                    if hot:
                        out.append(Finding(
                            "R1", f.rel, stmt.lineno,
                            f"python `for` over traced value(s) "
                            f"{sorted(hot)} inside {origin} body "
                            f"'{getattr(fn, 'name', '<lambda>')}' — "
                            "use lax.scan/fori_loop"))

        # cache-key discipline: `_Plan(key=...)` + the serving layer's
        # `SearchRequest(compat_key=...)`, the named key tuples that
        # feed either, and — since the mesh ragged plan family keys on
        # (mesh devices, params-class tuples, wire-knob kw) — every
        # RETURN of a key-returning function (`ragged_key` /
        # `coalesce_key` / `packing_key` / `mesh_key` spellings): a
        # list of device ids or a float() of runtime data in a mesh
        # ragged key is exactly as cache-fatal as in a `_Plan(key=)`
        # expression, and those keys are built in return position
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Call):
                nm = astutil.call_name(node) or ""
                if nm.split(".")[-1] in ("_Plan", "SearchRequest"):
                    for kw in node.keywords:
                        if kw.arg in ("key", "compat_key"):
                            _check_key_expr(f, kw.value, out)
            elif isinstance(node, ast.Assign):
                if (len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and node.targets[0].id in (
                            "key", "cache_key", "coalesce_key",
                            "compat_key", "ragged_key", "packing_key",
                            "mesh_ragged_key", "mesh_key")
                        and isinstance(node.value, ast.Tuple)):
                    _check_key_expr(f, node.value, out)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                # lstrip covers private spellings like _mesh_key — the
                # 2-D mesh identity tuple feeds every dist plan key, so
                # a lossy coercion there is cache-fatal mesh-wide
                if node.name.lstrip("_") in (
                        "ragged_key", "coalesce_key", "packing_key",
                        "mesh_ragged_key", "mesh_key"):
                    for stmt in ast.walk(node):
                        if (isinstance(stmt, ast.Return)
                                and isinstance(stmt.value, (
                                    ast.Tuple, ast.BinOp)
                                    + _BANNED_DISPLAYS)):
                            _check_key_expr(f, stmt.value, out)
    return out


# ---------------------------------------------------------------------------
# R2 — donation safety
# ---------------------------------------------------------------------------


def _positional_names(fn) -> list:
    return [a.arg for a in fn.args.posonlyargs + fn.args.args]


def _donated_argnums(call: ast.Call, resolve_fn=None) -> Optional[Set[int]]:
    """For a ``jax.jit(f, donate_argnums=...)`` /
    ``jax.jit(f, donate_argnames=...)`` call, the donated positional
    indices (None when the call is not a donating jit).
    ``donate_argnames`` needs the wrapped function's signature —
    ``resolve_fn`` maps its first argument to a local def when one is
    in scope."""
    nm = astutil.call_name(call) or ""
    if nm.split(".")[-1] != "jit":
        return None
    nums: Set[int] = set()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            nums |= {c.value for c in ast.walk(kw.value)
                     if isinstance(c, ast.Constant)
                     and isinstance(c.value, int)}
        if kw.arg == "donate_argnames" and resolve_fn is not None:
            names = {c.value for c in ast.walk(kw.value)
                     if isinstance(c, ast.Constant)
                     and isinstance(c.value, str)}
            fn = resolve_fn(call.args[0]) if call.args else None
            if fn is not None:
                pos = _positional_names(fn)
                nums |= {i for i, p in enumerate(pos) if p in names}
    return nums or None


def _decorator_donated_argnums(fn) -> Optional[Set[int]]:
    """Donated positional indices for the ``@partial(jax.jit,
    donate_argnums=...)`` / ``@jax.jit(donate_argnames=...)`` decorator
    forms — the shape 5 of the repo's 7 donation sites use."""
    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        nm = astutil.dotted(dec.func) or ""
        target = dec
        if nm.split(".")[-1] == "partial" and dec.args:
            inner = astutil.dotted(dec.args[0]) or ""
            if inner.split(".")[-1] != "jit":
                continue
        elif nm.split(".")[-1] != "jit":
            continue
        nums: Set[int] = set()
        pos = _positional_names(fn)
        for kw in target.keywords:
            if kw.arg == "donate_argnums":
                nums |= {c.value for c in ast.walk(kw.value)
                         if isinstance(c, ast.Constant)
                         and isinstance(c.value, int)}
            if kw.arg == "donate_argnames":
                names = {c.value for c in ast.walk(kw.value)
                         if isinstance(c, ast.Constant)
                         and isinstance(c.value, str)}
                nums |= {i for i, p in enumerate(pos) if p in names}
        if nums:
            return nums
    return None


def _scan_reads_after(f, scope, call_stmt_line: int,
                      donated: Set[str], out: List[Finding],
                      how: str) -> None:
    """Flag loads of donated names after the donating call, up to the
    first rebind (a rebind on the call line itself is the blessed
    ``state = step(state)`` threading idiom)."""
    loads = []
    stores = {}
    for n in ast.walk(scope):
        if isinstance(n, ast.Name) and n.id in donated:
            if isinstance(n.ctx, ast.Load):
                loads.append((n.lineno, n.id))
            else:
                stores.setdefault(n.id, []).append(n.lineno)
    for name in donated:
        rebinds = [ln for ln in stores.get(name, ())
                   if ln >= call_stmt_line]
        horizon = min(rebinds) if rebinds else float("inf")
        for ln, nm in loads:
            if nm == name and call_stmt_line < ln < horizon:
                out.append(Finding(
                    "R2", f.rel, ln,
                    f"'{name}' is read after being donated "
                    f"({how} at line {call_stmt_line}) — donated "
                    "buffers are deleted on donating backends; thread "
                    "the result instead"))
                break  # one finding per donated name is enough


@rule("R2", "donation-safety")
def check_donation(project: Project) -> Iterable[Finding]:
    """Arguments donated to a jitted call (donate_argnums at the
    jax.jit site, or the ``donate=True`` entry-point convention) must
    not be read after the call site."""
    out: List[Finding] = []
    for f in project.lib():
        if f.tree is None:
            continue
        all_fns = astutil.collect_functions(f.tree)
        by_name = {}
        for fn in all_fns:
            by_name.setdefault(fn.name, fn)

        def resolve_fn(arg):
            return by_name.get(arg.id) if isinstance(arg, ast.Name) \
                else None

        # donating callables visible from any scope: module-level
        # `g = jax.jit(f, donate_*)` bindings and decorator-form
        # `@partial(jax.jit, donate_*)` defs
        module_donating: dict = {}
        for stmt in astutil.walk_in_order(f.tree.body):
            if (isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Call)):
                nums = _donated_argnums(stmt.value, resolve_fn)
                if nums:
                    module_donating[stmt.targets[0].id] = nums
        for fn in all_fns:
            nums = _decorator_donated_argnums(fn)
            if nums:
                module_donating[fn.name] = nums
        scopes = [f.tree] + all_fns
        for scope in scopes:
            body = getattr(scope, "body", [])
            if not isinstance(body, list):
                continue
            donating: dict = dict(module_donating)
            # pass 1: names bound to donating jax.jit(...) in this scope
            for stmt in astutil.walk_in_order(body):
                if (isinstance(stmt, ast.Assign)
                        and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)
                        and isinstance(stmt.value, ast.Call)):
                    nums = _donated_argnums(stmt.value, resolve_fn)
                    if nums:
                        donating[stmt.targets[0].id] = nums
            # pass 2: call sites
            for stmt in astutil.walk_in_order(body):
                for call in ast.walk(stmt):
                    if not isinstance(call, ast.Call):
                        continue
                    nm = astutil.call_name(call) or ""
                    donated: Set[str] = set()
                    how = ""
                    if isinstance(call.func, ast.Name) \
                            and call.func.id in donating:
                        for i in donating[call.func.id]:
                            if i < len(call.args) and isinstance(
                                    call.args[i], ast.Name):
                                donated.add(call.args[i].id)
                        how = f"donate_argnums of '{call.func.id}'"
                    elif any(kw.arg == "donate"
                             and isinstance(kw.value, ast.Constant)
                             and kw.value.value is True
                             for kw in call.keywords):
                        # entry-point convention: fn(res, index, ...,
                        # donate=True) donates the INDEX-owned buffers
                        # (second positional or index= keyword) — later
                        # args (new rows, ids) stay caller-owned
                        donated = {a.id for a in call.args[1:2]
                                   if isinstance(a, ast.Name)}
                        donated |= {kw.value.id for kw in call.keywords
                                    if kw.arg == "index"
                                    and isinstance(kw.value, ast.Name)}
                        how = f"donate=True call to '{nm}'"
                    if donated:
                        _scan_reads_after(f, scope, call.lineno,
                                          donated, out, how)
    return out
