"""R7 — clock discipline in the serving frontend.

The serving fault suite is deterministic because virtual time is the
ONLY time: ``DynamicBatcher`` takes an injectable clock, the manual
clock advances when the test says so, and every deadline / max-wait /
arrival-rate / span timestamp is computed from ``clock.now()``. One
direct ``time.time()`` / ``time.monotonic()`` / ``time.perf_counter()``
in a serving module silently re-couples that logic to the wall clock:
the manual-clock harness keeps passing (nothing *races*), but the
quantity it thinks it controls — an expiry decision, a span duration,
a rate estimate — is now measured in a different time domain and
drifts under load. This is the failure mode that only shows up as
flaky prod telemetry, which is why it is a lint rule and not a test.

Scope: ``raft_tpu/serving/*``. The one blessed location is the
injectable-clock plumbing itself — a class whose name ends in
``Clock`` (``MonotonicClock`` is the production implementation;
harness clocks override ``now``/``wait``). Everything else must take
timestamps from the clock object or from values stamped by it
(``req.arrival``, ``deadline``). Every import spelling is covered —
``time.monotonic()``, ``import time as t; t.monotonic()``, and
``from time import time`` alike. ``time.sleep`` is not flagged: the
harness's real-clock fallbacks sleep by design, and sleeping reads no
clock.

PR 7 (graftscope v2) widened the rule with the new span/SLO call
sites: serving code now records spans, SLO-window samples and
burn-rate timestamps wherever it runs, and the one evasion route the
``time``-module machinery missed was the ``datetime`` module —
``datetime.datetime.now()`` / ``.utcnow()`` / ``date.today()`` read
the wall clock just as surely and additionally smuggle in a *civil*
time that doesn't even share the monotonic clock's epoch. Any such
read feeding a span or SLO sample splits the recording across two
time domains, so they are findings under the same rule.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from raft_tpu.analysis import astutil
from raft_tpu.analysis.core import Finding, Project, rule

SERVING_PREFIXES = ("raft_tpu/serving/", "raft_tpu/fleet/")
SERVING_PREFIX = SERVING_PREFIXES[0]
# PR 13: graftledger's core module is additionally in scope — the
# ledger publishes through the same scrape machinery the serving
# frontend does, and a wall-clock read sneaking into it (a staleness
# age, a sample timestamp) would split that surface across two time
# domains exactly like a serving-module read would. The ledger keeps
# no timestamps today; the rule keeps it that way.
EXTRA_FILES = ("raft_tpu/core/memwatch.py",)

# the clock-reading members of the time module
CLOCK_FNS = {"time", "monotonic", "perf_counter",
             "time_ns", "monotonic_ns", "perf_counter_ns"}

# the clock-reading constructors of the datetime module's classes
DATETIME_CLOCK_FNS = {"now", "utcnow", "today"}
DATETIME_CLASSES = {"datetime", "date"}


def _clock_class_spans(tree: ast.AST) -> List[tuple]:
    """(first, last) line ranges of ``class *Clock`` definitions — the
    injectable-clock plumbing where direct clock reads belong."""
    spans = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name.endswith("Clock"):
            spans.append((node.lineno,
                          getattr(node, "end_lineno", node.lineno)))
    return spans


def _time_module_aliases(tree: ast.AST) -> Set[str]:
    """Local names bound to the ``time`` module (``import time``,
    ``import time as t``) — aliasing must not evade the rule."""
    aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    aliases.add(a.asname or "time")
    return aliases


def _clock_fn_imports(tree: ast.AST) -> Set[str]:
    """Local names bound to clock functions via ``from time import
    ...`` (``from time import time``, ``from time import monotonic as
    now``) — the bare-call evasion route."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name in CLOCK_FNS:
                    names.add(a.asname or a.name)
    return names


def _datetime_aliases(tree: ast.AST) -> Set[str]:
    """Local names bound to the ``datetime`` MODULE (``import
    datetime``, ``import datetime as dt``)."""
    aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "datetime":
                    aliases.add(a.asname or "datetime")
    return aliases


def _datetime_class_names(tree: ast.AST) -> Set[str]:
    """Local names bound to the ``datetime``/``date`` CLASSES via
    ``from datetime import ...`` — ``datetime.now()`` spelled bare."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "datetime":
            for a in node.names:
                if a.name in DATETIME_CLASSES:
                    names.add(a.asname or a.name)
    return names


def _is_datetime_clock_read(nm: str, mod_aliases: Set[str],
                            class_names: Set[str]) -> bool:
    """True when dotted call name ``nm`` reads the wall clock through
    the datetime module: ``<mod>.datetime.now()``, ``<mod>.date
    .today()``, or ``<class>.now()``/``.utcnow()``/``.today()``.
    Constructors that transform an existing timestamp VALUE
    (``fromtimestamp``, ``combine``…) read no clock and stay exempt."""
    if "." not in nm:
        return False
    parts = nm.split(".")
    if parts[-1] not in DATETIME_CLOCK_FNS:
        return False
    if parts[0] in mod_aliases and len(parts) == 3 \
            and parts[1] in DATETIME_CLASSES:
        return True
    return parts[0] in class_names and len(parts) == 2


@rule("R7", "clock-discipline")
def check_clock_discipline(project: Project) -> Iterable[Finding]:
    """Direct ``time.time()``/``time.monotonic()``/``time.perf_counter()``
    calls (any import spelling) in ``raft_tpu/serving/`` outside a
    ``*Clock`` class — they bypass the injectable clock, so the
    manual-clock fault harness no longer controls the quantity being
    measured."""
    out: List[Finding] = []
    for f in project.lib():
        if f.tree is None or (not f.rel.startswith(SERVING_PREFIXES)
                              and f.rel not in EXTRA_FILES):
            continue
        clock_spans = _clock_class_spans(f.tree)
        mod_aliases = _time_module_aliases(f.tree)
        bare_names = _clock_fn_imports(f.tree)
        dt_mod_aliases = _datetime_aliases(f.tree)
        dt_class_names = _datetime_class_names(f.tree)
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            nm = astutil.call_name(node)
            if nm is None:
                continue
            if _is_datetime_clock_read(nm, dt_mod_aliases,
                                       dt_class_names):
                pass
            elif "." in nm:
                mod, fn = nm.split(".", 1)
                if mod not in mod_aliases or fn not in CLOCK_FNS:
                    continue
            elif nm not in bare_names:
                # a bare name is a clock read only when this module
                # imported it from `time` — locals stay exempt
                continue
            if any(lo <= node.lineno <= hi for lo, hi in clock_spans):
                continue
            out.append(Finding(
                "R7", f.rel, node.lineno,
                f"{nm}() in a serving module bypasses the injectable "
                "clock — take timestamps from the batcher clock "
                "(clock.now() / req.arrival) or put this inside the "
                "*Clock plumbing, or the manual-clock fault harness "
                "stops being deterministic"))
    return out
