"""Lloyd's k-means — analog of ``raft::cluster::kmeans`` (``cluster/kmeans.cuh:88``).

API parity with the reference (``cluster/kmeans_types.hpp:39-70``):
fit / predict / fit_predict / transform / cluster_cost, k-means++ or random
or user-provided init, per-iteration convergence on inertia change, and
``find_k`` (auto-k via dispersion, ``detail/kmeans_auto_find_k.cuh``).

TPU mapping: the E-step is the fused GEMM+argmin of
:func:`raft_tpu.distance.fused_l2_nn_argmin_precomputed` (the reference's
``fusedL2NN`` hot loop, SURVEY.md §3.1); the M-step is a ``segment_sum``
scatter-add (the ``calc_centers_and_sizes`` kernel). The whole EM loop is a
single ``lax.while_loop`` jitted once per (n, d, k) shape.
"""

from __future__ import annotations

import dataclasses
import enum
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.validation import expect
from raft_tpu.distance.fused_l2_nn import _fused_l2_nn
from raft_tpu.distance.pairwise import pairwise_distance
from raft_tpu.distance.types import DistanceType


class InitMethod(enum.IntEnum):
    """Mirrors ``kmeans_params::InitMethod``."""

    KMeansPlusPlus = 0
    Random = 1
    Array = 2


@dataclasses.dataclass(frozen=True)
class KMeansParams:
    """Mirrors ``raft::cluster::kmeans::KMeansParams``."""

    n_clusters: int = 8
    init: InitMethod = InitMethod.KMeansPlusPlus
    max_iter: int = 300
    tol: float = 1e-4
    metric: DistanceType = DistanceType.L2Expanded
    seed: int = 0
    oversampling_factor: float = 2.0  # kept for API parity (|| init)
    batch_samples: int = 1 << 15      # mini-batch E-step tile
    # wire format of the distributed EM's per-iteration centroid-sum
    # allreduce (f32|bf16|int8|auto — raft_tpu.distributed.kmeans.fit);
    # the single-chip fit has no wire and ignores it
    wire_dtype: str = "f32"


def _check_metric(params: "KMeansParams") -> None:
    """Lloyd's clustering here is L2-only (as the reference's main path);
    reject other metrics instead of silently clustering with L2."""
    expect(
        params.metric in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded),
        f"kmeans supports L2Expanded/L2SqrtExpanded, got {params.metric!r}",
    )


def _predict_labels(x, centroids, tile: int = 2048):
    """E-step: nearest centroid per point (squared L2)."""
    c_sq = jnp.sum(jnp.square(centroids.astype(jnp.float32)), axis=1)
    dist, labels = _fused_l2_nn(x, centroids, c_sq, False,
                                min(tile, max(64, centroids.shape[0])))
    return dist, labels


def _calc_centers_and_sizes(x, labels, n_clusters: int):
    """M-step: per-cluster mean + population — the scatter-add kernel
    ``detail/kmeans_balanced.cuh:257`` as a segment_sum."""
    sums = jax.ops.segment_sum(x, labels, num_segments=n_clusters)
    sizes = jax.ops.segment_sum(
        jnp.ones((x.shape[0],), jnp.float32), labels, num_segments=n_clusters
    )
    centers = sums / jnp.maximum(sizes, 1.0)[:, None]
    return centers, sizes


def _kmeanspp_init(key, x, n_clusters: int):
    """Greedy k-means++ seeding (role of ``detail/kmeans.cuh``
    kmeansPlusPlus, which likewise evaluates ``2 + log(k)`` candidate
    samples per step): draw L candidates ∝ current min squared distance,
    keep the one minimizing the resulting total potential."""
    n = x.shape[0]
    n_trials = 2 + int(np.ceil(np.log(max(n_clusters, 2))))
    k0, key = jax.random.split(key)
    first = jax.random.randint(k0, (), 0, n)
    centers0 = jnp.zeros((n_clusters, x.shape[1]), x.dtype).at[0].set(x[first])
    d0 = jnp.sum(jnp.square(x - x[first][None, :]), axis=1)

    def body(i, state):
        centers, min_d, key = state
        key, kc = jax.random.split(key)
        logits = jnp.log(jnp.maximum(min_d, 1e-30))
        cand = jax.random.categorical(kc, logits, shape=(n_trials,))
        cand_pts = x[cand]                                     # (L, d)
        d_cand = (
            jnp.sum(jnp.square(x), axis=1)[None, :]
            - 2.0 * cand_pts @ x.T
            + jnp.sum(jnp.square(cand_pts), axis=1)[:, None]
        )                                                      # (L, n)
        pot = jnp.sum(jnp.minimum(min_d[None, :], d_cand), axis=1)
        best = jnp.argmin(pot)
        c = cand_pts[best]
        centers = centers.at[i].set(c)
        return centers, jnp.minimum(min_d, d_cand[best]), key

    centers, _, _ = jax.lax.fori_loop(1, n_clusters, body, (centers0, d0, key))
    return centers


@partial(jax.jit, static_argnames=("n_clusters", "max_iter", "init"))
def _fit_impl(x, key, n_clusters: int, max_iter: int, tol, init: InitMethod,
              init_centroids=None):
    n = x.shape[0]
    if init == InitMethod.Array:
        centroids = init_centroids.astype(x.dtype)
    elif init == InitMethod.Random:
        idx = jax.random.choice(key, n, (n_clusters,), replace=False)
        centroids = x[idx]
    else:
        centroids = _kmeanspp_init(key, x, n_clusters)

    def cond(state):
        _, it, prev_inertia, inertia, _ = state
        rel = jnp.abs(prev_inertia - inertia) / jnp.maximum(prev_inertia, 1e-30)
        return jnp.logical_and(it < max_iter, rel > tol)

    def body(state):
        centroids, it, _, inertia, _ = state
        dist, labels = _predict_labels(x, centroids)
        new_inertia = jnp.sum(dist)
        new_centers, sizes = _calc_centers_and_sizes(x, labels, n_clusters)
        # keep previous center for empty clusters
        new_centers = jnp.where((sizes > 0)[:, None], new_centers, centroids)
        return new_centers, it + 1, inertia, new_inertia, labels

    # finite sentinels: inf would make the relative-change test NaN on the
    # first evaluation and skip the loop entirely
    init_state = (
        centroids,
        jnp.int32(0),
        jnp.float32(jnp.finfo(jnp.float32).max),
        jnp.float32(jnp.finfo(jnp.float32).max / 4),
        jnp.zeros((n,), jnp.int32),
    )
    centroids, n_iter, _, inertia, labels = jax.lax.while_loop(cond, body, init_state)
    # final E-step so labels/inertia match returned centroids
    dist, labels = _predict_labels(x, centroids)
    return centroids, labels, jnp.sum(dist), n_iter


def fit(
    res: Optional[Resources],
    params: KMeansParams,
    x,
    init_centroids=None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Train k-means; returns (centroids, inertia, n_iter)
    (``kmeans::fit``, ``cluster/kmeans.cuh:88``).

    Examples
    --------
    >>> import numpy as np
    >>> from raft_tpu.cluster import kmeans
    >>> x = np.asarray([[0.0], [0.1], [10.0], [10.1]], np.float32)
    >>> c, inertia, n_iter = kmeans.fit(
    ...     None, kmeans.KMeansParams(n_clusters=2, seed=0), x)
    >>> sorted(round(float(v)) for v in np.asarray(c).ravel())
    [0, 10]
    """
    res = ensure_resources(res)
    x = jnp.asarray(x, jnp.float32)
    expect(x.ndim == 2, "x must be (n_samples, n_features)")
    expect(params.n_clusters <= x.shape[0], "n_clusters > n_samples")
    _check_metric(params)
    key = jax.random.fold_in(jax.random.key(params.seed), 0)
    with tracing.range("raft_tpu.kmeans.fit"):
        centroids, _, inertia, n_iter = _fit_impl(
            x, key, params.n_clusters, params.max_iter,
            jnp.float32(params.tol), params.init,
            None if init_centroids is None else jnp.asarray(init_centroids),
        )
    return centroids, inertia, n_iter


def predict(res, params: KMeansParams, centroids, x) -> Tuple[jax.Array, jax.Array]:
    """Assign each point to the nearest centroid; returns (labels, inertia)."""
    ensure_resources(res)
    _check_metric(params)
    x = jnp.asarray(x, jnp.float32)
    dist, labels = _predict_labels(x, jnp.asarray(centroids, jnp.float32))
    return labels, jnp.sum(dist)


def fit_predict(res, params: KMeansParams, x, init_centroids=None):
    """Train and label in one pass — reuses the labels from fit's final
    E-step instead of re-running predict."""
    res = ensure_resources(res)
    x = jnp.asarray(x, jnp.float32)
    expect(x.ndim == 2, "x must be (n_samples, n_features)")
    expect(params.n_clusters <= x.shape[0], "n_clusters > n_samples")
    _check_metric(params)
    key = jax.random.fold_in(jax.random.key(params.seed), 0)
    with tracing.range("raft_tpu.kmeans.fit_predict"):
        centroids, labels, inertia, n_iter = _fit_impl(
            x, key, params.n_clusters, params.max_iter,
            jnp.float32(params.tol), params.init, None,
        )
    return centroids, labels, inertia, n_iter


def transform(res, params: KMeansParams, centroids, x) -> jax.Array:
    """Distance from every point to every centroid (``kmeans::transform``)."""
    res = ensure_resources(res)
    return pairwise_distance(res, jnp.asarray(x, jnp.float32),
                             jnp.asarray(centroids, jnp.float32), params.metric)


def cluster_cost(res, centroids, x) -> jax.Array:
    """Sum of squared distances to nearest centroid
    (``raft_runtime::cluster::kmeans::cluster_cost``)."""
    ensure_resources(res)
    dist, _ = _predict_labels(jnp.asarray(x, jnp.float32),
                              jnp.asarray(centroids, jnp.float32))
    return jnp.sum(dist)


def update_centroids(res, x, centroids, sample_weights=None):
    """One M-step: assign points to their nearest centroid and return the
    (weighted) per-cluster means — ``compute_new_centroids``
    (``pylibraft.cluster.kmeans.compute_new_centroids``). Empty clusters
    keep their previous centroid. Returns (new_centroids, labels)."""
    ensure_resources(res)
    x = jnp.asarray(x, jnp.float32)
    centroids = jnp.asarray(centroids, jnp.float32)
    k = centroids.shape[0]
    _, labels = _predict_labels(x, centroids)
    if sample_weights is None:
        sums, sizes = _calc_centers_and_sizes(x, labels, k)
        new = jnp.where((sizes > 0)[:, None], sums, centroids)
    else:
        w = jnp.asarray(sample_weights, jnp.float32)
        sums = jax.ops.segment_sum(x * w[:, None], labels, num_segments=k)
        wsum = jax.ops.segment_sum(w, labels, num_segments=k)
        new = jnp.where((wsum > 0)[:, None],
                        sums / jnp.maximum(wsum, 1e-30)[:, None], centroids)
    return new, labels


def find_k(
    res: Optional[Resources],
    x,
    k_max: int = 20,
    k_min: int = 2,
    max_iter: int = 100,
) -> Tuple[int, jax.Array]:
    """Auto-select k — role of ``detail/kmeans_auto_find_k.cuh`` (which
    maximizes a cluster-dispersion objective). Here: the Sugar–James jump
    method on distortion, robust for the well-separated case the reference
    targets: d_k = inertia/(n·dim); pick k maximizing
    d_k^(-dim/2) - d_{k-1}^(-dim/2)."""
    res = ensure_resources(res)
    x = jnp.asarray(x, jnp.float32)
    n, dim = x.shape
    power = -dim / 2.0
    inertias = {}
    prev_t = None
    best_k, best_jump, best_inertia = k_min, -float("inf"), None
    for k in range(max(1, k_min - 1), k_max + 1):
        params = KMeansParams(n_clusters=k, max_iter=max_iter, seed=res.seed)
        _, inertia, _ = fit(res, params, x)
        inertias[k] = inertia
        distortion = max(float(inertia) / (n * dim), 1e-30)
        t = distortion**power
        if prev_t is not None and k >= k_min:
            jump = t - prev_t
            if jump > best_jump:
                best_k, best_jump, best_inertia = k, jump, inertia
        prev_t = t
    return best_k, best_inertia
