"""Single-linkage agglomerative clustering — analog of
``cluster/single_linkage.cuh`` + ``cluster/detail/{mst,connectivities,
agglomerative}.cuh``: kNN-graph connectivity → MST → dendrogram → flat cut.

TPU re-design: graph construction, symmetrization and Borůvka MST run as
static-shape XLA programs (``raft_tpu.sparse``); the O(n) dendrogram
build is an inherently sequential union-find over the n-1 sorted MST
edges and runs on host (the reference also label-propagates on a serial
dependency chain there — it is not a hot loop).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp
import numpy as np

from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.validation import expect
from raft_tpu.distance.types import DistanceType


@dataclasses.dataclass
class SingleLinkageOutput:
    """``linkage_output`` analog (``cluster/single_linkage_types.hpp``)."""

    labels: np.ndarray        # (n,) flat cluster assignment
    children: np.ndarray      # (n-1, 2) merged pair per dendrogram step
    deltas: np.ndarray        # (n-1,) merge distances
    sizes: np.ndarray         # (n-1,) size of the merged cluster
    n_clusters: int


def _mst_edges_connected(res, x, k, metric):
    """kNN-graph MST; reconnects forest components with
    cross_component_nn edges until a single tree remains (the reference's
    connect_components loop in ``detail/mst.cuh``)."""
    from raft_tpu.sparse.linalg import coo_symmetrize
    from raft_tpu.sparse.convert import coo_to_csr
    from raft_tpu.sparse.neighbors import cross_component_nn, knn_graph
    from raft_tpu.sparse.solver import mst
    from raft_tpu.sparse.types import COO

    n = x.shape[0]
    g = knn_graph(res, x, k, metric)
    for _ in range(int(np.ceil(np.log2(max(n, 2)))) + 1):
        sym = coo_symmetrize(g)
        result = mst(res, coo_to_csr(sym))
        color = np.asarray(result.color)
        if len(np.unique(color)) == 1:
            return result
        extra = cross_component_nn(res, x, jnp.asarray(color), metric)
        g = COO(
            jnp.concatenate([g.rows, extra.rows]),
            jnp.concatenate([g.cols, extra.cols]),
            jnp.concatenate([g.vals, extra.vals]),
            (n, n),
        )
    raise RuntimeError("single_linkage: could not connect kNN graph")


def single_linkage(
    res: Optional[Resources],
    x,
    n_clusters: int,
    *,
    metric: DistanceType = DistanceType.L2SqrtExpanded,
    k: int = 15,
) -> SingleLinkageOutput:
    """Flat single-linkage clustering — ``cluster::single_linkage``
    (``single_linkage.cuh``; the reference's KNN-graph 'connectivity'
    mode with ``c``-neighborhood = k)."""
    res = ensure_resources(res)
    x = jnp.asarray(x)
    n = x.shape[0]
    expect(1 <= n_clusters <= n, "single_linkage: bad n_clusters")

    with tracing.range("raft_tpu.cluster.single_linkage"):
        result = _mst_edges_connected(res, x, k, metric)
        src = np.asarray(result.src)
        dst = np.asarray(result.dst)
        w = np.asarray(result.weights)
        valid = src >= 0
        src, dst, w = src[valid], dst[valid], w[valid]
        order = np.argsort(w, kind="stable")
        src, dst, w = src[order], dst[order], w[order]
        expect(len(src) == n - 1, "single_linkage: MST is not a tree")

        # sequential union-find dendrogram (scipy 'children' convention:
        # cluster ids >= n denote merged clusters, id = n + step)
        parent = np.arange(2 * n - 1)
        cluster_of = np.arange(n)           # current cluster id per root
        size = np.ones(2 * n - 1, dtype=np.int64)

        def find(a):
            root = a
            while parent[root] != root:
                root = parent[root]
            while parent[a] != root:
                parent[a], a = root, parent[a]
            return root

        children = np.zeros((n - 1, 2), dtype=np.int64)
        sizes = np.zeros(n - 1, dtype=np.int64)
        for step in range(n - 1):
            ra, rb = find(src[step]), find(dst[step])
            ca, cb = cluster_of[ra], cluster_of[rb]
            new_id = n + step
            children[step] = (min(ca, cb), max(ca, cb))
            parent[ra] = parent[rb] = new_id
            cluster_of = np.append(cluster_of, 0)  # grown lazily below
            size[new_id] = size[ra] + size[rb]
            sizes[step] = size[new_id]
            cluster_of = cluster_of[: 2 * n - 1]
            cluster_of[new_id] = new_id

        # flat cut: drop the n_clusters-1 largest merges
        keep = n - 1 - (n_clusters - 1)
        parent2 = np.arange(n)

        def find2(a):
            while parent2[a] != a:
                parent2[a] = parent2[parent2[a]]
                a = parent2[a]
            return a

        for step in range(keep):
            ra, rb = find2(src[step]), find2(dst[step])
            parent2[ra] = rb
        roots = np.array([find2(i) for i in range(n)])
        _, labels = np.unique(roots, return_inverse=True)
        return SingleLinkageOutput(
            labels=labels.astype(np.int32),
            children=children,
            deltas=w,
            sizes=sizes,
            n_clusters=n_clusters,
        )
