"""Clustering algorithms (reference ``raft/cluster/``)."""

from raft_tpu.cluster import kmeans
from raft_tpu.cluster import kmeans_balanced
from raft_tpu.cluster.kmeans import KMeansParams, InitMethod
from raft_tpu.cluster.kmeans_balanced import KMeansBalancedParams

__all__ = [
    "kmeans",
    "kmeans_balanced",
    "KMeansParams",
    "InitMethod",
    "KMeansBalancedParams",
]
