"""Clustering algorithms (reference ``raft/cluster/``)."""

from raft_tpu.cluster import kmeans
from raft_tpu.cluster import kmeans_balanced
from raft_tpu.cluster.kmeans import KMeansParams, InitMethod
from raft_tpu.cluster.kmeans_balanced import KMeansBalancedParams
from raft_tpu.cluster.single_linkage import SingleLinkageOutput, single_linkage

__all__ = [
    "kmeans",
    "kmeans_balanced",
    "KMeansParams",
    "InitMethod",
    "KMeansBalancedParams",
    "SingleLinkageOutput",
    "single_linkage",
]
