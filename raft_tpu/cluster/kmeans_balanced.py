"""Balanced k-means — analog of ``raft::cluster::kmeans_balanced``
(``cluster/kmeans_balanced.cuh:76``), the trainer behind IVF coarse
quantizers and PQ codebooks.

Reference semantics mirrored from ``detail/kmeans_balanced.cuh``:

- EM iterations (``balancing_em_iters:618``): predict → recompute centers
  (``calc_centers_and_sizes:257``) with a **balancing step** between
  iterations (``adjust_centers:524``): any cluster smaller than
  ``avg_size * balancing_threshold`` (0.25) is pulled toward a random
  sample from a large (≥ average) cluster with weight
  ``wc = min(size, 7)`` vs ``wd = 1`` (``kAdjustCentersWeight``,
  ``detail/kmeans_balanced.cuh:61,473``).
- For InnerProduct/Cosine/Correlation metrics centers are L2-normalized
  every iteration to avoid collapse to zero (``:655-670``).

TPU re-design: the predict step is the fused GEMM+argmin; the center
update is a ``segment_sum``; the adjust step is fully vectorized (one
weighted random point drawn per cluster instead of the CUDA atomic-counter
walk — same distributional intent, deterministic under a PRNG key). The
whole trainer is one jitted ``fori_loop``.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.validation import expect
from raft_tpu.distance.fused_l2_nn import _fused_l2_nn
from raft_tpu.distance.types import DistanceType

_ADJUST_CENTERS_WEIGHT = 7.0  # kAdjustCentersWeight
_BALANCING_THRESHOLD = 0.25   # default balancing_threshold

_NORMALIZED_METRICS = (
    DistanceType.InnerProduct,
    DistanceType.CosineExpanded,
    DistanceType.CorrelationExpanded,
)


@dataclasses.dataclass(frozen=True)
class KMeansBalancedParams:
    """Mirrors ``raft::cluster::kmeans_balanced_params``
    (``cluster/kmeans_balanced_types.hpp:38``)."""

    n_iters: int = 20
    metric: DistanceType = DistanceType.L2Expanded
    seed: int = 0


def _predict_impl(x, centroids, metric: DistanceType):
    """Nearest center under L2 or (normalized-center) inner product —
    ``detail/kmeans_balanced.cuh:371`` ``predict``."""
    if metric in _NORMALIZED_METRICS:
        sims = jax.lax.dot_general(
            x, centroids, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        labels = jnp.argmax(sims, axis=1).astype(jnp.int32)
        return labels
    c_sq = jnp.sum(jnp.square(centroids), axis=1)
    _, labels = _fused_l2_nn(x, centroids, c_sq, False,
                             min(2048, max(64, centroids.shape[0])))
    return labels


def _calc_centers_and_sizes(x, labels, n_clusters: int):
    sums = jax.ops.segment_sum(x, labels, num_segments=n_clusters)
    sizes = jax.ops.segment_sum(
        jnp.ones((x.shape[0],), jnp.float32), labels, num_segments=n_clusters
    )
    centers = sums / jnp.maximum(sizes, 1.0)[:, None]
    return centers, sizes


def _normalize_rows(c):
    n = jnp.linalg.norm(c, axis=1, keepdims=True)
    return c / jnp.maximum(n, 1e-12)


def _adjust_centers(key, centers, sizes, x, labels, n_clusters: int):
    """Vectorized balancing step (``adjust_centers_kernel``,
    ``detail/kmeans_balanced.cuh:438-483``)."""
    n = x.shape[0]
    average = n / n_clusters
    small = sizes < average * _BALANCING_THRESHOLD
    # draw one candidate point per cluster, weighted toward rows whose own
    # cluster is at least average-sized (the reference's do/while walk)
    weights = (sizes[labels] >= average).astype(jnp.float32) + 1e-6
    cand = jax.random.choice(key, n, (n_clusters,), replace=True, p=weights / weights.sum())
    points = x[cand]
    wc = jnp.minimum(sizes, _ADJUST_CENTERS_WEIGHT)[:, None]
    pulled = (wc * centers + points) / (wc + 1.0)
    return jnp.where(small[:, None], pulled, centers), jnp.any(small)


@partial(jax.jit, static_argnames=("n_clusters", "n_iters", "metric"))
def _fit_impl(x, key, n_clusters: int, n_iters: int, metric: DistanceType):
    n = x.shape[0]
    k_init, k_adjust = jax.random.split(key)
    # init: uniform subsample of the dataset (reference seeds from a strided
    # subsample of the trainset)
    idx = jax.random.choice(k_init, n, (n_clusters,), replace=False)
    centers = x[idx]
    if metric in _NORMALIZED_METRICS:
        centers = _normalize_rows(centers)

    def body(it, state):
        centers, sizes, labels = state
        # balancing step (not on the first iteration)
        def do_adjust(c):
            adjusted, _ = _adjust_centers(
                jax.random.fold_in(k_adjust, it), c, sizes, x, labels, n_clusters
            )
            return adjusted

        centers = jax.lax.cond(it > 0, do_adjust, lambda c: c, centers)
        if metric in _NORMALIZED_METRICS:
            centers = _normalize_rows(centers)
        labels = _predict_impl(x, centers, metric)
        new_centers, sizes = _calc_centers_and_sizes(x, labels, n_clusters)
        new_centers = jnp.where((sizes > 0)[:, None], new_centers, centers)
        return new_centers, sizes, labels

    init = (
        centers,
        jnp.zeros((n_clusters,), jnp.float32),
        jnp.zeros((n,), jnp.int32),
    )
    centers, sizes, labels = jax.lax.fori_loop(0, n_iters, body, init)
    if metric in _NORMALIZED_METRICS:
        centers = _normalize_rows(centers)
    return centers, labels, sizes


def fit(
    res: Optional[Resources],
    params: KMeansBalancedParams,
    x,
    n_clusters: int,
) -> jax.Array:
    """Train balanced k-means; returns centroids (n_clusters, d) float32
    (``kmeans_balanced::fit``, ``cluster/kmeans_balanced.cuh:76``)."""
    res = ensure_resources(res)
    x = jnp.asarray(x, jnp.float32)
    expect(x.ndim == 2, "x must be 2-D")
    expect(n_clusters <= x.shape[0], "n_clusters > n_samples")
    key = jax.random.key(params.seed)
    with tracing.range("raft_tpu.kmeans_balanced.fit"):
        centers, _, _ = _fit_impl(x, key, n_clusters, params.n_iters, params.metric)
    return centers


def predict(
    res: Optional[Resources],
    params: KMeansBalancedParams,
    centroids,
    x,
) -> jax.Array:
    """Label each row with its nearest centroid
    (``kmeans_balanced::predict``)."""
    ensure_resources(res)
    x = jnp.asarray(x, jnp.float32)
    centroids = jnp.asarray(centroids, jnp.float32)
    with tracing.range("raft_tpu.kmeans_balanced.predict"):
        return _predict_impl(x, centroids, params.metric)


def fit_predict(res, params: KMeansBalancedParams, x, n_clusters: int):
    centroids = fit(res, params, x, n_clusters)
    return centroids, predict(res, params, centroids, x)


def build_clusters(
    res: Optional[Resources],
    params: KMeansBalancedParams,
    x,
    n_clusters: int,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Train + label + sizes in one call — the IVF build entry
    (``kmeans_balanced::helpers::build_clusters``,
    ``cluster/kmeans_balanced.cuh:258``)."""
    res = ensure_resources(res)
    x = jnp.asarray(x, jnp.float32)
    key = jax.random.key(params.seed)
    centers, labels, sizes = _fit_impl(x, key, n_clusters, params.n_iters, params.metric)
    return centers, labels, sizes.astype(jnp.int32)


def calc_centers_and_sizes(x, labels, n_clusters: int):
    """Public helper mirroring ``kmeans_balanced::helpers::
    calc_centers_and_sizes`` (``cluster/kmeans_balanced.cuh:337``)."""
    x = jnp.asarray(x, jnp.float32)
    labels = jnp.asarray(labels, jnp.int32)
    centers, sizes = _calc_centers_and_sizes(x, labels, n_clusters)
    return centers, sizes.astype(jnp.int32)
