"""Matrix primitives incl. batched k-selection (reference ``raft/matrix/``)."""

from raft_tpu.matrix.select_k import select_k, SelectAlgo
from raft_tpu.matrix.ops import (
    gather,
    gather_if,
    scatter,
    slice,
    argmax,
    argmin,
    col_sort,
    linewise_op,
    reverse,
    triangular_upper,
    triangular_lower,
    matrix_print,
)

__all__ = [
    "select_k",
    "SelectAlgo",
    "gather",
    "gather_if",
    "scatter",
    "slice",
    "argmax",
    "argmin",
    "col_sort",
    "linewise_op",
    "reverse",
    "triangular_upper",
    "triangular_lower",
    "matrix_print",
]
