"""Batched k-selection — analog of ``matrix::select_k``
(``matrix/select_k.cuh:81``).

The reference ships three CUDA algorithm families (11-bit multi-pass radix,
warp-bitonic sort variants, FAISS block-select) behind a learned
decision-tree dispatcher (``matrix/detail/select_k-inl.cuh:219-268``). On
TPU the analogous fast path is XLA's native ``lax.top_k`` / ``approx_max_k``
(which lowers onto the TPU's sort/top-k units — the TPU-KNN paper's peak
FLOP/s recipe), so the dispatcher here selects between:

- ``TOPK``: exact ``lax.top_k`` (default; O(n log k), fully fused)
- ``APPROX``: ``lax.approx_max_k``/``approx_min_k`` with configurable
  recall target — the TPU-idiomatic answer to radix select for large n
- ``SORT``: full sort fallback (exact, stable ties like the reference's
  warpsort "stable" variants)

All return (values, indices) of shape (batch, k), matching the reference's
``select_k`` semantics including select_min direction.
"""

from __future__ import annotations

import enum
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.validation import expect


class SelectAlgo(enum.Enum):
    """Mirrors ``matrix::SelectAlgo`` (``matrix/select_k.cuh``) re-based on
    the TPU backend's real choices."""

    AUTO = "auto"
    TOPK = "topk"          # exact lax.top_k
    APPROX = "approx"      # lax.approx_min_k / approx_max_k
    SORT = "sort"          # full sort (exact + stable)
    TILES = "tiles"        # streamed Pallas merge (ops.select_k_tiles)


# TILES routing thresholds: below this width lax.top_k's fused lowering
# wins; above it the streamed merge reads the row once at HBM rate.
# The merge network unrolls k rounds, so big k stays on top_k.
_TILES_MIN_N = 16384
_TILES_MAX_K = 64


def _choose_algo(batch: int, n: int, k: int,
                 dtype=jnp.float32) -> SelectAlgo:
    """Heuristic dispatcher (role of ``choose_select_k_algorithm``,
    ``matrix/detail/select_k-inl.cuh:219``). AUTO always resolves to an
    *exact* algorithm — the reference's select_k is exact, so the
    approximate TPU top-k (``lax.approx_min_k``) is strictly opt-in.

    - ``k == n``: every element survives, so a full-width ``top_k``
      (O(n log n) with top-k's larger constants, then a gather) is
      wasted work — one stable sort answers directly, and its stable
      tie order matches the reference's "stable" warpsort variants.
    - near-full selection (k > 3n/4): the ``top_k`` lowering still
      materializes an order over essentially the whole row, so the
      stable sort is no slower and gives deterministic ties.
    - wide rows on a real TPU (n >= 16k, small k, float input that the
      kernel's f32 compare path represents exactly — f32/bf16/f16):
      the streamed Pallas merge (``ops.select_k_tiles`` — the
      radix/warpsort-select analog) reads the row exactly once at HBM
      rate with a VMEM running state; ties keep the first occurrence,
      like ``top_k``. Caveat it shares with the kNN kernels: a row
      with fewer than k *finite* entries fills the remainder with
      index -1 (top_k would return the positions of the non-finite
      entries). Off-TPU (and thus under interpret) ``lax.top_k``
      stays the dispatcher's choice — the merge is only forced via
      ``algo=TILES`` there.
    - otherwise: ``lax.top_k``, which lowers onto the TPU's native
      sort/top-k units (the TPU-KNN peak-FLOP/s recipe).
    """
    if k == n or k * 4 > n * 3:
        return SelectAlgo.SORT
    if (n >= _TILES_MIN_N and k <= _TILES_MAX_K
            and jnp.dtype(dtype) in (jnp.dtype(jnp.float32),
                                     jnp.dtype(jnp.bfloat16),
                                     jnp.dtype(jnp.float16))
            and jax.default_backend() == "tpu"):
        return SelectAlgo.TILES
    return SelectAlgo.TOPK


@partial(jax.jit, static_argnames=("k", "select_min", "algo", "recall_target"))
def _select_k_impl(values, k: int, select_min: bool, algo: SelectAlgo, recall_target: float):
    if algo == SelectAlgo.TILES:
        # lazy import: matrix.select_k is imported by the ops package's
        # kernels, so a module-level import would be circular
        from raft_tpu.ops.fused_topk import select_k_tiles

        vals, idx = select_k_tiles(values, k, select_min,
                                   interpret=jax.default_backend() != "tpu")
        # the kernel streams in f32; hand back the caller's dtype so
        # AUTO's route never flips the public output dtype (sub-f32
        # inputs round-trip exactly through the f32 compare path)
        return vals.astype(values.dtype), idx
    if algo == SelectAlgo.SORT:
        order = jnp.argsort(values, axis=-1, descending=not select_min, stable=True)
        idx = order[..., :k]
        vals = jnp.take_along_axis(values, idx, axis=-1)
        return vals, idx.astype(jnp.int32)
    if algo == SelectAlgo.APPROX:
        if select_min:
            vals, idx = jax.lax.approx_min_k(values, k, recall_target=recall_target)
        else:
            vals, idx = jax.lax.approx_max_k(values, k, recall_target=recall_target)
        return vals, idx.astype(jnp.int32)
    # TOPK
    if select_min:
        vals, idx = jax.lax.top_k(-values, k)
        return -vals, idx.astype(jnp.int32)
    vals, idx = jax.lax.top_k(values, k)
    return vals, idx.astype(jnp.int32)


def merge_topk(best_d, best_i, cand_d, cand_i, k: int, select_min: bool = True):
    """Merge a running top-k state with a new candidate block — the shared
    streamed-merge step of brute-force / IVF-Flat / IVF-PQ scans (role of
    the warp-level merge in the reference's tiled kNN,
    ``detail/knn_brute_force.cuh:238-280``).

    Args: (batch, k) running values/ids + (batch, m) candidates.
    Returns merged (batch, k) values/ids.
    """
    cat_d = jnp.concatenate([best_d, cand_d], axis=1)
    cat_i = jnp.concatenate([best_i, cand_i], axis=1)
    if select_min:
        new_d, pos = jax.lax.top_k(-cat_d, k)
        new_d = -new_d
    else:
        new_d, pos = jax.lax.top_k(cat_d, k)
    new_i = jnp.take_along_axis(cat_i, pos, axis=1)
    return new_d, new_i


def select_k(
    res: Optional[Resources],
    values,
    k: int,
    select_min: bool = True,
    index_values=None,
    algo: SelectAlgo = SelectAlgo.AUTO,
    recall_target: float = 0.95,
) -> Tuple[jax.Array, jax.Array]:
    """Select the k smallest (or largest) per row.

    Args:
      values: (batch, n) float scores.
      k: how many to keep (k <= n).
      select_min: True → smallest are best (``is_min_close`` semantics).
      index_values: optional (batch, n) int payload; when given, returned
        indices are gathered from it instead of being 0..n-1 positions —
        the reference's ``in_idx`` argument used by tiled kNN merges.
      algo: force a specific algorithm, or AUTO for the dispatcher.
      recall_target: quality knob for the APPROX path.

    Returns:
      (values (batch, k), indices (batch, k) int32)

    Examples
    --------
    >>> import numpy as np
    >>> from raft_tpu.matrix import select_k
    >>> v = np.asarray([[4.0, 1.0, 3.0, 2.0]], np.float32)
    >>> vals, idx = select_k(None, v, 2)
    >>> np.asarray(idx).ravel().tolist()
    [1, 3]
    """
    ensure_resources(res)
    values = jnp.asarray(values)
    expect(values.ndim == 2, "select_k expects (batch, n) values")
    n = values.shape[1]
    expect(0 < k <= n, f"k must be in (0, {n}], got {k}")
    if algo == SelectAlgo.AUTO:
        algo = _choose_algo(values.shape[0], n, k, values.dtype)
    with tracing.range("raft_tpu.select_k"):
        vals, idx = _select_k_impl(values, k, select_min, algo, recall_target)
    if index_values is not None:
        index_values = jnp.asarray(index_values)
        idx = jnp.take_along_axis(index_values, idx.astype(jnp.int32), axis=-1)
    return vals, idx
