"""Dense matrix operations — analog of ``raft/matrix/*.cuh`` (30 headers).

Most reference matrix primitives are one-liners in JAX; they are collected
here so the public surface matches the reference inventory (SURVEY.md §2.2
"matrix ops": gather/scatter, slice, per-row argmax/argmin, col-wise sort,
linewise op, reverse, triangular, print).
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp

import numpy as np


def gather(matrix, indices) -> jax.Array:
    """Row gather: out[i] = matrix[indices[i]] (``matrix/gather.cuh``)."""
    return jnp.take(jnp.asarray(matrix), jnp.asarray(indices), axis=0)


def gather_if(matrix, indices, stencil, pred: Callable) -> jax.Array:
    """Conditional row gather (``matrix::gather_if``): rows whose stencil
    fails the predicate are zeroed."""
    out = gather(matrix, indices)
    keep = pred(jnp.asarray(stencil))
    return jnp.where(keep[:, None], out, 0)


def scatter(matrix, indices, updates) -> jax.Array:
    """Row scatter: out[indices[i]] = updates[i] (``matrix/scatter.cuh``)."""
    return jnp.asarray(matrix).at[jnp.asarray(indices)].set(jnp.asarray(updates))


def slice(matrix, rows: Tuple[int, int], cols: Tuple[int, int]) -> jax.Array:
    """Contiguous sub-matrix copy (``matrix/slice.cuh``)."""
    return jnp.asarray(matrix)[rows[0] : rows[1], cols[0] : cols[1]]


def argmax(matrix, axis: int = 1) -> jax.Array:
    """Per-row argmax (``matrix/argmax.cuh``)."""
    return jnp.argmax(jnp.asarray(matrix), axis=axis).astype(jnp.int32)


def argmin(matrix, axis: int = 1) -> jax.Array:
    """Per-row argmin (``matrix/argmin.cuh``)."""
    return jnp.argmin(jnp.asarray(matrix), axis=axis).astype(jnp.int32)


def col_sort(keys, values=None):
    """Sort each row's columns by key (``matrix/col_wise_sort.cuh``);
    optionally permute a payload alongside."""
    keys = jnp.asarray(keys)
    order = jnp.argsort(keys, axis=1, stable=True)
    sorted_keys = jnp.take_along_axis(keys, order, axis=1)
    if values is None:
        return sorted_keys, order.astype(jnp.int32)
    return sorted_keys, jnp.take_along_axis(jnp.asarray(values), order, axis=1)


def linewise_op(matrix, vec, along_rows: bool, op: Callable) -> jax.Array:
    """Broadcast a vector op along rows or columns
    (``matrix/linewise_op.cuh`` / ``linalg::matrix_vector_op``)."""
    matrix = jnp.asarray(matrix)
    vec = jnp.asarray(vec)
    if along_rows:  # vec has one entry per column
        return op(matrix, vec[None, :])
    return op(matrix, vec[:, None])


def reverse(matrix, axis: int = 1) -> jax.Array:
    """Flip rows or columns (``matrix/reverse.cuh``)."""
    return jnp.flip(jnp.asarray(matrix), axis=axis)


def triangular_upper(matrix) -> jax.Array:
    """Upper-triangular copy (``matrix/triangular.cuh``)."""
    return jnp.triu(jnp.asarray(matrix))


def triangular_lower(matrix) -> jax.Array:
    return jnp.tril(jnp.asarray(matrix))


def matrix_print(matrix, name: str = "matrix", max_rows: int = 8, max_cols: int = 8):
    """Host-side pretty print (``matrix/print.cuh``)."""
    arr = np.asarray(jax.device_get(matrix))
    print(f"{name} shape={arr.shape} dtype={arr.dtype}")  # noqa: print is the op
    print(np.array2string(arr[:max_rows, :max_cols], precision=4))  # noqa


def copy(matrix) -> jax.Array:
    """Out-of-place copy (``matrix/copy.cuh``)."""
    return jnp.array(jnp.asarray(matrix))


def diagonal(matrix) -> jax.Array:
    """Extract the main diagonal (``matrix/diagonal.cuh``)."""
    return jnp.diagonal(jnp.asarray(matrix))


def set_diagonal(matrix, values) -> jax.Array:
    """Return a copy with the main diagonal replaced
    (``matrix::set_diagonal``)."""
    matrix = jnp.asarray(matrix)
    n = min(matrix.shape[0], matrix.shape[1])
    idx = jnp.arange(n)
    return matrix.at[idx, idx].set(jnp.asarray(values)[:n])


def fill(matrix, value) -> jax.Array:
    """Constant-fill with the input's shape/dtype (``matrix/init.cuh``)."""
    matrix = jnp.asarray(matrix)
    return jnp.full_like(matrix, value)


def eye(n: int, dtype=jnp.float32) -> jax.Array:
    """Identity matrix (``matrix::eye``)."""
    return jnp.eye(n, dtype=dtype)


def power(matrix, exponent) -> jax.Array:
    """Elementwise power (``matrix/power.cuh``)."""
    return jnp.power(jnp.asarray(matrix), exponent)


def sqrt(matrix) -> jax.Array:
    """Elementwise square root (``matrix/sqrt.cuh``)."""
    return jnp.sqrt(jnp.asarray(matrix))


def reciprocal(matrix, scalar=1.0, thres: float = 0.0) -> jax.Array:
    """``scalar / x`` with small-denominator guard
    (``matrix/reciprocal.cuh``): entries with |x| <= thres map to 0."""
    matrix = jnp.asarray(matrix)
    out = scalar / matrix
    return jnp.where(jnp.abs(matrix) <= thres, jnp.zeros_like(out), out)


def ratio(matrix) -> jax.Array:
    """Normalize so entries sum to one (``matrix/ratio.cuh``)."""
    matrix = jnp.asarray(matrix)
    return matrix / jnp.sum(matrix)


def sign_flip(matrix) -> jax.Array:
    """Flip each column's sign so its max-|value| entry is positive —
    deterministic eigenvector orientation (``matrix/sign_flip.cuh``)."""
    matrix = jnp.asarray(matrix)
    pivot = jnp.take_along_axis(
        matrix, jnp.argmax(jnp.abs(matrix), axis=0)[None, :], axis=0)
    return matrix * jnp.where(pivot < 0, -1.0, 1.0)


def zero_small_values(matrix, thres) -> jax.Array:
    """Zero entries whose MAGNITUDE is <= thres (``matrix/threshold.cuh``
    ``zero_small_values``: denoising that keeps large entries of either
    sign)."""
    matrix = jnp.asarray(matrix)
    return jnp.where(jnp.abs(matrix) <= thres, jnp.zeros_like(matrix),
                     matrix)


# reference alias: the public header is matrix/threshold.cuh
threshold = zero_small_values


def l2_norm(matrix) -> jax.Array:
    """Frobenius norm of the whole matrix (``matrix/norm.cuh``
    ``l2_norm``)."""
    return jnp.sqrt(jnp.sum(jnp.square(jnp.asarray(matrix, jnp.float32))))
