"""raft_tpu — TPU-native ML/IR primitives and vector-search framework.

A from-scratch JAX / XLA / Pallas / pjit re-design of the capabilities of
RAPIDS RAFT (reference: cpp/include/raft/** in the upstream repo): pairwise
distances, batched k-selection, (balanced) k-means, dense/sparse linear
algebra, statistics, random generation, and GPU-class vector search
(brute-force kNN, IVF-Flat, IVF-PQ, CAGRA, refinement) — built and served
entirely from TPU HBM, sharded over ICI/DCN meshes via ``jax.sharding``.

Layering (mirrors the reference's layer map, SURVEY.md §1):

- ``raft_tpu.core``       — resources handle, logging, serialization (L1)
- ``raft_tpu.linalg``     — dense math primitives (L2)
- ``raft_tpu.matrix``     — matrix ops incl. ``select_k`` (L2)
- ``raft_tpu.random``     — counter-based RNG + data generators (L2)
- ``raft_tpu.stats``      — statistics & ML metrics (L2)
- ``raft_tpu.sparse``     — sparse structures, distances, solvers (L2/L3)
- ``raft_tpu.distance``   — pairwise distances, fused L2 NN (L3)
- ``raft_tpu.cluster``    — kmeans, balanced kmeans, linkage, spectral (L3)
- ``raft_tpu.neighbors``  — brute force / IVF-Flat / IVF-PQ / CAGRA (L4)
- ``raft_tpu.comms``      — collectives over ICI/DCN device meshes (L5)
- ``raft_tpu.serving``    — request frontend: dynamic batching, admission
  control, deadline scheduling, load-shedding (L7)
- ``raft_tpu.ops``        — Pallas TPU kernels backing the hot paths
- ``raft_tpu.bench``      — ANN benchmark harness (L8)

Unlike the reference there is no explicit-instantiation layer (L6) — XLA's
jit cache replaces it — and the Python API *is* the primary API (L7).
"""

__version__ = "0.1.0"

from raft_tpu.core.resources import Resources, DeviceResources
from raft_tpu.core.executor import SearchExecutor
from raft_tpu.core.memwatch import CapacityExceeded, MemoryLedger

__all__ = [
    "Resources",
    "DeviceResources",
    "SearchExecutor",
    "CapacityExceeded",
    "MemoryLedger",
    "__version__",
]
