"""Dense factorizations & solvers — analog of the reference's cuSOLVER
wrappers: ``linalg/eig.cuh`` (eigDC / eigJacobi), ``linalg/svd.cuh``
(svdQR), ``linalg/qr.cuh``, ``linalg/rsvd.cuh`` (randomized SVD),
``linalg/lstsq.cuh``, ``linalg/cholesky_r1_update.cuh``.

XLA ships TPU-native eigh/svd/qr, so the dense solvers are thin,
handle-threaded wrappers; randomized SVD and the rank-1 Cholesky update
are implemented here (subspace iteration and a vectorized hypot-rotation
update respectively) since they are algorithms, not vendor calls.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.validation import expect


def eig_dc(res: Optional[Resources], a) -> Tuple[jax.Array, jax.Array]:
    """Symmetric eigendecomposition, ascending eigenvalues —
    analog of ``linalg::eigDC`` (cuSOLVER syevd). Returns (vectors, values)
    with ``vectors[:, i]`` the i-th eigenvector."""
    w, v = jnp.linalg.eigh(a)
    return v, w


def eig_jacobi(
    res: Optional[Resources], a, *, tol: float = 1e-7, sweeps: int = 15
) -> Tuple[jax.Array, jax.Array]:
    """Jacobi-method symmetric eigensolver (``linalg::eigJacobi``).

    On TPU the DC path is already native; kept for API parity — delegates
    to the same XLA eigh (tol/sweeps accepted for signature parity)."""
    return eig_dc(res, a)


def svd(
    res: Optional[Resources],
    a,
    *,
    full_matrices: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """SVD ``A = U S V^T`` — analog of ``linalg::svdQR``. Returns
    (U, S, V) with V (not V^T), matching the reference's output layout."""
    u, s, vt = jnp.linalg.svd(a, full_matrices=full_matrices)
    return u, s, vt.T


def qr(res: Optional[Resources], a) -> Tuple[jax.Array, jax.Array]:
    """Thin QR — analog of ``linalg::qrGetQR`` (``linalg/qr.cuh``)."""
    return jnp.linalg.qr(a, mode="reduced")


def rsvd(
    res: Optional[Resources],
    a,
    k: int,
    *,
    p: int = 10,
    n_iters: int = 2,
    key=None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Randomized truncated SVD — analog of ``linalg::rsvd``
    (``linalg/rsvd.cuh``), via Halko-style subspace iteration:
    range-find with a Gaussian sketch (rank k+p), ``n_iters`` power
    iterations with QR re-orthonormalization, then exact SVD of the
    small projected matrix. All heavy ops are MXU GEMMs + thin QR.

    Returns (U, S, V) with k columns/entries.
    """
    res = ensure_resources(res)
    m, n = a.shape
    expect(k >= 1 and k <= min(m, n), "rsvd: k out of range")
    ell = min(k + p, min(m, n))
    if key is None:
        key = res.next_key()
    a32 = a.astype(jnp.float32)
    omega = jax.random.normal(key, (n, ell), jnp.float32)
    y = a32 @ omega
    q, _ = jnp.linalg.qr(y)
    for _ in range(n_iters):
        z = a32.T @ q
        q, _ = jnp.linalg.qr(z)
        y = a32 @ q
        q, _ = jnp.linalg.qr(y)
    b = q.T @ a32  # (ell, n)
    ub, s, vt = jnp.linalg.svd(b, full_matrices=False)
    u = q @ ub
    return u[:, :k], s[:k], vt[:k, :].T


def lstsq(res: Optional[Resources], a, b) -> jax.Array:
    """Least-squares solve min |Ax - b| — analog of ``linalg::lstsq*``
    (``linalg/lstsq.cuh``; the reference offers SVD/QR/eig variants —
    one numerically-robust SVD path suffices here)."""
    x, *_ = jnp.linalg.lstsq(a.astype(jnp.float32), b.astype(jnp.float32))
    return x


def cholesky_rank_one_update(
    res: Optional[Resources],
    l_factor,
    x,
    *,
    lower: bool = True,
) -> jax.Array:
    """Update Cholesky factor of A to that of ``A + x x^T`` —
    analog of ``linalg::choleskyRank1Update``
    (``linalg/cholesky_r1_update.cuh``).

    Classic hyperbolic-rotation update, expressed as a ``lax.scan`` over
    columns (the loop is inherently sequential; each step is vectorized
    over the trailing rows).
    """
    n = l_factor.shape[0]
    expect(x.shape[0] == n, "cholesky_rank_one_update: size mismatch")
    lmat = l_factor.astype(jnp.float32)
    if not lower:
        lmat = lmat.T
    xv = x.astype(jnp.float32)

    def body(carry, k):
        lmat, xv = carry
        lkk = lmat[k, k]
        xk = xv[k]
        r = jnp.sqrt(lkk * lkk + xk * xk)
        c = r / lkk
        s = xk / lkk
        col = lmat[:, k]
        mask = (jnp.arange(n) > k).astype(jnp.float32)
        new_col = jnp.where(jnp.arange(n) == k, r, (col + s * xv) / c)
        new_col = jnp.where(jnp.arange(n) >= k, new_col, col)
        xv = xv * (1 - mask) + mask * (c * xv - s * new_col)
        lmat = lmat.at[:, k].set(new_col)
        return (lmat, xv), None

    (lmat, _), _ = jax.lax.scan(body, (lmat, xv), jnp.arange(n))
    return lmat if lower else lmat.T
