"""Elementwise map family — analog of ``linalg/map.cuh`` and the
add/subtract/multiply/divide/power/sqrt headers under ``raft/linalg/``.

The reference hand-writes vectorized CUDA kernels for each; under XLA
every one of these is a single fused VPU loop, so the value here is API
parity (free functions over arrays) rather than codegen.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp

from raft_tpu.core.resources import Resources


def unary_op(res: Optional[Resources], x, op: Callable):
    """Apply ``op`` elementwise (``linalg::unaryOp``, ``linalg/unary_op.cuh``)."""
    return op(x)


def binary_op(res: Optional[Resources], x, y, op: Callable):
    """Apply ``op(x, y)`` elementwise (``linalg::binaryOp``)."""
    return op(x, y)


def ternary_op(res: Optional[Resources], x, y, z, op: Callable):
    """Apply ``op(x, y, z)`` elementwise (``linalg::ternaryOp``)."""
    return op(x, y, z)


def map_offset(res: Optional[Resources], shape, op: Callable, dtype=jnp.float32):
    """Map over flat element offsets (``linalg::map_offset``,
    ``linalg/map.cuh``): ``out[i] = op(i)`` reshaped to ``shape``."""
    n = 1
    for s in shape:
        n *= s
    idx = jnp.arange(n, dtype=jnp.int32)
    return op(idx).astype(dtype).reshape(shape)


def map(res: Optional[Resources], op: Callable, *arrays):  # noqa: A001
    """Variadic elementwise map (``linalg::map``, ``linalg/map.cuh``)."""
    return op(*arrays)


def transpose(res: Optional[Resources], x):
    """Matrix transpose (``linalg/transpose.cuh``)."""
    return jnp.swapaxes(jnp.asarray(x), -1, -2)


def add(res: Optional[Resources], x, y):
    return x + y


def subtract(res: Optional[Resources], x, y):
    return x - y


def multiply(res: Optional[Resources], x, y):
    return x * y


def divide(res: Optional[Resources], x, y):
    return x / y


def scalar_add(res: Optional[Resources], x, scalar):
    return x + scalar


def scalar_multiply(res: Optional[Resources], x, scalar):
    return x * scalar


def power(res: Optional[Resources], x, y):
    return jnp.power(x, y)


def sqrt(res: Optional[Resources], x):
    return jnp.sqrt(x)
