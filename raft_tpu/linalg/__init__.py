"""Dense linear algebra primitives — TPU-native re-design of ``raft/linalg/``.

The reference wraps cuBLAS (gemm/gemv/axpy/dot), hand-writes elementwise /
reduction CUDA kernels, and wraps cuSOLVER for factorizations. On TPU the
BLAS layer is ``jax.lax.dot_general`` on the MXU, elementwise ops are XLA
fusions, and factorizations are ``jax.lax.linalg`` / ``jnp.linalg`` (which
XLA lowers to TPU-native routines). What this package adds on top is the
reference's *API surface*: free functions taking a ``Resources`` handle +
arrays, with the same semantics (row/col norms, strided vs coalesced
reductions, key-grouped reductions, rank-1 Cholesky update, randomized SVD).
"""

from raft_tpu.linalg.blas import axpy, dot, gemm, gemv
from raft_tpu.linalg.elementwise import (
    add,
    binary_op,
    divide,
    map,  # noqa: A004
    map_offset,
    multiply,
    power,
    scalar_add,
    scalar_multiply,
    sqrt,
    subtract,
    ternary_op,
    transpose,
    unary_op,
)
from raft_tpu.linalg.matrix_vector import matrix_vector_op
from raft_tpu.linalg.reduce import (
    L1Norm,
    L2Norm,
    LinfNorm,
    coalesced_reduction,
    map_reduce,
    mean_squared_error,
    norm,
    normalize,
    reduce,
    reduce_cols_by_key,
    reduce_rows_by_key,
    strided_reduction,
)
from raft_tpu.sparse.solver import lanczos_smallest  # noqa: F401  (linalg/lanczos alias)
from raft_tpu.linalg.solvers import (
    cholesky_rank_one_update,
    eig_dc,
    eig_jacobi,
    lstsq,
    qr,
    rsvd,
    svd,
)

__all__ = [
    "axpy",
    "dot",
    "gemm",
    "gemv",
    "add",
    "binary_op",
    "divide",
    # ``map`` stays importable (reference parity: raft/linalg/map.cuh) but is
    # deliberately omitted from __all__ so star-imports don't shadow the
    # Python builtin.
    "map_offset",
    "transpose",
    "multiply",
    "power",
    "scalar_add",
    "scalar_multiply",
    "sqrt",
    "subtract",
    "ternary_op",
    "unary_op",
    "matrix_vector_op",
    "L1Norm",
    "L2Norm",
    "LinfNorm",
    "coalesced_reduction",
    "map_reduce",
    "mean_squared_error",
    "norm",
    "normalize",
    "reduce",
    "reduce_cols_by_key",
    "reduce_rows_by_key",
    "strided_reduction",
    "cholesky_rank_one_update",
    "lanczos_smallest",
    "eig_dc",
    "eig_jacobi",
    "lstsq",
    "qr",
    "rsvd",
    "svd",
]
