"""Broadcast matrix ± vector ops — analog of ``linalg::matrix_vector_op``
(``linalg/matrix_vector_op.cuh``).

The reference picks vectorized-IO kernels by alignment; XLA handles layout,
so this reduces to a broadcast the compiler fuses into neighbors.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp

from raft_tpu.core.resources import Resources
from raft_tpu.core.validation import expect


def matrix_vector_op(
    res: Optional[Resources],
    matrix,
    vec,
    op: Callable = jnp.add,
    *,
    along_rows: bool = True,
):
    """Apply ``op(matrix, vec)`` broadcasting ``vec`` along rows or columns.

    ``along_rows=True`` broadcasts over the row axis (vec has one entry per
    column), matching the reference's ``bcastAlongRows``.
    """
    if along_rows:
        expect(vec.shape[0] == matrix.shape[1], "matrix_vector_op: |vec| != n_cols")
        return op(matrix, vec[None, :])
    expect(vec.shape[0] == matrix.shape[0], "matrix_vector_op: |vec| != n_rows")
    return op(matrix, vec[:, None])
