"""Reductions and norms — analog of ``linalg/reduce.cuh``,
``linalg/coalesced_reduction.cuh``, ``linalg/strided_reduction.cuh``,
``linalg/norm.cuh``, ``linalg/normalize.cuh``,
``linalg/mean_squared_error.cuh``, ``linalg/reduce_rows_by_key.cuh``,
``linalg/reduce_cols_by_key.cuh``.

The reference distinguishes *coalesced* (reduce along the contiguous
dimension) from *strided* reductions because GPU kernel shape differs; on
TPU both are one ``jnp`` reduction XLA lays out for the VPU, so the two
names are kept only as API parity aliases over ``axis=``.

Key-grouped reductions use ``segment_sum``-style one-hot matmuls: grouping
by key is a gather/scatter on GPU but is MXU-friendly as a one-hot GEMM on
TPU for the small key cardinalities these APIs target.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from raft_tpu.core.resources import Resources
from raft_tpu.core.validation import expect

# Norm types mirroring ``raft::linalg::NormType``.
L1Norm = "l1"
L2Norm = "l2"
LinfNorm = "linf"


def reduce(
    res: Optional[Resources],
    matrix,
    *,
    along_rows: bool = True,
    main_op: Callable = lambda x: x,
    reduce_op: Callable = jnp.sum,
    final_op: Callable = lambda x: x,
    init=None,
):
    """General map-reduce over one matrix axis (``linalg::reduce``).

    ``along_rows=True`` reduces each row to a scalar (output length n_rows),
    matching the reference's ``apply_along_rows``. ``init`` seeds the
    accumulator (reference semantics: correct for max/min reductions, not
    an additive bias) — implemented by reducing over the mapped matrix
    with an extra init-valued lane appended.
    """
    axis = 1 if along_rows else 0
    x = main_op(matrix)
    if init is not None:
        pad_shape = (x.shape[0], 1) if along_rows else (1, x.shape[1])
        x = jnp.concatenate([x, jnp.full(pad_shape, init, x.dtype)], axis=axis)
    return final_op(reduce_op(x, axis=axis))


def coalesced_reduction(res: Optional[Resources], matrix, **kwargs):
    """Row-wise reduction for row-major data (``linalg/coalesced_reduction.cuh``)."""
    return reduce(res, matrix, along_rows=True, **kwargs)


def strided_reduction(res: Optional[Resources], matrix, **kwargs):
    """Column-wise reduction for row-major data (``linalg/strided_reduction.cuh``)."""
    return reduce(res, matrix, along_rows=False, **kwargs)


def map_reduce(
    res: Optional[Resources],
    x,
    map_op: Callable,
    reduce_op: Callable = jnp.sum,
):
    """Fused map + full reduction (``linalg::mapThenReduce``)."""
    return reduce_op(map_op(x))


def norm(
    res: Optional[Resources],
    matrix,
    norm_type: str = L2Norm,
    *,
    along_rows: bool = True,
    sqrt: bool = False,
):
    """Row / column norms (``linalg::rowNorm`` / ``colNorm``,
    ``linalg/norm.cuh``). Note the reference's L2 norm is the *squared*
    norm unless ``sqrt=True`` — matched here."""
    axis = 1 if along_rows else 0
    x = matrix.astype(jnp.float32)
    if norm_type == L1Norm:
        out = jnp.sum(jnp.abs(x), axis=axis)
    elif norm_type == L2Norm:
        out = jnp.sum(jnp.square(x), axis=axis)
        if sqrt:
            out = jnp.sqrt(out)
        return out
    elif norm_type == LinfNorm:
        out = jnp.max(jnp.abs(x), axis=axis)
    else:
        raise ValueError(f"unknown norm type: {norm_type!r}")
    return out


def normalize(
    res: Optional[Resources],
    matrix,
    norm_type: str = L2Norm,
    *,
    eps: float = 1e-10,
):
    """Row-normalize (``linalg::row_normalize``, ``linalg/normalize.cuh``)."""
    if norm_type == L2Norm:
        n = jnp.sqrt(norm(res, matrix, L2Norm, along_rows=True))
    else:
        n = norm(res, matrix, norm_type, along_rows=True)
    return matrix / jnp.maximum(n, eps)[:, None]


def mean_squared_error(res: Optional[Resources], a, b, *, weight: float = 1.0):
    """``linalg::meanSquaredError``: weight * mean((a-b)^2) over all elements."""
    d = a.astype(jnp.float32) - b.astype(jnp.float32)
    return weight * jnp.mean(jnp.square(d))


def reduce_rows_by_key(
    res: Optional[Resources],
    matrix,
    keys,
    n_keys: int,
    *,
    weights=None,
):
    """Sum rows grouped by per-row key → ``(n_keys, n_cols)``
    (``linalg::reduce_rows_by_key``). One-hot GEMM: MXU-friendly scatter-add."""
    expect(keys.shape[0] == matrix.shape[0], "reduce_rows_by_key: |keys| != n_rows")
    onehot = jax.nn.one_hot(keys, n_keys, dtype=jnp.float32)
    x = matrix.astype(jnp.float32)
    if weights is not None:
        x = x * weights[:, None]
    out = jax.lax.dot_general(
        onehot, x, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    return out


def reduce_cols_by_key(
    res: Optional[Resources],
    matrix,
    keys,
    n_keys: int,
):
    """Sum columns grouped by per-column key → ``(n_rows, n_keys)``
    (``linalg::reduce_cols_by_key``)."""
    expect(keys.shape[0] == matrix.shape[1], "reduce_cols_by_key: |keys| != n_cols")
    onehot = jax.nn.one_hot(keys, n_keys, dtype=jnp.float32)
    return jax.lax.dot_general(
        matrix.astype(jnp.float32),
        onehot,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
