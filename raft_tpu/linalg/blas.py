"""BLAS-level ops — analog of the reference's cuBLAS wrappers
(``linalg/gemm.cuh``, ``linalg/detail/cublas_wrappers.hpp``).

On TPU there is no vendor handle to thread: every call is a
``jax.lax.dot_general`` that XLA tiles onto the MXU. The handle still
supplies the default matmul precision so callers get the same
precision-policy knob cuBLAS math modes gave the reference.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.validation import expect


def gemm(
    res: Optional[Resources],
    a,
    b,
    *,
    alpha: float = 1.0,
    beta: float = 0.0,
    c=None,
    trans_a: bool = False,
    trans_b: bool = False,
):
    """``alpha * op(A) @ op(B) + beta * C`` — analog of ``linalg::gemm``
    (reference ``linalg/gemm.cuh``). Accumulates in float32 on the MXU."""
    res = ensure_resources(res)
    if trans_a:
        a = a.T
    if trans_b:
        b = b.T
    expect(a.shape[1] == b.shape[0], "gemm: inner dimensions must agree")
    out = jax.lax.dot_general(
        a,
        b,
        (((1,), (0,)), ((), ())),
        precision=res.matmul_precision,
        preferred_element_type=jnp.float32,
    )
    out = alpha * out
    if beta != 0.0:
        expect(c is not None, "gemm: beta != 0 requires C")
        out = out + beta * c
    return out.astype(a.dtype)


def gemv(
    res: Optional[Resources],
    a,
    x,
    *,
    alpha: float = 1.0,
    beta: float = 0.0,
    y=None,
    trans: bool = False,
):
    """``alpha * op(A) @ x + beta * y`` — analog of the cuBLAS gemv wrapper."""
    res = ensure_resources(res)
    if trans:
        a = a.T
    expect(a.shape[1] == x.shape[0], "gemv: dimensions must agree")
    out = alpha * jnp.dot(
        a.astype(jnp.float32), x.astype(jnp.float32), precision=res.matmul_precision
    )
    if beta != 0.0:
        expect(y is not None, "gemv: beta != 0 requires y")
        out = out + beta * y
    return out.astype(a.dtype)


def axpy(res: Optional[Resources], alpha: float, x, y):
    """``y + alpha * x`` (functional: returns the result)."""
    return y + alpha * x


def dot(res: Optional[Resources], x, y):
    """Vector dot product with float32 accumulation."""
    return jnp.dot(x.astype(jnp.float32), y.astype(jnp.float32)).astype(x.dtype)
