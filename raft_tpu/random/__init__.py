"""RNG + data generators (reference ``raft/random/``)."""

from raft_tpu.random.rng import (
    GeneratorType,
    RngState,
    uniform,
    uniform_int,
    normal,
    lognormal,
    gumbel,
    logistic,
    laplace,
    exponential,
    rayleigh,
    bernoulli,
    scaled_bernoulli,
    permute,
    sample_without_replacement,
    subsample,
)
from raft_tpu.random.generators import (
    make_blobs,
    make_regression,
    rmat,
    multi_variable_gaussian,
)

__all__ = [
    "GeneratorType",
    "RngState",
    "uniform",
    "uniform_int",
    "normal",
    "lognormal",
    "gumbel",
    "logistic",
    "laplace",
    "exponential",
    "rayleigh",
    "bernoulli",
    "scaled_bernoulli",
    "permute",
    "sample_without_replacement",
    "subsample",
    "make_blobs",
    "make_regression",
    "rmat",
    "multi_variable_gaussian",
]
