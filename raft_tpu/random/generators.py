"""Synthetic data generators — ``make_blobs``, ``make_regression``, RMAT
graphs, multi-variable gaussian (reference ``random/make_blobs.cuh``,
``random/make_regression.cuh``, ``random/rmat_rectangular_generator.cuh``,
``random/multi_variable_gaussian.cuh``)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.random.rng import _key_of


def make_blobs(
    rng,
    n_samples: int,
    n_features: int,
    n_clusters: int = 3,
    cluster_std: float = 1.0,
    center_box: Tuple[float, float] = (-10.0, 10.0),
    centers=None,
    shuffle: bool = True,
    dtype=jnp.float32,
):
    """Isotropic Gaussian blobs (``random::make_blobs``). Returns (X, labels,
    centers).

    Examples
    --------
    >>> from raft_tpu import random as rrandom
    >>> X, labels, centers = rrandom.make_blobs(
    ...     rrandom.RngState(0), 30, 4, n_clusters=3)
    >>> (X.shape, labels.shape, centers.shape)
    ((30, 4), (30,), (3, 4))
    """
    key = _key_of(rng)
    k_centers, k_labels, k_noise, k_shuffle = jax.random.split(key, 4)
    if centers is None:
        centers = jax.random.uniform(
            k_centers, (n_clusters, n_features), dtype=dtype,
            minval=center_box[0], maxval=center_box[1],
        )
    else:
        centers = jnp.asarray(centers, dtype)
        n_clusters = centers.shape[0]
    labels = jax.random.randint(k_labels, (n_samples,), 0, n_clusters)
    noise = cluster_std * jax.random.normal(k_noise, (n_samples, n_features), dtype=dtype)
    x = centers[labels] + noise
    if shuffle:
        perm = jax.random.permutation(k_shuffle, n_samples)
        x, labels = x[perm], labels[perm]
    return x, labels.astype(jnp.int32), centers


def make_regression(
    rng,
    n_samples: int,
    n_features: int,
    n_informative: Optional[int] = None,
    n_targets: int = 1,
    bias: float = 0.0,
    noise: float = 0.0,
    shuffle: bool = True,
    dtype=jnp.float32,
):
    """Random linear regression problem (``random::make_regression``).
    Returns (X, y, coef)."""
    n_informative = n_informative if n_informative is not None else n_features
    key = _key_of(rng)
    k_x, k_w, k_noise, k_shuffle = jax.random.split(key, 4)
    x = jax.random.normal(k_x, (n_samples, n_features), dtype=dtype)
    coef = jnp.zeros((n_features, n_targets), dtype)
    w = 100.0 * jax.random.uniform(k_w, (n_informative, n_targets), dtype=dtype)
    coef = coef.at[:n_informative].set(w)
    y = x @ coef + bias
    if noise > 0:
        y = y + noise * jax.random.normal(k_noise, y.shape, dtype=dtype)
    if shuffle:
        perm = jax.random.permutation(k_shuffle, n_samples)
        x, y = x[perm], y[perm]
    return x, y, coef


def rmat(
    rng,
    r_scale: int,
    c_scale: int,
    n_edges: int,
    theta=None,
) -> jax.Array:
    """RMAT rectangular graph generator
    (``random::rmat_rectangular_generator``): recursively pick quadrants by
    (a,b,c,d) probabilities, one bit per level — fully vectorized over
    edges. Returns int32 (n_edges, 2) [src, dst]."""
    key = _key_of(rng)
    if theta is None:
        theta = jnp.array([0.57, 0.19, 0.19, 0.05], jnp.float32)
    theta = jnp.asarray(theta, jnp.float32).reshape(-1)[:4]
    probs = theta / theta.sum()
    # quadrant draw per (edge, level)
    max_scale = max(r_scale, c_scale)
    draws = jax.random.categorical(
        key, jnp.log(probs)[None, None, :], axis=-1,
        shape=(n_edges, max_scale),
    )
    # quadrant 0,1,2,3 → (row_bit, col_bit) = (q >> 1, q & 1)
    row_bits = (draws >> 1).astype(jnp.int32)
    col_bits = (draws & 1).astype(jnp.int32)
    # bit i contributes 2^(scale-1-i) within its own scale range
    r_pow = jnp.where(jnp.arange(max_scale) < r_scale,
                      2 ** (r_scale - 1 - jnp.arange(max_scale)), 0).astype(jnp.int32)
    c_pow = jnp.where(jnp.arange(max_scale) < c_scale,
                      2 ** (c_scale - 1 - jnp.arange(max_scale)), 0).astype(jnp.int32)
    src = (row_bits * r_pow[None, :]).sum(axis=1)
    dst = (col_bits * c_pow[None, :]).sum(axis=1)
    return jnp.stack([src, dst], axis=1).astype(jnp.int32)


def multi_variable_gaussian(rng, mean, cov, n_samples: int) -> jax.Array:
    """Draw from N(mean, cov) (``random::multi_variable_gaussian``) via
    Cholesky (jnp.linalg — XLA's TPU-native factorization)."""
    key = _key_of(rng)
    mean = jnp.asarray(mean, jnp.float32)
    cov = jnp.asarray(cov, jnp.float32)
    chol = jnp.linalg.cholesky(cov)
    z = jax.random.normal(key, (n_samples, mean.shape[0]), dtype=jnp.float32)
    return mean[None, :] + z @ chol.T
