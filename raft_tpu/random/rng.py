"""Random generation — analog of ``raft/random/`` (``random/rng.cuh``).

The reference uses counter-based Philox/PCG generators threaded via
``RngState`` (``random/rng_state.hpp:28-52``). JAX's threefry PRNG is
already counter-based and splittable, so ``RngState`` here simply wraps a
key + offset discipline with the same distribution surface: uniform,
uniformInt, normal, normalInt, lognormal, gumbel, logistic, laplace,
exponential, rayleigh, bernoulli, scaled_bernoulli, sample-without-
replacement, permute.
"""

from __future__ import annotations

import dataclasses
import enum

import jax
import jax.numpy as jnp


class GeneratorType(enum.IntEnum):
    """Mirrors ``random/rng_state.hpp`` (PCG default, Philox). Both map to
    JAX's counter-based threefry; the distinction is kept for API parity."""

    Pcg = 0
    Philox = 1


@dataclasses.dataclass
class RngState:
    """Seed + generator selector (``random::RngState``). ``advance`` mirrors
    the reference's subsequence advancing for reproducible parallel draws."""

    seed: int = 0
    type: GeneratorType = GeneratorType.Pcg
    _counter: int = 0

    def key(self) -> jax.Array:
        k = jax.random.fold_in(jax.random.key(self.seed), self._counter)
        self._counter += 1
        return k

    def advance(self, n: int = 1) -> None:
        self._counter += n


def _key_of(rng: "RngState | jax.Array | int") -> jax.Array:
    if isinstance(rng, RngState):
        return rng.key()
    if isinstance(rng, int):
        return jax.random.key(rng)
    return rng


def uniform(rng, shape, low=0.0, high=1.0, dtype=jnp.float32):
    return jax.random.uniform(_key_of(rng), shape, dtype=dtype, minval=low, maxval=high)


def uniform_int(rng, shape, low, high, dtype=jnp.int32):
    return jax.random.randint(_key_of(rng), shape, low, high, dtype=dtype)


def normal(rng, shape, mu=0.0, sigma=1.0, dtype=jnp.float32):
    return mu + sigma * jax.random.normal(_key_of(rng), shape, dtype=dtype)


def lognormal(rng, shape, mu=0.0, sigma=1.0, dtype=jnp.float32):
    return jnp.exp(normal(rng, shape, mu, sigma, dtype))


def gumbel(rng, shape, mu=0.0, beta=1.0, dtype=jnp.float32):
    return mu + beta * jax.random.gumbel(_key_of(rng), shape, dtype=dtype)


def logistic(rng, shape, mu=0.0, scale=1.0, dtype=jnp.float32):
    return mu + scale * jax.random.logistic(_key_of(rng), shape, dtype=dtype)


def laplace(rng, shape, mu=0.0, scale=1.0, dtype=jnp.float32):
    return mu + scale * jax.random.laplace(_key_of(rng), shape, dtype=dtype)


def exponential(rng, shape, lam=1.0, dtype=jnp.float32):
    return jax.random.exponential(_key_of(rng), shape, dtype=dtype) / lam


def rayleigh(rng, shape, sigma=1.0, dtype=jnp.float32):
    u = jax.random.uniform(_key_of(rng), shape, dtype=dtype)
    return sigma * jnp.sqrt(-2.0 * jnp.log1p(-u))


def bernoulli(rng, shape, prob=0.5):
    return jax.random.bernoulli(_key_of(rng), prob, shape)


def scaled_bernoulli(rng, shape, prob=0.5, scale=1.0, dtype=jnp.float32):
    return jnp.where(bernoulli(rng, shape, prob), dtype(scale), dtype(-scale))


def permute(rng, n: int) -> jax.Array:
    """Random permutation of [0, n) (``random::permute``)."""
    return jax.random.permutation(_key_of(rng), n)


def sample_without_replacement(
    rng,
    n_samples: int,
    population: int,
    weights=None,
) -> jax.Array:
    """Sample ``n_samples`` distinct indices from [0, population)
    (``random::sample_without_replacement``, weighted via Gumbel-top-k —
    the counter-based parallel formulation natural on TPU)."""
    key = _key_of(rng)
    if weights is None:
        return jax.random.permutation(key, population)[:n_samples]
    logits = jnp.log(jnp.asarray(weights, jnp.float32))
    g = jax.random.gumbel(key, (population,), dtype=jnp.float32)
    _, idx = jax.lax.top_k(logits + g, n_samples)
    return idx


def subsample(rng, population: int, n_samples: int) -> jax.Array:
    """Deterministic-stride subsample used for trainset selection
    (role of ``detail/ivf_pq_build.cuh:1537-1607`` subsampling)."""
    if n_samples >= population:
        return jnp.arange(population)
    stride = population // n_samples
    return (jnp.arange(n_samples) * stride).astype(jnp.int32)
