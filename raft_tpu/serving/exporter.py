"""Pull-based export surface (PR 6 graftscope): one stdlib
``http.server`` port serving every observability signal in the process.

Endpoints:

- ``/metrics`` — Prometheus text exposition (version 0.0.4): every
  :mod:`raft_tpu.core.tracing` counter and gauge, plus the latency
  histograms with CUMULATIVE bucket counts (``*_bucket{le="..."}`` /
  ``*_sum`` / ``*_count``) — scrapeable by any Prometheus-compatible
  agent. Metric names are the registry names with non-identifier
  characters folded to ``_`` (``serving.batcher.e2e_seconds`` →
  ``serving_batcher_e2e_seconds``).
- ``/snapshot.json`` — the JSON view: ``serving.metrics.snapshot()``
  (counters, gauges, histograms, occupancy, derived achieved GB/s),
  the attached executor's per-executable cost table, the attached
  batcher's degradation-ladder rung, and flight-recorder stats.
- ``/trace.json`` — the span ring as Chrome trace-event JSON; load it
  into Perfetto next to a ``jax.profiler`` capture to overlay host
  stage spans on the device timeline.
- ``/healthz`` — liveness probe.

The exporter holds NO state of its own: every request re-reads the
live registries, so a scrape is always current and costs the serving
path nothing (the registries are the same dicts the hot path already
writes; the scrape takes the same short locks any reader takes). The
server runs on a daemon thread; ``port=0`` binds an ephemeral port
(tests), a fixed port is the production deployment.

Example::

    exp = MetricsExporter(executor=ex, batcher=b)
    port = exp.start()
    # curl http://127.0.0.1:<port>/metrics
    exp.close()
"""

from __future__ import annotations

import http.server
import json
import re
import threading
from typing import Optional

from raft_tpu.core import tracing
from raft_tpu.serving import metrics as serving_metrics

_NAME_SUB = re.compile(r"[^a-zA-Z0-9_:]").sub


def prom_name(name: str) -> str:
    """Registry name → valid Prometheus metric name."""
    out = _NAME_SUB("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _fmt(v: float) -> str:
    """Shortest float text that round-trips (Prometheus accepts
    scientific notation); integral values render as integers."""
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_prometheus(counters: dict, gauges: dict,
                      histograms: dict) -> str:
    """Render registry snapshots as Prometheus text exposition.

    ``histograms`` maps name → :meth:`Histogram.snapshot` dicts (the
    PR 6 shape with ``bucket_bounds`` + cumulative ``bucket_counts``;
    the final overflow bucket becomes ``le="+Inf"``)."""
    lines = []
    for name in sorted(counters):
        pn = prom_name(name)
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {_fmt(counters[name])}")
    for name in sorted(gauges):
        pn = prom_name(name)
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {_fmt(gauges[name])}")
    for name in sorted(histograms):
        snap = histograms[name]
        pn = prom_name(name)
        lines.append(f"# TYPE {pn} histogram")
        bounds = snap.get("bucket_bounds", [])
        cumulative = snap.get("bucket_counts", [])
        for le, c in zip(bounds, cumulative):
            lines.append(f'{pn}_bucket{{le="{_fmt(le)}"}} {c}')
        lines.append(f'{pn}_bucket{{le="+Inf"}} {snap["count"]}')
        lines.append(f"{pn}_sum {_fmt(snap['sum'])}")
        lines.append(f"{pn}_count {snap['count']}")
    return "\n".join(lines) + "\n"


class MetricsExporter:
    """HTTP pull endpoint for the whole observability surface.

    ``executor`` (optional) contributes its per-executable cost table
    to ``/snapshot.json``; ``batcher`` (optional) contributes the live
    degradation rung and queue depth (polled at scrape time, so the
    rung is current even while the event-driven gauges are quiet)."""

    def __init__(self, executor=None, batcher=None, *,
                 host: str = "127.0.0.1", port: int = 0):
        self.executor = executor
        self.batcher = batcher
        self.host = host
        self.port = port
        self._server: Optional[http.server.ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- payloads (usable without the HTTP server, e.g. in tests) -----------

    def prometheus_text(self) -> str:
        """The ``/metrics`` body: full registries, freshly read."""
        self._refresh()
        return render_prometheus(tracing.counters(), tracing.gauges(),
                                 tracing.histograms())

    def snapshot(self) -> dict:
        """The ``/snapshot.json`` body."""
        self._refresh()
        out = dict(serving_metrics.snapshot())
        out["xla"] = tracing.counters("xla.")
        if self.executor is not None and hasattr(self.executor,
                                                 "executable_costs"):
            out["executables"] = self.executor.executable_costs()
        if self.batcher is not None:
            q = self.batcher._queue
            out["admission"] = {
                "queue_depth": len(q),
                "shed_level": q.shed_level(),
                "arrival_rate_hz": q.arrival_rate(),
            }
        rec = tracing.span_recorder()
        out["spans"] = {"recorded": len(rec), "dropped": rec.dropped,
                        "capacity": rec.capacity}
        return out

    def chrome_trace(self) -> dict:
        """The ``/trace.json`` body (Perfetto overlay input)."""
        return tracing.span_recorder().to_chrome_trace()

    def _refresh(self) -> None:
        """Re-publish the poll-style gauges from the attached executor
        and batcher so a scrape of a quiet service (or one taken after
        ``metrics.reset()``) still reads current state. Both delegate
        to the owning object — the gauge names and derivations live in
        one place each."""
        if self.executor is not None and hasattr(self.executor,
                                                 "publish_cost_gauges"):
            self.executor.publish_cost_gauges()
        if self.batcher is not None:
            self.batcher._queue.publish_gauges()

    # -- server lifecycle ---------------------------------------------------

    def start(self) -> int:
        """Bind and serve on a daemon thread; returns the bound port."""
        if self._server is not None:
            return self.port
        exporter = self

        class Handler(http.server.BaseHTTPRequestHandler):
            # the serving process logs through its own logger; default
            # BaseHTTPRequestHandler stderr chatter is noise
            def log_message(self, fmt, *args):  # noqa: ARG002
                pass

            def _send(self, body: bytes, ctype: str, code: int = 200):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — http.server API
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    self._send(exporter.prometheus_text().encode(),
                               "text/plain; version=0.0.4; "
                               "charset=utf-8")
                elif path == "/snapshot.json":
                    self._send(
                        json.dumps(exporter.snapshot(),
                                   default=str).encode(),
                        "application/json")
                elif path == "/trace.json":
                    self._send(json.dumps(exporter.chrome_trace()).encode(),
                               "application/json")
                elif path == "/healthz":
                    self._send(b"ok\n", "text/plain")
                else:
                    self._send(b"not found\n", "text/plain", 404)

        self._server = http.server.ThreadingHTTPServer(
            (self.host, self.port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="raft-tpu-metrics-exporter", daemon=True)
        self._thread.start()
        return self.port

    def url(self, path: str = "/metrics") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def close(self) -> None:
        """Stop serving and join the server thread (idempotent)."""
        if self._server is None:
            return
        self._server.shutdown()
        self._server.server_close()
        self._server = None
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.close()
