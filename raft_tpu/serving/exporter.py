"""Pull-based export surface (PR 6 graftscope): one stdlib
``http.server`` port serving every observability signal in the process.

Endpoints:

- ``/metrics`` — Prometheus text exposition (version 0.0.4): every
  :mod:`raft_tpu.core.tracing` counter and gauge, plus the latency
  histograms with CUMULATIVE bucket counts (``*_bucket{le="..."}`` /
  ``*_sum`` / ``*_count``) — scrapeable by any Prometheus-compatible
  agent. Metric names are the registry names with non-identifier
  characters folded to ``_`` (``serving.batcher.e2e_seconds`` →
  ``serving_batcher_e2e_seconds``).
- ``/snapshot.json`` — the JSON view: ``serving.metrics.snapshot()``
  (counters, gauges, histograms, occupancy, derived achieved GB/s),
  the attached executor's per-executable cost table, the attached
  batcher's degradation-ladder rung, and flight-recorder stats.
- ``/trace.json`` — the span ring as Chrome trace-event JSON; load it
  into Perfetto next to a ``jax.profiler`` capture to overlay host
  stage spans on the device timeline. ``?trace_id=N`` (PR 7) restricts
  the dump to one request's journey — per-request fetches stop paying
  for the whole ring; an unknown id returns an empty (valid) trace.
- ``/profile?seconds=N`` — on-demand ``jax.profiler`` capture (PR 7):
  gated on a ``profile_dir`` configured at construction (403 when
  absent — a scraper must not be able to write the service's disk), one
  capture at a time (409 while busy), N outside [0, 60] rejected with
  400 (no silent clamping — an operator asking for 120 s should learn
  the cap, not get a shorter capture than requested). Fetch
  ``/trace.json`` for the same window and open both in Perfetto — the
  automated version of the overlay recipe. The response carries the
  capture's ``trace_file`` path (PR 11), so graftflight and operators
  can find what was just captured.
- ``/incident.json`` — the latest graftflight incident bundle (PR 11):
  parsed device-truth attribution + span-ring snapshot + metrics
  snapshot + cost table + live shed rung, produced automatically when
  the multiburn alert or the latency-anomaly check fires (404 while no
  incident has been captured, or no :class:`~raft_tpu.serving.flight
  .FlightRecorder` is attached).
- ``/fleet.json`` — the merged multi-replica view (PR 12 graftfleet):
  with a :class:`~raft_tpu.serving.federation.FleetAggregator`
  attached, one scrape-and-merge over every replica's
  ``/snapshot.json`` — lifetime-ledger counter sums, bucket-merged
  histograms, fleet probe coverage, pooled-Wilson recall, pooled
  drift, per-replica health (404 when no aggregator is attached).
  The federated families also append to ``/metrics`` as
  ``replica=``-labeled + fleet-aggregate samples.
- ``/memory.json`` — the graftledger memory truth (PR 13): with a
  :class:`~raft_tpu.core.memwatch.MemoryLedger` attached, the
  per-index resident-bytes model, live ``device.memory_stats()``
  truth (honest ``supported: false`` on backends without it), the
  reservation forecast, headroom, and the modeled-vs-live divergence
  (404 when no ledger is attached).
- ``/memory_profile`` — a gated ``jax.profiler
  .device_memory_profile`` capture (PR 13): the per-buffer
  device-memory breakdown in pprof wire format, written into
  ``profile_dir`` — same gate (403 unarmed) and the same
  one-capture-at-a-time lock as ``/profile`` (409 while any capture
  runs, either direction). ``?diff=<seq>`` (PR 14) additionally
  parses THIS capture against the earlier sequence-numbered capture
  ``<seq>`` and returns the per-buffer-group byte deltas
  (:func:`raft_tpu.core.memwatch.diff_memory_profiles`) — two
  captures bracketing a window attribute the divergence gauge to
  buffers instead of the whole process (400 on an unknown or
  malformed sequence number).
- ``/tier.json`` — the grafttier placement truth (PR 14): with a
  :class:`~raft_tpu.serving.placement.TierManager` attached, the
  live hot/cold layout, the last placement epoch's plan + evidence
  (window total, hot-window fraction) and the policy config (404
  when no manager is attached). The scrape also drives the
  manager's epoch pacing (``tick``), exactly like graftfleet's
  continuous capture.
- ``POST /push?replica=<name>`` — federation push mode (PR 13): with
  a :class:`~raft_tpu.serving.federation.FleetAggregator` attached,
  a replica behind NAT POSTs its own ``/snapshot.json`` body here
  instead of being scraped; the snapshot enters the SAME type-correct
  merge path (400 without a replica name or a JSON-object body, 404
  without an aggregator).
- ``/healthz`` — liveness probe.

Prometheus label support (PR 7): the per-executable cost gauges render
as ONE metric family per field with a ``digest`` label
(``serving_executable_peak_hbm_bytes{digest="..."}``) instead of a
metric name per executable, and the modeled collective payloads label
by ``family``/``wire``/``probe_wire`` — so dashboards aggregate across
executables with plain PromQL. The old flat names — the sha1-embedded
``serving_executable_<digest>_*`` AND the dotted
``serving_collective_<family>_<wire>_<probe_wire>_*`` spellings — are
kept for one release behind ``legacy_executable_metrics=True``
(deprecated; emitted *in addition* to the labeled families).

The exporter holds NO state of its own: every request re-reads the
live registries, so a scrape is always current and costs the serving
path nothing (the registries are the same dicts the hot path already
writes; the scrape takes the same short locks any reader takes). The
server runs on a daemon thread; ``port=0`` binds an ephemeral port
(tests), a fixed port is the production deployment.

Example::

    exp = MetricsExporter(executor=ex, batcher=b)
    port = exp.start()
    # curl http://127.0.0.1:<port>/metrics
    exp.close()
"""

from __future__ import annotations

import http.server
import json
import re
import threading
import time
import urllib.parse
from typing import Optional

from raft_tpu.core import tracing
from raft_tpu.core.validation import RaftError
from raft_tpu.serving import metrics as serving_metrics

_NAME_SUB = re.compile(r"[^a-zA-Z0-9_:]").sub

# registry names that render as LABELED Prometheus families (PR 7):
# one family per field, one sample per digest / wire combination
_EXEC_GAUGE = re.compile(
    r"^serving\.executable\.([0-9a-f]+)\.([a-z_]+)$")
_COLLECTIVE_GAUGE = re.compile(
    r"^serving\.collective\.([^.]+)\.([^.]+)\.([^.]+)\.([a-z_]+)$")
# graftgauge (PR 8) labeled families: per-index probe-frequency
# top-N samples + summary fields, index-health stats, drift scores —
# the label value is the dot-free <label>/<name> segment
_PROBE_LIST_GAUGE = re.compile(
    r"^index\.probe_freq\.([^.]+)\.list\.([0-9]+)$")
_PROBE_GAUGE = re.compile(
    r"^index\.probe_freq\.([^.]+)\.([a-z0-9_]+)$")
_HEALTH_GAUGE = re.compile(
    r"^index\.health\.([^.]+)\.([a-z0-9_]+)$")
_DRIFT_GAUGE = re.compile(
    r"^index\.drift\.([^.]+)\.(score|alert|rebaselines)$")
# graftfleet (PR 12) labeled families: per-replica health gauges the
# aggregator publishes, fleet probe coverage + drift per index
_FLEET_REPLICA_GAUGE = re.compile(
    r"^fleet\.replica\.([^.]+)\.([a-z0-9_]+)$")
_FLEET_PROBE_GAUGE = re.compile(
    r"^fleet\.probe_freq\.([^.]+)\.([a-z0-9_]+)$")
_FLEET_DRIFT_GAUGE = re.compile(
    r"^fleet\.drift\.([^.]+)\.(score)$")
# graftledger (PR 13) labeled families: per-index resident-bytes
# model samples and per-device live memory truth
_MEM_INDEX_GAUGE = re.compile(
    r"^memory\.index\.([^.]+)\.([a-z0-9_]+)$")
_MEM_DEVICE_GAUGE = re.compile(
    r"^memory\.device\.([0-9]+)\.([a-z0-9_]+)$")
_FLEET_MEM_INDEX_GAUGE = re.compile(
    r"^fleet\.memory\.index\.([^.]+)\.(resident_bytes)$")
# graftroute labeled families: per-replica steer counts and planned
# hot-set sizes
_ROUTE_REPLICA_GAUGE = re.compile(
    r"^fleet\.route\.replica\.([^.]+)\.([a-z0-9_]+)$")
_PLAN_REPLICA_GAUGE = re.compile(
    r"^fleet\.plan\.replica\.([^.]+)\.([a-z0-9_]+)$")
# per-params-class latency histograms (PR 11 graftflight satellite):
# serving.batcher.execute_seconds.p<NP> renders as the base family
# with a params_class label, pairing the sweep recall gauges
# (index.recall.sweep.p<NP>) with a latency axis
_HIST_CLASS = re.compile(
    r"^(serving\.batcher\.[a-z0-9_]+_seconds)\.(p[0-9]+)$")
# per-(params class, tile) pad-waste split counters (graftragged):
# serving.execute.{rows,padded_rows}.p<NP>.t<TILE> render as labeled
# families DISTINCT from the flat aggregates (suffix _split — one
# family must not carry two HELP/TYPE headers), attributing pad waste
# to the small-vs-large dual-tile choice
_PAD_SPLIT = re.compile(
    r"^serving\.execute\.(rows|padded_rows)\.(p[0-9]+)\.t([0-9]+)$")

# HELP text per family prefix (longest match wins; the generic
# fallback keeps every family carrying *a* HELP line — the exposition
# satellite's parse-check requires one per family)
_HELP_PREFIXES = (
    ("serving.executable.", "per-executable compile-time cost analysis"),
    ("serving.collective.", "modeled mesh collective payload bytes"),
    ("serving.admission.", "admission-control state"),
    ("serving.batcher.", "dynamic micro-batcher stage metric"),
    ("serving.execute.", "executor dispatch accounting"),
    ("serving.mesh.", "mesh straggler attribution"),
    ("serving.slo.", "deadline-SLO attainment and burn rate"),
    ("serving.attribution.rolling.", "graftfleet rolling device-truth "
                                     "attribution (EWMA over "
                                     "continuous capture windows)"),
    ("serving.attribution.", "graftflight measured device-time "
                             "attribution totals"),
    ("serving.continuous.", "graftfleet continuous low-duty-cycle "
                            "capture scheduler"),
    ("serving.", "serving-path metric"),
    ("profiling.", "graftflight profiler-trace ingestion"),
    ("incident.", "graftflight incident-capture flight recorder"),
    ("continuous.", "graftfleet continuous-capture scheduling "
                    "accounting"),
    ("fleet.memory.", "graftledger federated memory view (headroom "
                      "min, resident sum)"),
    ("fleet.slo.", "graftledger fleet-level multiburn alert over the "
                   "merged SLO windows"),
    ("fleet.route.", "graftroute query routing (steer coverage, "
                     "fan-out, table lifecycle)"),
    ("fleet.plan.", "graftroute fleet placement planning"),
    ("fleet.", "graftfleet multi-replica federation"),
    ("memory.", "graftledger device-memory truth (resident model, "
                "live stats, reservation forecast)"),
    ("tier.", "grafttier hot/cold placement (layout, epoch policy, "
              "swap accounting)"),
    ("index.probe_freq.", "graftgauge per-list probe-frequency "
                          "accounting"),
    ("index.probe.", "graftgauge probe-accounting dispatch heartbeat"),
    ("index.health.", "graftgauge index-health stat"),
    ("index.recall.", "graftgauge online recall estimation"),
    ("index.drift.", "graftgauge query-drift detection"),
    ("xla.", "XLA backend compile accounting"),
)


def help_text(name: str) -> str:
    """One-line ``# HELP`` text for a registry (or family) name."""
    for prefix, text in _HELP_PREFIXES:
        if name.startswith(prefix):
            return text
    return "raft_tpu registry metric"


def prom_name(name: str) -> str:
    """Registry name → valid Prometheus metric name."""
    out = _NAME_SUB("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _fmt(v: float) -> str:
    """Shortest float text that round-trips (Prometheus accepts
    scientific notation); integral values render as integers."""
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_prometheus(counters: dict, gauges: dict, histograms: dict,
                      legacy_executable_metrics: bool = False) -> str:
    """Render registry snapshots as Prometheus text exposition.

    ``histograms`` maps name → :meth:`Histogram.snapshot` dicts (the
    PR 6 shape with ``bucket_bounds`` + cumulative ``bucket_counts``;
    the final overflow bucket becomes ``le="+Inf"``).

    Every family — flat counters/gauges, LABELED families, histograms
    — carries ``# HELP`` and ``# TYPE`` lines (PR 8 closed the gap
    where only flat families were annotated; the scrape test
    parse-checks the pairing line by line).

    Labeled families: per-executable cost gauges
    (``serving_executable_<field>{digest=...}``), modeled collective
    payloads (``serving_collective_<field>{family=,wire=,probe_wire=}``)
    and the graftgauge index surface —
    ``index_probe_freq_count{index=,list=}`` top-N samples,
    ``index_probe_freq_<field>{index=}`` summaries,
    ``index_health_<field>{index=}`` and ``index_drift_<field>{index=}``.
    ``legacy_executable_metrics=True`` ADDITIONALLY emits the
    deprecated flat names (both the sha1-embedded executable spellings
    and the dotted collective ones) for one release of overlap."""
    lines = []

    def emit_family(pn: str, mtype: str, help_name: str) -> None:
        lines.append(f"# HELP {pn} {help_text(help_name)}")
        lines.append(f"# TYPE {pn} {mtype}")

    # labeled counter families (graftragged pad-waste split): the
    # samples fold into ONE `_split`-suffixed family per base counter
    # — reusing the flat aggregate's name would emit its HELP/TYPE
    # header twice, which the exposition grammar forbids
    labeled_counters: dict = {}
    for name in sorted(counters):
        m = _PAD_SPLIT.match(name)
        if m:
            fam = f"serving_execute_{m.group(1)}_split"
            labeled_counters.setdefault(fam, []).append(
                (f'params_class="{m.group(2)}",tile="{m.group(3)}"',
                 counters[name]))
            continue
        pn = prom_name(name)
        emit_family(pn, "counter", name)
        lines.append(f"{pn} {_fmt(counters[name])}")
    for pn in sorted(labeled_counters):
        emit_family(pn, "counter", "serving.execute.")
        for labels, v in sorted(labeled_counters[pn]):
            lines.append(f"{pn}{{{labels}}} {_fmt(v)}")

    # family prom-name -> {"help": registry prefix, "samples": [...]}
    labeled: dict = {}

    def add_labeled(pn: str, help_name: str, labels: str, v) -> None:
        fam = labeled.setdefault(pn, {"help": help_name, "samples": []})
        fam["samples"].append((labels, v))

    for name in sorted(gauges):
        v = gauges[name]
        m = _EXEC_GAUGE.match(name)
        if m:
            add_labeled(f"serving_executable_{prom_name(m.group(2))}",
                        "serving.executable.",
                        f'digest="{m.group(1)}"', v)
            if not legacy_executable_metrics:
                continue
        else:
            m = _COLLECTIVE_GAUGE.match(name)
            if m:
                add_labeled(
                    f"serving_collective_{prom_name(m.group(4))}",
                    "serving.collective.",
                    f'family="{m.group(1)}",wire="{m.group(2)}",'
                    f'probe_wire="{m.group(3)}"', v)
                if not legacy_executable_metrics:
                    continue
            else:
                # graftgauge index families are labeled-only (they
                # were born in PR 8 — no legacy flat spelling to keep)
                m = _PROBE_LIST_GAUGE.match(name)
                if m:
                    add_labeled("index_probe_freq_count",
                                "index.probe_freq.",
                                f'index="{m.group(1)}",'
                                f'list="{m.group(2)}"', v)
                    continue
                m = _PROBE_GAUGE.match(name)
                if m:
                    add_labeled(
                        f"index_probe_freq_{prom_name(m.group(2))}",
                        "index.probe_freq.",
                        f'index="{m.group(1)}"', v)
                    continue
                m = _HEALTH_GAUGE.match(name)
                if m:
                    add_labeled(
                        f"index_health_{prom_name(m.group(2))}",
                        "index.health.", f'index="{m.group(1)}"', v)
                    continue
                m = _DRIFT_GAUGE.match(name)
                if m:
                    add_labeled(
                        f"index_drift_{prom_name(m.group(2))}",
                        "index.drift.", f'index="{m.group(1)}"', v)
                    continue
                m = _ROUTE_REPLICA_GAUGE.match(name)
                if m:
                    add_labeled(
                        f"fleet_route_replica_{prom_name(m.group(2))}",
                        "fleet.route.", f'replica="{m.group(1)}"', v)
                    continue
                m = _PLAN_REPLICA_GAUGE.match(name)
                if m:
                    add_labeled(
                        f"fleet_plan_replica_{prom_name(m.group(2))}",
                        "fleet.plan.", f'replica="{m.group(1)}"', v)
                    continue
                m = _FLEET_REPLICA_GAUGE.match(name)
                if m:
                    add_labeled(
                        f"fleet_replica_{prom_name(m.group(2))}",
                        "fleet.", f'replica="{m.group(1)}"', v)
                    continue
                m = _FLEET_PROBE_GAUGE.match(name)
                if m:
                    add_labeled(
                        f"fleet_probe_freq_{prom_name(m.group(2))}",
                        "fleet.", f'index="{m.group(1)}"', v)
                    continue
                m = _FLEET_DRIFT_GAUGE.match(name)
                if m:
                    add_labeled("fleet_drift_score", "fleet.",
                                f'index="{m.group(1)}"', v)
                    continue
                m = _MEM_INDEX_GAUGE.match(name)
                if m:
                    add_labeled(
                        f"memory_index_{prom_name(m.group(2))}",
                        "memory.", f'index="{m.group(1)}"', v)
                    continue
                m = _MEM_DEVICE_GAUGE.match(name)
                if m:
                    add_labeled(
                        f"memory_device_{prom_name(m.group(2))}",
                        "memory.", f'device="{m.group(1)}"', v)
                    continue
                m = _FLEET_MEM_INDEX_GAUGE.match(name)
                if m:
                    add_labeled("fleet_memory_index_resident_bytes",
                                "fleet.memory.",
                                f'index="{m.group(1)}"', v)
                    continue
        pn = prom_name(name)
        emit_family(pn, "gauge", name)
        lines.append(f"{pn} {_fmt(v)}")
    for pn in sorted(labeled):
        fam = labeled[pn]
        emit_family(pn, "gauge", fam["help"])
        for labels, v in sorted(fam["samples"]):
            lines.append(f"{pn}{{{labels}}} {_fmt(v)}")
    # histograms group into families first: a params-class variant
    # (serving.batcher.execute_seconds.p<NP>) becomes a LABELED sample
    # set of its base family — HELP/TYPE must be emitted once per
    # family, never once per label value (the exposition grammar the
    # line-by-line scrape test enforces)
    hist_fams: dict = {}
    for name in sorted(histograms):
        m = _HIST_CLASS.match(name)
        if m:
            base, labels = m.group(1), f'params_class="{m.group(2)}"'
        else:
            base, labels = name, ""
        fam = hist_fams.setdefault(prom_name(base),
                                   {"help": base, "samples": []})
        fam["samples"].append((labels, histograms[name]))
    for pn in sorted(hist_fams):
        fam = hist_fams[pn]
        emit_family(pn, "histogram", fam["help"])
        for labels, snap in sorted(fam["samples"], key=lambda s: s[0]):
            pre = labels + "," if labels else ""
            suf = f"{{{labels}}}" if labels else ""
            bounds = snap.get("bucket_bounds", [])
            cumulative = snap.get("bucket_counts", [])
            for le, c in zip(bounds, cumulative):
                lines.append(f'{pn}_bucket{{{pre}le="{_fmt(le)}"}} {c}')
            lines.append(f'{pn}_bucket{{{pre}le="+Inf"}} {snap["count"]}')
            lines.append(f"{pn}_sum{suf} {_fmt(snap['sum'])}")
            lines.append(f"{pn}_count{suf} {snap['count']}")
    return "\n".join(lines) + "\n"


class MetricsExporter:
    """HTTP pull endpoint for the whole observability surface.

    ``executor`` (optional) contributes its per-executable cost table
    to ``/snapshot.json``; ``batcher`` (optional) contributes the live
    degradation rung and queue depth (polled at scrape time, so the
    rung is current even while the event-driven gauges are quiet).
    ``profile_dir`` arms ``/profile`` (None keeps it 403-disabled);
    ``legacy_executable_metrics`` additionally emits the deprecated
    flat per-executable AND per-collective gauge names next to the
    labeled families."""

    def __init__(self, executor=None, batcher=None, *,
                 host: str = "127.0.0.1", port: int = 0,
                 profile_dir: Optional[str] = None,
                 legacy_executable_metrics: bool = False,
                 index_gauge=None, flight=None, continuous=None,
                 fleet=None, memory=None, tier=None, route=None):
        self.executor = executor
        self.batcher = batcher
        self.host = host
        self.port = port
        self.profile_dir = profile_dir
        self.legacy_executable_metrics = legacy_executable_metrics
        # graftgauge (PR 8): an IndexGauge refreshes the index-health /
        # probe-frequency / recall / drift surface per scrape and backs
        # the /index.json endpoint (404 when not attached)
        self.index_gauge = index_gauge
        # graftflight (PR 11): a FlightRecorder evaluates its incident
        # triggers per scrape and backs /incident.json (404 while no
        # incident has been captured — or no recorder is attached)
        self.flight = flight
        # graftfleet (PR 12): a ContinuousCapture ticks per scrape —
        # its low-duty-cycle captures keep the rolling attribution
        # fresh — and a FleetAggregator backs /fleet.json plus the
        # replica=-labeled exposition appended to /metrics
        self.continuous = continuous
        self.fleet = fleet
        # graftledger (PR 13): a MemoryLedger publishes the memory.*
        # gauge surface per scrape, backs /memory.json, and ships the
        # federation "memory" block inside /snapshot.json
        self.memory = memory
        # grafttier (PR 14): a TierManager backs /tier.json and its
        # placement epochs pace off the scrape (tick), like the
        # continuous capture — the exporter is the one periodic pulse
        # every serving process already has
        self.tier = tier
        # graftroute: a QueryRouter backs /route.json, refreshes the
        # fleet.route.* gauges per scrape, and accepts routing-table
        # delivery on the same POST /push channel the federation uses
        # (?route=1 — NAT-bound replicas can't be scraped OR pushed to,
        # so the control plane pushes the table through the exporter
        # they already reach)
        self.route = route
        self._profile_lock = threading.Lock()
        # /memory_profile capture sequence — a counter, not a clock
        # read (R7): the file name only needs to be unique per process
        self._memprof_seq = 0
        # seq -> capture path, for ?diff=<seq> (restart-safe: a seq
        # from a previous process resolves through the file name)
        self._memprof_paths: dict = {}
        for owner in (flight, continuous):
            if owner is not None and getattr(owner, "profile_lock",
                                             None) is None:
                # one profiler capture at a time, ALL directions: the
                # recorder's automatic capture defers while /profile
                # runs, /profile 409s while an incident is being
                # captured, and the continuous tick — the lowest-
                # priority customer — defers to both
                owner.profile_lock = self._profile_lock
        self._server: Optional[http.server.ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- payloads (usable without the HTTP server, e.g. in tests) -----------

    def prometheus_text(self) -> str:
        """The ``/metrics`` body: full registries, freshly read; with
        a :class:`~raft_tpu.serving.federation.FleetAggregator`
        attached, the ``replica=``-labeled + fleet-aggregate federated
        families append after the local ones."""
        self._refresh()
        if self.fleet is not None:
            # one scrape-and-merge per exposition: refreshes the
            # fleet.* gauges BEFORE the local registries render, so
            # the health/coverage families below are current
            self.fleet.fleet_snapshot()
        text = render_prometheus(
            tracing.counters(), tracing.gauges(), tracing.histograms(),
            legacy_executable_metrics=self.legacy_executable_metrics)
        if self.fleet is not None:
            text += self.fleet.prometheus_text()
        return text

    def snapshot(self) -> dict:
        """The ``/snapshot.json`` body. Since PR 12 it also carries
        the federation inputs a :class:`~raft_tpu.serving.federation
        .FleetAggregator` merges: ``counters_lifetime`` (the
        reset-proof ledger fleet counters sum from — the live
        ``counters`` view can go backwards across a
        ``reset_counters()``, the ledger cannot) and, when an
        :class:`~raft_tpu.serving.gauge.IndexGauge` is attached, the
        ``federation`` block (full probe planes, raw recall trials,
        drift state)."""
        self._refresh()
        out = dict(serving_metrics.snapshot())
        out["xla"] = tracing.counters("xla.")
        out["counters_lifetime"] = tracing.lifetime_counters()
        if self.executor is not None and hasattr(self.executor,
                                                 "executable_costs"):
            out["executables"] = self.executor.executable_costs()
        if self.batcher is not None:
            q = self.batcher._queue
            out["admission"] = {
                "queue_depth": len(q),
                "shed_level": q.shed_level(),
                "arrival_rate_hz": q.arrival_rate(),
            }
        if self.index_gauge is not None and hasattr(
                self.index_gauge, "federation_payload"):
            out["federation"] = self.index_gauge.federation_payload()
        if self.memory is not None:
            # graftledger: the memory block a FleetAggregator merges
            # (headroom min, resident sum) — shipped like the
            # graftgauge federation block, absent when no ledger is
            # attached (the aggregator must tolerate that)
            out["memory"] = self.memory.federation_payload()
        rec = tracing.span_recorder()
        out["spans"] = {"recorded": len(rec), "dropped": rec.dropped,
                        "capacity": rec.capacity}
        return out

    def chrome_trace(self, trace_id: Optional[int] = None) -> dict:
        """The ``/trace.json`` body (Perfetto overlay input);
        ``trace_id`` restricts it to one request's spans — an unknown
        id yields an empty trace, not an error (the id may simply have
        aged out of the ring)."""
        return tracing.span_recorder().to_chrome_trace(
            trace_id=trace_id)

    def profile(self, seconds: float) -> dict:
        """Run one gated ``jax.profiler`` capture of ``seconds`` into
        ``profile_dir`` and return ``{"log_dir", "seconds"}``.
        Raises ``PermissionError`` when no ``profile_dir`` was
        configured and ``RuntimeError`` when a capture is already in
        flight — the HTTP layer maps these to 403/409. The capture
        sleeps wall-clock (no clock *read* — R7-clean): profiling
        windows are a wall-time concern, not a batcher-clock one."""
        if self.profile_dir is None:
            raise PermissionError(
                "profiling is disabled: construct MetricsExporter with "
                "profile_dir=... to arm /profile")
        if not self._profile_lock.acquire(blocking=False):
            raise RuntimeError("a profiler capture is already running")
        # the capture's trace file rides in the response (PR 11
        # exporter hardening) so graftflight — and operators — can
        # find what was just captured without globbing profile_dir.
        # Only a file THIS capture produced qualifies (before/after
        # diff): "newest in the dir" would name a previous capture's
        # file — stale data presented as fresh — whenever the current
        # one writes no chrome-trace sidecar; null is the honest
        # answer then.
        from raft_tpu.core import profiling

        before = profiling.trace_snapshot(self.profile_dir)
        try:
            with tracing.capture(self.profile_dir):
                time.sleep(seconds)
        finally:
            self._profile_lock.release()
        return {"log_dir": self.profile_dir, "seconds": seconds,
                "trace_file": profiling.fresh_trace_file(
                    self.profile_dir, before)}

    def memory_snapshot(self) -> dict:
        """The ``/memory.json`` body: the attached
        :class:`~raft_tpu.core.memwatch.MemoryLedger`'s full
        structured view (resident model, live device truth, forecast,
        headroom, divergence, watermarks), freshly published. Raises
        ``LookupError`` when no ledger is attached — the HTTP layer
        maps it to 404."""
        if self.memory is None:
            raise LookupError(
                "no MemoryLedger attached: construct MetricsExporter "
                "with memory=... to arm /memory.json")
        return self.memory.publish()

    def memory_profile(self, diff_seq: Optional[int] = None) -> dict:
        """One gated ``jax.profiler.device_memory_profile`` capture
        — the per-buffer device-memory breakdown (pprof wire format)
        the live gauges summarize. Shares the ``/profile`` lock (one
        profiler customer at a time, all directions) and its gate:
        ``PermissionError`` without a configured ``profile_dir``
        (403), ``RuntimeError`` while any capture runs (409). The
        pprof bytes land in ``profile_dir`` as
        ``memory_profile_<n>.pb.gz`` (sequence-numbered — no clock
        read) and the response carries the path and sequence number.

        ``diff_seq`` (PR 14, ``?diff=<seq>`` over HTTP) additionally
        parses this capture against the earlier capture ``<seq>`` —
        two sequence-numbered captures bracketing a window — and
        returns the per-buffer-group byte deltas
        (:func:`raft_tpu.core.memwatch.diff_memory_profiles`), so
        the divergence gauge's growth attributes to buffer groups
        instead of the whole process. An unknown sequence number
        raises ``ValueError`` (400 over HTTP); a restarted process
        can diff against a previous run's on-disk capture by its
        number."""
        if self.profile_dir is None:
            raise PermissionError(
                "profiling is disabled: construct MetricsExporter with "
                "profile_dir=... to arm /memory_profile")
        import os

        before_path = None
        if diff_seq is not None:
            before_path = self._memprof_paths.get(int(diff_seq))
            if before_path is None:
                # restart-safe: resolve a previous process's capture
                # through the deterministic file name
                cand = os.path.join(
                    self.profile_dir,
                    f"memory_profile_{int(diff_seq):04d}.pb.gz")
                if os.path.exists(cand):
                    before_path = cand
            if before_path is None or not os.path.exists(before_path):
                raise ValueError(
                    f"no memory profile with sequence number "
                    f"{diff_seq} exists to diff against")
        if not self._profile_lock.acquire(blocking=False):
            raise RuntimeError("a profiler capture is already running")
        try:
            import jax

            data = jax.profiler.device_memory_profile()
            os.makedirs(self.profile_dir, exist_ok=True)
            # the sequence restarts with the process: skip names that
            # already exist so a restarted service can never overwrite
            # a previous run's capture — which may be the pre-crash
            # evidence an operator is about to read
            while True:
                self._memprof_seq += 1
                path = os.path.join(
                    self.profile_dir,
                    f"memory_profile_{self._memprof_seq:04d}.pb.gz")
                if not os.path.exists(path):
                    break
            with open(path, "wb") as f:
                f.write(data)
            # captured into a local INSIDE the lock: a concurrent
            # capture bumps _memprof_seq the moment we release, and
            # the response (and diff.to_seq) must name THIS capture
            seq = self._memprof_seq
            self._memprof_paths[seq] = path
        finally:
            self._profile_lock.release()
        out = {"path": path, "bytes": len(data), "seq": seq}
        if before_path is not None:
            from raft_tpu.core import memwatch

            with open(before_path, "rb") as f:
                before = memwatch.parse_memory_profile(f.read())
            after = memwatch.parse_memory_profile(data)
            out["diff"] = dict(
                memwatch.diff_memory_profiles(before, after),
                from_seq=int(diff_seq), to_seq=seq)
        return out

    def tier_snapshot(self) -> dict:
        """The ``/tier.json`` body: the attached
        :class:`~raft_tpu.serving.placement.TierManager`'s layout +
        last-plan view. Raises ``LookupError`` when no manager is
        attached — the HTTP layer maps it to 404."""
        if self.tier is None:
            raise LookupError(
                "no TierManager attached: construct MetricsExporter "
                "with tier=... to arm /tier.json")
        return self.tier.snapshot()

    def route_snapshot(self) -> dict:
        """The ``/route.json`` body: the attached
        :class:`~raft_tpu.fleet.router.QueryRouter`'s live routing
        table + router view. Raises ``LookupError`` when no router
        is attached (or none applied a table yet) — the HTTP layer
        maps it to 404."""
        if self.route is None:
            raise LookupError(
                "no QueryRouter attached: construct MetricsExporter "
                "with route=... to arm /route.json")
        return self.route.snapshot()

    def _refresh(self) -> None:
        """Re-publish the poll-style gauges from the attached executor
        and batcher so a scrape of a quiet service (or one taken after
        ``metrics.reset()``) still reads current state. Both delegate
        to the owning object — the gauge names and derivations live in
        one place each."""
        if self.executor is not None and hasattr(self.executor,
                                                 "publish_cost_gauges"):
            self.executor.publish_cost_gauges()
        if self.batcher is not None:
            self.batcher._queue.publish_gauges()
            if hasattr(self.batcher, "publish_slo_gauges"):
                # burn rate decays as misses age out of the window —
                # re-evaluated at the batcher clock's now per scrape
                self.batcher.publish_slo_gauges()
        if self.index_gauge is not None:
            # graftgauge: one probe-plane fetch shared across the
            # probe-frequency gauges and drift scoring, plus health
            # stats and the shadow-recall window refresh
            self.index_gauge.publish()
        if self.memory is not None:
            # graftledger: re-publish the memory truth (model + live
            # stats + forecast) — BEFORE the flight check below, so a
            # low-headroom trigger evaluates this scrape's numbers
            self.memory.publish()
        if self.tier is not None:
            # grafttier: refresh the layout gauges and pace the
            # placement epochs off the scrape (the manager's injected
            # clock decides whether an epoch is due — one tick runs
            # at most one epoch, like the continuous capture)
            self.tier.publish_gauges()
            self.tier.tick()
        if self.route is not None:
            # graftroute: refresh the coverage/fan-out/table-age
            # gauges from the router's counters (scrape-driven, like
            # the tier layout gauges)
            self.route.publish_gauges()
        if self.flight is not None:
            # graftflight: evaluate the incident triggers — a firing
            # multiburn alert / latency anomaly captures here, rate
            # limited by the recorder's cooldown (a triggered scrape
            # blocks for the short capture; that is the design — the
            # incident evidence is worth one slow scrape)
            self.flight.check()
        if self.continuous is not None:
            # graftfleet: the continuous tick runs AFTER the incident
            # check — incident captures grab the shared profile lock
            # first and the tick defers to them (and to /profile); a
            # due tick costs the scrape one short capture, the
            # duty-cycle budget bounds how often
            self.continuous.tick()

    def index_snapshot(self) -> dict:
        """The ``/index.json`` body: the attached
        :class:`~raft_tpu.serving.gauge.IndexGauge`'s full structured
        view (health, probe-frequency stats, drift, recall), freshly
        published. Raises ``LookupError`` when no gauge is attached —
        the HTTP layer maps it to 404."""
        if self.index_gauge is None:
            raise LookupError(
                "no IndexGauge attached: construct MetricsExporter "
                "with index_gauge=... to arm /index.json")
        return self.index_gauge.publish()

    # -- server lifecycle ---------------------------------------------------

    def start(self) -> int:
        """Bind and serve on a daemon thread; returns the bound port."""
        if self._server is not None:
            return self.port
        exporter = self

        class Handler(http.server.BaseHTTPRequestHandler):
            # the serving process logs through its own logger; default
            # BaseHTTPRequestHandler stderr chatter is noise
            def log_message(self, fmt, *args):  # noqa: ARG002
                pass

            def _send(self, body: bytes, ctype: str, code: int = 200):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — http.server API
                path, _, query = self.path.partition("?")
                # keep_blank_values: '?trace_id=' must surface as a
                # present-but-empty param and 400 below, not silently
                # vanish and dump the whole ring / default-capture
                qs = urllib.parse.parse_qs(query,
                                           keep_blank_values=True)
                if path == "/metrics":
                    self._send(exporter.prometheus_text().encode(),
                               "text/plain; version=0.0.4; "
                               "charset=utf-8")
                elif path == "/snapshot.json":
                    self._send(
                        json.dumps(exporter.snapshot(),
                                   default=str).encode(),
                        "application/json")
                elif path == "/index.json":
                    try:
                        out = exporter.index_snapshot()
                    except LookupError as e:
                        self._send(f"{e}\n".encode(), "text/plain", 404)
                        return
                    self._send(json.dumps(out, default=str).encode(),
                               "application/json")
                elif path == "/fleet.json":
                    if exporter.fleet is None:
                        self._send(b"no FleetAggregator attached\n",
                                   "text/plain", 404)
                        return
                    self._send(
                        json.dumps(exporter.fleet.fleet_snapshot(),
                                   default=str).encode(),
                        "application/json")
                elif path == "/memory.json":
                    try:
                        out = exporter.memory_snapshot()
                    except LookupError as e:
                        self._send(f"{e}\n".encode(), "text/plain", 404)
                        return
                    self._send(json.dumps(out, default=str).encode(),
                               "application/json")
                elif path == "/tier.json":
                    try:
                        out = exporter.tier_snapshot()
                    except LookupError as e:
                        self._send(f"{e}\n".encode(), "text/plain", 404)
                        return
                    self._send(json.dumps(out, default=str).encode(),
                               "application/json")
                elif path == "/route.json":
                    try:
                        out = exporter.route_snapshot()
                    except LookupError as e:
                        self._send(f"{e}\n".encode(), "text/plain", 404)
                        return
                    self._send(json.dumps(out, default=str).encode(),
                               "application/json")
                elif path == "/memory_profile":
                    diff_seq = None
                    if "diff" in qs:
                        try:
                            diff_seq = int(qs["diff"][0])
                        except ValueError:
                            self._send(
                                b"diff must be a capture sequence "
                                b"number\n", "text/plain", 400)
                            return
                    try:
                        out = exporter.memory_profile(diff_seq=diff_seq)
                    except ValueError as e:
                        self._send(f"{e}\n".encode(), "text/plain", 400)
                        return
                    except PermissionError as e:
                        self._send(f"{e}\n".encode(), "text/plain", 403)
                        return
                    except RuntimeError as e:
                        self._send(f"{e}\n".encode(), "text/plain", 409)
                        return
                    except Exception as e:  # noqa: BLE001 — report, don't die
                        self._send(f"capture failed: {e}\n".encode(),
                                   "text/plain", 500)
                        return
                    self._send(json.dumps(out).encode(),
                               "application/json")
                elif path == "/incident.json":
                    bundle = (exporter.flight.latest()
                              if exporter.flight is not None else None)
                    if bundle is None:
                        self._send(b"no incident captured\n",
                                   "text/plain", 404)
                        return
                    self._send(json.dumps(bundle, default=str).encode(),
                               "application/json")
                elif path == "/trace.json":
                    trace_id = None
                    if "trace_id" in qs:
                        try:
                            trace_id = int(qs["trace_id"][0])
                        except ValueError:
                            self._send(b"trace_id must be an integer\n",
                                       "text/plain", 400)
                            return
                    self._send(
                        json.dumps(exporter.chrome_trace(
                            trace_id=trace_id)).encode(),
                        "application/json")
                elif path == "/profile":
                    try:
                        seconds = float(qs.get("seconds", ["1.0"])[0])
                    except ValueError:
                        seconds = -1.0
                    if not 0.0 <= seconds <= 60.0:
                        self._send(b"seconds must be in [0, 60]\n",
                                   "text/plain", 400)
                        return
                    try:
                        out = exporter.profile(seconds)
                    except PermissionError as e:
                        self._send(f"{e}\n".encode(), "text/plain", 403)
                        return
                    except RuntimeError as e:
                        self._send(f"{e}\n".encode(), "text/plain", 409)
                        return
                    except Exception as e:  # noqa: BLE001 — report, don't die
                        self._send(f"capture failed: {e}\n".encode(),
                                   "text/plain", 500)
                        return
                    self._send(json.dumps(out).encode(),
                               "application/json")
                elif path == "/healthz":
                    self._send(b"ok\n", "text/plain")
                else:
                    self._send(b"not found\n", "text/plain", 404)

            def do_POST(self):  # noqa: N802 — http.server API
                path, _, query = self.path.partition("?")
                qs = urllib.parse.parse_qs(query,
                                           keep_blank_values=True)
                if path != "/push":
                    self._send(b"not found\n", "text/plain", 404)
                    return
                if "route" in qs:
                    # graftroute table delivery: the control plane
                    # pushes a fresh routing table over the channel
                    # a NAT-bound replica already exposes; version
                    # gating makes out-of-order delivery harmless
                    # (stale -> 409, the pusher's signal to re-read
                    # /route.json before trying again)
                    if exporter.route is None:
                        self._send(b"no QueryRouter attached\n",
                                   "text/plain", 404)
                        return
                    try:
                        length = int(
                            self.headers.get("Content-Length", 0))
                        if length > 8 * 1024 * 1024:
                            self._send(b"table body too large\n",
                                       "text/plain", 413)
                            return
                        doc = json.loads(
                            self.rfile.read(length).decode())
                        applied = exporter.route.apply_table(doc)
                    except (ValueError, UnicodeDecodeError,
                            RaftError) as e:
                        self._send(f"bad routing table: {e}\n"
                                   .encode(), "text/plain", 400)
                        return
                    if not applied:
                        self._send(b"stale table version\n",
                                   "text/plain", 409)
                        return
                    self._send(json.dumps({"applied": True}).encode(),
                               "application/json")
                    return
                # federation push mode (PR 13): replicas behind NAT
                # POST the same body they would serve at
                # /snapshot.json; it enters the aggregator through
                # the SAME type-correct merge path a scrape feeds
                if exporter.fleet is None or not hasattr(
                        exporter.fleet, "push"):
                    self._send(b"no FleetAggregator attached\n",
                               "text/plain", 404)
                    return
                replica = qs.get("replica", [""])[0]
                if not replica:
                    self._send(b"replica query parameter required\n",
                               "text/plain", 400)
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    # a snapshot body is a few MB at the very most —
                    # an unbounded read would let one request buffer
                    # arbitrary bytes into the aggregator process
                    if length > 8 * 1024 * 1024:
                        self._send(b"snapshot body too large\n",
                                   "text/plain", 413)
                        return
                    snap = json.loads(
                        self.rfile.read(length).decode())
                    if not isinstance(snap, dict):
                        raise ValueError("snapshot body must be a "
                                         "JSON object")
                except (ValueError, UnicodeDecodeError) as e:
                    self._send(f"bad snapshot body: {e}\n".encode(),
                               "text/plain", 400)
                    return
                try:
                    exporter.fleet.push(replica, snap)
                except ValueError as e:
                    # the push-replica registry cap: refuse loudly —
                    # 429 tells a legitimate replica to back off and
                    # an operator that the registry is full
                    self._send(f"{e}\n".encode(), "text/plain", 429)
                    return
                self._send(json.dumps({"accepted": replica}).encode(),
                           "application/json")

        self._server = http.server.ThreadingHTTPServer(
            (self.host, self.port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="raft-tpu-metrics-exporter", daemon=True)
        self._thread.start()
        return self.port

    def url(self, path: str = "/metrics") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def close(self) -> None:
        """Stop serving and join the server thread (idempotent)."""
        if self._server is None:
            return
        self._server.shutdown()
        self._server.server_close()
        self._server = None
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.close()
