"""graftflight incident capture (PR 11) — the flight recorder that
fires itself.

graftscope gave the serving plane a span ring, SLO burn-rate windows,
and a gated ``/profile`` capture — but an operator had to be watching
at the moment of an incident to use any of it: by the time a page
fires, the interesting spans have aged out of the ring and the device
behavior that caused the miss is gone. :class:`FlightRecorder` closes
that gap: the :class:`~raft_tpu.serving.metrics.MultiBurnAlert` (PR 8)
and a windowed latency-anomaly check ARM a short, rate-limited
automatic profiler capture, and the result — the parsed device-truth
attribution (:mod:`raft_tpu.core.profiling`), a span-ring snapshot,
the metrics snapshot, the executable cost table, and the live shed
rung — lands as an on-disk **incident bundle** and is retrievable at
the exporter's ``/incident.json`` endpoint (404 while none exists).

Triggers (evaluated by :meth:`FlightRecorder.check`, which the
exporter's scrape refresh drives):

- **multiburn_alert** — the ``serving.slo.alert`` gauge is firing
  (both burn-rate windows over budget — the SRE page condition).
- **latency_anomaly** — the e2e latency histogram's p99 over the
  window SINCE THE LAST CHECK exceeds the configured threshold (delta
  of the cumulative bucket counts, so a long-healthy service's history
  cannot mask a fresh stall, and the check is a pure function of the
  histogram snapshots — ManualClock tests pin it exactly).
- **low_headroom** (PR 13 graftledger) — an attached
  :class:`~raft_tpu.core.memwatch.MemoryLedger` reports device
  headroom at/below ``FlightConfig.low_headroom_bytes``: the replica
  is drifting toward an OOM, and the incident evidence worth having
  is the one from BEFORE the crash. The bundle then also carries the
  full memory snapshot (model, live stats, forecast, divergence).

Rate limiting: at most one bundle per ``cooldown_s`` (clock domain —
the batcher's injectable clock, so the manual-clock tests pin the
window exactly); suppressed triggers count into
``incident.suppressed``. Clock discipline (graftlint R7): every
timestamp comes from the injected clock; the only wall-time touch is
the capture's ``time.sleep`` (a duration, not a clock read — same
exemption as ``/profile``).
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import threading
import time
from typing import Callable, List, Optional

from raft_tpu.core import profiling, tracing
from raft_tpu.serving import metrics as serving_metrics
from raft_tpu.serving.batcher import MonotonicClock

# lifetime counters (ci/bench_compare.py snapshot floors): bundles
# actually produced, and triggers the cooldown swallowed
INCIDENT_BUNDLES = "incident.bundles"
INCIDENT_SUPPRESSED = "incident.suppressed"


def timed_capture(profile_dir: str, seconds: float) -> Optional[str]:
    """One short ``jax.profiler`` capture into ``profile_dir``;
    returns the trace file THIS capture produced, or None when it
    wrote none (before/after mtime diff — falling back to "newest in
    the dir" would republish a previous capture's device timings as
    current evidence). Shared by the flight recorder's incident
    capture and the continuous low-duty-cycle scheduler
    (:mod:`raft_tpu.serving.continuous`); callers own the one-capture-
    at-a-time lock discipline. ``time.sleep`` is a duration, not a
    clock read — the R7 exemption ``/profile`` documents."""
    before = profiling.trace_snapshot(profile_dir)
    with tracing.capture(profile_dir):
        time.sleep(seconds)
    return profiling.fresh_trace_file(profile_dir, before)


def window_quantile(bounds, cum_window, q: float) -> float:
    """Quantile estimate over a WINDOW histogram given as cumulative
    per-bucket counts (the delta of two
    :meth:`~raft_tpu.core.tracing.Histogram.snapshot` cumulative
    vectors is itself cumulative) — the same linear-in-bucket
    interpolation the live histograms use, as a pure function so the
    anomaly check is pinned by scripted observations. ``bounds`` has
    one entry fewer than ``cum_window`` (the last bucket is
    overflow, estimated inside ``(last, 2*last]``)."""
    total = cum_window[-1] if cum_window else 0
    if total <= 0:
        return 0.0
    target = q * total
    prev = 0
    for i, cum in enumerate(cum_window):
        c = cum - prev
        if cum >= target and c > 0:
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = (bounds[i] if i < len(bounds) else bounds[-1] * 2.0)
            return lo + (hi - lo) * (target - prev) / c
        prev = cum
    return bounds[-1] * 2.0 if bounds else 0.0


@dataclasses.dataclass(frozen=True)
class LatencyAnomaly:
    """Latency-anomaly trigger policy: fire when the named histogram's
    p99 over the window since the last check reaches
    ``p99_threshold_s``, provided the window saw at least
    ``min_count`` observations (a single slow request in an idle
    window is noise, not an incident)."""

    histogram: str = serving_metrics.E2E
    p99_threshold_s: float = 1.0
    min_count: int = 8


@dataclasses.dataclass(frozen=True)
class FlightConfig:
    """Tuning knobs for :class:`FlightRecorder`.

    ``cooldown_s`` rate-limits bundle production (clock domain);
    ``capture_seconds`` is the automatic profiler capture's length —
    deliberately short: the device behavior that is missing deadlines
    RIGHT NOW is the evidence, not a leisurely profile. ``bundle_dir``
    persists bundles as ``incident_<n>.json`` (None keeps them
    in-memory only — ``/incident.json`` still serves the latest);
    ``max_bundles`` bounds the in-memory ring. ``latency`` configures
    the anomaly trigger (None disables it; the multiburn trigger is
    always live when the gauge exists). ``low_headroom_bytes`` arms
    the graftledger memory trigger (PR 13): an attached
    :class:`~raft_tpu.core.memwatch.MemoryLedger` reporting headroom
    at/below this many bytes is an incident (None keeps it off — and
    a ledger that cannot measure headroom, e.g. on CPU, never
    fires)."""

    cooldown_s: float = 300.0
    capture_seconds: float = 0.5
    bundle_dir: Optional[str] = None
    max_bundles: int = 16
    latency: Optional[LatencyAnomaly] = dataclasses.field(
        default_factory=LatencyAnomaly)
    low_headroom_bytes: Optional[float] = None


class FlightRecorder:
    """SLO-triggered incident capture over the live registries.

    ``executor``/``batcher`` contribute the cost table (and its
    ``hlo_module`` correlation identities) and the live shed rung;
    ``clock`` defaults to the batcher's injectable clock so every
    bundle timestamp and the cooldown window live in the serving
    clock domain. ``profile_dir`` arms the automatic ``jax.profiler``
    capture (None skips it — bundles then carry no attribution);
    ``capture_fn`` overrides the capture entirely (tests inject a
    fixture trace; it may return a trace file path, a parsed
    Chrome-trace dict, or None).

    Example::

        flight = FlightRecorder(executor=ex, batcher=b,
                                profile_dir="/var/tmp/prof",
                                config=FlightConfig(cooldown_s=60.0))
        exp = MetricsExporter(executor=ex, batcher=b, flight=flight)
        # every scrape now evaluates the triggers; incidents land at
        # /incident.json and under bundle_dir
    """

    def __init__(self, executor=None, batcher=None, *,
                 config: Optional[FlightConfig] = None, clock=None,
                 profile_dir: Optional[str] = None,
                 capture_fn: Optional[Callable] = None,
                 memory=None):
        self.executor = executor
        self.batcher = batcher
        # graftledger (PR 13): a MemoryLedger arms the low_headroom
        # trigger and contributes the memory snapshot to every bundle
        self.memory = memory
        self.config = config or FlightConfig()
        if clock is None:
            clock = (batcher._clock if batcher is not None
                     else MonotonicClock())
        self._clock = clock
        self.profile_dir = profile_dir
        self.capture_fn = capture_fn
        # shared with the exporter's /profile endpoint when attached
        # (MetricsExporter wires its _profile_lock in): only one
        # profiler capture may run process-wide — jax.profiler raises
        # on a second start_trace, which would strip the incident of
        # its attribution exactly when an operator is already
        # investigating. A busy lock DEFERS the incident to the next
        # check instead of consuming the cooldown on a doomed capture.
        self.profile_lock: Optional[threading.Lock] = None
        self._lock = threading.Lock()
        self._bundles: "collections.deque" = collections.deque(  # guarded-by: _lock
            maxlen=max(int(self.config.max_bundles), 1))
        self._seq = 0  # guarded-by: _lock
        self._last_capture: Optional[float] = None  # guarded-by: _lock
        # latency-window baseline: primed at construction so the first
        # check's window starts HERE, not at process start (a service
        # attaching a recorder mid-life must not re-judge its history)
        self._last_cum: Optional[list] = None  # guarded-by: _lock
        if self.config.latency is not None:
            self._last_cum = tracing.get_histogram(
                self.config.latency.histogram).snapshot()["bucket_counts"]

    # -- triggers -----------------------------------------------------------

    def _latency_window(self) -> tuple:
        """(window p99, window count) since the last check — a delta
        of cumulative bucket counts, advancing the baseline. Called
        under the lock; advances on EVERY check (also rate-limited
        ones), so each observation is judged exactly once."""
        lat = self.config.latency
        snap = tracing.get_histogram(lat.histogram).snapshot()
        cum = snap["bucket_counts"]
        prev = self._last_cum
        self._last_cum = cum
        if prev is None or len(prev) != len(cum):
            prev = [0] * len(cum)
        window = [c - p for c, p in zip(cum, prev)]
        count = window[-1] if window else 0
        return window_quantile(snap["bucket_bounds"], window, 0.99), count

    def _triggers_locked(self) -> List[str]:
        reasons = []
        if tracing.get_gauge(serving_metrics.SLO_ALERT) >= 1.0:
            reasons.append("multiburn_alert")
        if self.config.latency is not None:
            p99, count = self._latency_window()
            if (count >= self.config.latency.min_count
                    and p99 >= self.config.latency.p99_threshold_s):
                reasons.append("latency_anomaly")
        if (self.memory is not None
                and self.config.low_headroom_bytes is not None):
            # a ledger that cannot measure headroom (None — no live
            # stats, no configured capacity) never fires: ignorance
            # is not an incident. The exporter's refresh publishes
            # the ledger right before this check runs — read that
            # snapshot instead of recomputing the same truth; only a
            # recorder driven with no publish at all (standalone
            # check() callers) pays the fresh read.
            snap = getattr(self.memory, "last_snapshot", None)
            room = (snap["headroom_bytes"] if snap is not None
                    else self.memory.headroom_bytes())
            if room is not None and \
                    room <= self.config.low_headroom_bytes:
                reasons.append("low_headroom")
        return reasons

    # -- capture ------------------------------------------------------------

    def _capture(self):
        """One short profiler capture; returns a trace source
        (:func:`raft_tpu.core.profiling.load_trace` input) or None.
        Only a file THIS capture produced is returned (before/after
        diff of the capture dir) — falling back to "newest in the
        dir" would republish a previous incident's device timings as
        current evidence when the fresh capture writes no chrome
        trace. ``time.sleep`` is a duration, not a clock read — the
        same R7 exemption the ``/profile`` endpoint documents."""
        if self.capture_fn is not None:
            return self.capture_fn()
        if self.profile_dir is None:
            return None
        return timed_capture(self.profile_dir,
                             self.config.capture_seconds)

    def _build_bundle(self, now: float, reasons: List[str],
                      seq: int) -> dict:
        attribution = None
        trace_file = None
        error = None
        try:
            source = self._capture()
            if source is not None and self.executor is not None \
                    and hasattr(self.executor, "executable_costs"):
                attr = profiling.attribute(
                    source, self.executor.executable_costs())
                # measured supersedes modeled at the moment it matters:
                # the incident's spans/gauges re-emit device truth
                profiling.publish(attr)
                attribution = attr.to_dict()
                trace_file = attr.trace_file
            elif isinstance(source, (str, os.PathLike)):
                trace_file = os.fspath(source)
        except Exception as e:  # noqa: BLE001 — a failed capture must not
            # fail the incident: a bundle without attribution still
            # carries the span ring and metrics the post-mortem needs
            error = f"{type(e).__name__}: {e}"
        rec = tracing.span_recorder()
        bundle = {
            "incident": seq,
            "time": now,
            "triggers": list(reasons),
            "slo": tracing.gauges("serving.slo."),
            "metrics": serving_metrics.snapshot(),
            "spans": rec.to_chrome_trace(),
            "span_ring": {"recorded": len(rec), "dropped": rec.dropped,
                          "capacity": rec.capacity},
            "attribution": attribution,
            "trace_file": trace_file,
        }
        if error is not None:
            bundle["capture_error"] = error
        if self.executor is not None and hasattr(self.executor,
                                                 "executable_costs"):
            bundle["executables"] = self.executor.executable_costs()
        if self.memory is not None:
            # the graftledger snapshot at the moment of the incident:
            # for a low_headroom trigger this IS the evidence; for
            # any other trigger it rules memory pressure in or out
            bundle["memory"] = self.memory.snapshot()
        if self.batcher is not None:
            q = self.batcher._queue
            bundle["shed_level"] = q.shed_level()
            bundle["queue_depth"] = len(q)
        return bundle

    def _persist(self, bundle: dict) -> Optional[str]:
        if self.config.bundle_dir is None:
            return None
        os.makedirs(self.config.bundle_dir, exist_ok=True)
        path = os.path.join(self.config.bundle_dir,
                            f"incident_{bundle['incident']:04d}.json")
        with open(path, "w") as f:
            json.dump(bundle, f, indent=2, sort_keys=True, default=str)
            f.write("\n")
        return path

    # -- public API ---------------------------------------------------------

    def check(self, now: Optional[float] = None) -> Optional[dict]:
        """Evaluate the triggers at clock time ``now`` and, when one
        fires outside the cooldown, capture → attribute → bundle.
        Returns the new bundle, or None (quiet, or rate-limited — the
        latter counted in ``incident.suppressed``). The exporter's
        scrape refresh calls this, so an armed service needs no extra
        thread; it can also be driven directly (tests, a sidecar
        loop)."""
        if now is None:
            now = self._clock.now()
        with self._lock:
            reasons = self._triggers_locked()
            if not reasons:
                return None
            for r in reasons:
                tracing.inc_counter(f"incident.trigger.{r}")
            if (self._last_capture is not None
                    and now - self._last_capture < self.config.cooldown_s):
                tracing.inc_counter(INCIDENT_SUPPRESSED)
                return None
            if (self.profile_lock is not None
                    and not self.profile_lock.acquire(blocking=False)):
                # an operator's /profile capture owns the profiler:
                # DEFER (cooldown untouched) rather than burn the one
                # rate-limited incident on a capture that cannot run
                tracing.inc_counter("incident.deferred")
                return None
            self._last_capture = now
            self._seq += 1
            seq = self._seq
        # the capture itself runs OUTSIDE the lock: it sleeps
        # capture_seconds, and a concurrent scrape's check() must see
        # the advanced cooldown stamp instead of blocking behind it
        # (the held profile_lock meanwhile 409s /profile — the same
        # one-capture-at-a-time contract, both directions)
        try:
            bundle = self._build_bundle(now, reasons, seq)
        finally:
            if self.profile_lock is not None:
                self.profile_lock.release()
        path = self._persist(bundle)
        if path is not None:
            bundle["bundle_path"] = path
        with self._lock:
            self._bundles.append(bundle)
            n = len(self._bundles)
        tracing.inc_counter(INCIDENT_BUNDLES)
        tracing.set_gauges({"incident.count": float(n),
                            "incident.last_time": now})
        return bundle

    def latest(self) -> Optional[dict]:
        """The most recent incident bundle (``/incident.json``'s body),
        or None when nothing has fired."""
        with self._lock:
            return self._bundles[-1] if self._bundles else None

    def bundles(self) -> List[dict]:
        """All retained bundles, oldest first."""
        with self._lock:
            return list(self._bundles)
