"""graftgauge — the index-health half of observability (PR 8).

graftscope (PRs 6-7) made the *serving path* legible; this module makes
the *index itself* legible. Four connected pieces, all publishing
through the :mod:`raft_tpu.core.tracing` registries so the existing
exporter scrapes them like everything else:

- **Probe-frequency accounting** lives in the executor
  (``SearchExecutor(probe_accounting=True)`` — a donated device-side
  counter plane per index, fetched once per scrape); this module's
  :class:`IndexGauge` drives its publication and shares the one fetch
  with drift detection.
- **Index health** (:func:`raft_tpu.core.tracing.index_health`) —
  list-occupancy skew, dead/overflow lists, per-shard imbalance —
  published as ``index.health.<name>.*`` gauges per watched index.
- **Online recall estimation** (:class:`ShadowSampler` /
  :class:`RecallWindow`) — a seeded fraction of live requests is
  re-run through an exact (brute-force) index as *background-class*
  work riding the normal admission ladder, so overload sheds shadow
  queries first; completed pairs feed a windowed recall estimate with
  a Wilson binomial confidence interval
  (``index.recall.estimate`` / ``.ci_low`` / ``.ci_high``).
- **Query-drift detection** (:class:`DriftDetector`) — the live
  centroid-assignment histogram (per-scrape deltas of the probe
  counters, EWMA-smoothed) against a build-time baseline snapshot via
  a streaming Jensen-Shannon divergence (``index.drift.score``), so a
  stale-index alert fires before recall visibly degrades.

Clock discipline (graftlint R7): every timestamp here comes from the
batcher's injectable clock, so the whole surface is deterministic
under the manual-clock fault harness. Host-sync discipline (R5): the
recall comparison and health fetches only touch handles that already
completed and index metadata — nothing here runs on the dispatch path.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import threading
from typing import Any, Dict, Optional

import numpy as np

from raft_tpu.core import tracing
from raft_tpu.serving.request import Overloaded, ShutDown

# counters: the shadow-query lifecycle ledger
SHADOW_SUBMITTED = "index.recall.shadow_submitted"
SHADOW_COMPLETED = "index.recall.shadow_completed"
SHADOW_SHED = "index.recall.shadow_shed"
SHADOW_DROPPED = "index.recall.shadow_dropped"
SHADOW_SKIPPED = "index.recall.shadow_skipped"


def wilson_interval(hits: int, trials: int,
                    z: float = 1.96) -> tuple:
    """Wilson score interval for a binomial proportion — the standard
    small-sample-honest CI (never escapes [0, 1], sane at p near 0/1
    where the normal approximation lies). Returns ``(low, high)``;
    an empty sample is maximally uncertain: ``(0, 1)``."""
    if trials <= 0:
        return (0.0, 1.0)
    p = hits / trials
    z2 = z * z
    denom = 1.0 + z2 / trials
    center = (p + z2 / (2.0 * trials)) / denom
    half = (z * math.sqrt(p * (1.0 - p) / trials
                          + z2 / (4.0 * trials * trials)) / denom)
    return (max(0.0, center - half), min(1.0, center + half))


class RecallWindow:
    """Sliding-window recall@k accounting in the batcher clock domain.

    Each completed (live, shadow) pair contributes ``hits`` matched
    neighbors out of ``trials = rows * k`` — a binomial sample, so the
    windowed estimate carries a Wilson interval. Same discipline as
    :class:`~raft_tpu.serving.metrics.SloWindow`: caller timestamps
    only, one lock, O(pairs-pruned) per operation."""

    def __init__(self, window_s: float = 300.0, z: float = 1.96,
                 decay_half_life_s: Optional[float] = None,
                 gauge_prefix: str = "index.recall"):
        self.window_s = window_s
        self.z = z
        # the published gauge family; a params-sweep leg publishes
        # under "index.recall.sweep.p<NP>" so the operating point and
        # the frontier samples stay separate scrape families
        self.gauge_prefix = gauge_prefix
        # exponential-decay weighting (PR 8 follow-on): a uniform
        # window reacts to sudden index staleness only as old pairs
        # age out; with a half-life each pair's weight is
        # 0.5**(age/half_life), so fresh evidence dominates within a
        # couple of half-lives while the window still bounds memory.
        # None (default) keeps the original uniform weighting.
        self.decay_half_life_s = decay_half_life_s
        self._lock = threading.Lock()
        self._events: "collections.deque" = collections.deque()  # guarded-by: _lock
        self._hits = 0    # guarded-by: _lock
        self._trials = 0  # guarded-by: _lock
        # decay path: running sums of event weights, anchored at
        # ``_anchor`` — scaling both sums by the elapsed decay factor
        # on access keeps record/estimate O(events-pruned), never
        # O(window); record() sits on the shadow-completion path
        self._wh = 0.0  # guarded-by: _lock
        self._wt = 0.0  # guarded-by: _lock
        self._anchor: Optional[float] = None  # guarded-by: _lock

    def _decay_to_locked(self, now: float) -> None:
        if self._anchor is None:
            self._anchor = now
        elif now > self._anchor:
            f = 0.5 ** ((now - self._anchor) / self.decay_half_life_s)
            self._wh *= f
            self._wt *= f
            self._anchor = now

    def _prune_locked(self, now: float) -> None:
        horizon = now - self.window_s
        while self._events and self._events[0][0] <= horizon:
            t, h, n = self._events.popleft()
            self._hits -= h
            self._trials -= n
            if self.decay_half_life_s is not None:
                # the event's CURRENT weight (sums sit at _anchor)
                w = 0.5 ** ((self._anchor - t)
                            / self.decay_half_life_s)
                self._wh -= w * h
                self._wt -= w * n

    def record(self, now: float, hits: int, trials: int) -> None:
        """Count one shadow pair's outcome and re-publish."""
        with self._lock:
            self._events.append((now, int(hits), int(trials)))
            self._hits += int(hits)
            self._trials += int(trials)
            if self.decay_half_life_s is not None:
                self._decay_to_locked(now)
                self._wh += int(hits)
                self._wt += int(trials)
        self.publish(now)

    def estimate(self, now: float) -> dict:
        """Windowed recall estimate + Wilson CI as of ``now``. With
        ``decay_half_life_s`` set, hits and trials are
        exponential-decay weighted by age; the CI then uses the
        weighted trial mass as its sample size — less than the raw
        count, so decay honestly WIDENS the interval as evidence
        ages."""
        with self._lock:
            if self.decay_half_life_s is not None:
                self._decay_to_locked(now)
            self._prune_locked(now)
            if self.decay_half_life_s is None:
                hits, trials = float(self._hits), float(self._trials)
            else:
                # float-subtraction residue from pruning stays tiny;
                # clamp so an emptied window reads exactly no evidence
                hits = self._wh if self._events else 0.0
                trials = self._wt if self._events else 0.0
                hits, trials = max(hits, 0.0), max(trials, 0.0)
            pairs = len(self._events)
        est = hits / trials if trials else 0.0
        lo, hi = wilson_interval(hits, trials, self.z)
        return {"estimate": est, "ci_low": lo, "ci_high": hi,
                "pairs": pairs, "trials": trials}

    def raw(self, now: float) -> dict:
        """The window's UNWEIGHTED hit/trial counts as of ``now`` —
        the federation payload (graftfleet): replicas pool raw trials
        and the fleet aggregator applies the Wilson interval to the
        POOLED sample, which is strictly tighter than any combination
        of per-replica intervals."""
        with self._lock:
            self._prune_locked(now)
            return {"hits": int(self._hits),
                    "trials": int(self._trials),
                    "pairs": len(self._events)}

    def publish(self, now: float) -> dict:
        """Re-publish the ``index.recall.*`` gauges as of ``now`` —
        called on every record and by the scrape-time refresh, so the
        estimate's window slides even while no shadows complete."""
        e = self.estimate(now)
        p = self.gauge_prefix
        tracing.set_gauges({
            f"{p}.estimate": e["estimate"],
            f"{p}.ci_low": e["ci_low"],
            f"{p}.ci_high": e["ci_high"],
            f"{p}.window_pairs": float(e["pairs"]),
            f"{p}.window_trials": float(e["trials"]),
        })
        return e


@dataclasses.dataclass(frozen=True)
class ShadowConfig:
    """Shadow-query sampling policy.

    ``fraction`` of live submissions re-run through the exact index;
    the sampler's RNG is seeded (``seed``) so the sampled subset — and
    therefore every downstream recall/drift number — is deterministic
    for a given submission sequence. Shadow requests ride the normal
    admission ladder as the *background class*: ``priority`` should
    sit at/above the batcher's ``LoadShed.background_priority`` so the
    ladder rejects shadow work first under load, and ``timeout_s``
    bounds how long a queued shadow may wait before the expiry shed
    reclaims it — live traffic never waits on shadow work.
    ``max_pending`` bounds the unresolved-pair buffer (overflow drops
    the oldest pair, counted in ``index.recall.shadow_dropped`` — as
    is a pair whose LIVE leg failed, so every submitted pair resolves
    into the ledger: submitted == completed + shed-after-admission +
    dropped; ``shadow_shed`` additionally counts admission-rejected
    shadows that never became pairs)."""

    fraction: float = 0.01
    seed: int = 0
    priority: int = 1 << 16
    timeout_s: Optional[float] = 1.0
    window_s: float = 300.0
    max_pending: int = 256
    # params-sweep shadow sampling (PR 8 follow-on): alternative
    # n_probes values to re-run sampled submissions at, as EXTRA
    # background-class legs paired against the same exact truth —
    # ``index.recall.sweep.p<NP>.*`` then maps the live
    # recall/latency frontier instead of just the operating point.
    # Values rotate round-robin across sampled submissions (seeded
    # sampling keeps the rotation deterministic); () disables.
    sweep_probes: tuple = ()


class ShadowSampler:
    """Online recall estimation by shadow re-execution.

    Wraps a :class:`~raft_tpu.serving.batcher.DynamicBatcher`:
    :meth:`submit` forwards the live request untouched and, for a
    seeded ``fraction`` of submissions, also enqueues the same query
    block against ``exact_index`` (the existing brute-force family) as
    a background-class request. :meth:`pump` — called from the
    exporter's scrape refresh, or directly in tests — resolves
    completed pairs into the :class:`RecallWindow`. Shadow failures of
    any typed serving kind count as sheds, never as errors: shedding
    shadow work under load is the design, and the recall gauge simply
    loses samples (its widening Wilson interval says so honestly).

    Example::

        exact = brute_force.build(res, BruteForceIndexParams(), dataset)
        sampler = ShadowSampler(batcher, exact,
                                ShadowConfig(fraction=0.05))
        handle = sampler.submit(index, queries, k=10, params=p)
    """

    def __init__(self, batcher, exact_index,
                 config: Optional[ShadowConfig] = None):
        import random

        self.batcher = batcher
        self.exact_index = exact_index
        self.config = config or ShadowConfig()
        self._clock = batcher._clock
        self._rng = random.Random(self.config.seed)
        self._lock = threading.Lock()
        self._pending: "collections.deque" = collections.deque()  # guarded-by: _lock
        self.window = RecallWindow(window_s=self.config.window_s)
        # params-sweep legs: one window per swept n_probes, published
        # as its own gauge family — together they sample the live
        # recall side of the recall/latency frontier
        self.sweep_windows = {
            int(p): RecallWindow(
                window_s=self.config.window_s,
                gauge_prefix=f"index.recall.sweep.p{int(p)}")
            for p in self.config.sweep_probes}
        self._sweep_cursor = 0  # guarded-by: _lock

    def submit(self, index, queries, k: int, params=None, **kw):
        """Submit one live request (exactly ``batcher.submit``) and
        maybe tag it with a shadow. Returns the LIVE handle; the
        shadow's lifecycle is the sampler's business alone. A shadow
        rejected at admission (the background class is the ladder's
        first casualty) is counted shed and the live path is
        unaffected.

        FILTERED requests are never shadowed: recall must compare the
        ANN result against the exact truth over the SAME candidate
        set, and the brute-force family has no filter support — so a
        filtered pair would score healthy traffic against the wrong
        (unfiltered) truth and read as permanent staleness. Such
        submissions count ``index.recall.shadow_skipped`` and the
        estimate honestly covers unfiltered traffic only.

        With ``sweep_probes`` configured, a sampled submission also
        re-runs at ONE alternative ``n_probes`` (round-robin over the
        sweep values) as an extra background-class leg scored against
        the same exact truth — the per-value
        ``index.recall.sweep.p<NP>.*`` windows then map the live
        recall frontier, not just the operating point. The sweep leg
        shares the shadow's shed-first discipline; a submission whose
        ``params`` has no ``n_probes`` knob simply sweeps nothing."""
        handle = self.batcher.submit(index, queries, k, params=params,
                                     **kw)
        with self._lock:
            sampled = self._rng.random() < self.config.fraction
        if not sampled:
            return handle
        if kw.get("sample_filter") is not None:
            tracing.inc_counter(SHADOW_SKIPPED)
            return handle
        try:
            shadow = self.batcher.submit(
                self.exact_index, queries, k,
                priority=self.config.priority,
                timeout_s=self.config.timeout_s)
        except (Overloaded, ShutDown):
            tracing.inc_counter(SHADOW_SHED)
            return handle
        tracing.inc_counter(SHADOW_SUBMITTED)
        with self._lock:
            self._pending.append((handle, shadow, k, None))
            while len(self._pending) > self.config.max_pending:
                self._pending.popleft()
                tracing.inc_counter(SHADOW_DROPPED)
        if self.sweep_windows and hasattr(params, "n_probes"):
            with self._lock:
                order = sorted(self.sweep_windows)
                probes = order[self._sweep_cursor % len(order)]
                self._sweep_cursor += 1
            sweep_params = dataclasses.replace(params, n_probes=probes)
            try:
                leg = self.batcher.submit(
                    index, queries, k, params=sweep_params,
                    priority=self.config.priority,
                    timeout_s=self.config.timeout_s)
            except (Overloaded, ShutDown):
                tracing.inc_counter(SHADOW_SHED)
                return handle
            tracing.inc_counter(SHADOW_SUBMITTED)
            with self._lock:
                self._pending.append((leg, shadow, k, probes))
                while len(self._pending) > self.config.max_pending:
                    self._pending.popleft()
                    tracing.inc_counter(SHADOW_DROPPED)
        return handle

    @staticmethod
    def _pair_hits(live_ids, exact_ids, k: int) -> tuple:
        """(hits, trials) of one completed pair: per-row overlap of
        the ANN ids with the exact ids — recall@k counted over
        ``rows * k`` binomial trials. Host arrays only (both handles
        completed, so the batcher already blocked on the device)."""
        a = np.asarray(live_ids)
        e = np.asarray(exact_ids)
        hits = 0
        for r in range(a.shape[0]):
            truth = e[r][e[r] >= 0]
            hits += int(np.isin(a[r], truth).sum())
        return hits, a.shape[0] * k

    def pump(self) -> int:
        """Resolve every pair whose handles both completed; returns
        pairs folded into the window. Unfinished pairs stay queued —
        this never blocks on a handle."""
        now = self._clock.now()
        done = []
        with self._lock:
            keep = collections.deque()
            for pair in self._pending:
                if pair[0].done() and pair[1].done():
                    done.append(pair)
                else:
                    keep.append(pair)
            self._pending = keep
        resolved = 0
        for live, shadow, k, probes in done:
            if shadow.exception(timeout=0) is not None:
                # expiry-shed / ladder-rejected / shutdown shadow —
                # the designed overload behavior, not an error
                tracing.inc_counter(SHADOW_SHED)
                continue
            if live.exception(timeout=0) is not None:
                # the LIVE (or sweep) leg failed (shed/cancelled) —
                # the pair is unscorable; count it dropped so the
                # lifecycle ledger keeps summing:
                # submitted == completed + shed + dropped
                tracing.inc_counter(SHADOW_DROPPED)
                continue
            hits, trials = self._pair_hits(
                live.result()[1], shadow.result()[1], k)
            window = (self.window if probes is None
                      else self.sweep_windows[probes])
            window.record(now, hits, trials)
            tracing.inc_counter(SHADOW_COMPLETED)
            resolved += 1
        return resolved

    def publish(self) -> dict:
        """Scrape-time refresh: resolve finished pairs and re-publish
        the recall gauges (operating point + every sweep window) at
        the clock's now."""
        self.pump()
        now = self._clock.now()
        for w in self.sweep_windows.values():
            w.publish(now)
        return self.window.publish(now)


class DriftDetector:
    """Streaming divergence of live traffic from a build-time baseline.

    ``baseline`` is the build-time centroid-assignment histogram — the
    index's ``list_sizes`` plane is exactly that (each stored row was
    assigned to its nearest center), so
    :meth:`from_index` snapshots it at attach time. :meth:`update`
    takes the *cumulative* live probe plane (the executor's counter
    fetch), diffs it against the previous scrape into a per-window
    assignment histogram, folds it into an EWMA (``alpha`` per
    scrape), and scores the smoothed histogram against the baseline
    with the bounded Jensen-Shannon divergence
    (:func:`raft_tpu.core.tracing.js_divergence`). Deterministic:
    pure function of the scrape sequence, no clock, no RNG — the
    fixed-seed shadow tests pin the score exactly. One lock serializes
    :meth:`update`: the exporter's HTTP server is threaded, and two
    concurrent scrapes racing the ``_last`` diff would double-fold the
    same traffic window into the EWMA."""

    def __init__(self, baseline, *, alpha: float = 0.2,
                 alert_threshold: float = 0.15):
        self.baseline = np.asarray(baseline, dtype=np.float64)
        self.alpha = alpha
        self.alert_threshold = alert_threshold
        self._lock = threading.Lock()
        self._last: Optional[np.ndarray] = None  # guarded-by: _lock
        self._ewma: Optional[np.ndarray] = None  # guarded-by: _lock
        # EWMA of per-window probe traffic (same alpha): the weight a
        # fleet aggregator scales this replica's normalized live
        # histogram by — without it, pooling would weigh an idle
        # replica the same as one carrying 99% of fleet traffic
        self._traffic = 0.0  # guarded-by: _lock
        # identity watch (PR 8 follow-on): which index object this
        # baseline was snapshotted from. extend()/rebuild returns a NEW
        # index whose list_sizes shifted — scoring live traffic against
        # the stale build-time histogram would read as permanent drift,
        # so the scrape-time publisher rebaselines when the watched
        # identity (or the plane shape) changes.
        self._watched = None
        self.score = 0.0
        self.updates = 0
        self.rebaselines = 0

    @classmethod
    def from_index(cls, index, **kw) -> "DriftDetector":
        """Snapshot ``index.list_sizes`` as the baseline (one fetch,
        at attach time — never on the dispatch path) and watch the
        index's identity for automatic rebaselining."""
        import jax

        det = cls(np.asarray(jax.device_get(index.list_sizes)), **kw)
        det.watch(index)
        return det

    def watch(self, index) -> None:
        """Pair this detector's baseline with ``index``'s identity (a
        weakref — the detector must not keep a replaced index alive)."""
        import weakref

        try:
            self._watched = weakref.ref(index)
        except TypeError:            # non-weakref-able index objects
            self._watched = None

    def matches(self, index) -> bool:
        """Whether the current baseline still describes ``index``: the
        plane shapes agree AND (when an identity is watched) the index
        is the very object the baseline came from. A detector built
        from a raw baseline array matches any shape-compatible index
        until it is first watched."""
        n = int(getattr(index, "n_lists", self.baseline.shape[0]))
        if self.baseline.shape[0] != n:
            return False
        return self._watched is None or self._watched() is index

    def rebaseline(self, index) -> None:
        """Re-snapshot the baseline from (a rebuilt/extended)
        ``index`` and reset the streaming state — the smoothed live
        histogram and the last-scrape plane describe traffic scored
        against the OLD baseline (and may even be the wrong length),
        so both restart; the score holds at 0 until fresh traffic
        accumulates. Counted in ``rebaselines`` (published per watched
        index by :class:`IndexGauge`)."""
        import jax

        sizes = np.asarray(jax.device_get(index.list_sizes),
                           dtype=np.float64)
        with self._lock:
            self.baseline = sizes
            self._last = None
            self._ewma = None
            self._traffic = 0.0
            self.score = 0.0
            self.updates = 0         # folds against the NEW baseline
            self.rebaselines += 1
        self.watch(index)

    @property
    def alert(self) -> bool:
        return self.score >= self.alert_threshold

    def state(self) -> dict:
        """The streaming state as plain lists — the federation
        payload (graftfleet): the fleet aggregator pools replicas'
        smoothed live histograms and baselines and re-scores the
        POOLED traffic. ``traffic`` (the EWMA of per-window probe
        counts) is the pooling weight: the live histogram is
        NORMALIZED per replica, so without it a drifted replica
        carrying 99% of fleet traffic would be averaged away by idle
        undrifted peers."""
        with self._lock:
            return {
                "baseline": [float(v) for v in self.baseline],
                "live": ([float(v) for v in self._ewma]
                         if self._ewma is not None else None),
                "traffic": self._traffic,
                "score": self.score,
                "updates": self.updates,
            }

    def update(self, cumulative_counts) -> float:
        """Fold one scrape's cumulative probe plane into the score."""
        c = np.asarray(cumulative_counts, dtype=np.float64)
        with self._lock:
            delta = c if self._last is None else np.maximum(
                c - self._last, 0.0)
            self._last = c
            if delta.sum() <= 0:
                return self.score    # no new traffic — score holds
            hist = delta / delta.sum()
            self._ewma = (hist if self._ewma is None
                          else self.alpha * hist
                          + (1.0 - self.alpha) * self._ewma)
            self._traffic = (float(delta.sum()) if self.updates == 0
                             else self.alpha * float(delta.sum())
                             + (1.0 - self.alpha) * self._traffic)
            self.score = tracing.js_divergence(self._ewma,
                                               self.baseline)
            self.updates += 1
            return self.score


class IndexGauge:
    """One scrape-time publisher tying graftgauge together.

    Attach it to the exporter (``MetricsExporter(index_gauge=...)``)
    and every ``/metrics`` scrape refreshes — with ONE probe-plane
    fetch shared between probe-frequency gauges and drift scoring —
    while ``/index.json`` serves the full structured view.

    ``indexes`` maps gauge names to served index objects (their
    ``list_sizes`` reduce through ``index_health`` each scrape — a
    small metadata fetch); ``drift`` maps the same names to
    :class:`DriftDetector` instances (paired with the live probe plane
    via ``executor.probe_label``); ``sampler`` is the optional
    :class:`ShadowSampler`."""

    def __init__(self, executor=None,
                 indexes: Optional[Dict[str, Any]] = None,
                 sampler: Optional[ShadowSampler] = None,
                 drift: Optional[Dict[str, DriftDetector]] = None,
                 top_n: int = 8):
        self.executor = executor
        self.indexes = dict(indexes or {})
        self.sampler = sampler
        self.drift = dict(drift or {})
        self.top_n = top_n

    def _health(self, name: str, index) -> dict:
        import jax

        sizes = np.asarray(jax.device_get(index.list_sizes))
        shards = getattr(getattr(index, "comms", None), "size", 0)
        stats = tracing.index_health(
            sizes, max_list_size=index.max_list_size, shards=shards)
        base = f"index.health.{name}."
        tracing.set_gauges({base + k: float(v)
                            for k, v in stats.items()})
        return stats

    def publish(self) -> dict:
        """Refresh every graftgauge surface; returns the
        ``/index.json`` body. One probe-plane fetch, one ``list_sizes``
        fetch per watched index — per scrape, never per dispatch."""
        out: dict = {"health": {}, "probe_freq": {}, "drift": {},
                     "recall": None}
        planes: dict = {}
        if self.executor is not None and hasattr(self.executor,
                                                 "probe_frequencies"):
            planes = self.executor.probe_frequencies()
            out["probe_freq"] = self.executor.publish_probe_gauges(
                top_n=self.top_n, planes=planes)
        for name, index in self.indexes.items():
            out["health"][name] = self._health(name, index)
        worst = 0.0
        for name, det in self.drift.items():
            index = self.indexes.get(name)
            if index is not None and not det.matches(index):
                # the watched index was rebuilt/extended (new identity
                # or a new list count): refresh the baseline instead of
                # scoring live traffic against the stale build-time
                # histogram
                det.rebaseline(index)
            elif index is not None:
                det.watch(index)     # adopt raw-baseline detectors
            label = (self.executor.probe_label(index)
                     if self.executor is not None and index is not None
                     else None)
            if label is not None and label in planes:
                det.update(planes[label])
            tracing.set_gauges({
                f"index.drift.{name}.score": det.score,
                f"index.drift.{name}.alert": float(det.alert),
                f"index.drift.{name}.rebaselines":
                    float(det.rebaselines),
            })
            worst = max(worst, det.score)
            out["drift"][name] = {"score": det.score,
                                  "alert": det.alert,
                                  "updates": det.updates,
                                  "rebaselines": det.rebaselines}
        if self.drift:
            tracing.set_gauge(tracing.DRIFT_SCORE, worst)
        if self.sampler is not None:
            out["recall"] = self.sampler.publish()
        return out

    def federation_payload(self) -> dict:
        """The type-correct merge inputs a fleet aggregator needs
        beyond the metric registries (graftfleet) — shipped inside
        ``/snapshot.json`` when an :class:`IndexGauge` is attached:

        - ``probe_planes`` — the FULL cumulative per-list probe
          plane per label (the top-N gauge samples are a rendering,
          not a mergeable plane; fleet hot/cold coverage needs every
          list's count so replica sums land exactly),
        - ``recall`` — raw windowed hit/trial counts per window
          (operating point + each sweep leg), pooled fleet-side
          BEFORE the Wilson interval,
        - ``drift`` — per watched index the smoothed live histogram
          and baseline, re-scored fleet-side on the pooled traffic.

        One probe-plane fetch, at scrape time — never per dispatch."""
        out: dict = {"probe_planes": {}, "recall": {}, "drift": {}}
        if self.executor is not None and hasattr(self.executor,
                                                 "probe_frequencies"):
            out["probe_planes"] = {
                label: [int(v) for v in plane]
                for label, plane in
                self.executor.probe_frequencies().items()}
        if self.sampler is not None:
            now = self.sampler._clock.now()
            out["recall"]["live"] = self.sampler.window.raw(now)
            for probes, w in self.sampler.sweep_windows.items():
                out["recall"][f"sweep.p{probes}"] = w.raw(now)
        for name, det in self.drift.items():
            out["drift"][name] = det.state()
        return out
