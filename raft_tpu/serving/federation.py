"""graftfleet metric federation (PR 12) — N replicas, one truth.

Every gauge plane the repo grew through PRs 6-11 — probe frequency,
recall windows, drift, SLO burn, attribution — is per-executor-
process: a deployment serving millions of users across N replicas has
N disconnected truths. :class:`FleetAggregator` closes that gap the
Prometheus-federation way: it scrapes each replica's
``/snapshot.json`` (stdlib urllib, bounded staleness, per-replica
health) and merges them with TYPE-CORRECT semantics — summing a gauge
that doesn't sum, or Wilson-intervaling per-replica estimates, would
produce confident nonsense:

- **Counters** sum from the **lifetime ledger**
  (``counters_lifetime`` — :func:`raft_tpu.core.tracing
  .lifetime_counters`), not the resettable live registries: a
  replica's mid-scrape ``reset_counters()`` folds into its ledger
  instead of vanishing, and the aggregator additionally holds a
  per-(replica, counter) high-water mark so a fleet counter can NEVER
  go backwards (regressions are clamped and counted in
  ``fleet.monotonicity_violations`` — a restarted replica resets its
  ledger legitimately; the fleet total must still be monotone).
- **Histograms** merge bucket-wise (same log2 bounds across the repo;
  cumulative bucket vectors sum elementwise) and the fleet quantiles
  recompute from the MERGED distribution — never averaged p99s.
- **Probe-frequency planes** sum per list into fleet hot/cold
  coverage (:func:`raft_tpu.core.tracing.probe_freq_stats` over the
  summed plane) — the tiered-storage placement signal at deployment
  scope, not per replica.
- **Recall windows** pool raw trials across replicas BEFORE the
  Wilson interval — strictly tighter than any combination of
  per-replica intervals.
- **Drift** re-scores the POOLED live histogram against the pooled
  baseline, so a drifted replica weighs by its traffic share.
- **Memory** (PR 13 graftledger): per-replica ``memory`` blocks merge
  as resident-bytes SUM (each replica holds its own copy) and
  headroom MIN over replicas that measured one — the placement
  question is "where does the hot tier still fit", answered by the
  worst-off replica, never an average. Replicas without the block
  (older builds, no ledger attached) are skipped and counted in
  ``replicas_reporting`` — missing data must not read as zero bytes
  or infinite room.

Two PR 13 additions close the PR 12 follow-ons: **push mode**
(:meth:`FleetAggregator.push` / the exporter's ``POST /push``) lets a
replica behind NAT deliver the same ``/snapshot.json`` body the
scraper would have fetched — it enters the same clamped-counter merge
path and the same staleness contract; and **fleet-level multiburn
alerting** (``FleetConfig(multiburn=...)``) folds each merge's delta
of the summed attained/missed counters into a 5 m + 1 h
:class:`~raft_tpu.serving.metrics.MultiBurnAlert` pair published as
``fleet.slo.burn_rate.{5m,1h}`` / ``fleet.slo.alert`` — the page
condition at deployment scope, where one burning replica hides inside
N−1 healthy peers' averages.

Staleness contract: a replica whose scrape fails keeps serving its
last snapshot until ``staleness_s``, then drops unhealthy. CUMULATIVE
surfaces (counters, probe planes) retain the stale replica's
last-known values — they are monotone lower bounds on truth, and
dropping them would make fleet counters jump backwards. WINDOWED and
instantaneous surfaces (recall, drift, admission gauges, histograms)
come from healthy replicas only — stale window contents are not
current state.

The merged view serves as ``/fleet.json`` plus a ``replica=``-labeled
and fleet-aggregate Prometheus exposition through the aggregator's
own :class:`~raft_tpu.serving.exporter.MetricsExporter`
(``MetricsExporter(fleet=...)``). Clock discipline (graftlint R7):
staleness ages come from the injected clock; host-sync discipline
(R5): everything here is urllib + dict work — no device anywhere.
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import json
import math
import re
import threading
import urllib.request
from typing import Dict, List, Optional, Tuple

from raft_tpu.core import tracing
from raft_tpu.serving.batcher import MonotonicClock
from raft_tpu.serving.flight import window_quantile
from raft_tpu.serving.gauge import wilson_interval
from raft_tpu.serving.metrics import MultiBurnAlert, MultiBurnConfig

SCRAPES = "fleet.scrapes"
SCRAPE_ERRORS = "fleet.scrape_errors"
MONOTONICITY_VIOLATIONS = "fleet.monotonicity_violations"
BOUND_MISMATCHES = "fleet.histogram_bound_mismatches"
PUSHES = "fleet.pushes"

# names/labels that reach gauge registry names (and from there
# Prometheus label values) must stay one dot-free segment of safe
# characters — push names and pushed memory labels arrive off the
# network, where a quote or newline in a label value is an exposition
# forgery, not a spelling (same discipline as MemoryLedger.watch)
_LABEL_SUB = re.compile(r"[^A-Za-z0-9_:-]").sub
_LABEL_MAX = 64

# at most this many pushed/merged per-index memory labels publish as
# fleet gauges per merge (largest residents win): gauges are
# process-lifetime, so unbounded label cardinality from ONE replica's
# snapshot body would grow every exposition forever (the same leak
# PR 8's top-N probe gauges and PR 11's params-class cap close)
MEMORY_LABEL_CAP = 32


def _safe_label(name: str) -> str:
    return _LABEL_SUB("-", str(name))[:_LABEL_MAX] or "unnamed"


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """``staleness_s`` bounds how long a failed replica's last
    snapshot keeps representing it; ``timeout_s`` is the per-replica
    HTTP fetch timeout (a hung replica must not stall the whole fleet
    scrape past it). ``multiburn`` (PR 13) arms fleet-level burn-rate
    alerting: per merge, the deltas of the summed replica
    attained/missed counters fold into a 5 m + 1 h
    :class:`~raft_tpu.serving.metrics.MultiBurnAlert` pair published
    under ``fleet.slo.*`` — the page condition at deployment scope,
    where one replica's burn can hide inside N−1 healthy peers'
    averages (None keeps fleet alerting off)."""

    staleness_s: float = 60.0
    timeout_s: float = 2.0
    multiburn: Optional[MultiBurnConfig] = None
    # push mode auto-registers unseen replica names; the endpoint is
    # network-reachable, so the registry must be bounded — a client
    # minting a fresh name per request would otherwise grow the
    # aggregator (and every merge/exposition walk) without limit
    max_push_replicas: int = 64


@dataclasses.dataclass
class ReplicaState:
    """One replica's scrape bookkeeping (all timestamps clock-domain).
    ``push: True`` marks a push-mode replica (PR 13): it is never
    fetched — its snapshots arrive via ``POST /push`` — but ages,
    merges, and goes stale exactly like a scraped one."""

    name: str
    url: str
    snapshot: Optional[dict] = None
    scraped_at: Optional[float] = None
    scrapes: int = 0
    errors: int = 0
    last_error: Optional[str] = None
    push: bool = False

    def age_s(self, now: float) -> float:
        return (float("inf") if self.scraped_at is None
                else now - self.scraped_at)

    def healthy(self, now: float, staleness_s: float) -> bool:
        return self.snapshot is not None and \
            self.age_s(now) <= staleness_s


@dataclasses.dataclass(frozen=True)
class ProbePlaneView:
    """One merged probe plane, typed (graftroute's planner input —
    the planner must never parse the ``/fleet.json`` dict by string
    key). ``counts`` is the elementwise sum over every replica that
    ever reported the label (stale last-known retained — the plane
    is cumulative, like the counters); ``stale_replicas`` names the
    contributors whose snapshot is past the staleness horizon."""

    label: str
    counts: Tuple[int, ...]
    replicas: Tuple[str, ...]
    stale_replicas: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class ReplicaHeadroom:
    """One replica's memory headroom, typed, with the staleness
    metadata a planner needs to discount it. ``headroom_bytes`` is
    None when the replica is stale or reported no (finite) headroom
    — absence of evidence, never a guessed number."""

    name: str
    headroom_bytes: Optional[float]
    age_s: Optional[float]
    healthy: bool
    push: bool


def _http_fetch(url: str, timeout: float) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode())


def merge_histograms(snaps: List[dict]) -> Optional[dict]:
    """Bucket-wise merge of same-bounds histogram snapshots (the
    :meth:`~raft_tpu.core.tracing.Histogram.snapshot` shape):
    cumulative bucket vectors sum elementwise, quantiles recompute
    from the merged distribution. None when no snapshot matches the
    first one's bounds (callers count the mismatch)."""
    snaps = [s for s in snaps if s and s.get("bucket_bounds")]
    if not snaps:
        return None
    bounds = list(snaps[0]["bucket_bounds"])
    merged = [s for s in snaps if list(s["bucket_bounds"]) == bounds]
    cum = [0] * (len(bounds) + 1)
    count, total = 0, 0.0
    for s in merged:
        for i, c in enumerate(s["bucket_counts"]):
            cum[i] += c
        count += s["count"]
        total += s["sum"]
    return {
        "count": count,
        "sum": total,
        "p50": window_quantile(bounds, cum, 0.50),
        "p95": window_quantile(bounds, cum, 0.95),
        "p99": window_quantile(bounds, cum, 0.99),
        "bucket_bounds": bounds,
        "bucket_counts": cum,
        "replicas": len(merged),
        "dropped_bound_mismatch": len(snaps) - len(merged),
    }


class FleetAggregator:
    """Scrape-and-merge federation over N replica exporters.

    ``replicas`` maps replica names to their ``/snapshot.json`` URLs
    (a bare base URL gets the path appended); a plain list of URLs
    auto-names them ``r0..rN``. ``fetch`` overrides the HTTP fetch
    (tests and fixtures inject ``fetch(url, timeout) -> dict``).

    Example::

        agg = FleetAggregator({"a": "http://10.0.0.1:9100",
                               "b": "http://10.0.0.2:9100"})
        exp = MetricsExporter(fleet=agg, port=9200)
        # curl :9200/fleet.json   — the merged fleet view
        # curl :9200/metrics      — replica=-labeled + fleet families
    """

    def __init__(self, replicas, *,
                 config: Optional[FleetConfig] = None, clock=None,
                 fetch=None):
        self.config = config or FleetConfig()
        self._clock = clock if clock is not None else MonotonicClock()
        self._fetch = fetch if fetch is not None else _http_fetch
        if not isinstance(replicas, dict):
            replicas = {f"r{i}": u for i, u in enumerate(replicas)}
        self._lock = threading.Lock()
        self._states: Dict[str, ReplicaState] = {}  # guarded-by: _lock
        for name, url in replicas.items():
            if not url.endswith(".json"):
                url = url.rstrip("/") + "/snapshot.json"
            self._states[name] = ReplicaState(name=name, url=url)
        # per-(replica, counter) high-water marks: the monotonicity
        # assertion — a fleet counter can never go backwards, however
        # a replica's registries were reset mid-scrape
        self._high: Dict[str, Dict[str, float]] = {  # guarded-by: _lock
            name: {} for name in self._states}
        # the last merged view (set by merge()): the exposition path
        # renders from it instead of re-running the whole merge —
        # /metrics already merged once in fleet_snapshot()
        self._last_merged: Optional[dict] = None  # guarded-by: _lock
        # fleet-level multiburn alerting (PR 13): the merged
        # attained/missed sums' last-seen values, and the paired
        # windows the per-merge deltas fold into. The fleet sums are
        # monotone by construction (high-water clamped), so the
        # deltas are non-negative however replicas restart.
        self._burn: Optional[MultiBurnAlert] = None
        self._burn_prev: Optional[Dict[str, float]] = None
        if self.config.multiburn is not None:
            self._burn = MultiBurnAlert(self.config.multiburn,
                                        prefix="fleet.slo.")

    # -- scraping -----------------------------------------------------------

    def _clamp_counters_locked(self, name: str, snap: dict) -> None:
        """Fold one snapshot's lifetime counters into the replica's
        high-water marks. The LIFETIME ledger is the source — the
        resettable live ``counters`` view is only a fallback for
        payloads predating it — and any regression (replica restart)
        clamps to the mark rather than dragging the fleet sum down."""
        counters = snap.get("counters_lifetime")
        if not isinstance(counters, dict):
            counters = snap.get("counters") or {}
        high = self._high[name]
        for cname, v in counters.items():
            try:
                v = float(v)
            except (TypeError, ValueError):
                continue
            if not math.isfinite(v):
                # JSON `1e999` parses to inf: ratcheting a high-water
                # mark to inf (or NaN) would poison every future
                # fleet sum — and the multiburn delta's int() —
                # irreversibly. Off the network, non-finite is
                # garbage, not a measurement.
                continue
            prev = high.get(cname, 0.0)
            if v < prev:
                tracing.inc_counter(MONOTONICITY_VIOLATIONS)
            high[cname] = max(prev, v)

    def scrape(self, now: Optional[float] = None) -> int:
        """Fetch every replica's snapshot once — CONCURRENTLY, so N
        hung replicas stall the whole scrape by ~one ``timeout_s``,
        not N of them stacked (the scrape runs inside the exporter's
        ``/metrics`` handler; a partial outage must not push the
        aggregator's own exposition past the Prometheus scrape
        timeout exactly when the fleet view matters most). Returns
        the healthy count. A failed fetch keeps the replica's
        previous snapshot (bounded by ``staleness_s`` at merge time)
        and counts into its error tally + ``fleet.scrape_errors``."""
        if now is None:
            now = self._clock.now()
        tracing.inc_counter(SCRAPES)
        # push-mode replicas are never fetched — their snapshots
        # arrive through push(); they still count into health below.
        # snapshot the replica list under the lock: a concurrent
        # push() registering a new replica mutates the dict
        with self._lock:
            states = [s for s in self._states.values() if not s.push]
            all_states = list(self._states.values())
        if not states:
            results = []
        elif len(states) == 1:
            results = [self._fetch_one(states[0])]
        else:
            with concurrent.futures.ThreadPoolExecutor(
                    max_workers=min(8, len(states))) as pool:
                results = list(pool.map(self._fetch_one, states))
        healthy = 0
        for state, (snap, err) in zip(states, results):
            if err is not None:
                state.errors += 1
                state.last_error = err
                tracing.inc_counter(SCRAPE_ERRORS)
                continue
            with self._lock:
                state.snapshot = snap
                state.scraped_at = now
                state.scrapes += 1
                self._clamp_counters_locked(state.name, snap)
        for state in all_states:
            if state.healthy(now, self.config.staleness_s):
                healthy += 1
        return healthy

    def push(self, name: str, snapshot: dict,
             now: Optional[float] = None) -> None:
        """Accept one pushed snapshot from replica ``name`` (the
        ``POST /push`` body — the same JSON the replica would serve
        at ``/snapshot.json``). Unknown names auto-register as
        push-mode replicas; a pushed snapshot enters the SAME
        clamped-counter bookkeeping a scrape does, so every merge
        semantic — lifetime-ledger sums, monotonicity, staleness —
        applies unchanged. A NAT replica that stops pushing simply
        goes stale after ``staleness_s``. At most
        ``config.max_push_replicas`` push-mode names may register
        (``ValueError`` past the cap — the endpoint is network-
        reachable, and an unbounded registry would let one
        name-minting client grow every merge walk forever)."""
        if not isinstance(snapshot, dict):
            raise ValueError(
                f"push for {name!r} got {type(snapshot).__name__}, "
                "not a snapshot dict")
        # the name reaches gauge registry names and Prometheus label
        # values — sanitize it the way MemoryLedger.watch does (a
        # quote/newline off the network is exposition forgery)
        name = _safe_label(name)
        if now is None:
            now = self._clock.now()
        with self._lock:
            state = self._states.get(name)
            if state is not None and not state.push:
                # an unauthenticated push must never impersonate a
                # configured scrape replica: overwriting its snapshot
                # would ratchet its monotone high-water counters with
                # whatever the pusher claims, irreversibly
                raise ValueError(
                    f"replica {name!r} is scrape-mode: refusing a "
                    "pushed snapshot for it")
            if state is None:
                pushed = sum(1 for s in self._states.values() if s.push)
                if pushed >= self.config.max_push_replicas:
                    raise ValueError(
                        f"push replica limit reached "
                        f"({self.config.max_push_replicas}): refusing "
                        f"to register {name!r}")
                state = ReplicaState(name=name, url=f"push:{name}",
                                     push=True)
                self._states[name] = state
                self._high.setdefault(name, {})
            tracing.inc_counter(PUSHES)
            state.snapshot = snapshot
            state.scraped_at = now
            state.scrapes += 1
            self._clamp_counters_locked(name, snapshot)

    def _fetch_one(self, state: ReplicaState) -> tuple:
        """(snapshot, None) or (None, error-text) — one replica's
        fetch, exception-safe (one dead replica must not fail the
        fleet scrape; pool.map would re-raise)."""
        try:
            snap = self._fetch(state.url, self.config.timeout_s)
            if not isinstance(snap, dict):
                raise ValueError(
                    f"replica {state.name} returned "
                    f"{type(snap).__name__}, not a snapshot dict")
            return snap, None
        except Exception as e:  # noqa: BLE001
            return None, f"{type(e).__name__}: {e}"

    # -- merging (pure functions of the scraped state) ----------------------

    def _merge_locked(self, now: float) -> dict:
        cfg = self.config
        states = list(self._states.values())
        healthy = [s for s in states
                   if s.healthy(now, cfg.staleness_s)]
        out: dict = {
            "size": len(states),
            "healthy": len(healthy),
            "replicas": {
                s.name: {
                    "url": s.url,
                    "healthy": s.healthy(now, cfg.staleness_s),
                    "age_s": (None if s.scraped_at is None
                              else now - s.scraped_at),
                    "scrapes": s.scrapes,
                    "errors": s.errors,
                    "last_error": s.last_error,
                } for s in states},
        }
        # counters: lifetime-ledger sums over the high-water marks —
        # stale replicas retain their last-known (monotone lower
        # bound) contribution; see the module docstring
        counters: Dict[str, float] = {}
        for name, high in self._high.items():
            for cname, v in high.items():
                counters[cname] = counters.get(cname, 0.0) + v
        out["counters"] = counters
        # tiering (PR 18 graftcast): the placement + prefetch
        # counters already entered the monotone clamped sums above —
        # restate them as one structured block (the /fleet.json
        # surface an operator reads for fleet-wide tier behaviour),
        # with the derived prefetch hit rate. A replica predating
        # tiering simply contributes zeros.
        tier = {
            "epochs": counters.get("tier.epochs", 0.0),
            "promotions": counters.get("tier.promotions", 0.0),
            "demotions": counters.get("tier.demotions", 0.0),
            "prefetch": {
                k: counters.get(f"tier.prefetch.{k}", 0.0)
                for k in ("issued", "hits", "misses", "cancelled")},
        }
        pf_total = (tier["prefetch"]["hits"]
                    + tier["prefetch"]["misses"])
        tier["prefetch"]["hit_rate"] = (
            tier["prefetch"]["hits"] / pf_total if pf_total else None)
        out["tier"] = tier
        # histograms: bucket-wise merge over HEALTHY replicas
        names: set = set()
        for s in healthy:
            names.update((s.snapshot.get("histograms") or {}))
        hists = {}
        for hname in sorted(names):
            snaps = [(s.snapshot.get("histograms") or {}).get(hname)
                     for s in healthy]
            merged = merge_histograms([h for h in snaps if h])
            if merged is None:
                continue
            if merged.pop("dropped_bound_mismatch", 0):
                tracing.inc_counter(BOUND_MISMATCHES)
            hists[hname] = merged
        out["histograms"] = hists
        # probe planes: elementwise sums (stale last-known retained —
        # cumulative, like the counters) -> fleet hot/cold coverage
        planes: Dict[str, List[int]] = {}
        for s in states:
            if s.snapshot is None:
                continue
            fed = s.snapshot.get("federation") or {}
            for label, plane in (fed.get("probe_planes") or {}).items():
                acc = planes.setdefault(label, [0] * len(plane))
                if len(acc) != len(plane):
                    continue
                for i, v in enumerate(plane):
                    acc[i] += int(v)
        out["probe_freq"] = {
            label: tracing.probe_freq_stats(plane)
            for label, plane in planes.items()}
        # recall: pool raw trials over healthy replicas, THEN Wilson
        pooled: Dict[str, Dict[str, int]] = {}
        for s in healthy:
            fed = s.snapshot.get("federation") or {}
            for key, raw in (fed.get("recall") or {}).items():
                acc = pooled.setdefault(
                    key, {"hits": 0, "trials": 0, "pairs": 0})
                for k in acc:
                    acc[k] += int(raw.get(k, 0))
        recall = {}
        for key, acc in pooled.items():
            lo, hi = wilson_interval(acc["hits"], acc["trials"])
            recall[key] = {
                **acc,
                "estimate": (acc["hits"] / acc["trials"]
                             if acc["trials"] else 0.0),
                "ci_low": lo, "ci_high": hi,
            }
        out["recall"] = recall
        # drift: re-score the pooled live histogram vs pooled
        # baseline. Each replica's live histogram is NORMALIZED (its
        # DriftDetector EWMA-folds per-window distributions), so it
        # must be scaled by the replica's ``traffic`` weight before
        # summing — otherwise an idle replica weighs the same as one
        # carrying 99% of fleet traffic and a heavily-drifted busy
        # replica gets averaged away by quiet healthy peers. Payloads
        # predating the weight fall back to 1.0 (equal weight).
        drift_live: Dict[str, List[float]] = {}
        drift_base: Dict[str, List[float]] = {}
        for s in healthy:
            fed = s.snapshot.get("federation") or {}
            for iname, st in (fed.get("drift") or {}).items():
                base = st.get("baseline") or []
                live = st.get("live")
                acc_b = drift_base.setdefault(iname, [0.0] * len(base))
                if len(acc_b) == len(base):
                    for i, v in enumerate(base):
                        acc_b[i] += float(v)
                if live is not None:
                    w = float(st.get("traffic", 1.0)) or 1.0
                    acc_l = drift_live.setdefault(
                        iname, [0.0] * len(live))
                    if len(acc_l) == len(live):
                        for i, v in enumerate(live):
                            acc_l[i] += w * float(v)
        out["drift"] = {
            iname: {
                "score": tracing.js_divergence(
                    drift_live.get(iname, []), base),
                "replicas": sum(
                    1 for s in healthy
                    if iname in ((s.snapshot.get("federation") or {})
                                 .get("drift") or {})),
            }
            for iname, base in drift_base.items()}
        # admission: depth/rate sum (fleet-wide queue pressure), shed
        # level is a rung — the fleet's worst rung is the signal
        depth = rate = 0.0
        shed = 0
        for s in healthy:
            adm = s.snapshot.get("admission") or {}
            depth += float(adm.get("queue_depth", 0))
            rate += float(adm.get("arrival_rate_hz", 0.0))
            shed = max(shed, int(adm.get("shed_level", 0)))
        out["admission"] = {"queue_depth": depth,
                            "arrival_rate_hz": rate,
                            "max_shed_level": shed}
        # memory (PR 13 graftledger): instantaneous state, so healthy
        # replicas only. Resident bytes SUM (each replica holds its
        # own copy — the fleet figure is what the deployment spends);
        # headroom takes the MIN over replicas that measured one
        # (null headroom = no live stats; ignorance must not read as
        # infinite room). Replicas predating the memory block — or
        # running without a ledger — are skipped and counted, never
        # guessed at.
        # every field is validated per value, like the counter clamp:
        # snapshots arrive from scrapes AND the network-reachable
        # POST /push — one replica's malformed memory block must cost
        # that replica's contribution, never the whole fleet merge
        def _num(v, default=None):
            try:
                v = float(v)
            except (TypeError, ValueError):
                return default
            # non-finite values (JSON 1e999 -> inf) would poison the
            # sums, break the label-cap sort (NaN is unordered), and
            # corrupt headroom_min — garbage, not a measurement
            return v if math.isfinite(v) else default

        mem_resident: Dict[str, float] = {}
        mem_total = 0.0
        replica_headroom: Dict[str, float] = {}
        forecast_max = 0.0
        reporting = 0
        for s in healthy:
            mem = s.snapshot.get("memory")
            if not isinstance(mem, dict):
                continue
            reporting += 1
            mem_total += _num(mem.get("resident_total_bytes"), 0.0)
            resident = mem.get("resident")
            if isinstance(resident, dict):
                for label, b in resident.items():
                    b = _num(b)
                    if b is not None:
                        label = _safe_label(label)
                        mem_resident[label] = \
                            mem_resident.get(label, 0.0) + b
            forecast_max = max(
                forecast_max, _num(mem.get("forecast_peak_bytes"), 0.0))
            room = _num(mem.get("headroom_bytes"))
            if room is not None:
                replica_headroom[s.name] = room
        headroom_min_replica = (
            min(replica_headroom, key=replica_headroom.get)
            if replica_headroom else None)
        out["memory"] = {
            "replicas_reporting": reporting,
            "resident_bytes": mem_total,
            "resident": mem_resident,
            "forecast_peak_max_bytes": forecast_max,
            "headroom_min_bytes": (
                replica_headroom[headroom_min_replica]
                if headroom_min_replica is not None else None),
            "headroom_min_replica": headroom_min_replica,
            "replica_headroom_bytes": replica_headroom,
        }
        return out

    def merge(self, now: Optional[float] = None) -> dict:
        """The merged fleet view from the current scraped state (no
        fetches) — pure of everything but the stored snapshots, so
        the fixture tests pin it exactly."""
        if now is None:
            now = self._clock.now()
        with self._lock:
            out = self._merge_locked(now)
            self._last_merged = out
            delta = None
            if self._burn is not None:
                # fleet-level multiburn (PR 13): claim this merge's
                # delta of the summed attained/missed counters UNDER
                # the lock — concurrent merges (ThreadingHTTPServer
                # serves /metrics and /fleet.json in parallel) must
                # each fold a DISJOINT slice, or the same outcomes
                # enter the windows twice and inflate the burn rate.
                # The fleet sums are high-water clamped, so deltas are
                # non-negative however replicas restart; the outcomes
                # were counted in their replica processes —
                # record_batch windows them without re-counting.
                cur = {k: out["counters"].get(k, 0.0)
                       for k in ("serving.slo.attained",
                                 "serving.slo.missed")}
                prev = self._burn_prev or {k: cur[k] for k in cur}
                self._burn_prev = cur
                delta = (int(cur["serving.slo.attained"]
                             - prev["serving.slo.attained"]),
                         int(cur["serving.slo.missed"]
                             - prev["serving.slo.missed"]))
        if self._burn is not None:
            # the fold itself runs outside the aggregator lock (the
            # windows carry their own locks; disjoint deltas compose)
            self._burn.record_batch(now, *delta)
            out["slo"] = {
                "burn_rates": dict(zip(
                    (self.config.multiburn.short_label,
                     self.config.multiburn.long_label),
                    self._burn.burn_rates(now))),
                "alert": self._burn.alert(now),
            }
        self._publish(out)
        return out

    def _publish(self, merged: dict) -> None:
        """Re-publish the fleet gauges into the aggregator process's
        own registries (its exporter renders them labeled). Stale
        per-replica and memory gauges retire FIRST: a replica that
        stopped reporting (or was dropped) must not keep advertising
        its last headroom — that is exactly the stale room an
        operator would place the hot tier on."""
        tracing.reset_gauges("fleet.replica.")
        tracing.reset_gauges("fleet.memory.")
        vals = {
            "fleet.replicas": float(merged["size"]),
            "fleet.replicas_healthy": float(merged["healthy"]),
        }
        for name, r in merged["replicas"].items():
            base = f"fleet.replica.{name}."
            vals[base + "healthy"] = 1.0 if r["healthy"] else 0.0
            vals[base + "age_s"] = (-1.0 if r["age_s"] is None
                                    else r["age_s"])
            vals[base + "errors"] = float(r["errors"])
        for label, stats in merged["probe_freq"].items():
            base = f"fleet.probe_freq.{label}."
            for k in ("total", "probed_fraction", "coverage_p01",
                      "coverage_p10"):
                vals[base + k] = float(stats[k])
        live = merged["recall"].get("live")
        if live:
            vals.update({
                "fleet.recall.estimate": live["estimate"],
                "fleet.recall.ci_low": live["ci_low"],
                "fleet.recall.ci_high": live["ci_high"],
                "fleet.recall.trials": float(live["trials"]),
            })
        for iname, d in merged["drift"].items():
            vals[f"fleet.drift.{iname}.score"] = d["score"]
        tier = merged.get("tier") or {}
        pf = tier.get("prefetch") or {}
        if tier.get("epochs") or pf.get("issued"):
            for k in ("epochs", "promotions", "demotions"):
                vals[f"fleet.tier.{k}"] = float(tier[k])
            for k in ("issued", "hits", "misses", "cancelled"):
                vals[f"fleet.tier.prefetch.{k}"] = float(pf[k])
            if pf.get("hit_rate") is not None:
                vals["fleet.tier.prefetch.hit_rate"] = pf["hit_rate"]
        mem = merged.get("memory") or {}
        if mem.get("replicas_reporting"):
            vals["fleet.memory.replicas_reporting"] = float(
                mem["replicas_reporting"])
            vals["fleet.memory.resident_bytes"] = mem["resident_bytes"]
            vals["fleet.memory.forecast_peak_max_bytes"] = \
                mem["forecast_peak_max_bytes"]
            if mem.get("headroom_min_bytes") is not None:
                vals["fleet.memory.headroom_min_bytes"] = \
                    mem["headroom_min_bytes"]
            # per-index gauges: at most MEMORY_LABEL_CAP publish
            # (largest residents win) — gauges are process-lifetime,
            # and label cardinality here is replica-supplied (see the
            # cap's comment above; stale labels retired by the
            # fleet.memory. reset above)
            resident = sorted(mem.get("resident", {}).items(),
                              key=lambda kv: -kv[1])
            for label, b in resident[:MEMORY_LABEL_CAP]:
                vals[f"fleet.memory.index.{label}.resident_bytes"] = b
            # per-replica headroom rides the existing replica=-labeled
            # family machinery (fleet.replica.<name>.<field>)
            for rname, room in mem.get("replica_headroom_bytes",
                                       {}).items():
                vals[f"fleet.replica.{rname}.headroom_bytes"] = room
        tracing.set_gauges(vals)

    def fleet_snapshot(self, now: Optional[float] = None) -> dict:
        """One scrape + merge — the ``/fleet.json`` body."""
        if now is None:
            now = self._clock.now()
        self.scrape(now)
        return self.merge(now)

    # -- typed accessors (graftroute planner inputs) -------------------------

    def merged_probe_plane(self, label: str,
                           now: Optional[float] = None
                           ) -> ProbePlaneView:
        """The merged probe plane for ``label``, typed — same
        elementwise-sum semantics as the ``/fleet.json`` merge
        (stale last-known retained; the plane is cumulative), read
        from the STORED snapshots (no fetch). Raises ``LookupError``
        when no replica ever reported the label."""
        if now is None:
            now = self._clock.now()
        stale_s = self.config.staleness_s
        acc: Optional[List[int]] = None
        contrib: List[str] = []
        stale: List[str] = []
        with self._lock:
            states = sorted(self._states.values(),
                            key=lambda s: s.name)
            for s in states:
                if s.snapshot is None:
                    continue
                fed = s.snapshot.get("federation") or {}
                plane = (fed.get("probe_planes") or {}).get(label)
                if plane is None:
                    continue
                if acc is None:
                    acc = [0] * len(plane)
                if len(acc) != len(plane):
                    continue
                for i, v in enumerate(plane):
                    acc[i] += int(v)
                contrib.append(s.name)
                if not s.healthy(now, stale_s):
                    stale.append(s.name)
        if acc is None:
            raise LookupError(
                f"no replica reported probe plane {label!r}")
        return ProbePlaneView(label=label, counts=tuple(acc),
                              replicas=tuple(contrib),
                              stale_replicas=tuple(stale))

    def probe_plane_labels(self) -> Tuple[str, ...]:
        """Every probe-plane label any replica ever reported."""
        labels: set = set()
        with self._lock:
            for s in self._states.values():
                if s.snapshot is None:
                    continue
                fed = s.snapshot.get("federation") or {}
                labels.update(fed.get("probe_planes") or {})
        return tuple(sorted(labels))

    def replica_headroom(self, now: Optional[float] = None
                         ) -> Tuple[ReplicaHeadroom, ...]:
        """Per-replica memory headroom, typed, sorted by name — one
        entry per REGISTERED replica (unreported/stale headroom is
        None with the staleness metadata attached, so a planner can
        tell 'no room' from 'no evidence')."""
        if now is None:
            now = self._clock.now()
        stale_s = self.config.staleness_s
        out: List[ReplicaHeadroom] = []
        with self._lock:
            states = sorted(self._states.values(),
                            key=lambda s: s.name)
            for s in states:
                ok = s.healthy(now, stale_s)
                room = None
                if ok:
                    mem = s.snapshot.get("memory")
                    if isinstance(mem, dict):
                        v = mem.get("headroom_bytes")
                        try:
                            v = float(v)
                        except (TypeError, ValueError):
                            v = None
                        if v is not None and math.isfinite(v):
                            room = v
                age = None if s.scraped_at is None \
                    else now - s.scraped_at
                out.append(ReplicaHeadroom(
                    name=s.name, headroom_bytes=room, age_s=age,
                    healthy=ok, push=s.push))
        return tuple(out)

    def replica_health(self, now: Optional[float] = None
                       ) -> Dict[str, bool]:
        """Replica name → healthy (the router's steer gate)."""
        if now is None:
            now = self._clock.now()
        stale_s = self.config.staleness_s
        with self._lock:
            return {s.name: s.healthy(now, stale_s)
                    for s in self._states.values()}

    # -- Prometheus exposition ----------------------------------------------

    def prometheus_text(self, now: Optional[float] = None) -> str:
        """``replica=``-labeled and fleet-aggregate exposition of the
        federated counters and histograms (appended to the attached
        exporter's ``/metrics`` body; the fleet gauges themselves ride
        the normal registry rendering). Every federated family is
        ``fleet_``-prefixed so it can never collide with a same-named
        family of the aggregator process's OWN registries in one
        exposition body. Per family: one sample per replica carrying
        its clamped lifetime value, plus the ``replica="fleet"`` sum —
        so dashboards slice per replica or fleet-wide with one PromQL
        label matcher."""
        from raft_tpu.serving.exporter import _fmt, help_text, prom_name

        if now is None:
            now = self._clock.now()
        with self._lock:
            # reuse the merge the preceding fleet_snapshot() already
            # ran (the exporter calls them back to back) — merging
            # every histogram twice per scrape doubles the handler's
            # blocking work for nothing; standalone callers without a
            # prior merge still get a fresh one
            merged = (self._last_merged if self._last_merged is not None
                      else self._merge_locked(now))
            per_replica = {name: dict(high)
                           for name, high in self._high.items()}
            healthy = [s.name for s in self._states.values()
                       if s.healthy(now, self.config.staleness_s)]
            rep_hists = {
                s.name: dict(s.snapshot.get("histograms") or {})
                for s in self._states.values()
                if s.name in healthy}
        lines = []
        for cname in sorted(merged["counters"]):
            pn = "fleet_" + prom_name(cname)
            lines.append(f"# HELP {pn} {help_text(cname)}")
            lines.append(f"# TYPE {pn} counter")
            for rname in sorted(per_replica):
                v = per_replica[rname].get(cname)
                if v is not None:
                    lines.append(f'{pn}{{replica="{rname}"}} {_fmt(v)}')
            lines.append(f'{pn}{{replica="fleet"}} '
                         f'{_fmt(merged["counters"][cname])}')
        for hname in sorted(merged["histograms"]):
            pn = "fleet_" + prom_name(hname)
            lines.append(f"# HELP {pn} {help_text(hname)}")
            lines.append(f"# TYPE {pn} histogram")
            samples = [(rname, rep_hists[rname][hname])
                       for rname in sorted(rep_hists)
                       if hname in rep_hists[rname]]
            samples.append(("fleet", merged["histograms"][hname]))
            for rname, snap in samples:
                pre = f'replica="{rname}",'
                for le, c in zip(snap.get("bucket_bounds", []),
                                 snap.get("bucket_counts", [])):
                    lines.append(
                        f'{pn}_bucket{{{pre}le="{_fmt(le)}"}} {c}')
                lines.append(f'{pn}_bucket{{{pre}le="+Inf"}} '
                             f'{snap["count"]}')
                lines.append(f'{pn}_sum{{replica="{rname}"}} '
                             f'{_fmt(snap["sum"])}')
                lines.append(f'{pn}_count{{replica="{rname}"}} '
                             f'{snap["count"]}')
        return "\n".join(lines) + "\n" if lines else ""
