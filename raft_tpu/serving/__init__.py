"""Serving frontend: async dynamic batching in front of
:class:`~raft_tpu.core.executor.SearchExecutor`.

PRs 1–3 made the query hot path shape-stable and zero-recompile; this
package is the request layer on top — the piece that turns many small
caller queries into the executor's power-of-two buckets without
letting tail latency or overload take the service down:

- :mod:`~raft_tpu.serving.request` — :class:`SearchRequest` +
  future-style :class:`ResultHandle` with cancellation and typed
  failures (:class:`Overloaded`, :class:`DeadlineExceeded`,
  :class:`Cancelled`, :class:`ShutDown`).
- :mod:`~raft_tpu.serving.batcher` — :class:`DynamicBatcher`: a
  background micro-batcher with a dual dispatch trigger (max-wait
  timer OR bucket-full) that coalesces compatible requests and splits
  results back per request, zero-recompile in steady state.
- :mod:`~raft_tpu.serving.admission` — bounded queue with
  backpressure, EDF-within-priority scheduling, deadline shedding, and
  the documented load-shed ladder (:class:`LoadShed`).
- :mod:`~raft_tpu.serving.metrics` — per-stage latency histograms and
  throughput/shed/occupancy counters via :mod:`raft_tpu.core.tracing`.
- :mod:`~raft_tpu.serving.harness` — fault-injection pieces (manual
  clock, executor shims, bursty open-loop load) the deterministic
  test suite and the bench rider share.
- :mod:`~raft_tpu.serving.exporter` — :class:`MetricsExporter`: the
  pull-based observability endpoint (PR 6 graftscope) — Prometheus
  text exposition (labeled per-executable families since PR 7), a JSON
  snapshot, the span flight recorder as Chrome trace-event JSON for
  Perfetto overlays (``?trace_id=`` per-request filter), and a gated
  on-demand ``/profile`` capture.
- :mod:`~raft_tpu.serving.flight` — :class:`FlightRecorder` (PR 11
  graftflight): SLO-triggered incident capture — the multiburn alert
  or a latency anomaly arms a short, rate-limited automatic profiler
  capture whose parsed device-truth attribution
  (:mod:`raft_tpu.core.profiling`) lands with the span ring, metrics
  snapshot, cost table, and shed rung as an on-disk incident bundle,
  retrievable at ``/incident.json``.
- :mod:`~raft_tpu.serving.continuous` — :class:`ContinuousCapture`
  (PR 12 graftfleet): the steady-state half — periodic ~100 ms
  profiler captures under a ≤1% duty-cycle budget feed the rolling
  EWMA attribution (``serving.attribution.rolling.*``), deferring to
  operator and incident captures on the shared profile lock.
- :mod:`~raft_tpu.serving.placement` — :class:`TierManager` (PR 14
  grafttier): the traffic×bytes promote/demote policy for tiered
  (HBM hot / host-RAM cold) indexes — a pure deterministic epoch
  function of (claimed probe-frequency window, current assignment)
  executed as fixed-width donated block swaps that only permute the
  hot slots, so serving stays zero-recompile across re-placement
  epochs; scrape-driven via ``MetricsExporter(tier=...)`` →
  ``/tier.json`` + ``tier.*`` gauges.
- :mod:`~raft_tpu.serving.federation` — :class:`FleetAggregator`
  (PR 12 graftfleet): N replicas' ``/snapshot.json`` merged with
  type-correct semantics (lifetime-ledger counter sums that can never
  go backwards, bucket-merged histograms, fleet probe coverage,
  pooled-Wilson recall, pooled drift) served at ``/fleet.json`` and
  as ``replica=``-labeled Prometheus families. PR 13 (graftledger)
  added per-replica memory merging (headroom MIN, resident SUM), a
  push mode for replicas behind NAT (``POST /push``), and
  fleet-level multiburn alerting (``fleet.slo.alert``); the memory
  plane itself lives in :mod:`raft_tpu.core.memwatch`
  (:class:`~raft_tpu.core.memwatch.MemoryLedger`, attached to the
  exporter via ``MetricsExporter(memory=...)`` → ``/memory.json`` +
  ``memory_*`` families + the gated ``/memory_profile`` capture).

graftscope v2 (PR 7) additions: deadline-SLO attainment counters and
a sliding-window burn-rate gauge (:class:`~raft_tpu.serving.metrics
.SloConfig` / ``SloWindow``, batcher clock domain), the opt-in
:class:`~raft_tpu.serving.batcher.AdaptiveWait` arrival-rate →
max-wait control law, and mesh-deep trace propagation (the batcher
hands its members' ``trace_id``s to the executor, whose mesh
dispatches record per-shard straggler spans).

Works unchanged for single-chip and mesh-sharded (``Distributed*``)
indexes — the batcher only talks to the executor API.
"""

from raft_tpu.serving.admission import AdmissionQueue, LoadShed
from raft_tpu.serving.batcher import (
    AdaptiveWait,
    BatcherConfig,
    DynamicBatcher,
)
from raft_tpu.serving.continuous import (
    ContinuousCapture,
    ContinuousConfig,
)
from raft_tpu.serving.exporter import MetricsExporter
from raft_tpu.serving.federation import (
    FleetAggregator,
    FleetConfig,
    ProbePlaneView,
    ReplicaHeadroom,
)
from raft_tpu.serving.flight import (
    FlightConfig,
    FlightRecorder,
    LatencyAnomaly,
)
from raft_tpu.serving.gauge import (
    DriftDetector,
    IndexGauge,
    RecallWindow,
    ShadowConfig,
    ShadowSampler,
)
from raft_tpu.serving.metrics import (
    MultiBurnAlert,
    MultiBurnConfig,
    SloConfig,
    SloWindow,
)
from raft_tpu.serving.placement import (
    PlacementConfig,
    PlacementPlan,
    TierManager,
    plan_epoch,
)
from raft_tpu.serving.request import (
    Cancelled,
    DeadlineExceeded,
    Overloaded,
    ResultHandle,
    SearchRequest,
    ServingError,
    ShutDown,
)

__all__ = [
    "AdaptiveWait",
    "AdmissionQueue",
    "BatcherConfig",
    "Cancelled",
    "ContinuousCapture",
    "ContinuousConfig",
    "DeadlineExceeded",
    "DriftDetector",
    "DynamicBatcher",
    "FleetAggregator",
    "FleetConfig",
    "FlightConfig",
    "FlightRecorder",
    "IndexGauge",
    "LatencyAnomaly",
    "LoadShed",
    "MetricsExporter",
    "MultiBurnAlert",
    "MultiBurnConfig",
    "Overloaded",
    "PlacementConfig",
    "PlacementPlan",
    "ProbePlaneView",
    "RecallWindow",
    "ReplicaHeadroom",
    "ResultHandle",
    "SearchRequest",
    "ServingError",
    "ShadowConfig",
    "ShadowSampler",
    "ShutDown",
    "SloConfig",
    "SloWindow",
    "TierManager",
    "plan_epoch",
]
