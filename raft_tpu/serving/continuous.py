"""graftfleet continuous attribution (PR 12) — the steady-state half
of the observability plane.

graftflight (PR 11) made device-measured attribution real, but only at
incident time: between SLO pages the freshest device evidence in the
process is whatever the LAST incident captured, and the TPU-KNN
roofline methodology (PAPERS.md) only pays off when achieved GB/s is
observed continuously against the compiled-in byte accounting — not
reconstructed after a page. :class:`ContinuousCapture` closes that
gap: a low-duty-cycle scheduler takes periodic short (~100 ms)
``jax.profiler`` captures under a configurable duty-cycle budget
(default ≤ 1% of wall time on the profiler), attributes each window
against the executor's cost table
(:func:`raft_tpu.core.profiling.attribute`), publishes it (measured
supersedes modeled, exactly as an incident would), and folds it into
the :class:`~raft_tpu.core.profiling.RollingAttribution` EWMA state —
so ``serving.attribution.rolling.*`` and ``metrics.derived()`` carry
a continuously-fresh measured number next to the wall-clock one.

Lock discipline (shared with graftflight): only one profiler capture
may run process-wide, and the continuous tick is the LOWEST-priority
customer — an operator's ``/profile`` capture or an incident capture
holding the exporter's profile lock makes the tick DEFER (counted in
``continuous.deferred``, the period stamp untouched, so the very next
tick retries) rather than queue behind it. Elapsed periods never
stack: however long the scheduler was deferred or simply not ticked,
at most ONE capture runs when it next fires.

Accounting contract (ManualClock-pinned):

- ``continuous.ticks`` — every evaluation.
- ``continuous.captures`` — windows actually captured + folded.
- ``continuous.deferred`` — ticks that yielded to a busier capture.
- ``continuous.skipped`` — due ticks the cumulative duty-cycle budget
  refused (capture seconds spent would exceed ``duty_cycle_budget``
  of elapsed time) — the budget is a hard ceiling, not advisory.
- ``continuous.empty`` / ``continuous.errors`` — captures that wrote
  no attributable window / raised (both still charge the budget: the
  profiler time was spent).

Clock discipline (graftlint R7): every timestamp comes from the
injected clock; the capture itself sleeps wall-clock via
:func:`raft_tpu.serving.flight.timed_capture` (a duration, not a
clock read — the documented exemption).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Optional

from raft_tpu.core import profiling, tracing
from raft_tpu.serving.batcher import MonotonicClock
from raft_tpu.serving.flight import timed_capture

TICKS = "continuous.ticks"
CAPTURES = "continuous.captures"
DEFERRED = "continuous.deferred"
SKIPPED = "continuous.skipped"
EMPTY = "continuous.empty"
ERRORS = "continuous.errors"

GAUGE_PREFIX = "serving.continuous."


@dataclasses.dataclass(frozen=True)
class ContinuousConfig:
    """Tuning knobs for :class:`ContinuousCapture`.

    ``period_s`` is the capture cadence; ``capture_seconds`` the
    window length — deliberately short (~100 ms holds several
    dispatches under load, which is what the per-dispatch invocation
    windows make usable). ``duty_cycle_budget`` caps the fraction of
    wall time spent inside the profiler (captured seconds over
    elapsed clock time, cumulative) — the default pairing (0.1 s
    every 15 s ≈ 0.67%) sits under the 1% ceiling, and a
    misconfigured period can only trigger budget SKIPS, never a
    budget breach. ``alpha`` is the rolling-attribution EWMA weight
    per window."""

    period_s: float = 15.0
    capture_seconds: float = 0.1
    duty_cycle_budget: float = 0.01
    alpha: float = 0.3


class ContinuousCapture:
    """Low-duty-cycle capture scheduler feeding the rolling
    attribution.

    ``executor`` contributes the cost table (and its ``hlo_module``
    correlation identities); ``clock`` defaults to the production
    monotonic clock (tests inject a ManualClock); ``profile_dir``
    arms the real ``jax.profiler`` capture; ``capture_fn`` overrides
    the capture entirely (tests — and the live round-trip test, which
    runs real traffic under a real capture inside it; it may return a
    trace source for :func:`raft_tpu.core.profiling.load_ops` or
    None). The exporter's scrape refresh drives :meth:`tick`, so an
    armed service needs no extra thread — with the default 15 s
    scrape interval of a Prometheus deployment the cadence IS the
    scrape cadence; a sidecar loop can drive it instead.

    Example::

        cc = ContinuousCapture(executor=ex, profile_dir="/tmp/prof")
        exp = MetricsExporter(executor=ex, continuous=cc)
        # every scrape now keeps serving.attribution.rolling.* fresh
    """

    def __init__(self, executor=None, *,
                 config: Optional[ContinuousConfig] = None, clock=None,
                 profile_dir: Optional[str] = None,
                 capture_fn: Optional[Callable] = None,
                 rolling: Optional[profiling.RollingAttribution] = None):
        self.executor = executor
        self.config = config or ContinuousConfig()
        self._clock = clock if clock is not None else MonotonicClock()
        self.profile_dir = profile_dir
        self.capture_fn = capture_fn
        self.rolling = (rolling if rolling is not None
                        else profiling.RollingAttribution(
                            alpha=self.config.alpha))
        # wired by MetricsExporter(continuous=...): the shared
        # one-capture-at-a-time lock — /profile and incident captures
        # always win; a busy lock defers the tick
        self.profile_lock: Optional[threading.Lock] = None
        self._lock = threading.Lock()
        self._armed_at: Optional[float] = None  # guarded-by: _lock
        self._last: Optional[float] = None      # guarded-by: _lock
        self._captured_s = 0.0                  # guarded-by: _lock

    def _budget_ok_locked(self, now: float) -> bool:
        """Is the cumulative profiler time ALREADY spent within
        ``duty_cycle_budget`` of elapsed time? Retrospective
        accounting: the first capture is always admissible (nothing
        spent yet — a scheduler that can never start collects no
        evidence), each subsequent one only once the spent fraction
        has amortized back under budget, so a misconfigured period
        degrades to the budget's own cadence
        (``capture_seconds / budget``) instead of breaching it."""
        budget = self.config.duty_cycle_budget
        if budget <= 0:
            return False
        elapsed = max(now - (self._armed_at if self._armed_at
                             is not None else now), 0.0)
        # the epsilon keeps exact-boundary cadences (period equal to
        # capture_seconds / budget) deterministic across float noise
        return self._captured_s <= budget * elapsed + 1e-9

    def duty_cycle(self, now: Optional[float] = None) -> float:
        """Measured fraction of elapsed clock time spent capturing."""
        if now is None:
            now = self._clock.now()
        with self._lock:
            if self._armed_at is None or now <= self._armed_at:
                return 0.0
            return self._captured_s / (now - self._armed_at)

    def _capture(self):
        if self.capture_fn is not None:
            return self.capture_fn()
        if self.profile_dir is None:
            return None
        return timed_capture(self.profile_dir,
                             self.config.capture_seconds)

    def tick(self, now: Optional[float] = None) -> Optional[dict]:
        """Evaluate the schedule at clock time ``now``; when a capture
        is due, within budget, and the profiler is free: capture →
        attribute → publish → fold. Returns the rolling snapshot for
        a captured-and-folded window, else None (not due / budget
        skip / deferred / empty window — each counted)."""
        if now is None:
            now = self._clock.now()
        with self._lock:
            tracing.inc_counter(TICKS)
            if self._armed_at is None:
                self._armed_at = now
            due = (self._last is None
                   or now - self._last >= self.config.period_s)
            if not due:
                return None
            if not self._budget_ok_locked(now):
                tracing.inc_counter(SKIPPED)
                return None
            if (self.profile_lock is not None
                    and not self.profile_lock.acquire(blocking=False)):
                # an operator/incident capture owns the profiler:
                # defer WITHOUT advancing the period stamp — the next
                # tick retries immediately; elapsed periods never
                # stack into more than one capture
                tracing.inc_counter(DEFERRED)
                return None
            # advance the stamp BEFORE the capture so a concurrent
            # scrape's tick sees the cadence taken, however many
            # periods elapsed while quiet (never stacked)
            self._last = now
            self._captured_s += self.config.capture_seconds
        snap = None
        err = None
        try:
            source = self._capture()
            if source is not None and self.executor is not None \
                    and hasattr(self.executor, "executable_costs"):
                attr = profiling.attribute(
                    source, self.executor.executable_costs())
                if attr.modules:
                    # measured supersedes modeled, continuously: the
                    # same publication an incident performs, then the
                    # EWMA fold that makes it rolling
                    profiling.publish(attr)
                    snap = self.rolling.fold(attr)
        except Exception as e:  # noqa: BLE001 — a failed capture must
            # not take the scrape (or a sidecar loop) down; the budget
            # charge stands — the profiler time was spent
            err = e
        finally:
            if self.profile_lock is not None:
                self.profile_lock.release()
        if err is not None:
            tracing.inc_counter(ERRORS)
            return None
        if snap is None:
            tracing.inc_counter(EMPTY)
            return None
        tracing.inc_counter(CAPTURES)
        tracing.set_gauges({
            GAUGE_PREFIX + "duty_cycle": self.duty_cycle(now),
            GAUGE_PREFIX + "last_capture": now,
            GAUGE_PREFIX + "windows": float(snap["windows"]),
        })
        return snap
