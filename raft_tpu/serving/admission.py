"""Admission control: bounded queue, deadline-aware scheduling, and the
graceful-degradation ladder.

The queue is the only stateful thing between callers and the batcher
thread, so its discipline carries the serving SLO:

- **Bounded with backpressure**: ``push`` raises
  :class:`~raft_tpu.serving.request.Overloaded` once ``capacity``
  requests are queued — callers see a typed error immediately instead
  of a silently growing queue and an unbounded tail.
- **Deadline-aware**: requests order by (priority class, earliest
  deadline, arrival); expired requests are shed at pop time — before
  any device work is spent on them — and complete with
  :class:`~raft_tpu.serving.request.DeadlineExceeded`.
- **Coalescing-aware**: requests group by their executor
  ``coalesce_key``; the batcher always pops one *group* (the one
  holding the globally most-urgent request) so a micro-batch only ever
  contains requests that may legally share one compiled call.

The degradation ladder (:class:`LoadShed`) maps queue occupancy to a
documented policy, mildest first:

====  ==========================  =========================================
rung  trigger (occupancy >=)      action
====  ==========================  =========================================
0     —                           normal: dual-trigger batching
1     ``shrink_wait_at``          max-wait shrinks to 0 — dispatch eagerly,
                                  trading batch occupancy for queue drain;
                                  background-class submissions (priority >=
                                  ``background_priority``, e.g. graftgauge
                                  shadow queries) reject from
                                  ``background_reject_at`` (default 0.5)
                                  while live traffic still admits
2     ``degrade_params_at``       the configured load-shed params override
                                  applies to NEW submissions (e.g. capped
                                  ``n_probes``) — cheaper device work per
                                  request; the override is part of the
                                  coalesce key, so warm it up ahead of time
3     queue full                  reject with typed ``Overloaded``
====  ==========================  =========================================
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

from raft_tpu.core import tracing
from raft_tpu.serving.request import Overloaded, SearchRequest


class GroupHead(NamedTuple):
    """One compatibility group's scheduling summary (see
    :meth:`AdmissionQueue.group_heads`): its key, the oldest member's
    arrival (the dual trigger's timer anchor), the queued row count
    (remaining rows — a ragged split's claimed rows are gone), the
    most-urgent member's order key, and whether the group rides the
    ragged packed-batch path."""

    key: Any
    arrival: float
    rows: int
    urgent: tuple
    ragged: bool


@dataclasses.dataclass(frozen=True)
class LoadShed:
    """Degradation-ladder configuration (see module docstring).

    ``params_override`` is a callable ``params -> params`` applied to
    new submissions at rung 2+ (e.g. ``lambda p: dataclasses.replace(p,
    n_probes=min(p.n_probes, 8))``). It must be deterministic: the
    overridden params join the coalesce key, and a warmup of the
    degraded specialization keeps rung 2 zero-recompile too.

    ``background_priority`` (PR 8, graftgauge) declares a background
    request class — priorities at/above it are the ladder's FIRST
    casualty: once occupancy reaches ``background_reject_at`` the
    queue rejects background submissions with typed ``Overloaded``
    while live traffic still admits normally. Shadow recall queries
    ride this class, so under load the recall estimator degrades (its
    widening CI says so) before any live request feels the queue."""

    shrink_wait_at: float = 0.5
    degrade_params_at: float = 0.75
    params_override: Optional[Any] = None
    background_priority: Optional[int] = None
    background_reject_at: float = 0.5


# EWMA smoothing for the arrival-rate gauge: each inter-arrival gap
# contributes 20% — a few bursts move the estimate, one outlier doesn't
_EWMA_ALPHA = 0.2

# instantaneous-rate floor: two arrivals at the SAME clock tick (bursts
# under a manual clock) read as one inter-arrival of this, not 1/0
_MIN_GAP_S = 1e-6


class AdmissionQueue:
    """Bounded, priority + EDF, coalescing-aware request queue.

    Exports live gauges (PR 6 graftscope): ``serving.admission
    .queue_depth``, ``.shed_level``, and ``.arrival_rate_hz`` — an
    EWMA over inter-arrival gaps in the *batcher clock's* domain (the
    timestamps come in on ``req.arrival``, so the queue itself never
    reads a clock and the manual-clock harness stays deterministic).
    The rate gauge is the measurement half of the planned adaptive
    ``max_wait_s`` control loop."""

    def __init__(self, capacity: int = 1024,
                 shed: Optional[LoadShed] = None, slo=None):
        self.capacity = capacity
        self.shed = shed or LoadShed()
        # optional SloWindow: deadline sheds are SLO misses, and they
        # happen here (lazy pruning) — the batcher injects its window
        # so both completion paths feed one burn-rate ledger
        self._slo = slo
        self._lock = threading.Lock()
        self._groups: Dict[Any, List[SearchRequest]] = {}  # guarded-by: _lock
        self._n = 0                                        # guarded-by: _lock
        self._rate = 0.0                                   # guarded-by: _lock
        self._last_arrival: Optional[float] = None         # guarded-by: _lock

    # -- state --------------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return self._n

    def occupancy(self) -> float:
        """Queued fraction of capacity (the ladder's trigger signal)."""
        with self._lock:
            return self._n / self.capacity if self.capacity else 1.0

    def _level_for(self, occ: float) -> int:
        if occ >= 1.0:
            return 3
        if occ >= self.shed.degrade_params_at:
            return 2
        if occ >= self.shed.shrink_wait_at:
            return 1
        return 0

    def shed_level(self) -> int:
        """Current degradation rung (0–3) from queue occupancy."""
        return self._level_for(self.occupancy())

    def arrival_rate(self) -> float:
        """EWMA arrival rate (requests/s, clock domain); 0.0 before the
        second arrival."""
        with self._lock:
            return self._rate

    def publish_gauges(self) -> None:
        """Re-publish the admission gauges from current state — the
        exporter's scrape-time refresh, so a quiet service (no
        admission events since the last scrape) still reads current
        depth, rung, and rate from the one place that defines them."""
        with self._lock:
            n, rate = self._n, self._rate
        self._publish_gauges(n, rate)

    def _publish_gauges(self, n: int, rate: float) -> None:
        occ = n / self.capacity if self.capacity else 1.0
        tracing.set_gauges({
            "serving.admission.queue_depth": float(n),
            "serving.admission.shed_level": float(self._level_for(occ)),
            "serving.admission.arrival_rate_hz": rate,
        })

    # -- producer side ------------------------------------------------------

    def push(self, req: SearchRequest) -> None:
        """Admit or raise typed :class:`Overloaded` (backpressure)."""
        with self._lock:
            # arrival-rate EWMA ticks on every offered request —
            # rejected ones are load too
            if self._last_arrival is not None:
                gap = max(req.arrival - self._last_arrival, _MIN_GAP_S)
                sample = 1.0 / gap
                self._rate = (_EWMA_ALPHA * sample
                              + (1.0 - _EWMA_ALPHA) * self._rate
                              if self._rate else sample)
            self._last_arrival = req.arrival
            rate = self._rate
            shed = self.shed
            if (shed.background_priority is not None
                    and req.priority >= shed.background_priority
                    and (self._n / self.capacity if self.capacity
                         else 1.0) >= shed.background_reject_at):
                # background class (shadow queries, compaction) is the
                # ladder's first casualty — rejected while live
                # traffic still admits
                tracing.inc_counter(
                    "serving.admission.rejected_background")
                self._publish_gauges(self._n, rate)
                tracing.span_event(
                    "serving.rejected", req.arrival,
                    trace_ids=(req.trace_id,),
                    attrs={"reason": "background_shed",
                           "priority": req.priority})
                raise Overloaded(
                    "background-class request rejected at occupancy >= "
                    f"{shed.background_reject_at} (ladder sheds "
                    "background work first)")
            if self._n >= self.capacity:
                tracing.inc_counter("serving.admission.rejected")
                self._publish_gauges(self._n, rate)
                tracing.span_event(
                    "serving.rejected", req.arrival,
                    trace_ids=(req.trace_id,),
                    attrs={"reason": "queue_full",
                           "capacity": self.capacity})
                raise Overloaded(
                    f"admission queue full ({self.capacity} requests); "
                    "retry with backoff or raise capacity")
            self._groups.setdefault(req.compat_key, []).append(req)
            self._n += 1
            n = self._n
        tracing.inc_counter("serving.admission.accepted")
        self._publish_gauges(n, rate)

    # -- consumer (batcher) side --------------------------------------------

    def next_deadline_group(self, now: float):
        """(compat_key, oldest-arrival, rows, most-urgent order_key) of
        the most urgent group, or None when empty — the single-group
        view of :meth:`group_heads`, kept for callers that only need
        the head of the line."""
        heads = self.group_heads(now)
        if not heads:
            return None
        h = heads[0]
        return (h.key, h.arrival, h.rows, h.urgent)

    def group_heads(self, now: float) -> List[GroupHead]:
        """Every queued group's :class:`GroupHead`, most urgent first —
        the batcher's full scheduling view, so a dispatch-ready group
        is never invisible behind a more-urgent one still waiting out
        its timer, and the fairness budget can pick the most urgent
        *other* group. Cancelled/expired requests are pruned lazily
        here, completing expired ones with ``DeadlineExceeded``
        *before* dispatch."""
        from raft_tpu.serving.request import DeadlineExceeded

        shed: List[SearchRequest] = []
        cancelled: List[SearchRequest] = []
        heads: List[GroupHead] = []
        with self._lock:
            for key, group in list(self._groups.items()):
                keep = []
                for r in group:
                    if r.handle.done():
                        # taken == 0: a pre-dispatch completion —
                        # caller cancellation (or shutdown) won while
                        # the request was still whole. taken > 0: a
                        # split remainder whose dispatched slice
                        # FAILED the handle — that outcome was already
                        # counted (failed_batches / SLO miss), so the
                        # remainder just leaves the queue uncounted
                        if r.taken == 0:
                            tracing.inc_counter(
                                "serving.batcher.cancelled")
                            cancelled.append(r)
                        continue
                    if r.taken == 0 and r.expired(now):
                        # an expired remainder whose first rows already
                        # dispatched is NOT shed: its handle is
                        # running, and the started work completes (the
                        # late result records its SLO miss normally)
                        shed.append(r)
                        continue
                    keep.append(r)
                self._n -= len(group) - len(keep)
                if keep:
                    self._groups[key] = keep
                    heads.append(GroupHead(
                        key=key,
                        arrival=min(r.arrival for r in keep),
                        rows=sum(r.rows_left for r in keep),
                        urgent=min(r.order_key() for r in keep),
                        ragged=any(r.ragged for r in keep)))
                else:
                    del self._groups[key]
            n, rate = self._n, self._rate
        for r in cancelled:
            tracing.span_event("serving.cancelled", now,
                               trace_ids=(r.trace_id,),
                               attrs={"reason": "cancelled_in_queue"})
        for r in shed:
            if r.handle._set_exception(DeadlineExceeded(
                    f"deadline passed {now - r.deadline:.6f}s before "
                    "dispatch; shed from queue")):
                tracing.inc_counter("serving.batcher.shed_deadline")
                tracing.span_event(
                    "serving.shed", now, trace_ids=(r.trace_id,),
                    attrs={"reason": "deadline",
                           "late_s": now - r.deadline})
                if self._slo is not None:
                    self._slo.record(now, False)
        if shed or cancelled:
            self._publish_gauges(n, rate)
        heads.sort(key=lambda h: h.urgent)
        return heads

    def pop_group(self, key, max_rows: int,
                  now: float = 0.0) -> List[SearchRequest]:
        """Claim up to ``max_rows`` query rows from the group, most
        urgent first (EDF within priority). Requests whose handle is no
        longer pending (cancel won the race) are skipped; claimed
        handles transition to *running* atomically, so a later cancel
        returns False. ``now`` only timestamps the cancellation
        markers in the span recorder."""
        out: List[SearchRequest] = []
        cancelled: List[SearchRequest] = []
        with self._lock:
            group = self._groups.get(key, [])
            group.sort(key=SearchRequest.order_key)
            rest: List[SearchRequest] = []
            rows = 0
            for r in group:
                if out and rows + r.rows > max_rows:
                    rest.append(r)
                    continue
                if not r.handle._try_start():
                    self._n -= 1
                    tracing.inc_counter("serving.batcher.cancelled")
                    cancelled.append(r)
                    continue
                out.append(r)
                rows += r.rows
                self._n -= 1
            if rest:
                self._groups[key] = rest
            else:
                self._groups.pop(key, None)
            n, rate = self._n, self._rate
        for r in cancelled:
            tracing.span_event("serving.cancelled", now,
                               trace_ids=(r.trace_id,),
                               attrs={"reason": "cancelled_at_assembly"})
        self._publish_gauges(n, rate)
        return out

    def pop_rows(self, key, max_rows: int,
                 now: float = 0.0) -> List[Tuple[SearchRequest, int, int]]:
        """Ragged claim: up to ``max_rows`` query ROWS from the group,
        most urgent first, **splitting the boundary request** instead
        of leaving the tile short — the continuous-admission half of
        ragged batching. Returns ``(request, start, stop)`` row slices;
        a request whose rows spill past the tile keeps its remainder
        queued (same order key, so EDF still holds and the remainder
        packs first-eligible into the next tile).

        A request's handle transitions to *running* when its FIRST
        slice is claimed (cancel races resolve exactly as on the
        whole-request path); continuation slices belong to an
        already-running request and are claimed unconditionally."""
        out: List[Tuple[SearchRequest, int, int]] = []
        cancelled: List[SearchRequest] = []
        with self._lock:
            group = self._groups.get(key, [])
            group.sort(key=SearchRequest.order_key)
            rest: List[SearchRequest] = []
            rows = 0
            for r in group:
                avail = max_rows - rows
                if avail <= 0:
                    rest.append(r)
                    continue
                if r.taken == 0 and not r.handle._try_start():
                    self._n -= 1
                    tracing.inc_counter("serving.batcher.cancelled")
                    cancelled.append(r)
                    continue
                if r.taken > 0 and r.handle.done():
                    # remainder of a split whose dispatched slice
                    # already failed the handle — the outcome was
                    # counted there; don't pack dead rows
                    self._n -= 1
                    continue
                take = min(r.rows_left, avail)
                start, stop = r.take(take)
                out.append((r, start, stop))
                rows += take
                if r.rows_left > 0:
                    rest.append(r)       # split: remainder stays queued
                else:
                    self._n -= 1
            if rest:
                self._groups[key] = rest
            else:
                self._groups.pop(key, None)
            n, rate = self._n, self._rate
        for r in cancelled:
            tracing.span_event("serving.cancelled", now,
                               trace_ids=(r.trace_id,),
                               attrs={"reason": "cancelled_at_assembly"})
        self._publish_gauges(n, rate)
        return out

    def drain(self) -> List[SearchRequest]:
        """Remove and return every queued request (shutdown path)."""
        with self._lock:
            all_reqs = [r for g in self._groups.values() for r in g]
            self._groups.clear()
            self._n = 0
            rate = self._rate
        self._publish_gauges(0, rate)
        return all_reqs
