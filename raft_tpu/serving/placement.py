"""grafttier placement — the traffic×bytes promote/demote policy.

Placement is a SERVING-plane decision: the two halves of its signal
already exist as observability planes — graftgauge's device-side
probe-frequency accounting says which lists are hot (the
``coverage_p01/p10`` tier-split evidence of PR 8), and graftledger's
memory truth says what fits where (PR 13). This module closes the
loop: a pure, deterministic **epoch function** (:func:`plan_epoch`)
of (claimed probe-frequency window, current assignment) emits a
promote/demote plan, and :class:`TierManager` executes it as
:func:`raft_tpu.neighbors.tiered.apply_plan`'s fixed-width donated
block swaps — which only permute which lists occupy the fixed hot
slots, so every ``SearchExecutor`` plan stays zero-recompile across
re-placement epochs.

Policy shape: pair the hottest cold lists with the coldest hot lists,
bounded by ``max_swaps_per_epoch`` (also the compiled swap width); a
pair swaps only when the cold list's window traffic beats the hot
list's by ``min_heat_ratio`` (hysteresis — border lists must not
ping-pong a 2×block-bytes transfer every epoch on noise). Ties break
to the smaller list id, so the plan is a pure function of its inputs
and two replicas observing the same window converge on the same
layout (ManualClock-pinned in ``tests/test_tiered.py``).

Clock discipline (graftlint R7): the manager never reads a wall
clock — epochs fire from an injected clock's ``now()`` (the batcher
convention), and the exporter's scrape drives :meth:`TierManager
.tick` exactly like graftfleet's continuous capture.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional, Tuple

import numpy as np

from raft_tpu.core import tracing
from raft_tpu.core.validation import expect

EPOCHS = "tier.epochs"
PROMOTIONS = "tier.promotions"
DEMOTIONS = "tier.demotions"


@dataclasses.dataclass(frozen=True)
class PlacementConfig:
    """Epoch policy knobs. ``max_swaps_per_epoch`` doubles as the
    fixed compiled swap width — raising it re-specializes the swap
    program once, never per epoch. ``prefetch_lead_s`` is how far
    BEFORE the epoch tick the graftcast prefetcher (when attached)
    stages its forecast promotions — enough lead for the background
    cold→HBM copies to complete off the epoch path."""

    epoch_every_s: float = 60.0
    max_swaps_per_epoch: int = 8
    min_heat_ratio: float = 1.5
    prefetch_lead_s: float = 10.0


@dataclasses.dataclass(frozen=True)
class PlacementPlan:
    """One epoch's decision: ``promotions[i]`` (a cold list id) takes
    the hot slot ``demotions[i]`` frees. ``window_total`` and
    ``hot_window_fraction`` carry the evidence the plan was computed
    from (the share of the window's probes that landed hot — the
    tier hit rate the gauges publish)."""

    promotions: Tuple[int, ...]
    demotions: Tuple[int, ...]
    window_total: int
    hot_window_fraction: float


def plan_epoch(window_counts, hot_lists, cold_lists, *,
               max_swaps: int = 8,
               min_heat_ratio: float = 1.5) -> PlacementPlan:
    """THE epoch function — pure and deterministic: given one claimed
    probe-frequency window (per-list counts) and the current
    assignment, pair the hottest cold lists against the coldest hot
    lists and keep each pair only while the cold side's traffic beats
    the hot side's by ``min_heat_ratio`` (a cold list with zero
    window traffic never promotes; a hot list with zero traffic
    demotes against any cold traffic). Ties break to the smaller
    list id on both sides."""
    counts = np.asarray(window_counts, np.int64)
    hot = np.asarray(hot_lists, np.int64)
    cold = np.asarray(cold_lists, np.int64)
    total = int(counts.sum())
    hot_frac = float(counts[hot].sum() / total) if total > 0 else 0.0
    # hottest cold first / coldest hot first, ties to smaller lid
    # (lexsort's last key is primary; lid is the secondary key)
    cold_order = cold[np.lexsort((cold, -counts[cold]))]
    hot_order = hot[np.lexsort((hot, counts[hot]))]
    promotions, demotions = [], []
    for c, h in zip(cold_order[:max_swaps], hot_order[:max_swaps]):
        cc, hc = int(counts[c]), int(counts[h])
        if cc <= 0 or cc < min_heat_ratio * hc:
            break
        promotions.append(int(c))
        demotions.append(int(h))
    return PlacementPlan(promotions=tuple(promotions),
                         demotions=tuple(demotions),
                         window_total=total,
                         hot_window_fraction=hot_frac)


class TierManager:
    """Drives placement epochs for one :class:`~raft_tpu.neighbors
    .tiered.TieredIvf` served by one probe-accounting
    ``SearchExecutor``.

    The traffic window is the DELTA of the executor's lifetime probe
    ledger between epochs (``probe_frequencies`` claims device
    windows into a monotone host ledger; differencing it here means
    however many scrapers also claim windows, no probe is ever lost
    to or double-counted by placement). Epochs fire from the injected
    ``clock`` when :meth:`tick` observes ``epoch_every_s`` elapsed —
    the exporter's scrape drives it (``MetricsExporter(tier=...)``),
    and tests drive :meth:`epoch` directly under a ManualClock.

    Gauges (flat — one manager serves one tiered index):
    ``tier.{hot_lists,cold_lists,hot_bytes,cold_bytes,host_resident,
    hot_window_fraction,last_swaps,window_total}``; counters
    ``tier.{epochs,promotions,demotions,swaps,swap_bytes}`` (the swap
    pair live in :func:`~raft_tpu.neighbors.tiered.apply_plan`, where
    the bytes actually move).
    """

    def __init__(self, tiered, executor, *,
                 config: Optional[PlacementConfig] = None, clock=None,
                 prefetcher=None):
        from raft_tpu.serving.batcher import MonotonicClock

        expect(getattr(executor, "probe_accounting", False),
               "TierManager needs a probe-accounting SearchExecutor — "
               "placement without the traffic signal would be blind "
               "(construct SearchExecutor(probe_accounting=True))")
        self.tiered = tiered
        self.executor = executor
        self.config = config or PlacementConfig()
        self._clock = clock or MonotonicClock()
        self._lock = threading.Lock()
        self._last_epoch_t: Optional[float] = None
        self._last_counts: Optional[np.ndarray] = None
        self._epochs = 0
        self._last_plan: Optional[PlacementPlan] = None
        self.prefetcher = prefetcher
        # one prefetch per epoch window: armed at each epoch, spent
        # at the lead-time tick
        self._prefetch_armed = True

    def enable_prefetch(self, *, config=None, ledger=None):
        """Attach a :class:`~raft_tpu.serving.prefetch.TierPrefetcher`
        sized to this manager's swap width and return it (a disabled
        one — zero capacity after the ledger gate — still attaches:
        every call degrades to the reactive path)."""
        from raft_tpu.serving.prefetch import TierPrefetcher

        self.prefetcher = TierPrefetcher(
            self.tiered, width=self.config.max_swaps_per_epoch,
            config=config, ledger=ledger)
        return self.prefetcher

    # -- the epoch ----------------------------------------------------------

    def _claim_window(self) -> np.ndarray:
        """This epoch's traffic window: the delta of the executor's
        lifetime probe ledger since the last epoch (zeros before the
        first accounted dispatch)."""
        label = self.executor.probe_label(self.tiered)
        n = self.tiered.n_lists
        if label is None:
            return np.zeros((n,), np.int64)
        counts = self.executor.probe_frequencies().get(
            label, np.zeros((n,), np.int64))
        last = self._last_counts
        self._last_counts = counts
        if last is None:
            return counts.copy()
        return counts - last

    def _peek_window(self) -> np.ndarray:
        """READ-ONLY view of the window accumulating toward the next
        epoch (lifetime ledger minus the last claim's baseline) — the
        prefetcher's forecast input. Never advances ``_last_counts``,
        so the epoch's claim still folds every probe exactly once;
        peeking double-counts nothing."""
        label = self.executor.probe_label(self.tiered)
        n = self.tiered.n_lists
        if label is None:
            return np.zeros((n,), np.int64)
        counts = self.executor.probe_frequencies().get(
            label, np.zeros((n,), np.int64))
        if self._last_counts is None:
            return counts.copy()
        return counts - self._last_counts

    def _epoch_locked(self) -> PlacementPlan:
        """The epoch body — ONE critical section (caller holds
        ``self._lock``): the probe window is claimed exactly once and
        that single claim feeds BOTH the placement plan and the
        prefetcher's forecast EWMA. Splitting the claim from either
        consumer would let a racing scrape double-fold a window (the
        exact bug class :class:`~raft_tpu.serving.gauge.DriftDetector`
        locks against — its ``_last`` diff and EWMA fold share one
        lock for the same reason)."""
        from raft_tpu.neighbors.tiered import apply_plan

        cfg = self.config
        window = self._claim_window()
        pf = self.prefetcher
        if pf is not None:
            pf.observe(window)
        plan = plan_epoch(window, self.tiered.hot_lists,
                          self.tiered.cold_lists,
                          max_swaps=cfg.max_swaps_per_epoch,
                          min_heat_ratio=cfg.min_heat_ratio)
        staged = None
        if pf is not None and plan.promotions:
            # resolve against the miss cache AT the pre-swap
            # generation: stale rows (an epoch or re-demotion moved
            # the placement since they staged) are refused inside
            # take() and counted cancelled
            staged = pf.take(plan.promotions, self.tiered.generation)
        # the executor rides along so the swap's donation
        # enqueues serialize with dispatch enqueues (see
        # apply_plan's concurrency discipline)
        apply_plan(self.tiered, plan.promotions, plan.demotions,
                   width=cfg.max_swaps_per_epoch,
                   executor=self.executor, staged=staged)
        self._epochs += 1
        self._last_plan = plan
        self._prefetch_armed = True
        return plan

    def epoch(self) -> PlacementPlan:
        """Run one placement epoch NOW: claim the window, plan, and
        execute the swaps. Returns the plan (empty plans execute
        nothing — the layout holds)."""
        with self._lock:
            plan = self._epoch_locked()
        tracing.inc_counters({
            EPOCHS: 1.0,
            PROMOTIONS: float(len(plan.promotions)),
            DEMOTIONS: float(len(plan.demotions)),
        })
        self.publish_gauges()
        return plan

    def tick(self) -> Optional[PlacementPlan]:
        """Scrape-driven pacing: run an epoch when ``epoch_every_s``
        has elapsed on the injected clock (the first tick only stamps
        the baseline — an epoch needs a window to judge). Elapsed
        multiples never stack: one tick runs at most one epoch. The
        epoch runs INSIDE the pacing lock acquisition — stamping the
        time and then re-locking for the epoch would open a gap where
        a racing direct :meth:`epoch` claims the window this tick
        decided to consume.

        With a prefetcher attached, the tick ``prefetch_lead_s``
        before the next epoch stages the forecast promotions (once
        per epoch window), and every non-epoch tick runs the miss
        cache's headroom maintenance — both OUTSIDE the lock: the
        background channel must never block a racing epoch."""
        now = self._clock.now()
        plan = None
        partial = None
        cfg = self.config
        with self._lock:
            if self._last_epoch_t is None:
                self._last_epoch_t = now
            elif now - self._last_epoch_t >= cfg.epoch_every_s:
                self._last_epoch_t = now
                plan = self._epoch_locked()
            elif (self.prefetcher is not None and self._prefetch_armed
                  and now - self._last_epoch_t
                  >= cfg.epoch_every_s - cfg.prefetch_lead_s):
                self._prefetch_armed = False
                # the forecast input peeks INSIDE the claim lock so
                # it is consistent with the baseline it diffs against
                partial = self._peek_window()
        if plan is not None:
            tracing.inc_counters({
                EPOCHS: 1.0,
                PROMOTIONS: float(len(plan.promotions)),
                DEMOTIONS: float(len(plan.demotions)),
            })
            self.publish_gauges()
            return plan
        if partial is not None:
            self.prefetcher.prefetch(
                max_swaps=cfg.max_swaps_per_epoch, window=partial)
        if self.prefetcher is not None:
            self.prefetcher.maintain()
        return None

    # -- scrape surface -----------------------------------------------------

    def publish_gauges(self) -> None:
        t = self.tiered
        plan = self._last_plan
        vals = {
            "tier.hot_lists": float(t.n_hot),
            "tier.cold_lists": float(t.n_cold),
            "tier.hot_bytes": float(t.hot_bytes),
            "tier.cold_bytes": float(t.cold_bytes),
            "tier.host_resident": 1.0 if t.host_resident else 0.0,
            "tier.last_swaps":
                float(len(plan.promotions)) if plan else 0.0,
            "tier.window_total":
                float(plan.window_total) if plan else 0.0,
            "tier.hot_window_fraction":
                plan.hot_window_fraction if plan else 0.0,
        }
        if self.prefetcher is not None:
            ps = self.prefetcher.snapshot()
            vals["tier.prefetch.enabled"] = 1.0 if ps["enabled"] else 0.0
            vals["tier.prefetch.capacity"] = float(ps["capacity"])
            vals["tier.prefetch.staged"] = float(ps["staged"])
            vals["tier.prefetch.staged_bytes"] = float(
                ps["staged_bytes"])
        tracing.set_gauges(vals)

    def snapshot(self) -> dict:
        """The ``/tier.json`` body: the live layout, the last epoch's
        plan and evidence, and the policy config."""
        with self._lock:
            plan = self._last_plan
            epochs = self._epochs
        out = {
            "layout": self.tiered.layout(),
            "epochs": epochs,
            "config": {
                "epoch_every_s": self.config.epoch_every_s,
                "max_swaps_per_epoch": self.config.max_swaps_per_epoch,
                "min_heat_ratio": self.config.min_heat_ratio,
                "prefetch_lead_s": self.config.prefetch_lead_s,
            },
            "last_plan": None,
            "prefetch": (self.prefetcher.snapshot()
                         if self.prefetcher is not None else None),
        }
        if plan is not None:
            out["last_plan"] = {
                "promotions": list(plan.promotions),
                "demotions": list(plan.demotions),
                "window_total": plan.window_total,
                "hot_window_fraction": plan.hot_window_fraction,
            }
        return out
