"""Request model for the serving frontend: a :class:`SearchRequest`
(query block + k + params + deadline + priority) paired with a
future-style :class:`ResultHandle` the caller blocks on.

The reference serves requests through its RPC layer; this repo's
TPU-native frontend instead hands every caller a handle immediately
(submission never blocks on device work) and completes it from the
batcher thread once the coalesced micro-batch executes. Failure is
always a *typed* exception on the handle — :class:`Overloaded`
(admission control rejected it), :class:`DeadlineExceeded` (it expired
in the queue and was shed before device dispatch), :class:`Cancelled`
(the caller cancelled before batch assembly), or :class:`ShutDown`
(the batcher was closed before it could run).
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
from typing import Any, Optional, Tuple

from raft_tpu.core import tracing


class ServingError(RuntimeError):
    """Base class of every typed serving-frontend failure."""


class Overloaded(ServingError):
    """Admission control rejected the request (bounded queue full, or
    the load-shed ladder reached its reject rung)."""


class DeadlineExceeded(ServingError):
    """The request's deadline passed while it waited in the queue; it
    was shed before any device work was spent on it."""


class Cancelled(ServingError):
    """The caller cancelled the request before batch assembly."""


class ShutDown(ServingError):
    """The batcher shut down before the request could be dispatched."""


_PENDING = "pending"
_RUNNING = "running"
_DONE = "done"


class ResultHandle:
    """Future-style handle for one :class:`SearchRequest`.

    Lifecycle: *pending* (queued, cancellable) → *running* (assembled
    into a micro-batch; no longer cancellable) → *done* (result or
    typed exception set). All transitions happen under one lock, so a
    ``cancel()`` racing batch assembly resolves deterministically to
    exactly one winner.
    """

    __slots__ = ("_lock", "_event", "_state", "_result", "_exception")

    def __init__(self):
        self._lock = threading.Lock()
        self._event = threading.Event()
        self._state = _PENDING  # guarded-by: _lock
        self._result: Optional[Tuple[Any, Any]] = None
        self._exception: Optional[BaseException] = None

    # -- caller side --------------------------------------------------------

    def done(self) -> bool:
        """True once a result or exception is set."""
        return self._event.is_set()

    def cancelled(self) -> bool:
        """True iff the handle completed with :class:`Cancelled`."""
        return isinstance(self._exception, Cancelled)

    def cancel(self) -> bool:
        """Cancel if still pending. Returns True when the cancellation
        won (the handle completes with :class:`Cancelled` and the
        batcher will skip it); False when the request already entered
        batch assembly or completed — its result arrives normally."""
        with self._lock:
            if self._state != _PENDING:
                return False
            self._state = _DONE
            self._exception = Cancelled("request cancelled by caller")
        self._event.set()
        return True

    def result(self, timeout: Optional[float] = None) -> Tuple[Any, Any]:
        """Block until done; return ``(distances, indices)`` or raise
        the typed failure. ``TimeoutError`` if not done in time."""
        if not self._event.wait(timeout):
            raise TimeoutError("result not ready")
        if self._exception is not None:
            raise self._exception
        return self._result

    def exception(self, timeout: Optional[float] = None):
        """Block until done; return the typed exception (None on
        success)."""
        if not self._event.wait(timeout):
            raise TimeoutError("result not ready")
        return self._exception

    # -- batcher side -------------------------------------------------------

    def _try_start(self) -> bool:
        """pending → running (batch assembly claimed this request).
        False when a cancel (or a shed) won the race — skip it."""
        with self._lock:
            if self._state != _PENDING:
                return False
            self._state = _RUNNING
            return True

    def _set_result(self, distances, indices) -> bool:
        """Complete with a result (no-op if already done). Returns
        True when this call performed the completion — the batcher's
        SLO accounting keys on it, so a shutdown-drained handle is
        never double-counted."""
        with self._lock:
            if self._state == _DONE:
                return False
            self._state = _DONE
            self._result = (distances, indices)
        self._event.set()
        return True

    def _set_exception(self, exc: BaseException) -> bool:
        """Complete with a typed failure (no-op if already done).
        Returns True when this call performed the completion."""
        with self._lock:
            if self._state == _DONE:
                return False
            self._state = _DONE
            self._exception = exc
        self._event.set()
        return True


_seq = itertools.count()


@dataclasses.dataclass
class SearchRequest:
    """One caller's query block plus its scheduling attributes.

    ``deadline`` is absolute, in the batcher clock's domain
    (``clock.now()``-relative); ``None`` means no deadline. Lower
    ``priority`` values are served first; within a priority class the
    queue is earliest-deadline-first, then FIFO by ``seq``.

    ``trace_id`` is minted at construction (PR 6 graftscope) and rides
    every stage span the request touches — admission, assembly,
    execute, split, and any shed/cancel marker — so one id pulls the
    request's whole journey out of the span flight recorder."""

    index: Any
    queries: Any                      # (m, dim) host array
    k: int
    params: Any = None
    deadline: Optional[float] = None
    priority: int = 0
    sample_filter: Any = None
    kw: dict = dataclasses.field(default_factory=dict)
    handle: ResultHandle = dataclasses.field(default_factory=ResultHandle)
    # filled at admission
    compat_key: Any = None
    arrival: float = 0.0
    seq: int = dataclasses.field(default_factory=lambda: next(_seq))
    trace_id: int = dataclasses.field(default_factory=tracing.new_trace_id)
    # ragged continuous batching: requests on the ragged path may SPLIT
    # across packed tiles — ``taken`` rows were already claimed by
    # earlier tiles (the queue holds only the remainder), and completed
    # row-range results accumulate in ``parts`` until the final slice
    # lands. ``taken`` is only touched under the admission queue's
    # lock; ``parts`` has its own lock because two dispatchers may
    # deliver slices of one request concurrently (``pump()`` is
    # documented as a flush alongside a running worker) — ``add_part``
    # elects exactly one assembler.
    ragged: bool = False
    taken: int = 0
    parts: list = dataclasses.field(default_factory=list)
    _parts_lock: Any = dataclasses.field(
        default_factory=threading.Lock)
    _assembled: bool = False

    @property
    def rows(self) -> int:
        import numpy as np

        return int(np.shape(self.queries)[0])

    @property
    def rows_left(self) -> int:
        """Rows not yet claimed by a packed tile (== ``rows`` for
        whole-request scheduling — ``taken`` only advances on the
        ragged path's tile-overflow splits)."""
        return self.rows - self.taken

    def take(self, n: int):
        """Claim the next ``n`` rows for a packed tile; returns the
        claimed ``(start, stop)`` row range."""
        start = self.taken
        self.taken = start + n
        return start, self.taken

    def add_part(self, start: int, distances, indices) -> bool:
        """Record one claimed slice's results; True once every row has
        landed (the request is then assembled and completable).
        Thread-safe and once-only: when slices of one request land
        from two dispatchers (worker + a concurrent ``pump()``),
        exactly one caller sees True and assembles."""
        with self._parts_lock:
            self.parts.append((start, distances, indices))
            if (self._assembled
                    or sum(p[1].shape[0] for p in self.parts)
                    < self.rows):
                return False
            self._assembled = True
            return True

    def assemble(self):
        """Concatenate the accumulated slices (by row range) into the
        request's full ``(distances, indices)`` — per-row independence
        makes the concatenation bit-identical to an unsplit call.
        Called only by the ``add_part`` winner, after every row has
        landed, so the parts list is complete and stable."""
        import numpy as np

        self.parts.sort(key=lambda p: p[0])
        if len(self.parts) == 1:
            return self.parts[0][1], self.parts[0][2]
        if all(isinstance(p[1], np.ndarray) for p in self.parts):
            return (np.concatenate([p[1] for p in self.parts]),
                    np.concatenate([p[2] for p in self.parts]))
        import jax.numpy as jnp

        return (jnp.concatenate([p[1] for p in self.parts]),
                jnp.concatenate([p[2] for p in self.parts]))

    def order_key(self) -> tuple:
        """EDF-within-priority ordering (deadline-less requests sort
        after any deadline, then FIFO)."""
        d = self.deadline if self.deadline is not None else float("inf")
        return (self.priority, d, self.seq)

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now >= self.deadline
