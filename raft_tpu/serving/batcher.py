"""Background dynamic micro-batcher — the continuous-batching discipline
of TPU LLM serving (Ragged Paged Attention, PAPERS.md) applied to ANN
queries.

Small requests must coalesce into the executor's power-of-two buckets
to reach the peak-FLOP/s regime (TPU-KNN), but naive accumulation blows
up tail latency. The batcher runs a **dual trigger**: a micro-batch
dispatches when its group's query rows reach ``full_batch_rows``
(bucket-full) OR when its oldest request has waited ``max_wait_s``
(timer) — whichever comes first. p99 latency is therefore bounded by
``max_wait_s`` + one device execute, while bursts fill whole buckets.

Requests coalesce only within a compatibility group — the executor's
:meth:`~raft_tpu.core.executor.SearchExecutor.coalesce_key` (same
index identity, same resolved statics/engine, same filter spec) — and
the assembled batch goes through
:meth:`~raft_tpu.core.executor.SearchExecutor.search_blocks`, i.e. the
*existing* bucket set: steady state stays zero-recompile (asserted in
the tests against ``xla.backend_compile_count``) and results are
bit-identical to direct ``SearchExecutor`` calls, because bucketing
pads with inert rows and every row's result is independent.

**Ragged continuous batching** (``BatcherConfig(ragged=True)``, PR 9)
replaces cycle-and-wait assembly for raggable submissions: requests
group by the executor's :meth:`~raft_tpu.core.executor.SearchExecutor
.ragged_key` (mixed per-request ``n_probes``/``k`` under one params
class share ONE packed executable), admit continuously into the open
packed tile, and SPLIT at tile boundaries instead of waiting for a
tile they fully fit — the dual trigger becomes tile-full OR max-wait,
EDF order is preserved (a split remainder keeps its order key), and
the degradation ladder's params override feeds the packing key
exactly as it fed the coalesce key. Since graftragged (PR 15) the
raggable set is the whole IVF zoo — flat, PQ, BQ, single-chip AND
list-sharded mesh indexes (mesh wire knobs ride the submit ``kw``
into the packing key) — and since graftbeam (PR 16) CAGRA packs too
(content-pure seeds; per-row iteration budgets ride the budget
plane), so continuous admission covers every family the executor can
pack. Non-raggable submissions (the documented residue: approx
coarse select, the rank-major engines, codes-only BQ, ``TieredIvf``,
brute force, CAGRA at a ``k`` class cap past ``itopk_size``) fall
back to the bucketed path transparently, with
:meth:`~raft_tpu.core.executor.SearchExecutor.ragged_fallback_reason`
naming why.

Scheduling is delegated to :class:`~raft_tpu.serving.admission
.AdmissionQueue` (bounded + backpressure, EDF within priority class,
expired requests shed before dispatch) and the load-shed ladder is
documented there. The batcher is pure-stdlib threading: one daemon
worker, one condition variable, an injectable clock — the fault
harness (:mod:`raft_tpu.serving.harness`) drives it deterministically
with ``start=False`` + :meth:`pump` and a manual clock.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import tracing
from raft_tpu.core.validation import expect
from raft_tpu.serving import metrics
from raft_tpu.serving.admission import AdmissionQueue, LoadShed
from raft_tpu.serving.request import (
    Overloaded,
    ResultHandle,
    SearchRequest,
    ShutDown,
)


class MonotonicClock:
    """Production clock: ``time.monotonic`` + plain condition waits."""

    def now(self) -> float:
        return time.monotonic()

    def wait(self, cond: threading.Condition, timeout: Optional[float]):
        """Block on ``cond`` (caller holds it) until notified or
        ``timeout`` elapses. Manual clocks override this to make the
        wait a deterministic rendezvous instead of a real sleep."""
        cond.wait(timeout)


@dataclasses.dataclass(frozen=True)
class AdaptiveWait:
    """Control law for the adaptive ``max_wait_s`` (PR 7, closing the
    serving follow-on whose measurement half —
    ``serving.admission.arrival_rate_hz`` — shipped in PR 6): map the
    admission queue's EWMA arrival rate to a bounded effective
    max-wait. High rate → shrink toward ``min_wait_s`` (bursts fill
    buckets fast; extra waiting only adds latency); idle → grow toward
    the configured ``max_wait_s`` cap (a lone request may as well wait
    the full budget for company). Linear interpolation between the two
    rate knees, so the manual-clock tests pin the output exactly; the
    rate itself is clock-domain (EWMA over ``req.arrival`` gaps), so
    the whole loop stays deterministic under the fault harness. Off by
    default — see :attr:`BatcherConfig.adaptive_wait`."""

    low_rate_hz: float = 50.0
    high_rate_hz: float = 2000.0
    min_wait_s: float = 0.0

    def wait_for(self, rate_hz: float, max_wait_s: float) -> float:
        """Effective max-wait for the observed arrival rate (0.0 rate
        — nothing measured yet — gets the full configured cap)."""
        if rate_hz <= self.low_rate_hz:
            return max_wait_s
        if rate_hz >= self.high_rate_hz:
            return self.min_wait_s
        frac = ((rate_hz - self.low_rate_hz)
                / (self.high_rate_hz - self.low_rate_hz))
        return max_wait_s + (self.min_wait_s - max_wait_s) * frac


@dataclasses.dataclass(frozen=True)
class BatcherConfig:
    """Tuning knobs for :class:`DynamicBatcher`.

    ``max_wait_s`` bounds the batching delay any request can be charged
    (the timer half of the dual trigger); ``full_batch_rows`` is the
    bucket-full half and the cap on rows per micro-batch (oversized
    single requests still dispatch alone — the executor tiles them).
    ``capacity`` bounds the admission queue; ``default_timeout_s``
    applies a deadline to requests that do not carry one (None = no
    deadline). ``shed`` is the degradation ladder. ``slo`` configures
    the deadline-attainment burn-rate window (None disables the SLO
    surface); ``multiburn`` (PR 8) swaps in the paired short+long
    multiwindow alert policy instead — outcomes then land in both
    windows and ``serving.slo.alert`` fires only when both burn.
    ``adaptive_wait`` (off by default) enables the arrival-rate →
    max-wait control law; the shed ladder's rung 1 (wait → 0) still
    takes precedence over it.

    ``ragged`` (off by default) routes raggable submissions onto the
    executor's packed-batch plan family: requests group by
    ``executor.ragged_key`` (mixed ``n_probes``/``k`` under one params
    class share ONE executable; flat, PQ, BQ, the list-sharded mesh
    families, and CAGRA all pack since graftragged/graftbeam), admit
    continuously into the open packed tile (``executor.ragged_tile``
    rows — the tile-full half of the dual trigger; a dual-tile
    executor picks its small tile at dispatch), and SPLIT at tile
    boundaries instead of waiting for a tile they fully fit.
    Non-raggable submissions (brute force, tiered, approx coarse
    select, the rank engines, codes-only BQ) fall back to the
    bucketed path transparently. ``group_budget`` caps consecutive
    dispatches from one compatibility group while another group is
    dispatch-ready (0 disables): one slow index family's group cannot
    monopolize the worker loop, and the wait of the groups passed over
    is published as the ``serving.batcher.group_starvation_s`` gauge."""

    max_wait_s: float = 0.002
    full_batch_rows: int = 256
    capacity: int = 1024
    default_timeout_s: Optional[float] = None
    shed: LoadShed = dataclasses.field(default_factory=LoadShed)
    slo: Optional[metrics.SloConfig] = dataclasses.field(
        default_factory=metrics.SloConfig)
    multiburn: Optional[metrics.MultiBurnConfig] = None
    adaptive_wait: Optional[AdaptiveWait] = None
    ragged: bool = False
    group_budget: int = 8


class DynamicBatcher:
    """Async dynamic micro-batcher in front of a ``SearchExecutor``.

    Example::

        ex = SearchExecutor(res)
        ex.warmup(index, k=10)
        b = DynamicBatcher(ex)
        h = b.submit(index, queries, 10, timeout_s=0.050)
        d, i = h.result()          # typed ServingError on failure
        b.close()

    ``submit`` never blocks on device work: it admits (or rejects with
    typed ``Overloaded``), wakes the worker, and returns a
    :class:`~raft_tpu.serving.request.ResultHandle`. With
    ``start=False`` no thread runs and :meth:`pump` processes ready
    work synchronously — the deterministic mode the fault-injection
    suite drives with a manual clock."""

    def __init__(self, executor, config: Optional[BatcherConfig] = None,
                 *, clock=None, start: bool = True):
        self.executor = executor
        self.config = config or BatcherConfig()
        expect(self.config.max_wait_s >= 0.0, "max_wait_s must be >= 0")
        expect(self.config.full_batch_rows > 0,
               "full_batch_rows must be > 0")
        self._clock = clock or MonotonicClock()
        # multiburn (paired windows + alert) and the single window are
        # duck-type equivalent on the completion paths: record/publish
        if self.config.multiburn is not None:
            self._slo = metrics.MultiBurnAlert(self.config.multiburn)
        else:
            self._slo = (metrics.SloWindow(self.config.slo)
                         if self.config.slo is not None else None)
        # the queue records deadline-shed requests as SLO misses (they
        # are pruned inside its lock, where the batcher never sees them)
        self._queue = AdmissionQueue(self.config.capacity,
                                     self.config.shed, slo=self._slo)
        self._cond = threading.Condition()
        self._closing = False   # guarded-by: _cond
        # fairness bookkeeping: the group served last and its streak
        self._last_key = None   # guarded-by: _cond
        self._consecutive = 0   # guarded-by: _cond
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(
                target=self._loop, name="raft-tpu-batcher", daemon=True)
            self._thread.start()

    # -- caller side --------------------------------------------------------

    def submit(self, index, queries, k: int, params=None, *,
               timeout_s: Optional[float] = None,
               deadline: Optional[float] = None, priority: int = 0,
               sample_filter=None, **kw) -> ResultHandle:
        """Enqueue one search. ``timeout_s`` (relative) or ``deadline``
        (absolute, clock domain) bound its queue life; expired requests
        are shed before device dispatch. A 2-D (per-row) filter rides
        the request and is re-concatenated at dispatch; a 1-D (shared)
        filter coalesces by words-array identity — pass the same
        filter object for requests that should share a call. Raises
        typed ``Overloaded`` on a full queue and ``ShutDown`` after
        :meth:`close`; unsupported index/params/filter combinations
        fail here, synchronously."""
        if self._closing:  # graftlint: disable=R8(benign racy fast-fail; the authoritative check re-runs under _cond before enqueue)
            raise ShutDown("batcher is closed")
        now = self._clock.now()
        if deadline is None:
            t = (timeout_s if timeout_s is not None
                 else self.config.default_timeout_s)
            deadline = now + t if t is not None else None
        shed = self.config.shed
        degrade_events = ()
        if (shed.params_override is not None
                and self._queue.shed_level() >= 2):
            params = shed.params_override(params)
            tracing.inc_counter("serving.batcher.shed_degraded_params")
            degrade_events = ((now, "degraded_params",
                               {"reason": "shed_rung_2"}),)
        # resolve the filter to its words ONCE (wrapper types carry no
        # row info themselves); the executor's coalesce key validates
        # the plan up front but carries only the filter's spec, so 1-D
        # (shared) words additionally key by array identity — two
        # different bitsets of equal shape must never share a call
        from raft_tpu.neighbors.filters import resolve_filter_words

        fw = resolve_filter_words(sample_filter)
        # ragged continuous batching: raggable submissions group by the
        # executor's packing key (mixed n_probes/k in one params class
        # pack into ONE executable; the ladder's params override was
        # already applied above, so a degraded submission keys — and
        # packs — exactly like any other bearer of those params).
        # Everything else falls back to the bucketed coalesce key.
        ragged = False
        compat_key = None
        if self.config.ragged and hasattr(self.executor, "ragged_key"):
            compat_key = self.executor.ragged_key(
                index, k, params=params, sample_filter=fw, **kw)
            ragged = compat_key is not None
        if compat_key is None:
            compat_key = self.executor.coalesce_key(
                index, k, params=params, sample_filter=fw, **kw)
        if fw is not None:
            if fw.ndim == 1:
                compat_key = compat_key + (id(fw),)
            else:
                expect(fw.shape[0] == int(np.shape(queries)[0]),
                       "2-D filter rows must match query rows")
        req = SearchRequest(index=index, queries=queries, k=k,
                            params=params, deadline=deadline,
                            priority=priority,
                            sample_filter=fw, kw=dict(kw),
                            compat_key=compat_key, arrival=now,
                            ragged=ragged)
        # admission happens under the scheduler lock: a submit racing
        # close() either lands before the final drain (and is drained)
        # or sees _closing and fails typed — never a stranded handle
        with self._cond:
            if self._closing:
                raise ShutDown("batcher is closed")
            try:
                self._queue.push(req)  # typed Overloaded on overflow
            except Overloaded:
                # a rejected deadline-carrying request IS an SLO miss:
                # under total overload the window must fill with misses,
                # not sit empty reading burn_rate = 0 during the outage
                if self._slo is not None and req.deadline is not None:
                    self._slo.record(now, False)
                raise
            self._cond.notify_all()
        tracing.record_span(
            "serving.admission", now, self._clock.now(),
            trace_ids=(req.trace_id,),
            attrs={"rows": req.rows, "priority": priority,
                   "deadline": deadline},
            events=degrade_events)
        return req.handle

    def pump(self) -> int:
        """Synchronously dispatch every micro-batch that is ready at
        the current clock time (deterministic mode; also usable as a
        flush with a running worker). Returns batches dispatched."""
        n = 0
        while True:
            batch = self._poll()
            if not batch:
                return n
            key, items, ragged = batch
            if ragged:
                self._dispatch_ragged(key, items)
            else:
                self._dispatch(key, items)
            n += 1

    def close(self, drain: bool = True) -> None:
        """Shut down. ``drain=True`` dispatches everything still queued
        (in-flight batches complete normally); ``drain=False`` fails
        queued requests with typed ``ShutDown``. Idempotent; joins the
        worker thread, so no threads or pending futures leak."""
        def _shutdown_shed(reqs):
            now = self._clock.now()
            for r in reqs:
                if r.handle._set_exception(
                        ShutDown("batcher closed before dispatch")):
                    tracing.inc_counter("serving.batcher.shutdown_shed")
                    tracing.span_event(
                        "serving.shed", now, trace_ids=(r.trace_id,),
                        attrs={"reason": "shutdown"})

        with self._cond:
            if self._closing:
                self._cond.notify_all()
            self._closing = True
            if not drain:
                _shutdown_shed(self._queue.drain())
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        elif drain:
            self.pump()            # threadless mode drains inline
        # anything left (e.g. raced submits) fails typed rather than
        # hanging its caller forever
        _shutdown_shed(self._queue.drain())

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- worker -------------------------------------------------------------

    def publish_slo_gauges(self) -> None:
        """Re-publish the SLO burn-rate gauges as of the batcher
        clock's now — the exporter's scrape-time refresh, so misses age
        out of the window even while no new requests complete."""
        if self._slo is not None:
            self._slo.publish(self._clock.now())

    def _effective_max_wait(self) -> float:
        """Ladder rung 1: above ``shrink_wait_at`` occupancy the timer
        trigger collapses to 0 — drain beats batching delay. Below it,
        the optional :class:`AdaptiveWait` control law maps the
        observed arrival rate into [min_wait, max_wait] (published as
        the ``serving.batcher.effective_max_wait_s`` gauge)."""
        if self._queue.shed_level() >= 1:
            return 0.0
        aw = self.config.adaptive_wait
        if aw is None:
            return self.config.max_wait_s
        wait = aw.wait_for(self._queue.arrival_rate(),
                           self.config.max_wait_s)
        tracing.set_gauge("serving.batcher.effective_max_wait_s", wait)
        return wait

    def _poll(self):
        """One non-blocking scheduling decision: the next ready
        micro-batch as ``(key, requests)``, or ``()`` when nothing is
        ready yet."""
        with self._cond:
            return self._select(block=False)

    def _tile_rows(self, head) -> int:
        """The row cap of one micro-batch for this group: the ragged
        plan family's fixed packed tile, or the bucketed
        ``full_batch_rows``."""
        if head.ragged:
            return int(getattr(self.executor, "ragged_tile",
                               self.config.full_batch_rows))
        return self.config.full_batch_rows

    def _pick_fair(self, ready):
        """Most urgent dispatch-ready group, except when one group has
        held the worker ``group_budget`` consecutive dispatches while
        another group is also ready — then the most urgent OTHER ready
        group is served (cross-index fairness: a slow family's group
        cannot monopolize the loop). Pure selection: the streak only
        advances in :meth:`_record_pick`, once the pop actually yields
        a dispatch — a cancel-race empty pop must not burn budget the
        picked group never used."""
        pick = ready[0]
        budget = self.config.group_budget
        if (budget and len(ready) > 1 and pick.key == self._last_key
                and self._consecutive >= budget):
            pick = ready[1]
        return pick

    def _record_pick(self, pick, ready, now: float) -> None:
        """Account one real dispatch to the fairness streak and
        publish the ``serving.batcher.group_starvation_s`` gauge: the
        longest any passed-over ready group has waited."""
        if pick.key == self._last_key:
            self._consecutive += 1
        else:
            self._last_key = pick.key
            self._consecutive = 1
        starve = max((now - h.arrival for h in ready
                      if h.key != pick.key), default=0.0)
        tracing.set_gauge("serving.batcher.group_starvation_s", starve)

    def _select(self, block: bool):
        """Core of the dual trigger (caller holds ``self._cond``)."""
        while True:
            now = self._clock.now()
            heads = self._queue.group_heads(now)
            if not heads:
                if self._closing or not block:
                    return None if self._closing else ()
                self._clock.wait(self._cond, None)
                continue
            wait = self._effective_max_wait()
            # every group's trigger is evaluated (not only the most
            # urgent group's): a tile-full group is never stuck behind
            # a more-urgent group still waiting out its timer
            ready = [h for h in heads
                     if h.rows >= self._tile_rows(h)
                     or now >= h.arrival + wait or self._closing]
            if ready:
                pick = self._pick_fair(ready)
                if pick.ragged:
                    items = self._queue.pop_rows(
                        pick.key, self._tile_rows(pick), now)
                else:
                    items = self._queue.pop_group(
                        pick.key, self._tile_rows(pick), now)
                if not items:      # cancels won every race — rescan
                    continue
                self._record_pick(pick, ready, now)
                return (pick.key, items, pick.ragged)
            if not block:
                return ()
            soonest = min(h.arrival + wait for h in heads)
            self._clock.wait(self._cond, soonest - now)

    def _loop(self) -> None:
        while True:
            with self._cond:
                batch = self._select(block=True)
            if batch is None:
                return             # closed and drained
            if batch:
                key, items, ragged = batch
                if ragged:
                    self._dispatch_ragged(key, items)
                else:
                    self._dispatch(key, items)

    def _dispatch(self, key, reqs) -> None:
        """Assemble one micro-batch, execute, split results back.

        Each stage records a span into the flight recorder carrying
        every member request's ``trace_id`` — pure host-side deque
        appends in the batcher clock's domain, so the device dispatch
        sequence (and its zero-recompile guarantee) is untouched."""
        t0 = self._clock.now()
        ids = tuple(r.trace_id for r in reqs)
        for r in reqs:
            metrics.observe_stage(metrics.QUEUE_WAIT, t0 - r.arrival)
        rep = reqs[0]
        blocks = [r.queries for r in reqs]
        n_rows = sum(r.rows for r in reqs)
        # requests carry RESOLVED filter words (see submit): 1-D words
        # are shared by coalesce-key construction, 2-D (per-row) words
        # concatenate to match the concatenated query rows
        fw = rep.sample_filter
        if fw is not None and fw.ndim == 2 and len(reqs) > 1:
            parts = [r.sample_filter for r in reqs]
            if all(isinstance(p, np.ndarray) for p in parts):
                fw = np.concatenate(parts)
            else:
                fw = jnp.concatenate([jnp.asarray(p) for p in parts])
        t1 = self._clock.now()
        metrics.observe_stage(metrics.ASSEMBLY, t1 - t0)
        tracing.record_span("serving.assembly", t0, t1, trace_ids=ids,
                            attrs={"requests": len(reqs), "rows": n_rows})
        try:
            # trace_ids ride into the executor so mesh dispatches (and
            # their per-shard straggler spans) attribute back to the
            # member requests — graftscope v2's mesh-deep propagation
            results = self.executor.search_blocks(
                rep.index, blocks, rep.k, params=rep.params,
                sample_filter=fw, trace_ids=ids, **rep.kw)
            results = jax.block_until_ready(results)
        except Exception as e:  # noqa: BLE001 — fail the handles, not the worker
            t_fail = self._clock.now()
            for r in reqs:
                performed = r.handle._set_exception(e)
                # a failed deadline-carrying request is an SLO miss: a
                # wedged executor must drive the burn rate up, not
                # starve the window into a healthy-looking 0.0. Keyed
                # on the handle transition so a shutdown-drained
                # request (already completed, exempt by contract) is
                # not recorded a second time.
                if performed and self._slo is not None \
                        and r.deadline is not None:
                    self._slo.record(t_fail, False)
            tracing.inc_counter("serving.batcher.failed_batches")
            tracing.record_span(
                "serving.execute", t1, t_fail, trace_ids=ids,
                attrs={"requests": len(reqs), "rows": n_rows},
                events=((t_fail, "failed",
                         {"error": type(e).__name__}),))
            return
        t2 = self._clock.now()
        metrics.observe_stage(metrics.EXECUTE, t2 - t1)
        # per-params-class latency (graftflight satellite): the class
        # label pairs this histogram with the params-sweep recall
        # gauges (index.recall.sweep.p<NP>) — a coalesced batch shares
        # one params object, so one observation covers the batch
        cls = metrics.params_class(rep.params)
        if cls is not None:
            metrics.observe_execute_class(cls, t2 - t1)
        tracing.record_span("serving.execute", t1, t2, trace_ids=ids,
                            attrs={"requests": len(reqs), "rows": n_rows})
        delivered = [r.handle._set_result(d, i)
                     for r, (d, i) in zip(reqs, results)]
        t3 = self._clock.now()
        metrics.observe_stage(metrics.SPLIT, t3 - t2)
        tracing.record_span("serving.split", t2, t3, trace_ids=ids,
                            attrs={"requests": len(reqs)})
        for r, ok in zip(reqs, delivered):
            metrics.observe_stage(metrics.E2E, t3 - r.arrival)
            tracing.record_span("serving.request", r.arrival, t3,
                                trace_ids=(r.trace_id,),
                                attrs={"rows": r.rows})
            # SLO attainment: a deadline-carrying request that completed
            # is attained iff its result landed before the deadline (a
            # late completion is a miss even though the caller gets a
            # result — the deadline-shed path records its misses inside
            # the admission queue). Keyed on the handle transition
            # (``ok``) so a request something else already completed —
            # the shutdown drain — lands exactly one outcome.
            if ok and self._slo is not None and r.deadline is not None:
                self._slo.record(t3, t3 <= r.deadline)
        metrics.batch_dispatched(len(reqs), n_rows)

    def _dispatch_ragged(self, key, slices) -> None:
        """Assemble one packed ragged tile from (request, start, stop)
        row slices, execute through ``executor.search_ragged``, and
        complete every request whose final slice landed. A split
        request's earlier slices accumulate on the request; completion
        (result, SLO outcome, ``serving.request`` span) happens exactly
        once, when the last slice arrives. Stage spans mirror the
        bucketed dispatch, with the packing described in attrs."""
        t0 = self._clock.now()
        ids = tuple(dict.fromkeys(r.trace_id for r, _, _ in slices))
        n_rows = sum(stop - start for _, start, stop in slices)
        blocks, ks, params_list = [], [], []
        fw2 = []
        rep = slices[0][0]
        for r, start, stop in slices:
            if start == 0:
                metrics.observe_stage(metrics.QUEUE_WAIT,
                                      t0 - r.arrival)
            blocks.append(r.queries[start:stop])
            ks.append(r.k)
            params_list.append(r.params)
            if r.sample_filter is not None and r.sample_filter.ndim == 2:
                fw2.append(r.sample_filter[start:stop])
        # 1-D filter words are shared by packing-key construction (the
        # words' identity joins the key); 2-D per-row words concatenate
        # to the packed rows
        fw = rep.sample_filter
        if fw2:
            if all(isinstance(p, np.ndarray) for p in fw2):
                fw = np.concatenate(fw2)
            else:
                fw = jnp.concatenate([jnp.asarray(p) for p in fw2])
        t1 = self._clock.now()
        metrics.observe_stage(metrics.ASSEMBLY, t1 - t0)
        tracing.record_span(
            "serving.assembly", t0, t1, trace_ids=ids,
            attrs={"requests": len(ids), "slices": len(slices),
                   "rows": n_rows, "ragged": True})
        try:
            results = self.executor.search_ragged(
                rep.index, blocks, ks, params_list=params_list,
                sample_filter=fw, trace_ids=ids, **rep.kw)
            results = jax.block_until_ready(results)
        except Exception as e:  # noqa: BLE001 — fail the handles, not the worker
            t_fail = self._clock.now()
            for r in {id(r): r for r, _, _ in slices}.values():
                performed = r.handle._set_exception(e)
                if performed and self._slo is not None \
                        and r.deadline is not None:
                    self._slo.record(t_fail, False)
            tracing.inc_counter("serving.batcher.failed_batches")
            tracing.record_span(
                "serving.execute", t1, t_fail, trace_ids=ids,
                attrs={"requests": len(ids), "rows": n_rows,
                       "ragged": True},
                events=((t_fail, "failed",
                         {"error": type(e).__name__}),))
            return
        t2 = self._clock.now()
        metrics.observe_stage(metrics.EXECUTE, t2 - t1)
        # ragged tiles pack MIXED n_probes under one class: the shared
        # execute latency lands once in each distinct class present,
        # so every sweep operating point keeps a latency axis
        for cls in dict.fromkeys(
                metrics.params_class(p) for p in params_list):
            if cls is not None:
                metrics.observe_execute_class(cls, t2 - t1)
        tracing.record_span("serving.execute", t1, t2, trace_ids=ids,
                            attrs={"requests": len(ids), "rows": n_rows,
                                   "ragged": True})
        finished = []
        for (r, start, stop), (d, i) in zip(slices, results):
            if start == 0 and stop == r.rows:
                finished.append((r, d, i))       # unsplit fast path
            elif r.add_part(start, d, i):
                fd, fi = r.assemble()
                finished.append((r, fd, fi))
        delivered = [(r, r.handle._set_result(d, i))
                     for r, d, i in finished]
        t3 = self._clock.now()
        metrics.observe_stage(metrics.SPLIT, t3 - t2)
        tracing.record_span("serving.split", t2, t3, trace_ids=ids,
                            attrs={"requests": len(finished)})
        for r, ok in delivered:
            metrics.observe_stage(metrics.E2E, t3 - r.arrival)
            tracing.record_span("serving.request", r.arrival, t3,
                                trace_ids=(r.trace_id,),
                                attrs={"rows": r.rows, "ragged": True})
            if ok and self._slo is not None and r.deadline is not None:
                self._slo.record(t3, t3 <= r.deadline)
        metrics.batch_dispatched(len(finished), n_rows)
