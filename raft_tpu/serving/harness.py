"""Fault-injection harness for the serving frontend — the pieces the
deterministic test suite (and the ``BENCH_SERVING=1`` rider) drive the
batcher with:

- :class:`ManualClock` — virtual time under test control. With
  ``DynamicBatcher(start=False)`` the suite advances time and calls
  ``pump()``; nothing sleeps, nothing races, so deadline expiry /
  dual-trigger timing are exact. In threaded mode the clock's ``wait``
  is a real rendezvous that :meth:`ManualClock.advance` wakes.
- :class:`FakeExecutor` — a device-free executor stand-in with the
  batcher-facing API (``coalesce_key`` / ``search_blocks`` /
  ``buckets``). Results encode the query rows (``indices[r, j]`` is
  ``queries[r, 0]``), so re-split correctness is directly assertable.
- :class:`ShimExecutor` — wraps any executor-like with scripted
  latency (charged to the injected clock) and scripted failures, plus
  a call log: the "slow executor" the overflow/backpressure tests use
  to pile up a queue deterministically.
- :func:`burst_schedule` / :func:`drive_open_loop` — bursty
  *open-loop* load (submission times fixed in advance, independent of
  completions — the load model under which shed/overflow behavior is
  meaningful).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np


class ManualClock:
    """Deterministic virtual clock.

    ``now()`` returns the current virtual time; :meth:`advance` moves
    it forward and wakes any condition a batcher worker is parked on.
    ``wait`` ignores its timeout — virtual time only moves when the
    test says so, which is exactly what makes expiry tests exact."""

    def __init__(self, start: float = 0.0):
        self._t = start
        self._lock = threading.Lock()
        self._conds: List[threading.Condition] = []

    def now(self) -> float:
        with self._lock:
            return self._t

    def advance(self, dt: float) -> float:
        with self._lock:
            self._t += dt
            conds = list(self._conds)
            t = self._t
        for c in conds:
            with c:
                c.notify_all()
        return t

    def wait(self, cond: threading.Condition, timeout: Optional[float]):
        with self._lock:
            if cond not in self._conds:
                self._conds.append(cond)
        cond.wait()    # woken by advance()/submit()/close(), never time


class FakeExecutor:
    """Batcher-facing executor stand-in: no jax, no compiles.

    ``search_blocks`` returns, per block, ``distances[r, j] = (sum of
    row r) + j`` and ``indices[r, j] = int(queries[r, 0]) * k + j`` —
    row-identifying outputs, so a mis-split or cross-request mixup is
    caught by value. ``calls`` records every dispatched micro-batch as
    ``(n_blocks, total_rows)``."""

    def __init__(self, ragged_tile: int = 8):
        self.buckets = (8, 16, 32, 64, 128, 256)
        self.ragged_tile = ragged_tile
        self.calls: List[Tuple[int, int]] = []
        # ragged dispatches land here as (n_slices, claimed_rows)
        self.ragged_calls: List[Tuple[int, int]] = []

    def coalesce_key(self, index, k: int, params=None,
                     sample_filter=None, **kw) -> tuple:
        return (id(index), "fake", k, repr(params),
                tuple(sorted((n, str(v)) for n, v in kw.items())))

    def ragged_key(self, index, k: int, params=None, sample_filter=None,
                   **kw):
        """Fake packing key: everything is raggable (mixed k packs —
        the fake's params class is just the index identity) unless the
        index object opts out with ``bucketed_only = True`` — the
        tests' stand-in for CAGRA/approx-coarse fallback."""
        if getattr(index, "bucketed_only", False):
            return None
        return (id(index), "fake_ragged", repr(params),
                tuple(sorted((n, str(v)) for n, v in kw.items())))

    def search_blocks(self, index, blocks, k: int, params=None,
                      sample_filter=None, **kw):
        self.calls.append((len(blocks),
                           sum(int(np.shape(b)[0]) for b in blocks)))
        out = []
        for b in blocks:
            # graftlint: disable=R5(device-free test shim: inputs are host arrays by contract)
            b = np.asarray(b, np.float32)
            base = b.sum(axis=1, keepdims=True)
            d = base + np.arange(k, dtype=np.float32)[None, :]
            i = (b[:, :1].astype(np.int64) * k
                 + np.arange(k, dtype=np.int64)[None, :]).astype(np.int32)
            out.append((d, i))
        return out

    def search_ragged(self, index, blocks, ks, params_list=None,
                      sample_filter=None, **kw):
        """Packed-path stand-in: same row-identifying formula as
        ``search_blocks`` with per-block ``k`` — a mis-split slice or a
        cross-tile mixup is caught by value."""
        n = len(blocks)
        if not isinstance(ks, (list, tuple)):
            ks = [ks] * n
        self.ragged_calls.append(
            (n, sum(int(np.shape(b)[0]) for b in blocks)))
        out = []
        for b, k in zip(blocks, ks):
            # graftlint: disable=R5(device-free test shim: inputs are host arrays by contract)
            b = np.asarray(b, np.float32)
            base = b.sum(axis=1, keepdims=True)
            d = base + np.arange(k, dtype=np.float32)[None, :]
            i = (b[:, :1].astype(np.int64) * k
                 + np.arange(k, dtype=np.int64)[None, :]).astype(np.int32)
            out.append((d, i))
        return out


class ShimExecutor:
    """Wrap an executor-like with scripted latency and failures.

    ``delay_s`` is charged to ``clock`` per ``search_blocks`` call
    (virtual clocks advance; real clocks sleep) — the *slow executor*
    that makes queues pile up on demand. ``fail_on`` maps 0-based call
    ordinals to exceptions to raise instead of executing. The wrapped
    executor's results pass through untouched.

    ``shard_times`` scripts per-shard mesh timings (graftscope v2): a
    sequence of per-shard seconds applied to every call, or a dict of
    0-based call ordinal → sequence. Each scripted call records the
    timings through :func:`raft_tpu.core.tracing.record_mesh_spans` at
    the injected clock's current time, exactly as a mesh dispatch
    would — so the straggler detector's ``serving.mesh.*`` gauges are
    pinned to the script, device-free."""

    def __init__(self, inner, *, delay_s: float = 0.0, clock=None,
                 fail_on: Optional[dict] = None, shard_times=None):
        self.inner = inner
        self.delay_s = delay_s
        self.clock = clock
        self.fail_on = dict(fail_on or {})
        self.shard_times = shard_times
        self.calls: List[Tuple[int, int]] = []

    @property
    def buckets(self):
        return self.inner.buckets

    @property
    def ragged_tile(self):
        return getattr(self.inner, "ragged_tile", 256)

    def coalesce_key(self, *a, **kw):
        return self.inner.coalesce_key(*a, **kw)

    def ragged_key(self, *a, **kw):
        inner = getattr(self.inner, "ragged_key", None)
        return inner(*a, **kw) if inner is not None else None

    def _charge_call(self, n_blocks: int, rows: int) -> int:
        """Shared scripted-latency/failure bookkeeping of both
        dispatch entries; returns the 0-based call ordinal."""
        ordinal = len(self.calls)
        self.calls.append((n_blocks, rows))
        if self.delay_s:
            if self.clock is not None and hasattr(self.clock, "advance"):
                self.clock.advance(self.delay_s)
            else:
                import time

                time.sleep(self.delay_s)
        if ordinal in self.fail_on:
            raise self.fail_on[ordinal]
        return ordinal

    def search_ragged(self, index, blocks, ks, **kw):
        self._charge_call(len(blocks),
                          sum(int(np.shape(b)[0]) for b in blocks))
        return self.inner.search_ragged(index, blocks, ks, **kw)

    def search_blocks(self, index, blocks, k: int, **kw):
        ordinal = self._charge_call(
            len(blocks), sum(int(np.shape(b)[0]) for b in blocks))
        times = self.shard_times
        if isinstance(times, dict):
            times = times.get(ordinal)
        if times:
            from raft_tpu.core import tracing

            t0 = self.clock.now() if self.clock is not None else 0.0
            tracing.record_mesh_spans(
                "shim", t0, t0 + max(times),
                trace_ids=tuple(kw.get("trace_ids", ())),
                shard_timings=list(times))
        return self.inner.search_blocks(index, blocks, k, **kw)


def burst_schedule(n_bursts: int, burst_size: int, period_s: float,
                   start_s: float = 0.0) -> List[Tuple[float, int]]:
    """Open-loop burst plan: ``n_bursts`` bursts of ``burst_size``
    submissions, one burst every ``period_s`` seconds."""
    return [(start_s + i * period_s, burst_size) for i in range(n_bursts)]


def drive_open_loop(
    submit: Callable[[int, float], Any],
    schedule: Sequence[Tuple[float, int]],
    clock,
    pump: Optional[Callable[[], Any]] = None,
) -> List[Any]:
    """Run an open-loop load: at each scheduled virtual/wall time, call
    ``submit(request_ordinal, t)`` for every request of the burst —
    regardless of what completed. With a :class:`ManualClock`,
    ``clock.advance`` moves between bursts and ``pump`` (when given)
    runs the batcher's ready work after each burst; with a real clock
    the schedule is honored by sleeping. Returns everything ``submit``
    returned (handles), in submission order."""
    out: List[Any] = []
    ordinal = 0
    for t, n in schedule:
        dt = t - clock.now()
        if dt > 0:
            if hasattr(clock, "advance"):
                clock.advance(dt)
            else:
                import time

                time.sleep(dt)
        if pump is not None:
            pump()
        now = clock.now()
        for _ in range(n):
            out.append(submit(ordinal, now))
            ordinal += 1
        if pump is not None:
            pump()
    return out
