"""graftcast prefetch — forecast-driven tier promotion ahead of the
epoch tick.

grafttier (PR 14) promotes REACTIVELY: a shifting hot set pays the
cold tier's host-link bandwidth on the serving path until the next
placement epoch catches up. Every signal a predictor needs already
exists — the claimed probe-frequency window the epoch plans from and
graftledger's live headroom — so this module closes the gap with
three pieces, none of which adds a compile or a serving-path stall:

- **Forecast** (:func:`forecast_plan`) — a pure, deterministic
  function: the per-epoch claimed windows fold into a per-list EWMA
  (``alpha`` per epoch — the :class:`~raft_tpu.serving.gauge
  .DriftDetector` convention), and the NEXT epoch's plan is predicted
  by running the very :func:`~raft_tpu.serving.placement.plan_epoch`
  policy over the smoothed counts. Same inputs → same prediction on
  every replica; no clock, no RNG.
- **Staged promotion channel** (:meth:`TierPrefetcher.prefetch`) —
  at the :class:`~raft_tpu.serving.placement.TierManager`'s lead-time
  tick, predicted promotions copy their cold blocks into a fixed
  ``(K, ...)`` staged plane per hot plane — one donated
  ``dynamic_update_index_in_dim`` program per plane geometry
  (:func:`_stage_row_fn`), compiled once and reused forever, so the
  prefetcher adds ZERO compiles to a warm service. The copy out of
  the host-committed cold plane IS the promotion DMA, issued in the
  background instead of inside the epoch; at the epoch,
  :meth:`TierPrefetcher.take` hands :func:`~raft_tpu.neighbors.tiered
  .apply_plan` the staged rows and only the MISSES stream from the
  cold tier on the epoch path (the ``tier.promote_cold_bytes``
  surface ``BENCH_TIERED`` gates).
- **Miss cache + capacity discipline** — the staged planes double as
  a cold-tier miss cache pinning the last ``K``
  promoted-but-unplaced blocks in spare HBM. ``K`` is sized from
  live ledger headroom at construction, and the ACTIVE staged bytes
  ride the ledger as a named reservation
  (:meth:`~raft_tpu.core.memwatch.MemoryLedger.reserve`) through the
  capacity gate: a prefetch that would not fit raises
  :class:`~raft_tpu.core.memwatch.CapacityExceeded` HOST-side and the
  prefetcher degrades to the reactive path (counted, never an error
  on a search), and :meth:`TierPrefetcher.maintain` evicts
  least-recently-staged rows when headroom shrinks under it.

Staleness: every staged row is stamped with the tiered container's
placement ``generation``. :func:`~raft_tpu.neighbors.tiered
.apply_plan` bumps it under the swap lock, so a prefetch that
completes after the epoch it aimed at (or after its list was demoted
again) is detectably stale — :meth:`take` refuses the row and counts
it ``tier.prefetch.cancelled``; the promotion falls back to the cold
stream and stays bit-identical.

Counters: ``tier.prefetch.{issued,hits,misses,cancelled}`` (federated
into ``/fleet.json`` like the other tier counters).

Clock discipline (graftlint R7 — this module is IN scope): the
prefetcher holds NO clock at all. Lead-time pacing lives in
:meth:`TierManager.tick` on its injected clock; the prefetcher's only
notion of order is a logical stage counter (LRU age) and the
container's placement generation.

Host-sync discipline (R5 — in scope): the stage path enqueues device
programs and keeps every decision (row choice, generation stamp,
byte accounting) in host numpy; nothing fetches a device array.
"""

from __future__ import annotations

import dataclasses
import threading
from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import memwatch, tracing
from raft_tpu.core.memwatch import CapacityExceeded
from raft_tpu.core.validation import expect

ISSUED = "tier.prefetch.issued"
HITS = "tier.prefetch.hits"
MISSES = "tier.prefetch.misses"
CANCELLED = "tier.prefetch.cancelled"


@dataclasses.dataclass(frozen=True)
class PrefetchConfig:
    """Forecast + staging knobs. ``alpha`` is the per-epoch EWMA fold
    (the DriftDetector convention: higher = faster adaptation, more
    noise). ``capacity`` fixes the staged-plane row count ``K``;
    ``None`` sizes it from the swap width, clamped by ledger headroom
    × (1 − ``safety_fraction``) when a ledger with known headroom is
    attached. ``min_heat_ratio`` is the forecast's hysteresis —
    default matches the placement policy so the prediction is the
    plan the epoch would run on the smoothed window.
    ``prior_weight`` scales the EWMA against the live rolling window
    in the forecast fold (see :func:`forecast_plan`)."""

    alpha: float = 0.3
    capacity: Optional[int] = None
    safety_fraction: float = 0.25
    min_heat_ratio: float = 1.5
    prior_weight: float = 0.25


@dataclasses.dataclass(frozen=True)
class StagedBlocks:
    """What :func:`~raft_tpu.neighbors.tiered.apply_plan` consumes:
    ``rows[i]`` is the staged-plane row holding ``promotions[i]``'s
    blocks (−1 = miss, stream from cold), ``planes`` maps each hot
    plane name to its fixed ``(K, ...)`` staged storage."""

    rows: np.ndarray
    planes: Dict[str, jax.Array]


def forecast_plan(ewma, hot_lists, cold_lists, *, max_swaps: int,
                  min_heat_ratio: float = 1.5, window=None,
                  prior_weight: float = 0.25):
    """Predict the next epoch's plan: fold the ROLLING probe window
    (the traffic accumulated since the last epoch — a read-only peek
    of the ledger, so the epoch's claim still sees every probe) with
    the per-epoch drift EWMA (the history prior that keeps a sparse
    partial window from whipsawing the forecast), then run the SAME
    :func:`~raft_tpu.serving.placement.plan_epoch` policy over the
    folded counts (scaled to integers — the policy compares ratios,
    so a common scale changes nothing) against the current
    assignment. The EWMA enters DOWN-WEIGHTED (``prior_weight``): it
    is a full-epoch-magnitude prior, and on an abrupt drift its stale
    heat on the incumbent hot lists would otherwise swamp the partial
    window and hold the hysteresis ratio shut exactly when the next
    epoch is about to swap. Pure and deterministic; ties break
    exactly like the real epoch, so a correct forecast IS the plan."""
    from raft_tpu.serving.placement import plan_epoch

    counts = np.asarray(ewma, np.float64)
    if window is not None:
        counts = prior_weight * counts + np.asarray(window, np.float64)
    counts = np.rint(counts * 1024.0)
    return plan_epoch(counts.astype(np.int64), hot_lists, cold_lists,
                      max_swaps=max_swaps,
                      min_heat_ratio=min_heat_ratio)


@partial(jax.jit, donate_argnums=(0,))
def _stage_row_fn(staged_plane, cold_plane, cold_slot, row):
    """One background promotion DMA: copy cold list block
    ``cold_slot`` into staged row ``row``. The staged plane is
    DONATED (updates in place — the miss cache must not double its
    HBM while staging); slot and row are traced scalars, so one
    compiled program per plane geometry serves every prefetch — the
    zero-compile discipline the acceptance gate measures."""
    block = jax.lax.dynamic_index_in_dim(cold_plane, cold_slot, 0,
                                         keepdims=False)
    return jax.lax.dynamic_update_index_in_dim(staged_plane, block,
                                               row, 0)


class TierPrefetcher:
    """The graftcast background promotion channel for one tiered
    container (any :class:`~raft_tpu.neighbors.tiered._TieredPlanes`
    family — flat, PQ, or BQ; the staged planes mirror the
    container's ``_PLANE_PAIRS`` hot geometry).

    Driven entirely by the :class:`~raft_tpu.serving.placement
    .TierManager`: :meth:`observe` folds each epoch's claimed window
    (under the manager's epoch lock — the window is claimed ONCE and
    feeds plan and forecast from the same read), :meth:`prefetch`
    stages predicted promotions at the lead-time tick, :meth:`take`
    hands staged rows to ``apply_plan`` at the epoch. A ``width=0``
    or capacity-refused prefetcher is DISABLED: every method is a
    cheap no-op and serving is exactly the reactive PR 14 path.
    """

    def __init__(self, tiered, *, width: int,
                 config: Optional[PrefetchConfig] = None,
                 ledger: Optional[object] = None):
        self.tiered = tiered
        self.config = config or PrefetchConfig()
        self.ledger = ledger
        self._lock = threading.Lock()
        self._ewma = np.zeros((tiered.n_lists,), np.float64)  # guarded-by: _lock
        self._epochs_observed = 0                             # guarded-by: _lock
        self._stage_seq = 0                                   # guarded-by: _lock
        cap = self.config.capacity
        if cap is None:
            cap = int(width)
        cap = max(0, min(int(cap), tiered.n_cold))
        led = self._ledger()
        if led is not None and cap > 0:
            headroom = led.headroom_bytes()
            if headroom is not None:
                usable = max(
                    float(headroom)
                    * (1.0 - self.config.safety_fraction), 0.0)
                cap = min(cap, int(usable // max(tiered.block_bytes,
                                                 1)))
        self.capacity = cap
        # row bookkeeping (host-side truth): which list each staged
        # row holds (−1 free), the placement generation it was staged
        # against, and a logical age for LRU eviction
        self._row_list = np.full((cap,), -1, np.int64)  # guarded-by: _lock
        self._row_gen = np.zeros((cap,), np.int64)      # guarded-by: _lock
        self._row_age = np.zeros((cap,), np.int64)      # guarded-by: _lock
        # fixed (K, ...) staged storage per hot plane, committed to
        # the default device like the hot tier it feeds — allocated
        # ONCE; every stage donates it back in place
        self.planes: Dict[str, jax.Array] = {}
        if cap > 0:
            dev = jax.sharding.SingleDeviceSharding(jax.devices()[0])
            self.planes = jax.device_put(
                {hot_name: jnp.zeros(
                    (cap,) + tuple(getattr(tiered, hot_name).shape[1:]),
                    getattr(tiered, hot_name).dtype)
                 for hot_name, _ in type(tiered)._PLANE_PAIRS}, dev)

    # -- wiring ---------------------------------------------------------------

    def _ledger(self):
        """The capacity authority: an explicitly attached ledger wins,
        else the process-wide armed gate (so ``install_gate`` covers
        prefetch exactly like build/extend admission)."""
        return self.ledger if self.ledger is not None \
            else memwatch.gate()

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    # -- forecast -------------------------------------------------------------

    def observe(self, window_counts) -> None:
        """Fold one CLAIMED epoch window into the traffic EWMA.
        Called by the TierManager inside its epoch critical section —
        the same single claim feeds the epoch plan and this forecast,
        so a racing scrape can never double-fold a window (the
        DriftDetector locking model)."""
        window = np.asarray(window_counts, np.float64)
        a = self.config.alpha
        with self._lock:
            expect(window.shape == self._ewma.shape,
                   "observe() needs one count per list")
            if self._epochs_observed == 0:
                self._ewma = window.copy()
            else:
                self._ewma = a * window + (1.0 - a) * self._ewma
            self._epochs_observed += 1

    def predict(self, *, max_swaps: int, window=None):
        """The next-epoch plan forecast from the rolling window (the
        TierManager's read-only peek at the traffic since the last
        epoch) + the EWMA prior, against the container's CURRENT
        assignment (snapshotted under its swap lock so a concurrent
        epoch can't tear hot/cold)."""
        with self._lock:
            ewma = self._ewma.copy()
        with self.tiered._swap_lock:
            hot = self.tiered.hot_lists.copy()
            cold = self.tiered.cold_lists.copy()
        return forecast_plan(ewma, hot, cold, max_swaps=max_swaps,
                             min_heat_ratio=self.config.min_heat_ratio,
                             window=window,
                             prior_weight=self.config.prior_weight)

    # -- the background channel -----------------------------------------------

    def prefetch(self, *, max_swaps: int, window=None) -> int:
        """Stage the forecast promotions' cold blocks into the miss
        cache, ahead of the epoch. Returns the number of stage DMAs
        issued. Capacity-refused staging (the ledger gate says the
        active bytes would not fit) degrades to the reactive path:
        the remaining predictions are cancelled (counted), nothing
        raises toward serving."""
        if not self.enabled:
            return 0
        plan = self.predict(max_swaps=max_swaps, window=window)
        if not plan.promotions:
            return 0
        from raft_tpu.neighbors.tiered import _slot_maps

        issued = cancelled = 0
        pair_map = dict(type(self.tiered)._PLANE_PAIRS)
        with self._lock:
            # host mirrors under the swap lock — the slot truth
            # without fetching the device maps (R5: the prefetch
            # path never syncs on an array)
            with self.tiered._swap_lock:
                gen = self.tiered.generation
                _, cold_map = _slot_maps(self.tiered.hot_lists,
                                         self.tiered.cold_lists,
                                         self.tiered.n_lists)
            for lid in plan.promotions:
                if self._find_row_locked(lid, gen) >= 0:
                    continue                     # already staged, fresh
                cs = int(cold_map[lid])
                if cs < 0:
                    continue                     # promoted meanwhile
                row = self._free_row_locked()
                if row < 0:
                    row = self._evict_lru_locked()
                    cancelled += 1
                try:
                    self._admit_locked(extra_rows=1)
                except CapacityExceeded:
                    # degrade to reactive: free the row we grabbed,
                    # count the refusal, stop staging this round —
                    # the epoch will stream these from cold as before
                    self._row_list[row] = -1
                    cancelled += 1
                    break
                for hot_name in self.planes:
                    cold_plane = getattr(self.tiered,
                                         pair_map[hot_name])
                    self.planes[hot_name] = _stage_row_fn(
                        self.planes[hot_name], cold_plane,
                        jnp.int32(cs), jnp.int32(row))
                self._stage_seq += 1
                self._row_list[row] = int(lid)
                self._row_gen[row] = gen
                self._row_age[row] = self._stage_seq
                issued += 1
        if issued:
            tracing.inc_counter(ISSUED, float(issued))
        if cancelled:
            tracing.inc_counter(CANCELLED, float(cancelled))
        return issued

    def take(self, promotions, generation: int) -> Optional[StagedBlocks]:
        """Resolve one epoch's promotions against the miss cache:
        rows staged for these lists AT this placement generation are
        hits (consumed — ``apply_plan`` mixes them in and the rows
        free), everything else is a miss and streams from cold. Rows
        staged against an OLDER generation are stale — the epoch (or
        a re-demotion) moved the placement under them — and are
        cancelled, never served: bit-stability beats byte savings."""
        if not self.enabled:
            return None
        rows = np.full((len(promotions),), -1, np.int32)
        hits = stale = 0
        with self._lock:
            # retire stale rows first so a stale stage can never hit
            old = (self._row_list >= 0) & (self._row_gen
                                           != int(generation))
            stale = int(old.sum())
            self._row_list[old] = -1
            for i, lid in enumerate(promotions):
                r = self._find_row_locked(int(lid), int(generation))
                if r >= 0:
                    rows[i] = r
                    self._row_list[r] = -1       # consumed
                    hits += 1
            self._release_locked()
        misses = len(promotions) - hits
        tracing.inc_counters({HITS: float(hits),
                              MISSES: float(misses)})
        if stale:
            tracing.inc_counter(CANCELLED, float(stale))
        if hits == 0:
            return None
        return StagedBlocks(rows=rows, planes=dict(self.planes))

    def maintain(self) -> int:
        """Miss-cache eviction under shrinking headroom: while the
        ACTIVE staged bytes exceed what the ledger's current headroom
        sustains (headroom already excludes this prefetcher's own
        hold), evict least-recently-staged rows and shrink the hold.
        Returns rows evicted (counted ``tier.prefetch.cancelled``)."""
        led = self._ledger()
        if not self.enabled or led is None:
            return 0
        evicted = 0
        with self._lock:
            headroom = led.headroom_bytes()
            if headroom is None:
                return 0
            block = max(int(self.tiered.block_bytes), 1)
            allowance = max(
                (float(headroom) + self._active_bytes_locked())
                * (1.0 - self.config.safety_fraction), 0.0)
            budget_rows = int(allowance // block)
            while int((self._row_list >= 0).sum()) > budget_rows:
                self._evict_lru_locked()
                evicted += 1
            self._release_locked()
        if evicted:
            tracing.inc_counter(CANCELLED, float(evicted))
        return evicted

    # -- row bookkeeping (all under self._lock) -------------------------------

    def _find_row_locked(self, lid: int, gen: int) -> int:
        m = np.nonzero((self._row_list == lid)
                       & (self._row_gen == gen))[0]
        return int(m[0]) if m.size else -1

    def _free_row_locked(self) -> int:
        m = np.nonzero(self._row_list < 0)[0]
        return int(m[0]) if m.size else -1

    def _evict_lru_locked(self) -> int:
        live = np.nonzero(self._row_list >= 0)[0]
        if not live.size:
            return -1
        row = int(live[np.argmin(self._row_age[live])])
        self._row_list[row] = -1
        return row

    def _active_bytes_locked(self) -> int:
        return int((self._row_list >= 0).sum()) \
            * int(self.tiered.block_bytes)

    def _admit_locked(self, extra_rows: int = 0) -> None:
        """Grow the ledger hold to cover the active rows plus
        ``extra_rows`` about to stage — THE capacity-gate touchpoint:
        :class:`CapacityExceeded` propagates to :meth:`prefetch`'s
        degrade path, so a prefetch can never OOM what serving
        needs."""
        led = self._ledger()
        if led is None or not hasattr(led, "reserve"):
            return
        led.reserve("tier.prefetch", self._active_bytes_locked()
                    + extra_rows * int(self.tiered.block_bytes))

    def _release_locked(self) -> None:
        led = self._ledger()
        if led is None or not hasattr(led, "reserve"):
            return
        led.reserve("tier.prefetch", self._active_bytes_locked())

    # -- scrape surface -------------------------------------------------------

    def snapshot(self) -> dict:
        """The ``/tier.json`` ``prefetch`` block."""
        with self._lock:
            staged = int((self._row_list >= 0).sum())
            return {
                "enabled": self.enabled,
                "capacity": int(self.capacity),
                "staged": staged,
                "staged_bytes": self._active_bytes_locked(),
                "epochs_observed": int(self._epochs_observed),
                "config": {
                    "alpha": self.config.alpha,
                    "safety_fraction": self.config.safety_fraction,
                    "min_heat_ratio": self.config.min_heat_ratio,
                    "prior_weight": self.config.prior_weight,
                },
            }
