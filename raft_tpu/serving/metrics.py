"""Serving-frontend metrics, exported through the existing
:mod:`raft_tpu.core.tracing` registry.

Per-stage latency **histograms** (log2 buckets, p50/p95/p99 estimates):

- ``serving.batcher.queue_wait_seconds``   — admission → batch assembly
- ``serving.batcher.assembly_seconds``     — group pop + block concat
- ``serving.batcher.execute_seconds``      — device execute (blocked)
- ``serving.batcher.split_seconds``        — result re-split + handle set
- ``serving.batcher.e2e_seconds``          — admission → handle complete

**Counters** (throughput / shed / occupancy):

- ``serving.admission.accepted`` / ``.rejected``  — admission outcomes
- ``serving.batcher.requests`` / ``.rows``        — dispatched work
- ``serving.batcher.batches``                     — executor calls made
- ``serving.batcher.shed_deadline``               — expired → shed
- ``serving.batcher.cancelled``                   — cancelled in queue
- ``serving.batcher.shutdown_shed``               — shed at close()

Batch **occupancy** — the coalescing win the ISSUE's acceptance
criterion gates on — is derived, not stored: ``requests / batches``
(and ``rows / batches``) from one counters snapshot.
"""

from __future__ import annotations

from raft_tpu.core import tracing

PREFIX = "serving.batcher."

QUEUE_WAIT = PREFIX + "queue_wait_seconds"
ASSEMBLY = PREFIX + "assembly_seconds"
EXECUTE = PREFIX + "execute_seconds"
SPLIT = PREFIX + "split_seconds"
E2E = PREFIX + "e2e_seconds"


def observe_stage(name: str, seconds: float) -> None:
    """Record one stage latency into its histogram."""
    tracing.observe(name, seconds)


def batch_dispatched(n_requests: int, n_rows: int) -> None:
    """Count one dispatched micro-batch."""
    tracing.inc_counter(PREFIX + "batches")
    tracing.inc_counter(PREFIX + "requests", n_requests)
    tracing.inc_counter(PREFIX + "rows", n_rows)


def occupancy() -> dict:
    """Derived batch-occupancy stats: mean requests and rows per
    dispatched micro-batch (1.0 requests/batch == no coalescing)."""
    batches = tracing.get_counter(PREFIX + "batches")
    if batches == 0:
        return {"batches": 0, "requests_per_batch": 0.0,
                "rows_per_batch": 0.0}
    return {
        "batches": int(batches),
        "requests_per_batch":
            tracing.get_counter(PREFIX + "requests") / batches,
        "rows_per_batch": tracing.get_counter(PREFIX + "rows") / batches,
    }


def snapshot() -> dict:
    """One scrape of the whole serving surface: counters + per-stage
    histogram summaries + derived occupancy (the bench rider's and any
    monitoring agent's single entry point)."""
    return {
        "counters": tracing.counters("serving."),
        "histograms": tracing.histograms(PREFIX),
        "occupancy": occupancy(),
    }


def reset() -> None:
    """Zero every serving counter and histogram — test/bench isolation."""
    tracing.reset_counters("serving.")
    tracing.reset_histograms(PREFIX)
