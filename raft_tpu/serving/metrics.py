"""Serving-frontend metrics, exported through the existing
:mod:`raft_tpu.core.tracing` registry.

Per-stage latency **histograms** (log2 buckets, p50/p95/p99 estimates):

- ``serving.batcher.queue_wait_seconds``   — admission → batch assembly
- ``serving.batcher.assembly_seconds``     — group pop + block concat
- ``serving.batcher.execute_seconds``      — device execute (blocked)
- ``serving.batcher.split_seconds``        — result re-split + handle set
- ``serving.batcher.e2e_seconds``          — admission → handle complete

**Counters** (throughput / shed / occupancy):

- ``serving.admission.accepted`` / ``.rejected``  — admission outcomes
- ``serving.batcher.requests`` / ``.rows``        — dispatched work
- ``serving.batcher.batches``                     — executor calls made
- ``serving.batcher.shed_deadline``               — expired → shed
- ``serving.batcher.cancelled``                   — cancelled in queue
- ``serving.batcher.shutdown_shed``               — shed at close()
- ``serving.execute.calls`` / ``.rows`` /
  ``.modeled_flops`` / ``.modeled_bytes``         — executor dispatches
  priced by each executable's compile-time ``cost_analysis()``
- ``serving.execute.padded_rows``                 — dispatched row
  capacity incl. bucket/tile pad; with ``.rows`` it derives the
  pad-waste fraction the ragged-vs-bucketed A/B gates on
- ``serving.execute.{rows,padded_rows}.p<NP>.t<T>`` — the ragged
  dispatch core's per-(params class, tile) split of the two counters
  above (graftragged): ``derived()["pad_waste_by_class"]`` and the
  exporter's ``serving_execute_*{params_class=,tile=}`` labeled
  families attribute pad waste to the small-vs-large tile choice
- ``serving.batcher.group_starvation_s``          — (gauge) longest any
  dispatch-ready group waited while another was served — the
  cross-index fairness budget's observable

**Gauges** (PR 6 graftscope):

- ``serving.admission.queue_depth`` / ``.shed_level`` /
  ``.arrival_rate_hz``                            — admission state
- ``serving.executable.<digest>.flops`` /
  ``.bytes_accessed`` / ``.peak_hbm_bytes``       — per-executable cost
- ``serving.executor.cached_executables``         — AOT cache size
- ``serving.collective.<family>.<wire>.<probe_wire>.*_bytes``
                                                  — modeled mesh wire

**SLO surface** (PR 7 graftscope v2, batcher clock domain):

- ``serving.slo.attained`` / ``.missed``          — deadline-attainment
  counters: every deadline-carrying request that reaches ``submit()``
  lands as exactly one of the two (on-time result → attained; completed
  past its deadline, shed for expiry before dispatch, rejected at
  admission, or failed with its batch → missed — overload and executor
  failure must drive the burn rate UP, not starve the window into a
  healthy-looking 0.0; exempt are the deliberate shutdown drain and
  caller cancellation that wins before dispatch — a request the client
  abandoned is not a service outcome)
- ``serving.slo.burn_rate``                       — sliding-window gauge:
  the window's miss fraction over the SLO's error budget
  (``1 − target``); 1.0 = burning budget exactly as provisioned, >1 =
  on track to exhaust it. All timestamps come from the batcher clock,
  so the manual-clock tests pin the window arithmetic exactly.
- ``serving.slo.window_total`` / ``.window_missed`` — current window
  contents (the burn rate's numerator/denominator, for debugging)
- ``serving.mesh.shard_skew`` / ``.slowest_shard`` /
  ``.shard_time_{max,mean}_s``                    — straggler detector
  output (see :func:`raft_tpu.core.tracing.record_mesh_spans`)
- ``serving.slo.burn_rate.<label>`` / ``serving.slo.alert`` — the
  multiwindow burn-rate policy (PR 8): labeled per-window gauges plus
  the combined alert that fires only when every window burns
  (:class:`MultiBurnConfig` / :class:`MultiBurnAlert`)

**graftgauge surface** (PR 8, published at scrape time by
:class:`~raft_tpu.serving.gauge.IndexGauge` and the executor):

- ``index.probe_freq.<label>.{total,probed_fraction,coverage_p01,
  coverage_p10}`` + ``.list.<lid>`` top-N samples — device-side
  probe-frequency accounting; ``index.probe_freq.accounted`` is the
  monotone counter mirror the CI snapshot floors check, and
  ``index.probe.{dispatches,rows}`` the per-dispatch host heartbeat
- ``index.health.<name>.*`` — list-occupancy skew, dead/overflow
  lists, fill fraction, Gini, per-shard imbalance
- ``index.recall.{estimate,ci_low,ci_high,window_pairs,window_trials}``
  + the ``index.recall.shadow_*`` lifecycle counters — windowed online
  recall estimation from shadow queries
- ``index.drift.score`` / ``index.drift.<name>.{score,alert}`` —
  streaming divergence of live traffic from the build-time baseline

**graftflight surface** (PR 11):

- ``serving.batcher.execute_seconds.p<NP>`` — per-params-class
  execute-latency histograms (:func:`params_class` /
  :func:`observe_execute_class`; rendered as
  ``{params_class=...}``-labeled Prometheus families) — the latency
  axis pairing the ``index.recall.sweep.p<NP>`` recall gauges
- ``serving.attribution.{device_seconds,modeled_bytes,modeled_flops}``
  + ``serving.executable.<digest>.measured_*`` — device-truth
  attribution from profiler captures
  (:mod:`raft_tpu.core.profiling`); :func:`derived` publishes
  ``device_achieved_gbps``/``gflops`` and ``measured_executables``
  next to the wall-clock-derived numbers
- ``profiling.captures`` / ``incident.*`` — trace-ingestion and
  flight-recorder (:mod:`raft_tpu.serving.flight`) lifetime counters

**graftfleet surface** (PR 12):

- ``serving.attribution.rolling.*`` — the EWMA-folded steady-state
  attribution (:class:`raft_tpu.core.profiling.RollingAttribution`)
  the continuous low-duty-cycle scheduler
  (:mod:`raft_tpu.serving.continuous`) feeds; :func:`derived` carries
  the ``rolling_*`` columns next to the wall-clock and incident-
  snapshot numbers
- ``serving.mesh.shard_skew_p50``/``_p99`` — per-dispatch straggler
  skew distribution from a capture's invocation windows
- ``continuous.{ticks,captures,deferred,skipped,empty,errors}`` +
  ``profiling.rolling.folds`` — scheduler/fold lifetime accounting
- ``fleet.*`` — multi-replica federation
  (:mod:`raft_tpu.serving.federation`): scrape/health counters, fleet
  probe coverage, pooled recall, pooled drift

**graftledger surface** (PR 13, published at scrape time by
:class:`raft_tpu.core.memwatch.MemoryLedger`):

- ``memory.index.<label>.{resident_bytes,shard_bytes}`` — the
  resident-bytes model per watched index (labeled Prometheus
  families); ``memory.resident.total_bytes`` the sum
- ``memory.device.<ordinal>.{in_use,peak,limit}_bytes`` — live
  ``device.memory_stats()`` truth (absent on backends without it;
  ``memory.live.supported`` says which)
- ``memory.forecast.peak_bytes`` / ``memory.reserved.*`` — the
  reservation forecast (resident + donated state + probe planes +
  max compile-time temp); ``memory.hbm.headroom_bytes`` the live
  headroom (−1 when unknowable); ``memory.divergence_bytes`` the
  modeled-vs-live gap (fragmentation / untracked allocations)
- ``memory.watermark.{in_use,forecast}_peak_bytes`` — dispatch-time
  high-water marks; ``memory.samples`` the heartbeat counter the CI
  snapshot floor checks; ``memory.gate.{admitted,refused}`` the
  capacity-gate ledger
- ``fleet.memory.{resident_bytes,headroom_min_bytes}`` +
  ``fleet.replica.<name>.headroom_bytes`` — the federated memory
  view (headroom min / resident sum); ``fleet.slo.burn_rate.*`` /
  ``fleet.slo.alert`` the fleet-level multiburn alert over the
  merged windows

Batch **occupancy** — the coalescing win the ISSUE's acceptance
criterion gates on — is derived, not stored: ``requests / batches``
(and ``rows / batches``) from one counters snapshot. Likewise the
**achieved-bandwidth** numbers (:func:`derived`): modeled bytes/flops
over the measured execute-latency sum — the TPU-KNN roofline
accounting as a running metric, from the same inputs the BENCH rider
reports — plus the executor cache hit-rate.
"""

from __future__ import annotations

import collections
import dataclasses
import re
import threading
from typing import Optional

from raft_tpu.core import profiling, tracing

PREFIX = "serving.batcher."

QUEUE_WAIT = PREFIX + "queue_wait_seconds"
ASSEMBLY = PREFIX + "assembly_seconds"
EXECUTE = PREFIX + "execute_seconds"
SPLIT = PREFIX + "split_seconds"
E2E = PREFIX + "e2e_seconds"

SLO_ATTAINED = "serving.slo.attained"
SLO_MISSED = "serving.slo.missed"
SLO_BURN_RATE = "serving.slo.burn_rate"
SLO_ALERT = "serving.slo.alert"


@dataclasses.dataclass(frozen=True)
class SloConfig:
    """Deadline-SLO definition for the burn-rate window.

    ``target`` is the attainment objective (0.999 = "99.9% of
    deadline-carrying requests complete on time"); its complement is
    the error budget the burn rate is normalized by. ``window_s`` is
    the sliding window (batcher clock domain) the rate is computed
    over — short windows catch fast burns, long windows catch slow
    leaks; run one exporter-side recording per deployment and let the
    alerting layer combine windows."""

    window_s: float = 60.0
    target: float = 0.999


class SloWindow:
    """Deadline-attainment accounting in the batcher clock's domain.

    :meth:`record` counts one deadline-carrying request's outcome into
    the monotone ``serving.slo.{attained,missed}`` counters AND a
    sliding window of (timestamp, attained) events; the **burn rate**
    — window miss fraction ÷ error budget, the standard SRE
    multiwindow-alerting quantity — publishes as the
    ``serving.slo.burn_rate`` gauge. Everything is keyed to caller
    timestamps (``clock.now()`` / the batcher's stage times), so the
    window never reads a wall clock and the manual-clock tests pin it
    exactly. Thread-safe: one lock, O(events-in-window) memory; the
    miss count is maintained incrementally on append/prune, so every
    operation is O(events-pruned), not O(window) — record() sits on
    the per-request completion path.

    ``label`` suffixes the published gauge names
    (``serving.slo.burn_rate.<label>``) so several windows over the
    same outcome stream — the multiburn alert's 5 m + 1 h pair —
    publish side by side; unlabeled keeps the original flat names.
    ``prefix`` relocates the whole gauge family (default
    ``serving.slo.`` — the fleet aggregator's federated windows
    publish under ``fleet.slo.`` so a replica-local and a fleet-wide
    burn rate can coexist in one registry)."""

    def __init__(self, config: Optional[SloConfig] = None, *,
                 label: Optional[str] = None,
                 prefix: str = "serving.slo."):
        self.config = config or SloConfig()
        self.label = label
        self.prefix = prefix
        self._suffix = f".{label}" if label else ""
        self._lock = threading.Lock()
        # events are (timestamp, attained, n): n > 1 carries a BATCH
        # of same-outcome outcomes in one entry — the federation path
        # folds per-merge deltas of fleet counter sums, and appending
        # thousands of unit events per merge would make the window
        # O(fleet traffic) instead of O(merges)
        self._events: "collections.deque" = collections.deque()  # guarded-by: _lock
        self._total = 0   # guarded-by: _lock
        self._missed = 0  # guarded-by: _lock

    def _prune_locked(self, now: float) -> None:
        horizon = now - self.config.window_s
        while self._events and self._events[0][0] <= horizon:
            _, ok, n = self._events.popleft()
            self._total -= n
            if not ok:
                self._missed -= n

    def _counts(self, now: float):
        with self._lock:
            self._prune_locked(now)
            return self._total, self._missed

    def _append(self, now: float, attained: bool, n: int = 1) -> None:
        """Window bookkeeping only — no counter bump, no publish. The
        multiburn alert fans one outcome into several windows and must
        bump the process-wide attained/missed counters exactly once."""
        if n <= 0:
            return
        with self._lock:
            self._events.append((now, attained, n))
            self._total += n
            if not attained:
                self._missed += n

    def record_batch(self, now: float, attained_n: int,
                     missed_n: int) -> None:
        """Fold a BATCH of outcomes into the window WITHOUT bumping
        the process-wide attained/missed counters — the federation
        path: the outcomes already counted in their replica processes,
        and the aggregator only needs them windowed. Publishes."""
        self._append(now, True, int(attained_n))
        self._append(now, False, int(missed_n))
        self.publish(now)

    def record(self, now: float, attained: bool) -> None:
        """Count one outcome at clock time ``now`` and re-publish."""
        tracing.inc_counter(SLO_ATTAINED if attained else SLO_MISSED)
        self._append(now, attained)
        self.publish(now)

    def burn_rate(self, now: float) -> float:
        """Window miss fraction over the error budget at ``now`` (0.0
        for an empty window — no traffic burns no budget)."""
        total, missed = self._counts(now)
        if total == 0:
            return 0.0
        budget = max(1.0 - self.config.target, 1e-9)
        return (missed / total) / budget

    def publish(self, now: float) -> None:
        """Re-publish the window gauges as of ``now`` — called on every
        record and by the exporter's scrape-time refresh, so a quiet
        service's burn rate decays as its misses age out of the
        window."""
        total, missed = self._counts(now)
        budget = max(1.0 - self.config.target, 1e-9)
        tracing.set_gauges({
            self.prefix + "burn_rate" + self._suffix:
                (missed / total) / budget if total else 0.0,
            self.prefix + "window_total" + self._suffix: float(total),
            self.prefix + "window_missed" + self._suffix: float(missed),
        })


@dataclasses.dataclass(frozen=True)
class MultiBurnConfig:
    """Multiwindow burn-rate alert policy (the SRE multiburn pattern):
    a short window catches fast burns, a long window confirms they are
    sustained, and the alert fires only when BOTH burn past
    ``alert_burn`` — a short spike that the long window absorbs, or a
    slow leak the short window has already recovered from, pages
    nobody. Defaults pair 5 m + 1 h at burn 1.0 (consuming error
    budget exactly as provisioned)."""

    short: SloConfig = SloConfig(window_s=300.0)
    long: SloConfig = SloConfig(window_s=3600.0)
    short_label: str = "5m"
    long_label: str = "1h"
    alert_burn: float = 1.0


class MultiBurnAlert:
    """Paired :class:`SloWindow` recorder + the ``serving.slo.alert``
    gauge. Batcher-facing duck type of a single ``SloWindow``
    (``record(now, attained)`` / ``publish(now)``), so
    ``BatcherConfig.multiburn`` swaps it in without touching any
    completion path; each outcome bumps the process-wide
    attained/missed counters exactly once and lands in both windows.
    All timestamps are caller-clock-domain — the ManualClock tests pin
    window arithmetic and the alert transition exactly."""

    def __init__(self, config: Optional[MultiBurnConfig] = None, *,
                 prefix: str = "serving.slo."):
        self.config = config or MultiBurnConfig()
        self.prefix = prefix
        self.windows = (
            SloWindow(self.config.short, label=self.config.short_label,
                      prefix=prefix),
            SloWindow(self.config.long, label=self.config.long_label,
                      prefix=prefix),
        )

    def record(self, now: float, attained: bool) -> None:
        """One outcome → both windows; counters bumped once."""
        tracing.inc_counter(SLO_ATTAINED if attained else SLO_MISSED)
        for w in self.windows:
            w._append(now, attained)
        self.publish(now)

    def record_batch(self, now: float, attained_n: int,
                     missed_n: int) -> None:
        """Batched outcomes → both windows, NO process-counter bumps
        — the federation path (see :meth:`SloWindow.record_batch`):
        the fleet aggregator folds per-merge deltas of the summed
        replica attained/missed counters, whose unit outcomes were
        already counted where they happened."""
        for w in self.windows:
            w._append(now, True, int(attained_n))
            w._append(now, False, int(missed_n))
        self.publish(now)

    def burn_rates(self, now: float) -> tuple:
        return tuple(w.burn_rate(now) for w in self.windows)

    def alert(self, now: float) -> bool:
        """True iff EVERY window burns at/above the policy threshold."""
        return all(r >= self.config.alert_burn
                   for r in self.burn_rates(now))

    def publish(self, now: float) -> None:
        """Re-publish each window's labeled gauges plus the combined
        ``serving.slo.alert`` (1.0 firing / 0.0 quiet) — scrape-time
        refresh decays both windows and may clear the alert."""
        for w in self.windows:
            w.publish(now)
        tracing.set_gauge(self.prefix + "alert",
                          1.0 if self.alert(now) else 0.0)


def observe_stage(name: str, seconds: float) -> None:
    """Record one stage latency into its histogram."""
    tracing.observe(name, seconds)


def params_class(params) -> Optional[str]:
    """The latency label of a request's search params — ``p<NP>`` for
    params carrying ``n_probes`` (graftflight satellite, the
    graftgauge carried follow-on): the SAME spelling the params-sweep
    recall gauges use (``index.recall.sweep.p<NP>``), so the sweep's
    recall axis pairs with a measured latency axis and the live
    recall/latency frontier is complete. None for params with no
    ``n_probes`` knob (brute force, CAGRA) — those observe only the
    unlabeled family."""
    n_probes = getattr(params, "n_probes", None)
    if n_probes is None:
        return None
    return f"p{int(n_probes)}"


# label-cardinality bound for the per-params-class histograms:
# n_probes is client-supplied, and histograms are process-lifetime —
# without a cap, a client sweeping arbitrary values (an autotuner)
# would grow the registry and every /metrics payload without bound
# (the same leak PR 8's top-N probe gauges were engineered around).
# 32 distinct classes covers any realistic sweep; overflow is counted,
# not silent.
EXECUTE_CLASS_CAP = 32
_execute_classes: set = set()  # guarded-by: _execute_classes_lock
_execute_classes_lock = threading.Lock()


def observe_execute_class(label: str, seconds: float) -> None:
    """Record one dispatch's execute latency into the per-params-class
    histogram (``serving.batcher.execute_seconds.<label>`` — rendered
    by the exporter as the labeled
    ``serving_batcher_execute_seconds{params_class="<label>"}``
    Prometheus family next to the unlabeled aggregate). At most
    :data:`EXECUTE_CLASS_CAP` distinct labels materialize per process;
    past the cap a new label's observation lands only in the unlabeled
    aggregate and bumps ``serving.batcher.execute_class_dropped``."""
    with _execute_classes_lock:
        if label not in _execute_classes:
            if len(_execute_classes) >= EXECUTE_CLASS_CAP:
                tracing.inc_counter(PREFIX + "execute_class_dropped")
                return
            _execute_classes.add(label)
    tracing.observe(f"{EXECUTE}.{label}", seconds)


def batch_dispatched(n_requests: int, n_rows: int) -> None:
    """Count one dispatched micro-batch."""
    tracing.inc_counter(PREFIX + "batches")
    tracing.inc_counter(PREFIX + "requests", n_requests)
    tracing.inc_counter(PREFIX + "rows", n_rows)


def occupancy() -> dict:
    """Derived batch-occupancy stats: mean requests and rows per
    dispatched micro-batch (1.0 requests/batch == no coalescing)."""
    batches = tracing.get_counter(PREFIX + "batches")
    if batches == 0:
        return {"batches": 0, "requests_per_batch": 0.0,
                "rows_per_batch": 0.0}
    return {
        "batches": int(batches),
        "requests_per_batch":
            tracing.get_counter(PREFIX + "requests") / batches,
        "rows_per_batch": tracing.get_counter(PREFIX + "rows") / batches,
    }


def derived() -> dict:
    """Metrics computed from one counters read: executor cache
    hit-rate and live achieved GB/s / GFLOP/s (modeled bytes & flops
    from compile-time cost analysis, divided by the measured execute
    histogram's latency sum)."""
    hits = tracing.get_counter("serving.cache_hits")
    misses = tracing.get_counter("serving.cache_misses")
    exec_s = tracing.get_histogram(EXECUTE).snapshot()["sum"]
    rows = tracing.get_counter("serving.execute.rows")
    padded = tracing.get_counter("serving.execute.padded_rows")
    out = {
        "cache_hit_rate": hits / (hits + misses) if hits + misses else 0.0,
        "execute_seconds_total": exec_s,
        # the pad-waste fraction the ragged-vs-bucketed A/B gates on:
        # share of dispatched row capacity that was bucket/tile pad
        # (bucketed pow2 rounding wastes up to ~50%; the packed ragged
        # tile only pads the final partial tile)
        "pad_waste_fraction": 1.0 - rows / padded if padded else 0.0,
        "modeled_bytes_total":
            tracing.get_counter("serving.execute.modeled_bytes"),
        "modeled_flops_total":
            tracing.get_counter("serving.execute.modeled_flops"),
    }
    # per-(params class, tile) pad-waste attribution (graftragged):
    # the ragged dispatch core splits its rows/padded_rows counters as
    # serving.execute.{rows,padded_rows}.p<NP>.t<TILE>, so the waste
    # attributes to the small-vs-large tile choice per class — the
    # signal that says whether the dual tile earns its second
    # executable at the observed load mix
    by_class = {}
    split_pad = tracing.counters("serving.execute.padded_rows.")
    for name, pad in split_pad.items():
        label = name[len("serving.execute.padded_rows."):]
        r = tracing.get_counter("serving.execute.rows." + label)
        if pad:
            by_class[label] = 1.0 - r / pad
    out["pad_waste_by_class"] = by_class
    out["achieved_gbps"] = (
        out["modeled_bytes_total"] / exec_s / 1e9 if exec_s > 0 else 0.0)
    out["achieved_gflops"] = (
        out["modeled_flops_total"] / exec_s / 1e9 if exec_s > 0 else 0.0)
    # graftflight (PR 11): the DEVICE-measured counterparts, published
    # when a profiler capture was attributed — modeled bytes/flops over
    # MEASURED device seconds, next to the wall-clock-derived numbers
    # above so the two accountings can disagree visibly (wall clock
    # includes dispatch/readiness overhead the device never saw)
    att_s = tracing.get_counter(profiling.ATTRIBUTED_SECONDS)
    out["measured_device_seconds_total"] = att_s
    out["device_achieved_gbps"] = (
        tracing.get_counter(profiling.ATTRIBUTED_BYTES) / att_s / 1e9
        if att_s > 0 else 0.0)
    out["device_achieved_gflops"] = (
        tracing.get_counter(profiling.ATTRIBUTED_FLOPS) / att_s / 1e9
        if att_s > 0 else 0.0)
    # graftfleet (PR 12): the ROLLING measured view — EWMA over the
    # continuous scheduler's periodic capture windows, so this number
    # is continuously fresh rather than the last incident's snapshot
    rp = profiling.ROLLING_PREFIX
    out["rolling_windows"] = tracing.get_gauge(rp + "windows")
    out["rolling_device_seconds"] = tracing.get_gauge(
        rp + "device_seconds")
    out["rolling_gbps"] = tracing.get_gauge(rp + "gbps")
    out["rolling_gflops"] = tracing.get_gauge(rp + "gflops")
    # per-executable measured view, re-read from the attribution's
    # gauges (one scrape shows each resident program's measured
    # achieved GB/s / GFLOP/s — bytes-per-call x trace invocations
    # over its own measured device seconds)
    measured: dict = {}
    pat = re.compile(
        r"^serving\.executable\.([0-9a-f]+)\.measured_([a-z_]+)$")
    for name, v in tracing.gauges("serving.executable.").items():
        m = pat.match(name)
        if m:
            measured.setdefault(m.group(1), {})[m.group(2)] = v
    out["measured_executables"] = measured
    return out


def snapshot() -> dict:
    """One scrape of the whole serving surface: counters + gauges +
    per-stage histogram summaries + derived occupancy and achieved
    bandwidth (the bench rider's, the exporter's, and any monitoring
    agent's single entry point)."""
    return {
        "counters": tracing.counters("serving."),
        "gauges": tracing.gauges("serving."),
        "histograms": tracing.histograms(PREFIX),
        "occupancy": occupancy(),
        "derived": derived(),
    }


def reset() -> None:
    """Zero every serving + graftgauge counter, gauge, histogram, and
    the span flight recorder — test/bench isolation (counters fold
    into the lifetime ledger, so session artifacts survive)."""
    tracing.reset_counters("serving.")
    tracing.reset_gauges("serving.")
    tracing.reset_counters("index.")
    tracing.reset_gauges("index.")
    tracing.reset_counters("memory.")
    tracing.reset_gauges("memory.")
    tracing.reset_histograms(PREFIX)
    # the class-label cap tracks the histograms it guards
    with _execute_classes_lock:
        _execute_classes.clear()
    tracing.reset_spans()
